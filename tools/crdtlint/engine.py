"""crdtlint engine: module loading, import graph, findings, baseline.

Everything here is stdlib-only (``ast`` + ``json``): the linter must be
runnable in environments where the package's own dependencies (jax,
numpy) are absent or expensive to import, and must never execute the
code it analyses.
"""

from __future__ import annotations

import ast
import dataclasses
import datetime
import json
import re
from pathlib import Path
from typing import Iterable

#: allow-comment grammar: ``# crdtlint: allow[tag,tag2] optional why``.
#: A tag may carry an expiry — ``allow[LOCK003 expires=2026-12-31]`` —
#: after which SUPPRESS003 flags the comment for re-justification.
#: The justification text after the bracket is free-form but encouraged
#: (ARCHITECTURE.md documents the convention: every allow states a why).
_ALLOW_RE = re.compile(r"#\s*crdtlint:\s*allow\[([A-Za-z0-9_\-, =]+)\]")

#: one comma-separated allow tag, with its optional expiry date
_TAG_RE = re.compile(r"^([A-Za-z0-9_\-]+)(?:\s+expires=(\d{4}-\d{2}-\d{2}))?$")

#: rule-family tag -> rule-id prefix (an exact rule id or ``all`` also work)
FAMILY_TAGS = {
    "lock": "LOCK",
    "race": "RACE",
    "host-sync": "SYNC",
    "purity": "PURE",
    "donation": "DONATE",
    "wire": "WIRE",
    "wal": "WAL",
    "obs": "OBS",
    "shape": "SHAPE",
    "leak": "LEAK",
    "spmd": "SPMD",
    "transfer": "TRANSFER",
    "fault": "FAULT",
}

#: hygiene meta-rules (stale/expired suppressions). They report on the
#: suppression machinery itself, so they are deliberately NOT
#: suppressible by allow comments or the baseline — the fix is always
#: to delete the stale allow/entry (or regenerate the baseline), or to
#: re-justify and re-date an expired one (SUPPRESS003).
SUPPRESS_RULES = ("SUPPRESS001", "SUPPRESS002", "SUPPRESS003")


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str  # posix path relative to the lint root
    line: int
    rule: str  # e.g. "LOCK001"
    message: str

    def fingerprint(self) -> tuple[str, str, str]:
        """Baseline identity: line numbers drift on unrelated edits, so
        the fingerprint is (path, rule, message) with a per-fingerprint
        count in the baseline file."""
        return (self.path, self.rule, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"


@dataclasses.dataclass
class AllowRecord:
    """One ``allow[...]`` comment: where it sits, which source lines it
    covers, and whether any finding actually used it this run (the
    stale-suppression hygiene check, SUPPRESS001). ``expiries`` maps a
    tag to its ``expires=YYYY-MM-DD`` date string (tags without one are
    perpetual); an expired tag still suppresses — the gate goes red
    through ONE actionable SUPPRESS003 at the comment, not through the
    original finding popping back up at an unrelated line."""

    comment_line: int
    lines: frozenset
    tags: frozenset
    expiries: dict = dataclasses.field(default_factory=dict)
    used: bool = False

    def expired_tags(self, today: "datetime.date") -> list[tuple[str, str]]:
        """``(tag, date_str)`` for every tag whose expiry has passed (an
        unparseable date — e.g. month 13 — counts as expired: a typo'd
        guard must fail closed, not silently never expire)."""
        out: list[tuple[str, str]] = []
        for tag, date_str in sorted(self.expiries.items()):
            try:
                expired = datetime.date.fromisoformat(date_str) < today
            except ValueError:
                expired = True
            if expired:
                out.append((tag, date_str))
        return out


class ModuleInfo:
    """One parsed module: AST, raw source lines, allow-comment map, and
    the import table used to resolve cross-module references."""

    def __init__(self, name: str, path: Path, rel: str, source: str):
        self.name = name  # dotted module name, e.g. pkg.ops.join
        self.path = path
        self.rel = rel  # posix relpath used in findings/baseline
        self.source = source
        self.tree = ast.parse(source, filename=str(path))
        self.lines = source.splitlines()
        self.allow_records = self._scan_allows(self.lines)
        # alias -> ("mod", modname) | ("sym", modname, symbol)
        self.imports: dict[str, tuple] = {}
        # top-level function/async defs by name (methods live under classes)
        self.functions: dict[str, ast.FunctionDef] = {}
        self.classes: dict[str, ast.ClassDef] = {}

    @staticmethod
    def _scan_allows(lines: list[str]) -> list[AllowRecord]:
        records: list[AllowRecord] = []
        for i, raw in enumerate(lines, start=1):
            m = _ALLOW_RE.search(raw)
            if not m:
                continue
            tags: set[str] = set()
            expiries: dict[str, str] = {}
            for chunk in m.group(1).split(","):
                chunk = chunk.strip()
                if not chunk:
                    continue
                tm = _TAG_RE.match(chunk)
                if tm is None:
                    # malformed tag spec (e.g. a bad expires= shape):
                    # keep the raw text as a tag so it matches nothing
                    # and SUPPRESS001 surfaces the typo
                    tags.add(chunk)
                    continue
                tags.add(tm.group(1))
                if tm.group(2) is not None:
                    expiries[tm.group(1)] = tm.group(2)
            covered = {i}
            if raw.lstrip().startswith("#"):
                # a pure-comment allow annotates the next SOURCE line:
                # justification comments may continue over further
                # comment lines, which must not eat the projection
                j = i  # 0-based index of the line after line i
                while j < len(lines) and lines[j].lstrip().startswith("#"):
                    j += 1
                covered.add(j + 1)
            records.append(
                AllowRecord(i, frozenset(covered), frozenset(tags), expiries)
            )
        return records

    def match_allow(self, line: int, rule: str) -> AllowRecord | None:
        # only this line's tags: trailing comments register on their own
        # line, comment-only lines cover exactly the next line — so an
        # allow can never bleed onto a neighbouring statement's findings
        for rec in self.allow_records:
            if line not in rec.lines:
                continue
            if "all" in rec.tags or rule in rec.tags:
                return rec
            if any(
                (prefix := FAMILY_TAGS.get(tag)) and rule.startswith(prefix)
                for tag in rec.tags
            ):
                return rec
        return None


def _dotted(node: ast.AST) -> str | None:
    """`a.b.c` attribute chain -> "a.b.c" (None when not a plain chain)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class Project:
    """All modules of one target package plus the import graph among
    them.

    ``overlay`` maps relative posix paths to replacement source text —
    used by the test suite to re-lint a mutated copy of a real module
    without touching the working tree.
    """

    def __init__(self, package_dir: Path, root: Path | None = None,
                 overlay: dict[str, str] | None = None,
                 manifest: Path | None = None):
        package_dir = package_dir.resolve()
        if not (package_dir / "__init__.py").exists():
            raise ValueError(f"{package_dir} is not a package (no __init__.py)")
        self.package_dir = package_dir
        self.package_name = package_dir.name
        #: protocol manifest consulted by the WIRE005 wire-compat lock
        #: (None -> the checked-in default next to the linter)
        self.manifest_path = manifest
        self.root = (root or package_dir.parent).resolve()
        overlay = overlay or {}
        self.modules: dict[str, ModuleInfo] = {}
        for path in sorted(package_dir.rglob("*.py")):
            rel = path.relative_to(self.root).as_posix()
            parts = path.relative_to(package_dir.parent).with_suffix("").parts
            if parts[-1] == "__init__":
                parts = parts[:-1]
            name = ".".join(parts)
            source = overlay.get(rel)
            if source is None:
                source = path.read_text(encoding="utf-8")
            self.modules[name] = ModuleInfo(name, path, rel, source)
        for mod in self.modules.values():
            self._index_module(mod)

    # -- indexing ------------------------------------------------------

    def _index_module(self, mod: ModuleInfo) -> None:
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mod.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                mod.classes[node.name] = node
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    target = alias.name
                    if self._in_project(target):
                        mod.imports[alias.asname or target.split(".")[0]] = (
                            ("mod", target)
                            if alias.asname
                            else ("modroot", target.split(".")[0])
                        )
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from(mod, node)
                if base is None:
                    continue
                for alias in node.names:
                    full = f"{base}.{alias.name}"
                    key = alias.asname or alias.name
                    if full in self.modules:
                        mod.imports[key] = ("mod", full)
                    elif base in self.modules:
                        mod.imports[key] = ("sym", base, alias.name)

    def _in_project(self, dotted: str) -> bool:
        return dotted == self.package_name or dotted.startswith(self.package_name + ".")

    def _resolve_from(self, mod: ModuleInfo, node: ast.ImportFrom) -> str | None:
        if node.level == 0:
            return node.module if node.module and self._in_project(node.module) else None
        # relative import: walk up from the containing package
        base_parts = mod.name.split(".")
        # a package's own name counts as one level; a module's parent too
        is_pkg = mod.path.name == "__init__.py"
        up = node.level - (1 if is_pkg else 0)
        if up >= len(base_parts):
            return None
        parent = base_parts[: len(base_parts) - up]
        if node.module:
            parent = parent + node.module.split(".")
        dotted = ".".join(parent)
        return dotted if self._in_project(dotted) else None

    # -- resolution ----------------------------------------------------

    def resolve_function(
        self, mod: ModuleInfo, expr: ast.AST, _depth: int = 0
    ) -> tuple[ModuleInfo, ast.FunctionDef] | None:
        """Resolve an expression to a project function def, unwrapping
        ``jax.vmap(f)`` / ``functools.partial(f, ...)`` / ``jax.checkpoint``
        wrappers and following one level of module-local assignment
        (``g = jax.jit(f)`` style)."""
        if _depth > 6:
            return None
        if isinstance(expr, ast.Call):
            chain = _dotted(expr.func) or ""
            leaf = chain.rsplit(".", 1)[-1]
            if leaf in {"vmap", "partial", "checkpoint", "named_call", "wraps"}:
                if expr.args:
                    return self.resolve_function(mod, expr.args[0], _depth + 1)
            return None
        if isinstance(expr, ast.Name):
            if expr.id in mod.functions:
                return mod, mod.functions[expr.id]
            imp = mod.imports.get(expr.id)
            if imp and imp[0] == "sym":
                target = self.modules.get(imp[1])
                if target and imp[2] in target.functions:
                    return target, target.functions[imp[2]]
            # module-local alias: name = <expr> at module top level
            assigned = self._module_assignment(mod, expr.id)
            if assigned is not None:
                return self.resolve_function(mod, assigned, _depth + 1)
            return None
        if isinstance(expr, ast.Attribute):
            chain = _dotted(expr)
            if chain is None:
                return None
            head, _, rest = chain.partition(".")
            imp = mod.imports.get(head)
            if imp is None or not rest:
                return None
            if imp[0] in ("mod", "modroot"):
                modname = imp[1] if imp[0] == "mod" else chain.rsplit(".", 1)[0]
                target = self.modules.get(modname)
                leaf = chain.rsplit(".", 1)[-1]
                if target and leaf in target.functions:
                    return target, target.functions[leaf]
        return None

    @staticmethod
    def _module_assignment(mod: ModuleInfo, name: str) -> ast.AST | None:
        for node in mod.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        return node.value
        return None


# ----------------------------------------------------------------------
# baseline

def load_baseline(path: Path) -> dict[tuple[str, str, str], int]:
    data = json.loads(path.read_text(encoding="utf-8"))
    out: dict[tuple[str, str, str], int] = {}
    for entry in data.get("findings", []):
        key = (entry["path"], entry["rule"], entry["message"])
        out[key] = out.get(key, 0) + int(entry.get("count", 1))
    return out


def write_baseline(path: Path, findings: Iterable[Finding]) -> None:
    counts: dict[tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.fingerprint()] = counts.get(f.fingerprint(), 0) + 1
    entries = [
        {"path": p, "rule": r, "message": m, "count": c}
        for (p, r, m), c in sorted(counts.items())
    ]
    path.write_text(
        json.dumps({"version": 1, "findings": entries}, indent=2) + "\n",
        encoding="utf-8",
    )


# ----------------------------------------------------------------------
# orchestration

def _rules_worker(
    pkg: str,
    root: str | None,
    overlay: dict[str, str] | None,
    manifest: str | None,
    rule_names: list[str],
) -> list[tuple[str, list[Finding], float]]:
    """Run a chunk of rule families over ONE freshly built project —
    the unit of ``--jobs`` process parallelism. Per-rule (not per-file)
    sharding is deliberate: most families are whole-project analyses
    (the lock-order graph spans classes, wire dispatch spans modules,
    the thread graph spans the import graph), so a file shard would
    silently lose every cross-file edge."""
    import time

    from tools.crdtlint.rules import ALL_RULES

    by_name = {f.__name__: f for f in ALL_RULES}
    project = Project(
        Path(pkg),
        root=Path(root) if root else None,
        overlay=overlay,
        manifest=Path(manifest) if manifest else None,
    )
    out: list[tuple[str, list[Finding], float]] = []
    for rule_name in rule_names:
        t0 = time.perf_counter()
        found = by_name[rule_name](project)
        out.append((rule_name, found, time.perf_counter() - t0))
    return out


def _collect_findings(
    pkg: Path,
    project: "Project",
    root: Path | None,
    overlay: dict[str, str] | None,
    manifest: Path | None,
    jobs: int,
    stats_out: dict[str, float] | None,
) -> list[Finding]:
    import time

    from tools.crdtlint.rules import ALL_RULES

    findings: list[Finding] = []
    if jobs <= 1:
        for rule_fn in ALL_RULES:
            t0 = time.perf_counter()
            findings.extend(rule_fn(project))
            if stats_out is not None:
                stats_out[rule_fn.__name__] = (
                    stats_out.get(rule_fn.__name__, 0.0)
                    + time.perf_counter() - t0
                )
        return findings
    import concurrent.futures
    import multiprocessing

    # round-robin the families into one chunk per worker, so every
    # worker builds the project exactly once
    names = [fn.__name__ for fn in ALL_RULES]
    n_chunks = max(1, min(jobs, len(names)))
    chunks = [names[i::n_chunks] for i in range(n_chunks)]
    by_rule: dict[str, list[Finding]] = {}
    # spawn, not fork: the caller may be a test process with live JAX
    # threads, and forking a multithreaded process can deadlock. The
    # linter is stdlib-only, so a spawned worker's import cost is small.
    with concurrent.futures.ProcessPoolExecutor(
        max_workers=n_chunks,
        mp_context=multiprocessing.get_context("spawn"),
    ) as pool:
        futures = [
            pool.submit(
                _rules_worker, str(pkg),
                str(root) if root else None, overlay,
                str(manifest) if manifest else None, chunk,
            )
            for chunk in chunks
        ]
        for fut in futures:
            for rule_name, out, dt in fut.result():
                by_rule[rule_name] = out
                if stats_out is not None:
                    stats_out[rule_name] = stats_out.get(rule_name, 0.0) + dt
    # merge in registration order: the list handed to the suppression
    # pass must be identical to a serial run's, so tie ordering of
    # same-(path, line, rule) findings cannot drift with scheduling
    for fn in ALL_RULES:
        findings.extend(by_rule.get(fn.__name__, []))
    return findings


def run_lint(
    package_dirs: list[Path],
    root: Path | None = None,
    baseline: dict[tuple[str, str, str], int] | None = None,
    overlay: dict[str, str] | None = None,
    select: set[str] | None = None,
    manifest: Path | None = None,
    hygiene: bool = True,
    jobs: int = 1,
    stats_out: dict[str, float] | None = None,
) -> tuple[list[Finding], list[Finding], list[Finding]]:
    """Lint the given packages.

    Returns ``(new, baselined, allowed)``: findings not suppressed by
    anything, findings absorbed by the baseline, and findings silenced
    by inline allow comments.

    With ``hygiene`` (and no ``select`` — a partial run cannot tell a
    stale suppression from one whose rule simply didn't run), stale
    suppressions are reported as findings in ``new``: SUPPRESS001 for an
    ``allow[...]`` comment no finding used, SUPPRESS002 for a baseline
    entry with leftover count. Neither is itself suppressible — the fix
    is to delete the stale allow/entry (``--write-baseline`` prunes).

    ``jobs > 1`` fans the rule families out to worker processes (each
    rebuilds the project; findings and their order are identical to a
    serial run). ``stats_out`` accumulates per-rule wall seconds.
    """
    new: list[Finding] = []
    baselined: list[Finding] = []
    allowed: list[Finding] = []
    remaining = dict(baseline or {})
    hygiene = hygiene and select is None
    for pkg in package_dirs:
        project = Project(Path(pkg), root=root, overlay=overlay,
                          manifest=manifest)
        findings = _collect_findings(
            Path(pkg), project, root, overlay, manifest, jobs, stats_out,
        )
        by_rel = {m.rel: m for m in project.modules.values()}
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
            if select and f.rule not in select:
                continue
            mod = by_rel.get(f.path)
            rec = mod.match_allow(f.line, f.rule) if mod is not None else None
            if rec is not None:
                rec.used = True
                allowed.append(f)
            elif remaining.get(f.fingerprint(), 0) > 0:
                remaining[f.fingerprint()] -= 1
                baselined.append(f)
            else:
                new.append(f)
        if hygiene:
            today = datetime.date.today()
            for mod in project.modules.values():
                for rec in mod.allow_records:
                    expired = rec.expired_tags(today)
                    for tag, date_str in expired:
                        new.append(Finding(
                            mod.rel, rec.comment_line, "SUPPRESS003",
                            f"expired suppression: allow[{tag} "
                            f"expires={date_str}] is past its expiry — "
                            f"re-justify with a new date, or fix the "
                            f"underlying finding and delete the comment",
                        ))
                    if not rec.used and not expired:
                        # an expired record's SUPPRESS003 subsumes the
                        # staleness complaint — one actionable finding
                        # per comment, not two
                        tags = ",".join(sorted(rec.tags))
                        new.append(Finding(
                            mod.rel, rec.comment_line, "SUPPRESS001",
                            f"stale suppression: allow[{tags}] matches no "
                            f"finding — delete the comment (or fix the tag)",
                        ))
    if hygiene:
        for (path, rule, message), count in sorted(remaining.items()):
            if count > 0:
                new.append(Finding(
                    path, 0, "SUPPRESS002",
                    f"stale baseline entry ({rule}): {message!r} matches no "
                    f"finding — regenerate with --write-baseline",
                ))
    return new, baselined, allowed
