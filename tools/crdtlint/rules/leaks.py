"""LEAK001 — buffer-pinning closure captures (the PR 9 bug, as a class).

The worst perf bug found so far: the deferred ``SYNC_DONE`` accounting
closures captured the whole ``MergeRowsResult`` — including
``res.state`` — and were parked in the drain's deferral window. Every
superseded store generation stayed referenced for the window, defeating
XLA's input-buffer reuse on each subsequent merge (a full-store copy
per dispatch, ~40% of enabled ingest wall at coalesce depth 64). The
fix idiom: **default-arg capture of just the count/scalar leaves**
(``lambda ins=res.n_ins_row, kill=res.n_kill_row: …``) — defaults
evaluate at definition time, so the closure holds only the small
arrays, never ``res``.

LEAK001 generalises that to the class: in a hot-path module (replica /
fleet), a nested def or lambda that

- **captures something heavy** — a kernel-result pytree (a local bound
  from a ``jit_*`` / merge-kernel call), a ``Store``-typed or
  state/store-named value, or ``self`` with the body reading a
  ``self.*state*``/``self.*store*`` attribute — as a free variable OR
  as a default argument whose value is the bare heavy name (or its
  ``.state``/``.store`` leaf: ``s=res.state`` pins exactly what free
  capture would), and
- **escapes its defining scope** — appended/put to a container, stored
  to an attribute or subscript, or passed to a project function/method
  that stores its parameter (``_note_state_changed`` → the deferral
  list, ``telemetry.attach``, collector registration), discovered by a
  fix-point over parameter flows so a deferral one call down still
  counts,

is red: the capture pins superseded device buffers across the deferral
window. Narrow it to the count/scalar leaves via default-arg capture
(recognised as green), or keep the closure local (a closure handed to
an immediately-applied combinator like ``jax.tree.map`` never parks).

Closure factories are folded through: a nested def returning another
nested def contributes the inner function's captures to any escape of
the factory's *call result* (``counts_for(lane)`` handed to
``fleet_commit``).
"""

from __future__ import annotations

import ast

from tools.crdtlint.engine import Finding, ModuleInfo, Project, _dotted
from tools.crdtlint.rules import (
    MUTATOR_METHODS,
    iter_function_defs,
    outer_function_defs,
)

RULE = "LEAK001"

#: modules whose nested defs are checked (the drain/tick hot paths;
#: ``treesync`` is ISSUE 15's relay module)
_HOT_LEAVES = {"replica", "fleet", "serve", "treesync"}

#: call leaves returning kernel-result pytrees / new store generations
_KERNEL_LEAVES = {
    "merge_rows", "merge_slice", "row_apply", "clear_all", "compact_rows",
    "merge_rows_into", "merge_group_into", "merge_into", "tier_retry_merge",
    "fleet_merge_rows", "fleet_row_apply", "fleet_compact_rows",
    "grow", "grow_table", "rehash", "stack_states", "stack_pytrees",
    # extraction results hold device buffers sliced off the live store
    # generation (ISSUE 15: the relay flush extracts per epoch) —
    # parking one in a deferral closure pins exactly like a merge result
    "extract_rows", "extract_own_delta", "interval_slices",
    "fleet_extract_rows", "fleet_extract_own_delta",
}
#: attribute-name substrings that make a value "heavy" (a store pytree
#: or a stacked state)
_HEAVY_ATTR_MARKERS = ("state", "store")
#: parameter names that carry store pytrees by convention in this tree
_HEAVY_PARAM_NAMES = {"state", "states", "store", "stacked", "stacked_in", "res"}
#: callees OUTSIDE the project that store their argument (handler /
#: collector registration — the "handed to telemetry/metrics" sinks)
_EXTERNAL_STORING_LEAVES = {
    "attach", "register_collector", "add_varz_source", "add_health_check",
    "append", "appendleft", "add", "put", "put_nowait", "setdefault",
}


def _attr_is_heavy(name: str) -> bool:
    return any(m in name for m in _HEAVY_ATTR_MARKERS)


def _call_leaf(node: ast.Call) -> str:
    return (_dotted(node.func) or "").rsplit(".", 1)[-1] or (
        node.func.attr if isinstance(node.func, ast.Attribute) else ""
    )


# ----------------------------------------------------------------------
# storing-parameter fix point: which params does each function park?


def _assigned_names(target: ast.AST) -> list[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: list[str] = []
        for e in target.elts:
            out.extend(_assigned_names(e))
        return out
    return []


def _names_in(expr: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


class _FnUnit:
    """One function/method: its params and body, keyed for the
    storing-parameter fix point (methods keyed by bare name as well —
    hot-module classes are a closed world, so a ``rep.fleet_commit``
    receiver resolves by method name)."""

    def __init__(self, mod: ModuleInfo, qual: tuple, fn: ast.FunctionDef):
        self.mod = mod
        self.qual = qual
        self.fn = fn
        a = fn.args
        self.params = [p.arg for p in (a.posonlyargs + a.args)]
        self.kwonly = [p.arg for p in a.kwonlyargs]
        self.storing: set[str] = set()  # param names this fn parks


def _project_units(project: Project) -> dict[str, list[_FnUnit]]:
    """bare function/method name -> units (all modules; names merge)."""
    units: dict[str, list[_FnUnit]] = {}
    for name in sorted(project.modules):
        mod = project.modules[name]
        for qual, fn in iter_function_defs(mod.tree):
            units.setdefault(fn.name, []).append(_FnUnit(mod, qual, fn))
    return units


def _make_param_storing(units: dict[str, list[_FnUnit]]):
    """``(callee leaf, positional index | None, kwarg | None) -> parks?``
    over the current fix-point state. Callees merge across classes by
    bare name (the hot modules are a closed world — conservative)."""

    def param_storing(leaf: str, index: int | None, kw: str | None) -> bool:
        for u in units.get(leaf, ()):
            names = u.params
            if kw is not None:
                if kw in u.storing:
                    return True
                continue
            if index is None:
                continue
            # method receivers burn params[0] == "self" at a call site
            # like obj.m(a): positional arg 0 -> params[1]
            for off in (0, 1):
                j = index + off
                if j < len(names) and names[j] in u.storing and (
                    off == 0 or (names and names[0] == "self")
                ):
                    return True
        return False

    return param_storing


def _storing_fixpoint(units: dict[str, list[_FnUnit]]) -> None:
    """Mark params that escape into storage, propagating through
    project-internal calls until stable (bounded: monotone over a
    finite set)."""
    param_storing = _make_param_storing(units)
    changed = True
    while changed:
        changed = False
        for us in units.values():
            for u in us:
                tracked = set(u.params) | set(u.kwonly)
                before = len(u.storing)
                u.storing |= _stored_names(u.fn, tracked, param_storing)
                if len(u.storing) != before:
                    changed = True


def _stored_names(
    fn: ast.FunctionDef, tracked: set[str], param_storing
) -> set[str]:
    """Names from ``tracked`` that this function's body parks: stored
    to an attribute/subscript, appended/put to a container, or passed
    at a storing position of another function. One level of local
    aliasing (``q = p``) is followed."""
    alias_of: dict[str, str] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Name):
            for t in node.targets:
                if isinstance(t, ast.Name) and node.value.id in tracked:
                    alias_of[t.id] = node.value.id

    def root(name: str) -> str | None:
        if name in tracked:
            return name
        return alias_of.get(name)

    def roots_in(expr: ast.AST) -> set[str]:
        out: set[str] = set()
        for n in ast.walk(expr):
            if isinstance(n, ast.Name):
                r = root(n.id)
                if r is not None:
                    out.add(r)
        return out

    stored: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    stored |= roots_in(node.value)
        elif isinstance(node, ast.Call):
            leaf = _call_leaf(node)
            if leaf in _EXTERNAL_STORING_LEAVES or leaf in MUTATOR_METHODS:
                for a in node.args:
                    stored |= roots_in(a)
                for kw in node.keywords:
                    stored |= roots_in(kw.value)
                continue
            for i, a in enumerate(node.args):
                for r in roots_in(a):
                    if param_storing(leaf, i, None):
                        stored.add(r)
            for kw in node.keywords:
                for r in roots_in(kw.value):
                    if param_storing(leaf, None, kw.arg):
                        stored.add(r)
    return stored


# ----------------------------------------------------------------------
# heavy locals of one enclosing function


def _heavy_locals(fn: ast.FunctionDef) -> set[str]:
    """Locals (and params) of ``fn`` holding kernel results / store
    pytrees. Transitive through plain-name and ``.state``-leaf
    assignments."""
    heavy: set[str] = set()
    a = fn.args
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        ann = p.annotation
        ann_name = (
            (_dotted(ann) or "").rsplit(".", 1)[-1]
            if ann is not None else ""
        )
        if p.arg in _HEAVY_PARAM_NAMES or ann_name.endswith("Store"):
            heavy.add(p.arg)

    def value_heavy(expr: ast.AST) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in heavy
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id in heavy | {"self"}:
                return _attr_is_heavy(expr.attr)
            return False
        if isinstance(expr, ast.Call):
            leaf = _call_leaf(expr)
            if leaf in _KERNEL_LEAVES or leaf.startswith("jit_"):
                return True
            return False
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(value_heavy(e) for e in expr.elts)
        return False

    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if not value_heavy(node.value):
                continue
            for t in node.targets:
                for name in _assigned_names(t):
                    if name not in heavy:
                        heavy.add(name)
                        changed = True
    return heavy


# ----------------------------------------------------------------------
# closures: captures, factories, escapes


def _bound_names(fn: "ast.FunctionDef | ast.Lambda") -> set[str]:
    a = fn.args
    bound = {
        p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)
    }
    if a.vararg:
        bound.add(a.vararg.arg)
    if a.kwarg:
        bound.add(a.kwarg.arg)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    bound |= set(_assigned_names(t))
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.For)):
                bound |= set(_assigned_names(node.target))
            elif isinstance(node, ast.comprehension):
                bound |= set(_assigned_names(node.target))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bound.add(node.name)
    return bound


def _free_names(fn: "ast.FunctionDef | ast.Lambda") -> set[str]:
    bound = _bound_names(fn)
    free: set[str] = set()
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id not in bound:
                    free.add(node.id)
    return free


def _self_reads_heavy(fn: "ast.FunctionDef | ast.Lambda") -> str | None:
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and _attr_is_heavy(node.attr)
            ):
                return node.attr
    return None


def _heavy_defaults(
    fn: "ast.FunctionDef | ast.Lambda", heavy: set[str]
) -> str | None:
    """A default-arg value that is the bare heavy name (or its
    state/store leaf) re-widens the capture — ``ins=res.n_ins_row``
    narrows to a leaf and is green, ``r=res`` / ``s=res.state`` pin the
    whole pytree."""
    for d in fn.args.defaults + [d for d in fn.args.kw_defaults if d]:
        if isinstance(d, ast.Name) and d.id in heavy:
            return d.id
        if (
            isinstance(d, ast.Attribute)
            and isinstance(d.value, ast.Name)
            and d.value.id in heavy
            and _attr_is_heavy(d.attr)
        ):
            return f"{d.value.id}.{d.attr}"
    return None


def _closure_heavies(
    fn: "ast.FunctionDef | ast.Lambda", heavy: set[str]
) -> list[str]:
    """Descriptions of every heavy thing this closure holds."""
    out: list[str] = []
    for name in sorted(_free_names(fn) & heavy):
        out.append(f"free variable {name!r} (kernel result / store pytree)")
    self_attr = _self_reads_heavy(fn)
    if self_attr is not None and "self" in _free_names(fn):
        out.append(f"self.{self_attr} through captured self")
    d = _heavy_defaults(fn, heavy)
    if d is not None:
        out.append(f"default argument bound to {d}")
    return out


def _returned_nested(fn: ast.FunctionDef) -> "list[ast.AST]":
    """Nested defs/lambdas this function returns (closure factory)."""
    local = {
        n.name: n
        for n in ast.walk(fn)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n is not fn
    }
    out: list[ast.AST] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            v = node.value
            if isinstance(v, ast.Lambda):
                out.append(v)
            elif isinstance(v, ast.Name) and v.id in local:
                out.append(local[v.id])
    return out


def check_leaks(project: Project) -> list[Finding]:
    units = _project_units(project)
    _storing_fixpoint(units)
    param_storing = _make_param_storing(units)
    findings: list[Finding] = []
    for mod_name in sorted(project.modules):
        mod = project.modules[mod_name]
        if mod_name.rsplit(".", 1)[-1] not in _HOT_LEAVES:
            continue
        # outer functions only: closures are analysed within their
        # outermost scope (captures resolve against its heavy locals),
        # so each closure is reported at most once
        for qual, fn in outer_function_defs(mod.tree):
            findings.extend(
                _function_findings(mod, qual, fn, param_storing)
            )
    return findings


def _function_findings(
    mod: ModuleInfo, qual: tuple, fn: ast.FunctionDef, param_storing
) -> list[Finding]:
    heavy = _heavy_locals(fn)
    if not heavy and not any(
        isinstance(n, ast.Attribute)
        and isinstance(n.value, ast.Name)
        and n.value.id == "self"
        for n in ast.walk(fn)
    ):
        return []

    # nested closures directly under fn (deeper nesting is analysed when
    # iter_function_defs reaches the inner def as its own unit)
    closures: dict[str, ast.AST] = {}
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
            closures[node.name] = node

    def closure_payload(c: "ast.FunctionDef | ast.Lambda") -> list[str]:
        """Heavy captures of the closure itself plus — for a factory —
        of the closure it returns."""
        out = _closure_heavies(c, heavy)
        if isinstance(c, ast.FunctionDef):
            for inner in _returned_nested(c):
                for h in _closure_heavies(inner, heavy):
                    if h not in out:
                        out.append(h)
        return out

    findings: list[Finding] = []
    flagged: set[int] = set()

    def name_of(c) -> str:
        base = ".".join(qual)
        if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return f"{base}.{c.name}"
        return f"{base}.<lambda>"

    def report(c, sink: str) -> None:
        if id(c) in flagged:
            return
        payload = closure_payload(c)
        if not payload:
            return
        flagged.add(id(c))
        findings.append(Finding(
            mod.rel, c.lineno, RULE,
            f"closure {name_of(c)} escapes its defining scope ({sink}) "
            f"capturing {payload[0]} — it pins superseded device buffers "
            f"across the deferral window (the PR 9 ingest bug class); "
            f"narrow the capture to count/scalar leaves via default-arg "
            f"capture ({mod.name})",
        ))

    def escaping_exprs(expr: ast.AST, sink: str) -> None:
        """Mark closures referenced by ``expr`` as escaping via ``sink``.
        A call of a closure factory carries the factory's payload."""
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in closures:
                report(closures[n.id], sink)
            elif isinstance(n, ast.Lambda):
                report(n, sink)
            elif (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id in closures
                and _returned_nested(closures[n.func.id])
            ):
                report(closures[n.func.id], sink)

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    escaping_exprs(node.value, "stored to an attribute/container")
        elif isinstance(node, ast.Call):
            leaf = _call_leaf(node)
            if leaf in _EXTERNAL_STORING_LEAVES or leaf in MUTATOR_METHODS:
                for a in list(node.args) + [kw.value for kw in node.keywords]:
                    escaping_exprs(a, f"handed to .{leaf}(...)")
                continue
            for i, a in enumerate(node.args):
                if param_storing(leaf, i, None):
                    escaping_exprs(a, f"deferred by {leaf}(...)")
            for kw in node.keywords:
                if param_storing(leaf, None, kw.arg):
                    escaping_exprs(kw.value, f"deferred by {leaf}(...)")
    return findings
