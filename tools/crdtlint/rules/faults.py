"""FAULT001–005 — failure-atomicity audit over the runtime's hot path.

The anti-entropy algorithm survives crash/recovery only if the failure
paths keep the seq/WAL/state invariants — and with a deterministic
fault-injection runtime (``utils/faults.py``) now able to raise at any
labelled boundary, "an exception here is impossible" stops being an
excuse anywhere a fault point is reachable. This family makes the
failure discipline static:

- **FAULT001** — torn-invariant window: a unit writes one of the
  commit-group attributes (``_seq``, ``_serve_pub``, ``_outstanding``,
  ``_ack_seq``), then performs a raise-capable durability/fault-point
  call, then writes a commit-group attribute again — with no enclosing
  ``try`` whose handler/finally restores a group attribute. An
  injected raise mid-window leaves the group half-advanced (a seq that
  names a record that never landed). A loop whose body contains both a
  group write and an unprotected raise-capable call is the same window
  wrapped around the back edge.
- **FAULT002** — swallowed exception: a bare/broad ``except`` in a hot
  module that neither re-raises, nor logs, nor records to the flight
  recorder, nor even reads the bound exception. Under fault injection
  a silent swallow converts a scheduled failure into a wedged replica
  with an empty black box.
- **FAULT003** — commit-ordering: in any unit with both a durability
  event (``self._durable``/``self._durable_batch``/``self._wal.append``)
  and a state-publication event (``self._publish_serve`` /
  ``self._note_state_changed`` / ``self._emit_diffs`` / a store to
  ``self._serve_pub``), no publication may precede the unit's first
  durability event in statement order. A crash between a publication
  and its append loses work readers already observed — the
  undocumented direction; the documented one (durable but
  unpublished) replays idempotently on recovery.
- **FAULT004** — cleanup-on-all-paths: a hot-module class constructing
  a joinable/closable resource (``Thread`` → ``join``, ``WalLog`` /
  sockets → ``close``) must reach that cleanup from EVERY terminal
  method it defines (``stop``/``close``/``crash``/``shutdown``), via
  self-calls if need be — a crash path that skips the WAL close leaks
  the fd and, worse, skips the crash-model contract.
- **FAULT005** — fault-point label hygiene (the TRANSFER002 shape over
  ``utils/faults.py``): a non-literal ``faultpoint(...)`` label, a
  label outside the closed ``SITES`` vocabulary, one label used from
  two call sites (a chaos schedule must pin ONE program point), and a
  ``SITES`` entry with no call site (ghost vocabulary).
"""

from __future__ import annotations

import ast

from tools.crdtlint.engine import Finding, ModuleInfo, Project, _dotted
from tools.crdtlint.rules import call_leaf, outer_function_defs, self_attr
from tools.crdtlint.rules.threadgraph import ClassAnalysis

RULE_TORN = "FAULT001"
RULE_SWALLOW = "FAULT002"
RULE_ORDER = "FAULT003"
RULE_CLEANUP = "FAULT004"
RULE_LABELS = "FAULT005"

#: the failure-path hot modules: the replica/fleet commit planes, the
#: serving front door, the relay tier, the TCP transport, and the WAL
_HOT_LEAVES = {"replica", "fleet", "serve", "treesync", "tcp_transport", "wal"}

#: attributes forming the replica's commit invariant group: a fault
#: between two writes of these tears the seq/WAL/ack/publication story
_COMMIT_ATTRS = {"_seq", "_serve_pub", "_outstanding", "_ack_seq"}

#: ``self._wal.<leaf>`` calls that may raise (I/O) mid-commit
_WAL_RAISING = {"append", "commit", "rotate", "maybe_sync"}

#: self-call leaves that are raise-capable durability points
_DURABLE_LEAVES = {"_durable", "_durable_batch"}

#: publication call leaves — the moment other threads may observe state
_PUBLISH_LEAVES = {"_publish_serve", "_note_state_changed", "_emit_diffs"}

#: resource constructor leaf -> accepted cleanup call leaves (FAULT004)
_RESOURCE_CLEANUP = {
    "Thread": ("join",),
    "WalLog": ("close",),
    "socket": ("close",),
}

#: terminal methods: every one a class defines must reach each cleanup
_TERMINAL_METHODS = ("close", "crash", "shutdown", "stop")


def _is_hot(mod_name: str) -> bool:
    return mod_name.rsplit(".", 1)[-1] in _HOT_LEAVES


# ----------------------------------------------------------------------
# FAULT001 — torn-invariant windows


def _commit_attr_write(stmt: ast.stmt) -> str | None:
    """Statement writing a commit-group attr: ``self._seq += 1``,
    ``self._seq = x``, ``self._outstanding[k] = v`` / ``del`` forms."""
    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        )
        for t in targets:
            base = t.value if isinstance(t, ast.Subscript) else t
            attr = self_attr(base)
            if attr in _COMMIT_ATTRS:
                return attr
    if isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            base = t.value if isinstance(t, ast.Subscript) else t
            attr = self_attr(base)
            if attr in _COMMIT_ATTRS:
                return attr
    return None


def _is_raise_capable(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id == "faultpoint"
    if isinstance(f, ast.Attribute):
        if f.attr == "faultpoint":
            return True
        if f.attr in _DURABLE_LEAVES and self_attr(f) is not None:
            return True
        if f.attr in _WAL_RAISING and _dotted(f.value) == "self._wal":
            return True
    return False


def _restorer_methods(tree: ast.AST) -> set[str]:
    """Method names whose body writes a commit-group attr — one
    interprocedural step: a handler calling ``self._commit_abort(...)``
    restores the group exactly as an inline ``self._seq -= 1`` does,
    and factoring the rollback into a helper must not read as torn."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for inner in ast.walk(node):
                if isinstance(inner, ast.stmt) and _commit_attr_write(inner):
                    out.add(node.name)
                    break
    return out


def _restores_group(stmts: list[ast.stmt], restorers: set[str]) -> bool:
    """Does this handler/finally suite restore the commit group —
    directly (attr write) or via a same-class restorer method?"""
    for s in stmts:
        for node in ast.walk(s):
            if isinstance(node, ast.stmt) and _commit_attr_write(node):
                return True
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and self_attr(node.func) in restorers
            ):
                return True
    return False


def _try_protects(node: ast.Try, restorers: set[str]) -> bool:
    if _restores_group(node.finalbody, restorers):
        return True
    return any(_restores_group(h.body, restorers) for h in node.handlers)


class _TornScan:
    """Ordered event walk of one unit body: ``("w", attr, line)`` for
    commit-group writes, ``("c", line)`` for raise-capable calls NOT
    under a restoring ``try``. Loop bodies are summarised separately so
    the back-edge window (write in iteration N+1 after an unprotected
    call in iteration N) is caught."""

    def __init__(self, restorers: set[str] | None = None) -> None:
        self.restorers = restorers or set()
        self.findings_at: list[tuple[int, str]] = []

    def scan_unit(self, fn: ast.FunctionDef) -> None:
        events = self._suite(fn.body, protected=False)
        self._straight_line(events)

    def _straight_line(self, events: list[tuple]) -> None:
        armed_write: str | None = None
        pending_call: int | None = None
        for ev in events:
            if ev[0] == "w":
                if armed_write is not None and pending_call is not None:
                    self.findings_at.append((ev[2], armed_write))
                    pending_call = None
                armed_write = ev[1]
            elif ev[0] == "c":
                if armed_write is not None:
                    pending_call = ev[1]

    def _suite(self, stmts: list[ast.stmt], protected: bool) -> list[tuple]:
        events: list[tuple] = []
        for stmt in stmts:
            attr = _commit_attr_write(stmt)
            if attr is not None:
                events.append(("w", attr, stmt.lineno))
                # fall through: the value expression may also call
            if isinstance(stmt, ast.Try):
                inner = protected or _try_protects(stmt, self.restorers)
                events.extend(self._suite(stmt.body, inner))
                for h in stmt.handlers:
                    events.extend(self._suite(h.body, protected))
                events.extend(self._suite(stmt.orelse, protected))
                events.extend(self._suite(stmt.finalbody, protected))
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                body_events = self._suite(stmt.body, protected)
                # back-edge window: an unprotected raise-capable call
                # anywhere in the body + any group write in the body
                calls = [e for e in body_events if e[0] == "c"]
                writes = [e for e in body_events if e[0] == "w"]
                if calls and writes:
                    self.findings_at.append((writes[0][2], writes[0][1]))
                events.extend(body_events)
                events.extend(self._suite(stmt.orelse, protected))
                continue
            if isinstance(stmt, ast.If):
                events.extend(self._expr_events(stmt.test, protected))
                events.extend(self._suite(stmt.body, protected))
                events.extend(self._suite(stmt.orelse, protected))
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    events.extend(
                        self._expr_events(item.context_expr, protected)
                    )
                events.extend(self._suite(stmt.body, protected))
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # closures run at their call sites; analysed inline,
                # conservatively with the current protection state
                events.extend(self._suite(stmt.body, protected))
                continue
            events.extend(self._expr_events(stmt, protected))
        return events

    def _expr_events(self, node: ast.AST, protected: bool) -> list[tuple]:
        if protected:
            return []
        return [
            ("c", n.lineno)
            for n in ast.walk(node)
            if isinstance(n, ast.Call) and _is_raise_capable(n)
        ]


def _torn_findings(mod: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    restorers = _restorer_methods(mod.tree)
    for qual, fn in outer_function_defs(mod.tree):
        scan = _TornScan(restorers)
        scan.scan_unit(fn)
        name = ".".join(qual)
        seen: set[int] = set()
        for line, attr in scan.findings_at:
            if line in seen:
                continue
            seen.add(line)
            findings.append(Finding(
                mod.rel, line, RULE_TORN,
                f"torn-invariant window in {mod.name}.{name}: commit-group "
                f"write (self.{attr}) follows a raise-capable "
                f"durability/fault-point call with no try/finally restoring "
                f"the group — an injected raise leaves the commit "
                f"half-advanced; wrap the call and roll the group back in "
                f"the handler",
            ))
    return findings


# ----------------------------------------------------------------------
# FAULT002 — swallowed exceptions


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True

    def broad(n: ast.AST) -> bool:
        return isinstance(n, ast.Name) and n.id in ("Exception", "BaseException")

    if broad(t):
        return True
    return isinstance(t, ast.Tuple) and any(broad(e) for e in t.elts)


def _handler_records(handler: ast.ExceptHandler) -> bool:
    """Re-raises, logs, flight-records, or at least reads the bound
    exception — any of which makes the swallow observable."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            chain = _dotted(node.func) or ""
            head = chain.split(".", 1)[0]
            if head in ("logger", "logging", "log"):
                return True
            leaf = call_leaf(node)
            if leaf in ("record", "_flight", "dump"):
                return True
            if "flight" in chain:
                return True
        if (
            handler.name is not None
            and isinstance(node, ast.Name)
            and node.id == handler.name
            and isinstance(node.ctx, ast.Load)
        ):
            return True
    return False


def _swallow_findings(mod: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad_handler(node):
            continue
        if _handler_records(node):
            continue
        what = "bare except" if node.type is None else "broad except"
        findings.append(Finding(
            mod.rel, node.lineno, RULE_SWALLOW,
            f"{what} in hot module {mod.name} swallows the exception "
            f"silently — under fault injection this wedges the replica "
            f"with an empty black box; re-raise, log, or record to the "
            f"flight recorder",
        ))
    return findings


# ----------------------------------------------------------------------
# FAULT003 — commit-ordering (durability happens-before publication)


def _order_event(node: ast.AST) -> tuple[str, int] | None:
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _DURABLE_LEAVES and self_attr(f) is not None:
                return ("dur", node.lineno)
            if f.attr == "append" and _dotted(f.value) == "self._wal":
                return ("dur", node.lineno)
            if f.attr in _PUBLISH_LEAVES and self_attr(f) is not None:
                return ("pub", node.lineno)
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            if self_attr(t) == "_serve_pub":
                return ("pub", node.lineno)
    return None


def _ordered_events(fn: ast.FunctionDef) -> list[tuple[str, int]]:
    """Statement-ordered (kind, line) durability/publication events —
    ``ast.walk`` is breadth-first, so walk child statements in source
    order instead."""
    events: list[tuple[str, int]] = []

    def visit(node: ast.AST) -> None:
        ev = _order_event(node)
        if ev is not None:
            events.append(ev)
        for child in ast.iter_child_nodes(node):
            visit(child)

    for stmt in fn.body:
        visit(stmt)
    return events


def _order_findings(mod: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    for qual, fn in outer_function_defs(mod.tree):
        name = ".".join(qual)
        if "replay" in fn.name:
            # replay re-publishes already-durable history — the one
            # commit path where publication without an append is the
            # contract, not a tear
            continue
        events = _ordered_events(fn)
        durs = [ln for k, ln in events if k == "dur"]
        if not durs:
            continue
        seen_dur = False
        for kind, line in events:
            if kind == "dur":
                seen_dur = True
            elif not seen_dur:
                findings.append(Finding(
                    mod.rel, line, RULE_ORDER,
                    f"commit-ordering violation in {mod.name}.{name}: state "
                    f"is published before the unit's WAL append — a crash "
                    f"in between loses work readers already observed; "
                    f"append first, publish after",
                ))
    return findings


# ----------------------------------------------------------------------
# FAULT004 — cleanup on all terminal paths


def _cleanup_findings(mod: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    for cls_node in mod.tree.body:
        if not isinstance(cls_node, ast.ClassDef):
            continue
        ca = ClassAnalysis(mod, cls_node)
        resources: dict[str, tuple[str, ...]] = {}
        for attr, chain in ca.attr_ctors.items():
            leaf = chain.rsplit(".", 1)[-1]
            if leaf in _RESOURCE_CLEANUP:
                resources[attr] = _RESOURCE_CLEANUP[leaf]
        if not resources:
            continue
        terminals = [m for m in _TERMINAL_METHODS if m in ca.methods]
        if not terminals:
            continue
        from tools.crdtlint.rules.threadgraph import scan_unit

        scans = {
            name: scan_unit(ca, name, fn) for name, fn in ca.methods.items()
        }
        for term in terminals:
            # attr-calls reachable from the terminal via self-call edges
            reach: set[str] = set()
            stack = [term]
            calls: list = []
            while stack:
                u = stack.pop()
                if u in reach or u not in scans:
                    continue
                reach.add(u)
                calls.extend(scans[u].attr_calls)
                stack.extend(e.callee for e in scans[u].edges)
            for attr, cleanups in sorted(resources.items()):
                if any(
                    c.attr == attr and c.callee in cleanups for c in calls
                ):
                    continue
                findings.append(Finding(
                    mod.rel, ca.methods[term].lineno, RULE_CLEANUP,
                    f"{mod.name}.{ca.name}.{term}() never reaches "
                    f"self.{attr}.{cleanups[0]}() — the "
                    f"{ca.attr_ctors[attr].rsplit('.', 1)[-1]} resource "
                    f"leaks on this terminal path (threads/sockets/WAL "
                    f"handles must be released on stop() AND crash())",
                ))
    return findings


# ----------------------------------------------------------------------
# FAULT005 — fault-point label hygiene


def _find_sites_vocabulary(
    project: Project,
) -> tuple[ModuleInfo, int, list[str]] | None:
    """The ``SITES`` tuple in the utils ``faults`` module."""
    for name in sorted(project.modules):
        if not name.endswith(".faults") or ".utils." not in f".{name}.":
            continue
        mod = project.modules[name]
        for node in mod.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "SITES"
                and isinstance(node.value, (ast.Tuple, ast.List))
            ):
                labels = [
                    e.value
                    for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                ]
                return mod, node.lineno, labels
    return None


def _is_faultpoint_call(node: ast.Call, mod: ModuleInfo) -> bool:
    f = node.func
    if isinstance(f, ast.Name) and f.id == "faultpoint":
        imp = mod.imports.get("faultpoint")
        return (
            imp is not None
            and imp[0] == "sym"
            and imp[1].rsplit(".", 1)[-1] == "faults"
        )
    if isinstance(f, ast.Attribute) and f.attr == "faultpoint":
        if isinstance(f.value, ast.Name):
            imp = mod.imports.get(f.value.id)
            if imp is not None and imp[0] in ("mod", "modroot"):
                return imp[1].rsplit(".", 1)[-1] == "faults"
            return f.value.id == "faults"
    return False


def _label_findings(project: Project) -> list[Finding]:
    vocab = _find_sites_vocabulary(project)
    if vocab is None:
        return []
    faults_mod, sites_line, labels = vocab
    findings: list[Finding] = []
    #: label -> list of (module rel, line) call sites
    sites: dict[str, list[tuple[str, int]]] = {}
    for mod_name in sorted(project.modules):
        mod = project.modules[mod_name]
        if mod is faults_mod:
            continue  # the registry's own machinery takes label params
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and _is_faultpoint_call(node, mod)
            ):
                continue
            label_node = node.args[0] if node.args else None
            if not (
                isinstance(label_node, ast.Constant)
                and isinstance(label_node.value, str)
            ):
                findings.append(Finding(
                    mod.rel, node.lineno, RULE_LABELS,
                    f"faultpoint label in {mod.name} is not a string "
                    f"literal — chaos schedules key on statically knowable "
                    f"site names",
                ))
                continue
            label = label_node.value
            if label not in labels:
                findings.append(Finding(
                    mod.rel, node.lineno, RULE_LABELS,
                    f"faultpoint label {label!r} in {mod.name} is not in "
                    f"the SITES vocabulary ({faults_mod.rel}) — add it "
                    f"there (sorted) or fix the typo",
                ))
                continue
            sites.setdefault(label, []).append((mod.rel, node.lineno))
    for label, where in sorted(sites.items()):
        if len(where) > 1:
            first = where[0]
            for rel, line in where[1:]:
                findings.append(Finding(
                    rel, line, RULE_LABELS,
                    f"faultpoint label {label!r} already used at "
                    f"{first[0]}:{first[1]} — one label must pin exactly "
                    f"one program point (a chaos schedule naming it would "
                    f"trip at whichever site hits first)",
                ))
    for label in labels:
        if label not in sites:
            findings.append(Finding(
                faults_mod.rel, sites_line, RULE_LABELS,
                f"SITES entry {label!r} has no faultpoint call site — "
                f"ghost vocabulary: chaos schedules targeting it can "
                f"never trip (delete the entry or add the call site)",
            ))
    return findings


# ----------------------------------------------------------------------


def check_faults(project: Project) -> list[Finding]:
    findings = _label_findings(project)
    for mod_name in sorted(project.modules):
        mod = project.modules[mod_name]
        if not _is_hot(mod_name):
            continue
        findings.extend(_torn_findings(mod))
        findings.extend(_swallow_findings(mod))
        findings.extend(_order_findings(mod))
        findings.extend(_cleanup_findings(mod))
    return findings
