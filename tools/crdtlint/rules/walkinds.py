"""WAL001/WAL002 — WAL record-kind exhaustiveness.

Every record kind the runtime *writes* must be handled by every
dispatcher that *reads* the log, or durability degrades silently:

- **WAL001** — a produced kind with no arm in the recovery replay
  dispatcher: records of that kind are skipped on restart (the
  "unknown kind" warning path), i.e. acknowledged-durable data does not
  come back. Also fires when kinds are produced but NO replay
  dispatcher exists at all — a rename must not disarm the rule.
- **WAL002** — a produced kind with no explicit classification in the
  log-shipping serving scan: unknown kinds there become serving
  BARRIERS (safe but degraded — every catch-up past one falls back to
  the digest walk). A new kind must be classified on purpose: servable
  (its touched rows computed) or an explicit barrier, never by default.

Discovery is structural, not name-listed: a *producer* is any dict
literal with both a ``"kind": <str>`` and a ``"seq"`` key (the WAL
record schema); a *dispatcher* is any function comparing an expression
rooted in ``[...]["kind"]`` / ``.get("kind")`` against string literals.
Dispatcher role comes from the function name: ``replay``/``recover``
functions are recovery, ``scan_log``/``serve``/``catchup`` functions
are the serving classifier.
"""

from __future__ import annotations

import ast
import re

from tools.crdtlint.engine import Finding, ModuleInfo, Project
from tools.crdtlint.rules import iter_function_defs

RULE_REPLAY = "WAL001"
RULE_SERVING = "WAL002"

_REPLAY_NAME = re.compile(r"replay|recover")
_SERVING_NAME = re.compile(r"scan_log|serve_log|catchup|log_rows")


def _producers(mod: ModuleInfo) -> list[tuple[str, int]]:
    """``(kind, line)`` for every WAL record literal in this module."""
    out: list[tuple[str, int]] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Dict):
            continue
        keys = {
            k.value: v
            for k, v in zip(node.keys, node.values)
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        }
        if "kind" not in keys or "seq" not in keys:
            continue
        kind = keys["kind"]
        if isinstance(kind, ast.Constant) and isinstance(kind.value, str):
            out.append((kind.value, node.lineno))
    return out


def _is_kind_expr(node: ast.AST, kind_names: set[str]) -> bool:
    """``rec["kind"]`` / ``rec.get("kind")`` / a name bound from one."""
    if isinstance(node, ast.Name):
        return node.id in kind_names
    if isinstance(node, ast.Subscript):
        sl = node.slice
        return isinstance(sl, ast.Constant) and sl.value == "kind"
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        return (
            node.func.attr == "get"
            and len(node.args) >= 1
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value == "kind"
        )
    return False


def _dispatcher_kinds(fn: ast.FunctionDef) -> set[str] | None:
    """String literals this function compares a kind expression against
    (``==``, ``!=``, ``in``/``not in`` over literal containers); None
    when the function never inspects a kind."""
    kind_names: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and _is_kind_expr(node.value, set()):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    kind_names.add(t.id)
    compared: set[str] = set()
    saw_kind_compare = False
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left, *node.comparators]
        if not any(_is_kind_expr(s, kind_names) for s in sides):
            continue
        saw_kind_compare = True
        for s in sides:
            if isinstance(s, ast.Constant) and isinstance(s.value, str):
                compared.add(s.value)
            elif isinstance(s, (ast.Tuple, ast.List, ast.Set)):
                compared.update(
                    e.value for e in s.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
    return compared if saw_kind_compare else None


def check_wal_kinds(project: Project) -> list[Finding]:
    produced: dict[str, tuple[str, int]] = {}  # kind -> first (path, line)
    for name in sorted(project.modules):
        mod = project.modules[name]
        for kind, line in _producers(mod):
            produced.setdefault(kind, (mod.rel, line))
    if not produced:
        return []

    replay: list[tuple[ModuleInfo, str, set[str]]] = []
    serving: list[tuple[ModuleInfo, str, set[str]]] = []
    for name in sorted(project.modules):
        mod = project.modules[name]
        for qual, fn in iter_function_defs(mod.tree):
            kinds = _dispatcher_kinds(fn)
            if kinds is None:
                continue
            fname = qual[-1]
            if _REPLAY_NAME.search(fname):
                replay.append((mod, ".".join(qual), kinds))
            elif _SERVING_NAME.search(fname):
                serving.append((mod, ".".join(qual), kinds))

    findings: list[Finding] = []
    first_path, first_line = min(produced.values())
    if not replay:
        findings.append(Finding(
            first_path, first_line, RULE_REPLAY,
            "WAL record kinds are produced but no recovery replay "
            "dispatcher was found (a function matching 'replay|recover' "
            "comparing record kinds) — durable records would never be "
            "replayed",
        ))
    if not serving:
        findings.append(Finding(
            first_path, first_line, RULE_SERVING,
            "WAL record kinds are produced but no log-shipping serving "
            "classifier was found (a function matching "
            "'scan_log|serve_log|catchup|log_rows' comparing record "
            "kinds) — catch-up cannot classify records",
        ))
    for kind in sorted(produced):
        path, line = produced[kind]
        for mod, qual, kinds in replay:
            if kind not in kinds:
                findings.append(Finding(
                    path, line, RULE_REPLAY,
                    f"WAL record kind {kind!r} has no replay arm in "
                    f"{qual} — records of this kind are silently skipped "
                    f"on crash recovery (durability hole)",
                ))
        for mod, qual, kinds in serving:
            if kind not in kinds:
                findings.append(Finding(
                    path, line, RULE_SERVING,
                    f"WAL record kind {kind!r} has no explicit serving "
                    f"classification in {qual} — it degrades every "
                    f"catch-up stream to a barrier + digest-walk "
                    f"fallback; classify it as servable or as an "
                    f"intentional barrier",
                ))
    return findings
