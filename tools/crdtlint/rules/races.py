"""RACE001–005 — happens-before race detection across the thread graph.

The LOCK family checks lock *discipline* (what a lock guards, the
acquisition order, blocking under a lock). It is blind to the actual
race condition: two thread roots touching the same mutable state with
no common lock and no happens-before edge — the bug class that breaks
convergence silently (a torn merge is not a join). These rules consume
the shared thread-graph/happens-before engine
(:mod:`tools.crdtlint.rules.threadgraph`), which discovers every thread
entry root over the real import graph (``Thread(target=...)`` bound
methods and nested loop defs — the fleet tick loop, the replica event
loop, the TCP accept/heartbeat/serve/HELLO-wait threads — plus module
functions reached from those roots cross-module) and the HB edges
``Thread.start/join``, ``Event.set/wait``, per-object ``Queue.put/get``.

- **RACE001** — shared mutable state (``self._*`` attribute, or an
  underscore module global) written on one thread root and accessed on
  another with no common lock and no happens-before path. LOCK001
  cannot see these: an attribute never written under any lock mints no
  guard, so a completely lock-free cross-thread counter passes the
  discipline check while every read of it is torn.
- **RACE002** — a mutable object captured by a thread-entry closure,
  mutated inside the thread and accessed by the enclosing scope after
  ``start()`` (or vice versa). A ``join()`` before the enclosing access
  orders it; a threadsafe capture (``Queue``/``Event``) is exempt.
- **RACE003** — check-then-act on a version field: a read of a
  lock-guarded monotone counter (``_state_version`` shape: ``+= 1``
  under a lock) feeding a comparison OUTSIDE that lock in a unit that
  later takes the lock. The check's answer is stale by the time the
  act runs; the optimistic-commit pattern requires the re-check itself
  to sit inside the lock (``Replica.fleet_commit`` is the model).
- **RACE004** — an attribute published after ``Thread.start()`` that
  the started thread reads: the init-race window where the thread can
  observe the pre-assignment value (or an ``AttributeError``). Writes
  sequenced before ``start()`` are the blessed publication idiom.
- **RACE005** — lock-free iteration (``for``/comprehension/snapshot
  builtins like ``list(...)``/``sorted(...)``) of a collection another
  root mutates: ``RuntimeError: dictionary changed size`` at best, a
  silently-skipped element at worst.

Write notion (deliberately narrower than LOCK001's guard inference):
stores, deletes, augmented/item assignment, and calls of known mutator
methods — an unknown method call is NOT assumed mutating, or every
cross-thread socket/file shutdown idiom would flood the report.
Known boundary (documented): cross-class method calls through member
references (``rep.fleet_commit(...)`` from the fleet loop) are analysed
in the callee class's own context, where the external-caller root
already models "some other thread".
"""

from __future__ import annotations

import ast
import builtins

from tools.crdtlint.engine import Finding, Project
from tools.crdtlint.rules import MUTATOR_METHODS, THREADSAFE_CONSTRUCTORS, self_attr
from tools.crdtlint.rules.threadgraph import (
    Access,
    ConcurrencyModel,
    build_models,
    infer_guards,
    is_race_write,
    pair_unordered,
)

RULE_SHARED = "RACE001"
RULE_ESCAPE = "RACE002"
RULE_CHECK_ACT = "RACE003"
RULE_PUBLISH = "RACE004"
RULE_ITER = "RACE005"

_BUILTIN_NAMES = frozenset(dir(builtins))

def _verb(acc: Access) -> str:
    if acc.kind == "call":
        return ("mutating call" if acc.leaf in MUTATOR_METHODS
                else "method call")
    return {"read": "read", "write": "write", "iter": "iteration"}[acc.kind]


def _unit_fns(model: ConcurrencyModel) -> dict[str, ast.FunctionDef]:
    fns = dict(model.owner.methods)
    fns.update(model.owner.thread_entries)
    return fns


def _unit_label(model: ConcurrencyModel, unit: str) -> str:
    if model.owner.is_module:
        return f"{model.mod.name.rsplit('.', 1)[-1]}.{unit}"
    return f"{model.owner.name}.{unit}"


def _guards(model: ConcurrencyModel) -> dict[str, set[str]]:
    return infer_guards(model.scans, model.entry_states)


# ----------------------------------------------------------------------
# RACE001 — cross-root shared state, no lock, no happens-before

def _race001(model: ConcurrencyModel) -> list[Finding]:
    findings: list[Finding] = []
    for attr in sorted(model.tracked_attrs()):
        sites = list(model.accesses_of(attr))
        if not sites:
            continue
        iter_lines = {
            (unit, acc.line)
            for unit, acc, _roots, _locks in model.accesses_of(attr, include_iters=True)
            if acc.kind == "iter"
        }
        writes = [s for s in sites if is_race_write(s[1])]
        if not writes:
            continue
        flagged: set[tuple[str, int]] = set()
        for au, a, ar, al in sites:
            if not is_race_write(a) and (au, a.line) in iter_lines:
                # RACE005 owns iteration sites: a snapshot builtin like
                # list(self._x.values()) records both a plain read AND
                # a non-mutating 'call' access on the same line — skip
                # every non-write shape, or one defect reports twice
                continue
            counterpart = None
            for wu, w, wr, wl in writes:
                if wl & al:
                    continue  # common lock on every path
                pair = pair_unordered(model, wu, w, wr, au, a, ar)
                if pair is None:
                    continue
                cand = (wu, w.line, pair[0], pair[1])
                if counterpart is None or cand < counterpart:
                    counterpart = cand
            if counterpart is None:
                continue
            key = (au, a.line)
            if key in flagged:
                continue
            flagged.add(key)
            wu, _wline, root_w, root_a = counterpart
            label = model.attr_label(attr)
            findings.append(Finding(
                model.mod.rel, a.line, RULE_SHARED,
                f"cross-thread race on {label}: {_verb(a)} in "
                f"{_unit_label(model, au)} (thread root {root_a}) is "
                f"concurrent with the write in {_unit_label(model, wu)} "
                f"(root {root_w}) — no common lock, no happens-before edge",
            ))
    return findings


# ----------------------------------------------------------------------
# RACE002 — mutable closure capture across a thread boundary

def _free_names(fn: ast.FunctionDef) -> set[str]:
    bound = {fn.name}
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs
              + ([args.vararg] if args.vararg else [])
              + ([args.kwarg] if args.kwarg else [])):
        bound.add(a.arg)
    used: set[str] = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Name):
            if isinstance(n.ctx, (ast.Store, ast.Del)):
                bound.add(n.id)
            else:
                used.add(n.id)
        elif isinstance(n, ast.arg):
            bound.add(n.arg)
    return used - bound - _BUILTIN_NAMES - {"self"}


def _mutation_lines(fn: ast.AST, name: str,
                    after: int = 0, exclude: "tuple[int, int] | None" = None
                    ) -> list[int]:
    """Lines where the object bound to ``name`` is mutated in place."""

    def rooted(node: ast.AST) -> bool:
        while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
            node = (node.func if isinstance(node, ast.Call)
                    else node.value)
        return isinstance(node, ast.Name) and node.id == name

    out: list[int] = []
    for n in ast.walk(fn):
        line = getattr(n, "lineno", None)
        if line is None or line <= after:
            continue
        if exclude and exclude[0] <= line <= exclude[1]:
            continue
        if isinstance(n, (ast.Attribute, ast.Subscript)):
            if isinstance(n.ctx, (ast.Store, ast.Del)) and rooted(n.value):
                out.append(line)
        elif isinstance(n, ast.AugAssign):
            if isinstance(n.target, (ast.Attribute, ast.Subscript)) and rooted(
                n.target.value
            ):
                out.append(line)
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            if n.func.attr in MUTATOR_METHODS and rooted(n.func.value):
                out.append(line)
    return sorted(out)


def _access_lines(fn: ast.AST, name: str,
                  after: int = 0, exclude: "tuple[int, int] | None" = None
                  ) -> list[int]:
    out = []
    for n in ast.walk(fn):
        if isinstance(n, ast.Name) and n.id == name:
            line = n.lineno
            if line <= after:
                continue
            if exclude and exclude[0] <= line <= exclude[1]:
                continue
            out.append(line)
    return sorted(out)


def _race002(model: ConcurrencyModel) -> list[Finding]:
    findings: list[Finding] = []
    fns = _unit_fns(model)
    for entry_name, entry_fn in sorted(model.owner.thread_entries.items()):
        if ".<" not in entry_name:
            continue  # bound-method entries capture self, handled per class
        encl_name = entry_name.split(".<", 1)[0]
        encl_fn = fns.get(encl_name)
        scan = model.scans.get(encl_name)
        if encl_fn is None or scan is None:
            continue
        starts = [s for s in scan.syncs if s.op == "start" and s.obj == entry_name]
        if not starts:
            continue
        start_line = min(s.line for s in starts)
        joins = [s.line for s in scan.syncs
                 if s.op == "join" and s.obj == entry_name]
        join_line = min(joins) if joins else None
        span = (entry_fn.lineno, entry_fn.end_lineno or entry_fn.lineno)
        # names the enclosing scope bound to threadsafe objects are the
        # blessed cross-thread channels, not escapes
        safe: set[str] = set()
        for n in ast.walk(encl_fn):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                leaf = (n.value.func.attr if isinstance(n.value.func, ast.Attribute)
                        else n.value.func.id if isinstance(n.value.func, ast.Name)
                        else None)
                if leaf in THREADSAFE_CONSTRUCTORS:
                    safe.update(t.id for t in n.targets if isinstance(t, ast.Name))
        for name in sorted(_free_names(entry_fn) - safe
                           - set(model.owner.methods)
                           - getattr(model.owner, "trackable", set())):
            entry_muts = _mutation_lines(entry_fn, name)
            entry_uses = _access_lines(entry_fn, name)

            def _unjoined(lines: list[int]) -> list[int]:
                return [l for l in lines if join_line is None or l < join_line]

            encl_uses_after = _unjoined(
                _access_lines(encl_fn, name, after=start_line, exclude=span))
            encl_muts_after = _unjoined(
                _mutation_lines(encl_fn, name, after=start_line, exclude=span))
            if entry_muts and encl_uses_after:
                findings.append(Finding(
                    model.mod.rel, encl_uses_after[0], RULE_ESCAPE,
                    f"mutable {name!r} escapes into thread entry "
                    f"{_unit_label(model, entry_name)}: the thread mutates it "
                    f"and {_unit_label(model, encl_name)} still uses it after "
                    f"start() — join first, or hand it over via a "
                    f"Queue/Event",
                ))
            elif encl_muts_after and entry_uses:
                findings.append(Finding(
                    model.mod.rel, encl_muts_after[0], RULE_ESCAPE,
                    f"mutable {name!r} escapes into thread entry "
                    f"{_unit_label(model, entry_name)}: "
                    f"{_unit_label(model, encl_name)} mutates it after "
                    f"start() while the thread reads it — mutate before "
                    f"start(), or synchronize the handoff",
                ))
    return findings


# ----------------------------------------------------------------------
# RACE003 — check-then-act on version fields

def _race003(model: ConcurrencyModel) -> list[Finding]:
    guards = _guards(model)
    aug_attrs = {
        acc.attr
        for scan in model.scans.values()
        for acc in scan.accesses
        if acc.aug
    }
    version_fields = {a for a in aug_attrs if guards.get(a)}
    if not version_fields:
        return []
    findings: list[Finding] = []
    fns = _unit_fns(model)
    for unit in sorted(model.scans):
        scan = model.scans[unit]
        fn = fns.get(unit)
        if fn is None or not model.roots.get(unit):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Compare):
                continue
            for sub in ast.walk(node):
                attr = (sub.id if model.owner.is_module and isinstance(sub, ast.Name)
                        else self_attr(sub))
                if attr not in version_fields:
                    continue
                acc = next(
                    (a for a in scan.accesses
                     if a.attr == attr and a.line == sub.lineno
                     and a.kind == "read"),
                    None,
                )
                if acc is None:
                    continue
                locks = model.effective_locks(unit, acc)
                if locks is None or locks & guards[attr]:
                    continue
                later_acquire = any(
                    aq.lock in guards[attr] and aq.line > node.lineno
                    for aq in scan.acquires
                )
                if not later_acquire:
                    continue
                lock_name = "/".join(
                    sorted(model.attr_label(l) for l in guards[attr]))
                findings.append(Finding(
                    model.mod.rel, node.lineno, RULE_CHECK_ACT,
                    f"check-then-act on {model.attr_label(attr)} in "
                    f"{_unit_label(model, unit)}: the version check runs "
                    f"outside {lock_name} (which guards its writes) and the "
                    f"lock is only taken afterwards — the check is stale by "
                    f"commit time; move it inside the locked region",
                ))
    # one finding per compare site
    seen: set[tuple[int, str]] = set()
    out = []
    for f in findings:
        if (f.line, f.message) not in seen:
            seen.add((f.line, f.message))
            out.append(f)
    return out


# ----------------------------------------------------------------------
# RACE004 — publication after Thread.start

def _race004(model: ConcurrencyModel) -> list[Finding]:
    if model.owner.is_module:
        return []  # module globals published post-start fall to RACE001
    findings: list[Finding] = []
    fns = _unit_fns(model)
    seen: set[tuple[int, str]] = set()
    for unit in sorted(model.scans):
        scan = model.scans[unit]
        fn = fns.get(unit)
        if fn is None:
            continue
        starts = [s for s in scan.syncs if s.op == "start"]
        if not starts:
            continue
        # writes to self.X (ANY name — public config attrs are exactly
        # the init-race candidates) after the earliest start of each root
        for s in starts:
            root = s.obj
            reader_units = [
                u for u, roots in model.roots.items() if root in roots
            ]
            if not reader_units:
                continue
            entry_def = model.owner.thread_entries.get(root)
            span = (
                (entry_def.lineno, entry_def.end_lineno or entry_def.lineno)
                if entry_def is not None and ".<" in root else None
            )
            for node in ast.walk(fn):
                if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                    continue
                line = node.lineno
                if line <= s.line:
                    continue
                if span and span[0] <= line <= span[1]:
                    continue
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    attr = self_attr(t)
                    if attr is None or attr in model.owner.exempt_attrs:
                        continue
                    read_in = next(
                        (u for u in sorted(reader_units)
                         if _reads_self_attr(fns.get(u), attr)),
                        None,
                    )
                    if read_in is None:
                        continue
                    key = (line, attr)
                    if key in seen:
                        continue
                    seen.add(key)
                    findings.append(Finding(
                        model.mod.rel, line, RULE_PUBLISH,
                        f"self.{attr} is assigned after Thread.start() of "
                        f"{root} in {_unit_label(model, unit)}, but the "
                        f"started thread reads it "
                        f"({_unit_label(model, read_in)}) — the thread can "
                        f"observe the pre-assignment value; initialise "
                        f"before start()",
                    ))
    return findings


def _reads_self_attr(fn: "ast.FunctionDef | None", attr: str) -> bool:
    if fn is None:
        return False
    for n in ast.walk(fn):
        if (
            isinstance(n, ast.Attribute)
            and isinstance(n.ctx, ast.Load)
            and self_attr(n) == attr
        ):
            return True
    return False


# ----------------------------------------------------------------------
# RACE005 — lock-free iteration of a cross-thread-mutated collection

def _race005(model: ConcurrencyModel) -> list[Finding]:
    findings: list[Finding] = []
    seen: set[tuple[int, str]] = set()
    for attr in sorted(model.tracked_attrs()):
        all_sites = list(model.accesses_of(attr, include_iters=True))
        iters = [s for s in all_sites if s[1].kind == "iter"]
        if not iters:
            continue
        writes = [s for s in all_sites if is_race_write(s[1])]
        if not writes:
            continue
        for iu, i, ir, il in iters:
            counterpart = None
            for wu, w, wr, wl in writes:
                if wu == iu and w.line == i.line:
                    continue  # the loop's own body mutating as it goes
                if wl & il:
                    continue
                pair = pair_unordered(model, wu, w, wr, iu, i, ir)
                if pair is None:
                    continue
                cand = (wu, w.line, pair[0], pair[1])
                if counterpart is None or cand < counterpart:
                    counterpart = cand
            if counterpart is None:
                continue
            key = (i.line, attr)
            if key in seen:
                continue
            seen.add(key)
            wu, _wline, root_w, root_i = counterpart
            findings.append(Finding(
                model.mod.rel, i.line, RULE_ITER,
                f"lock-free iteration of {model.attr_label(attr)} in "
                f"{_unit_label(model, iu)} (thread root {root_i}) while "
                f"{_unit_label(model, wu)} (root {root_w}) mutates it — "
                f"snapshot under a lock before iterating",
            ))
    return findings


def check_races(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for model in build_models(project):
        if model.owner.is_module or model.thread_owning:
            findings.extend(_race001(model))
            findings.extend(_race002(model))
            findings.extend(_race004(model))
            findings.extend(_race005(model))
        # RACE003 also covers lock-owning classes WITHOUT their own
        # thread entries: a shared object's callers can come from any
        # thread, and a version check hoisted outside the lock that
        # guards the counter is stale by commit time regardless of who
        # owns the threads (the same altitude LOCK001 operates at)
        findings.extend(_race003(model))
    return findings
