"""TRANSFER001/TRANSFER002 — device↔host transfer-boundary audit.

The device-resident data-plane campaign (ROADMAP) retires host
round-trips one measured step at a time, and the instrument only works
if EVERY crossing in the hot modules is audited: a raw
``jax.device_get`` snuck into a drain path is invisible to the ledger
(``utils/transfers.py``), so every bench gate diffing ledger snapshots
under-counts and every later retirement's before/after evidence lies.

- **TRANSFER001** — a device↔host crossing in a hot module
  (``replica`` / ``fleet`` / ``meshplane`` / ``serve`` / ``treesync`` /
  ``transition`` / ``tcp_transport``) that does not go through an
  audited transfer site. Crossing forms, per the jax data-movement
  model: ``jax.device_get``/``jax.device_put`` calls;
  ``np.asarray``/``np.array`` on a device-tainted value; ``.item()`` /
  ``.tolist()`` / ``.__array__()`` on a device-tainted receiver;
  ``int()``/``float()`` coercion of a device-tainted value (static
  shape arithmetic exempt, as in SYNC001); host-side iteration
  directly over a device-tainted array. The audited forms —
  ``<site>.get(x)`` / ``<site>.put(x)`` where ``<site>`` is a
  module-level handle from ``transfers.register("label")``, or
  ``transfers.audited_get/put(x, site)`` — are green: that is the
  ledger's counted path.
- **TRANSFER002** — the declared-vs-reached cross-check over the site
  labels themselves, mirroring OBS001: a ``transfers.register`` call
  whose label is not a string literal (the ledger keys benches diff
  must be statically knowable); two registrations sharing one label
  (the runtime collision guard raises at import — the lint catches it
  before a process does); a registered handle never used in its module
  (ghost label: it inflates the declared-site vocabulary while
  auditing nothing).

Device taint is per-outer-function fix-point in the shapes.py style:
seeds are jit-dispatch results (``jit_*`` / ``jit.<kernel>`` /
``fleet_*``), ``self.model.*`` kernel calls, ``jnp.*`` constructions,
``device_put``/``<site>.put`` results, and the canonical device-state
attributes (``self.state`` / ``self._dev``); taint propagates through
assignment, tuple unpacking, and attribute/subscript reads, and dies
at any host materialisation (``<site>.get``, ``np.asarray``,
``device_get``, ``int``/``float``, ``.item()``/``.tolist()``).
Host-side numpy work in the same functions stays unflagged — precision
is what makes a default-on boundary rule livable.
"""

from __future__ import annotations

import ast

from tools.crdtlint.engine import Finding, ModuleInfo, Project, _dotted
from tools.crdtlint.rules import outer_function_defs
from tools.crdtlint.rules.hostsync import _numpy_aliases, _static_shape_only
from tools.crdtlint.rules.shapes import _is_jit_dispatch

RULE_BOUNDARY = "TRANSFER001"
RULE_LEDGER = "TRANSFER002"

#: the device-adjacent hot modules the ledger discipline covers: the
#: replica/fleet data planes, the mesh collective plane, the serving
#: front door, the relay tier, the pure-transition layer, and the TCP
#: transport (which must never touch device arrays at all)
_HOT_LEAVES = {
    "replica", "fleet", "meshplane", "serve", "treesync", "transition",
    "tcp_transport",
}

#: receiver-method crossings (``.__array__()`` is the protocol form
#: ``np.asarray`` lowers to; callers rarely write it, but a rename must
#: not open a bypass)
_CROSS_METHODS = {"item", "tolist", "__array__"}

_SITE_METHODS = {"get", "put", "note"}


def _leaf(chain: str) -> str:
    return chain.rsplit(".", 1)[-1]


def _is_transfers_module_name(mod: ModuleInfo, name: str) -> bool:
    """Does ``name`` resolve (via the real import table) to the
    project's ``utils/transfers`` ledger module? Literal ``transfers``
    accepted as fallback, the obs-rule idiom."""
    imp = mod.imports.get(name)
    if imp is not None and imp[0] in ("mod", "modroot"):
        return imp[1].rsplit(".", 1)[-1] == "transfers"
    return name == "transfers"


def _is_register_call(node: ast.Call, mod: ModuleInfo) -> bool:
    """``transfers.register(...)`` / imported ``register(...)``."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "register":
        if isinstance(f.value, ast.Name):
            return _is_transfers_module_name(mod, f.value.id)
        return False
    if isinstance(f, ast.Name) and f.id == "register":
        imp = mod.imports.get("register")
        return (
            imp is not None
            and imp[0] == "sym"
            and imp[1].rsplit(".", 1)[-1] == "transfers"
        )
    return False


def _is_audited_helper(node: ast.Call, mod: ModuleInfo, leaf: str) -> bool:
    """``transfers.audited_get/put(x, site)`` function forms."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == leaf:
        if isinstance(f.value, ast.Name):
            return _is_transfers_module_name(mod, f.value.id)
        return False
    if isinstance(f, ast.Name) and f.id == leaf:
        imp = mod.imports.get(leaf)
        return (
            imp is not None
            and imp[0] == "sym"
            and imp[1].rsplit(".", 1)[-1] == "transfers"
        )
    return False


def _site_handles(mod: ModuleInfo) -> dict[str, tuple[int, ast.Call]]:
    """Module-level ``NAME = transfers.register("label")`` handles:
    name -> (line, register call). Handles are module constants by
    convention — that is what makes ``NAME.get`` statically auditable."""
    out: dict[str, tuple[int, ast.Call]] = {}
    for node in mod.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
            and _is_register_call(node.value, mod)
        ):
            out[node.targets[0].id] = (node.lineno, node.value)
    return out


def _is_site_method_call(node: ast.Call, handles: dict) -> bool:
    """``<handle>.get/.put/.note(...)`` on a module-level site handle."""
    f = node.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr in _SITE_METHODS
        and isinstance(f.value, ast.Name)
        and f.value.id in handles
    )


class _Taint:
    """Per-outer-function device-value taint, fix-point over the
    function body (nested defs included — closures share the enclosing
    scope's locals, the shapes.py/OBS002 convention)."""

    def __init__(self, mod: ModuleInfo, fn: ast.FunctionDef, handles: dict):
        self.mod = mod
        self.handles = handles
        self.names: set[str] = set()
        self._fix_point(fn)

    # -- expression classification ------------------------------------

    def _device_call(self, node: ast.Call) -> bool:
        """Calls whose RESULT lives on device: jit dispatches, model
        kernel seams, jnp constructions, placements."""
        if _is_jit_dispatch(node):
            return True
        chain = _dotted(node.func) or ""
        parts = chain.split(".")
        # self.model.winners_for_keys / model.row_apply — the store
        # kernel seam returns device pytrees
        if len(parts) >= 2 and parts[-2] == "model":
            return True
        if parts[0] in ("jnp", "lax") or chain.startswith("jax.numpy"):
            return True
        if _leaf(chain) == "device_put":
            return True
        # audited placement: <site>.put(x) returns a device value
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "put"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in self.handles
        ):
            return True
        if _is_audited_helper(node, self.mod, "audited_put"):
            return True
        return False

    def _host_call(self, node: ast.Call) -> bool:
        """Calls whose result is HOST regardless of operand taint."""
        chain = _dotted(node.func) or ""
        leaf = _leaf(chain)
        if leaf in ("device_get", "int", "float", "len", "bool"):
            return True
        if leaf in ("asarray", "array"):
            return True
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ("get", "item", "tolist")
        ):
            return True
        if _is_audited_helper(node, self.mod, "audited_get"):
            return True
        return False

    def is_tainted(self, node: ast.AST) -> bool:
        """Is this expression device-valued under the current taint?"""
        if isinstance(node, ast.Name):
            return node.id in self.names
        if isinstance(node, ast.Attribute):
            chain = _dotted(node) or ""
            # canonical device-state attributes
            if chain.startswith("self.state") or chain.startswith("self._dev"):
                return True
            # static-shape reads of a device array are host ints
            if node.attr in ("shape", "ndim", "size", "dtype", "nbytes"):
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            # getattr(state, c) reads a column off a (possibly tainted)
            # store pytree — the dynamic-column idiom on snapshot paths
            if (
                isinstance(node.func, ast.Name)
                and node.func.id == "getattr"
                and node.args
            ):
                return self.is_tainted(node.args[0])
            if self._host_call(node):
                return False
            return self._device_call(node)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(v is not None and self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or self.is_tainted(node.orelse)
        if isinstance(node, ast.BinOp):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            return self.is_tainted(node.elt)
        return False

    # -- fix point ------------------------------------------------------

    def _fix_point(self, fn: ast.FunctionDef) -> None:
        while True:
            before = len(self.names)
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    if self.is_tainted(node.value):
                        for t in node.targets:
                            self._taint_target(t)
                elif isinstance(node, ast.AnnAssign):
                    if node.value is not None and self.is_tainted(node.value):
                        self._taint_target(node.target)
                elif isinstance(node, ast.AugAssign):
                    if self.is_tainted(node.value):
                        self._taint_target(node.target)
            if len(self.names) == before:
                return

    def _taint_target(self, t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            self.names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            # tuple unpack of a device pytree: every bound name is a
            # device leaf (jax.device_get((a, b)) would have killed the
            # taint before we got here)
            for e in t.elts:
                self._taint_target(e)
        elif isinstance(t, ast.Starred):
            self._taint_target(t.value)


def _boundary_findings(
    mod: ModuleInfo,
    qual: tuple[str, ...],
    fn: ast.FunctionDef,
    handles: dict,
    np_aliases: set[str],
) -> list[Finding]:
    taint = _Taint(mod, fn, handles)
    name = ".".join(qual)
    findings: list[Finding] = []
    seen: set[int] = set()

    def report(line: int, msg: str) -> None:
        if line not in seen:
            seen.add(line)
            findings.append(Finding(mod.rel, line, RULE_BOUNDARY, msg))

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            chain = _dotted(node.func) or ""
            leaf = _leaf(chain)
            # raw placement/readback: ALWAYS a crossing, audited or not
            # — the audited form is <site>.get/.put, never device_get
            if leaf in ("device_get", "device_put") and not _is_site_method_call(
                node, handles
            ):
                report(
                    node.lineno,
                    f"raw jax.{leaf} in hot module function {mod.name}.{name} "
                    f"— route the crossing through an audited transfer site "
                    f"(utils/transfers.register) so the ledger counts it",
                )
                continue
            # np.asarray / np.array on a device value: implicit readback
            head = chain.split(".", 1)[0] if chain else ""
            if (
                chain
                and (head in np_aliases or chain in np_aliases)
                and leaf in ("asarray", "array")
                and node.args
                and taint.is_tainted(node.args[0])
            ):
                report(
                    node.lineno,
                    f"{chain}(...) materialises a device array on host in "
                    f"{mod.name}.{name} — an unaudited crossing; use "
                    f"<site>.get(...) so the ledger counts it",
                )
                continue
            # .item()/.tolist()/__array__() on a device receiver
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _CROSS_METHODS
                and taint.is_tainted(node.func.value)
            ):
                report(
                    node.lineno,
                    f".{node.func.attr}() on a device value in "
                    f"{mod.name}.{name} — an unaudited crossing; fetch via "
                    f"an audited site first",
                )
                continue
            # int()/float() coercion of a device scalar
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in ("int", "float")
                and len(node.args) == 1
                and not _static_shape_only(node.args[0])
                and taint.is_tainted(node.args[0])
            ):
                report(
                    node.lineno,
                    f"{node.func.id}() coerces a device value to host in "
                    f"{mod.name}.{name} — an unaudited crossing; fetch via "
                    f"an audited site first",
                )
        elif isinstance(node, ast.For):
            if taint.is_tainted(node.iter) and not isinstance(
                node.iter, ast.Call
            ):
                report(
                    node.iter.lineno,
                    f"host-side iteration over a device array in "
                    f"{mod.name}.{name} — one readback per element; fetch "
                    f"once via an audited site and iterate the host copy",
                )
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                if taint.is_tainted(gen.iter) and not isinstance(
                    gen.iter, ast.Call
                ):
                    report(
                        gen.iter.lineno,
                        f"host-side iteration over a device array in "
                        f"{mod.name}.{name} — one readback per element; "
                        f"fetch once via an audited site and iterate the "
                        f"host copy",
                    )
    return findings


def _ledger_findings(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    #: label -> (module rel, line) of first registration, package-wide
    labels: dict[str, tuple[str, int]] = {}
    for mod_name in sorted(project.modules):
        mod = project.modules[mod_name]
        handles = _site_handles(mod)
        if not handles:
            continue
        used: set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id in handles:
                    used.add(node.id)
        for hname in sorted(handles):
            line, call = handles[hname]
            label_node = call.args[0] if call.args else None
            if not (
                isinstance(label_node, ast.Constant)
                and isinstance(label_node.value, str)
            ):
                findings.append(Finding(
                    mod.rel, line, RULE_LEDGER,
                    f"transfer site {hname} registers a non-literal label — "
                    f"ledger keys must be statically knowable (bench gates "
                    f"and dashboards key on them)",
                ))
                continue
            label = label_node.value
            prior = labels.get(label)
            if prior is not None:
                findings.append(Finding(
                    mod.rel, line, RULE_LEDGER,
                    f"transfer site label {label!r} already registered at "
                    f"{prior[0]}:{prior[1]} — duplicate labels merge ledger "
                    f"counts (the runtime guard raises at import; rename "
                    f"one site)",
                ))
            else:
                labels[label] = (mod.rel, line)
            if hname not in used:
                findings.append(Finding(
                    mod.rel, line, RULE_LEDGER,
                    f"transfer site {hname} (label {label!r}) is registered "
                    f"but never used in {mod.name} — ghost label: it "
                    f"declares an audited crossing that audits nothing "
                    f"(delete it or route the crossing through it)",
                ))
    return findings


def check_transfers(project: Project) -> list[Finding]:
    findings = _ledger_findings(project)
    for mod_name in sorted(project.modules):
        mod = project.modules[mod_name]
        if mod_name.rsplit(".", 1)[-1] not in _HOT_LEAVES:
            continue
        handles = _site_handles(mod)
        np_aliases = _numpy_aliases(mod)
        for qual, fn in outer_function_defs(mod.tree):
            findings.extend(
                _boundary_findings(mod, qual, fn, handles, np_aliases)
            )
    return findings
