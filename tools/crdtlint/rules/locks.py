"""LOCK001 — lock discipline over ``self._*`` attributes.

For every class that owns a ``threading.Lock``/``RLock`` attribute, the
rule infers which underscore-prefixed instance attributes are *guarded*
(written at a program point where some lock is held — lexically inside
``with self._lock:``, after ``self._lock.acquire()``, or, propagated
interprocedurally, inside a private helper only ever called with the
lock held) and then flags every access to a guarded attribute at a
program point reachable **without** that attribute's guarding lock.

Entry points that start lock-free: public methods (no ``_`` prefix) and
thread entries (anything passed as ``target=`` to ``Thread(...)`` —
bound methods or nested defs). ``__init__`` is exempt: the object is
not yet published to other threads, so it neither mints guards nor
produces findings. Private methods never reached from any entry are
assumed to be called under the lock (the class's documented
convention); external callers violating that convention are out of
scope for a static pass.

Attributes holding synchronisation primitives or thread-safe containers
(``Lock``/``Event``/``Queue``/``Thread``/…) are exempt — serialising
access to an object whose whole job is cross-thread signalling would be
noise, not discipline.

"Write" means: plain/augmented assignment, ``del``, item assignment
through the attribute (``self._x[k] = v``), or any method call rooted
at the attribute (``self._x.append(...)``, ``self._x.commit()`` — a
static pass cannot prove a method pure, so calls count as mutation for
guard inference). Reads count when flagging — a torn read of
lock-guarded state is as much a race as a write.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator

from tools.crdtlint.engine import Finding, ModuleInfo, Project
from tools.crdtlint.rules import THREADSAFE_CONSTRUCTORS, self_attr

RULE = "LOCK001"

#: pseudo lock-state token for __init__-reachable code (pre-publication:
#: single-threaded by construction, so neither flagged nor guard-minting)
INIT = "<init>"


@dataclasses.dataclass(frozen=True)
class Access:
    method: str
    line: int
    attr: str
    kind: str  # "read" | "write" | "call"
    held: frozenset  # lock attrs held lexically at this point


@dataclasses.dataclass(frozen=True)
class CallEdge:
    callee: str  # method name on self
    held: frozenset


@dataclasses.dataclass(frozen=True)
class AcquireEvent:
    """One lock-acquisition site (``with self._x:`` or ``.acquire()``)
    with the lexical lock state just BEFORE it — the raw material of the
    LOCK002 acquisition-order graph."""

    method: str
    line: int
    lock: str
    held_before: frozenset


@dataclasses.dataclass(frozen=True)
class BlockingEvent:
    """A call that can block the thread (fsync, socket I/O, sleep,
    thread join, device sync…) and the lexical lock state at the call —
    LOCK003 flags those reachable with any lock held."""

    method: str
    line: int
    what: str
    held: frozenset


@dataclasses.dataclass(frozen=True)
class AttrCall:
    """``self.X.m(...)`` — a method call on a member object. When X's
    class is statically known (constructed in this class), LOCK002/003
    follow the edge into that class's methods."""

    method: str
    line: int
    attr: str
    callee: str
    held: frozenset


#: call leaves that block the calling thread regardless of receiver
BLOCKING_LEAVES = {
    "fsync": "os.fsync",
    "sendall": "socket sendall",
    "recv": "socket recv",
    "accept": "socket accept",
    "connect": "socket connect",
    "create_connection": "socket connect",
    "getaddrinfo": "DNS resolution",
    "sleep": "time.sleep",
    "block_until_ready": "device sync (block_until_ready)",
    "fsync_dir": "os.fsync (directory)",
}

#: leaves that block only for specific receiver types — counted when the
#: receiver is a ``self.`` attribute constructed as one of these
BLOCKING_RECEIVER_LEAVES = {
    "join": ("Thread",),
    "wait": ("Event", "Condition", "Barrier"),
}


def _dotted_chain(node: ast.AST) -> str | None:
    """``a.b.C`` attribute chain -> "a.b.C" (None when not a plain chain)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _call_receiver_attr(func: ast.AST) -> str | None:
    """Root ``self._x`` of a call-receiver chain: ``self._x.m(...)``,
    ``self._x[k].m(...)``, ``self._x.a.m(...)`` all root at ``_x``."""
    if not isinstance(func, ast.Attribute):
        return None
    node = func.value
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        if isinstance(node, ast.Attribute):
            found = self_attr(node)
            if found is not None:
                return found
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        else:
            node = node.func
    return self_attr(node) if isinstance(node, ast.Attribute) else None


class _MethodScan(ast.NodeVisitor):
    """One pass over a method body collecting attribute accesses, call
    edges to other ``self.`` methods, and the lexical lock state.

    Lock state tracking is statement-ordered: a ``with self._lock:``
    holds inside its body; ``self._lock.acquire(...)`` (or a call to an
    acquire-wrapper method) holds until ``self._lock.release()`` in the
    same or an outer suite. Nested function defs are analysed inline at
    their definition point (closures run with whatever lock state their
    caller establishes — conservative for callbacks, exact for the
    immediately-called lambda idiom), except thread-entry defs, which
    the class analysis lifts into separate lock-free entry points.
    """

    def __init__(self, cls: "_ClassAnalysis", method: str, skip_defs: set[ast.AST]):
        self.cls = cls
        self.method = method
        self.skip_defs = skip_defs
        self.held: set[str] = set()
        self.accesses: list[Access] = []
        self.edges: list[CallEdge] = []
        self.acquires: list[AcquireEvent] = []
        self.blocking: list[BlockingEvent] = []
        self.attr_calls: list[AttrCall] = []

    # -- lock state ----------------------------------------------------

    def _is_lock_attr(self, node: ast.AST) -> str | None:
        attr = self_attr(node)
        return attr if attr in self.cls.lock_attrs else None

    def visit_With(self, node: ast.With) -> None:
        entered: list[str] = []
        for item in node.items:
            lock = self._is_lock_attr(item.context_expr)
            if lock is not None:
                self.acquires.append(AcquireEvent(
                    self.method, item.context_expr.lineno, lock,
                    frozenset(self.held),
                ))
                # only locks not already held: a nested reentrant
                # ``with self._lock:`` (RLock) must not release the
                # outer hold when the inner block exits
                if lock not in self.held:
                    entered.append(lock)
            else:
                self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        self.held.update(entered)
        for stmt in node.body:
            self.visit(stmt)
        for lock in entered:
            self.held.discard(lock)

    visit_AsyncWith = visit_With

    @staticmethod
    def _terminates(stmts: list[ast.stmt]) -> bool:
        return bool(stmts) and isinstance(
            stmts[-1], (ast.Raise, ast.Return, ast.Continue, ast.Break)
        )

    def visit_If(self, node: ast.If) -> None:
        # branch-merge: a lock acquired in only one branch is not held
        # after the join (the acquire-then-raise guard idiom keeps its
        # lock because the acquiring branch is the TEST, visited first,
        # and a terminating branch contributes nothing to the join)
        self.visit(node.test)
        pre = set(self.held)
        self.held = set(pre)
        for s in node.body:
            self.visit(s)
        body_held = self.held
        self.held = set(pre)
        for s in node.orelse:
            self.visit(s)
        else_held = self.held
        if self._terminates(node.body):
            self.held = else_held
        elif node.orelse and self._terminates(node.orelse):
            self.held = body_held
        else:
            self.held = body_held & else_held

    def _visit_loop(self, node) -> None:
        # a loop body may run zero times: locks acquired (or released)
        # inside don't survive the loop — intersect with the pre-state
        pre = set(self.held)
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.held &= pre

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    def _note_blocking(self, func: ast.Attribute | ast.Name, line: int) -> None:
        leaf = func.attr if isinstance(func, ast.Attribute) else func.id
        what = BLOCKING_LEAVES.get(leaf)
        if what is None and isinstance(func, ast.Attribute):
            # receiver-typed blockers: thread join, event/condition wait
            ctors = BLOCKING_RECEIVER_LEAVES.get(leaf)
            if ctors:
                recv = self_attr(func.value)
                chain = self.cls.attr_ctors.get(recv) if recv is not None else None
                ctor = chain.rsplit(".", 1)[-1] if chain else None
                if ctor in ctors:
                    what = f"{ctor}.{leaf}"
        if what is not None:
            self.blocking.append(
                BlockingEvent(self.method, line, what, frozenset(self.held))
            )

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            lock = self._is_lock_attr(func.value)
            if lock is not None:
                if func.attr == "acquire":
                    self.acquires.append(AcquireEvent(
                        self.method, node.lineno, lock, frozenset(self.held)
                    ))
                    self.held.add(lock)
                elif func.attr == "release":
                    self.held.discard(lock)
                for arg in node.args + [kw.value for kw in node.keywords]:
                    self.visit(arg)
                return
            callee = self_attr(func)
            if callee is not None and callee in self.cls.methods:
                # self.helper(...): record the call edge; an acquire-
                # wrapper helper (net-acquires, e.g. Replica._acquire)
                # flips our lexical state exactly like a raw acquire()
                self.edges.append(CallEdge(callee, frozenset(self.held)))
                for arg in node.args + [kw.value for kw in node.keywords]:
                    self.visit(arg)
                self.held.update(self.cls.acquire_wrappers.get(callee, set()))
                return
            self._note_blocking(func, node.lineno)
            recv = _call_receiver_attr(func)
            if recv is not None:
                # method call rooted at a self attribute: potential
                # in-place mutation of that attribute's object
                self._record(recv, func.lineno, "call")
                direct = self_attr(func.value)
                if direct is not None:
                    self.attr_calls.append(AttrCall(
                        self.method, node.lineno, direct, func.attr,
                        frozenset(self.held),
                    ))
                self.visit(func.value)
                for arg in node.args + [kw.value for kw in node.keywords]:
                    self.visit(arg)
                return
        elif isinstance(func, ast.Name):
            self._note_blocking(func, node.lineno)
        self.generic_visit(node)

    # -- accesses ------------------------------------------------------

    def _record(self, attr: str, line: int, kind: str) -> None:
        if attr in self.cls.exempt_attrs or not attr.startswith("_"):
            return
        if attr in self.cls.methods or attr in self.cls.thread_entries:
            return  # bound-method reference, not state
        self.accesses.append(
            Access(self.method, line, attr, kind, frozenset(self.held))
        )

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = self_attr(node)
        if attr is not None:
            kind = "write" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
            self._record(attr, node.lineno, kind)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # self._x[k] = v / del self._x[k]: the Attribute itself is Load,
        # but the container is mutated — count a write
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            attr = self_attr(node.value)
            if attr is not None:
                self._record(attr, node.lineno, "write")
                self.visit(node.slice)
                return
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = self_attr(node.target)
        if attr is not None:
            self._record(attr, node.lineno, "write")
            self.visit(node.value)
            return
        self.generic_visit(node)

    # -- nested defs ---------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node in self.skip_defs:
            return  # analysed separately as a thread entry
        for stmt in node.body:  # inline: closures see the caller's locks
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.visit(node.body)


class _ClassAnalysis:
    def __init__(self, mod: ModuleInfo, node: ast.ClassDef):
        self.mod = mod
        self.node = node
        self.name = node.name
        # keyed by a UNIQUE unit name: a class may define several defs
        # under one name (property getter + setter/deleter overloads) —
        # a plain name-keyed dict would shadow all but the last, leaving
        # e.g. a property getter's lock region entirely unanalysed
        self.methods: dict[str, ast.FunctionDef] = {}
        for n in node.body:
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            name = n.name
            k = 2
            while name in self.methods:
                name = f"{n.name}#{k}"  # "#k" never collides with real names
                k += 1
            self.methods[name] = n
        self.lock_attrs = self._find_constructed(("Lock", "RLock"))
        self.exempt_attrs = self._find_constructed(tuple(THREADSAFE_CONSTRUCTORS))
        self.exempt_attrs |= self.lock_attrs
        #: attr -> constructor leaf name for attrs assigned a direct
        #: ``self.x = Ctor(...)`` (receiver-typed blocking + the
        #: cross-class edges of the LOCK002/003 order analysis)
        self.attr_ctors: dict[str, str] = self._find_attr_ctors()
        # thread-entry units: entry name -> FunctionDef (bound methods
        # and nested defs passed as Thread(target=...))
        self.thread_entries: dict[str, ast.FunctionDef] = {}
        self.nested_entry_defs: set[ast.AST] = set()
        self._find_thread_entries()
        # methods that net-acquire a lock for their caller
        self.acquire_wrappers: dict[str, set[str]] = self._find_acquire_wrappers()

    def _find_constructed(self, ctor_names: tuple[str, ...]) -> set[str]:
        out: set[str] = set()
        for body_fn in self.methods.values():
            for stmt in ast.walk(body_fn):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                value = stmt.value
                if not isinstance(value, ast.Call):
                    continue
                leaf = (
                    value.func.attr
                    if isinstance(value.func, ast.Attribute)
                    else value.func.id if isinstance(value.func, ast.Name) else None
                )
                if leaf not in ctor_names:
                    continue
                for t in targets:
                    attr = self_attr(t)
                    if attr is not None:
                        out.add(attr)
        return out

    def _find_attr_ctors(self) -> dict[str, str]:
        """attr -> constructor dotted chain (``WalLog`` / ``wal.WalLog``
        / ``threading.Thread``) for direct ``self.x = Ctor(...)``
        assignments. Consumers compare the LEAF for receiver typing and
        resolve the full chain for cross-class edges."""
        out: dict[str, str] = {}
        for body_fn in self.methods.values():
            for stmt in ast.walk(body_fn):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                value = stmt.value
                if not isinstance(value, ast.Call):
                    continue
                chain = (
                    value.func.id
                    if isinstance(value.func, ast.Name)
                    else _dotted_chain(value.func)
                )
                if chain is None:
                    continue
                for t in targets:
                    attr = self_attr(t)
                    if attr is not None:
                        out[attr] = chain
        return out

    def _find_thread_entries(self) -> None:
        for mname, body_fn in self.methods.items():
            nested = {
                n.name: n
                for n in ast.walk(body_fn)
                if isinstance(n, ast.FunctionDef) and n is not body_fn
            }
            for call in ast.walk(body_fn):
                if not isinstance(call, ast.Call):
                    continue
                leaf = (
                    call.func.attr
                    if isinstance(call.func, ast.Attribute)
                    else call.func.id if isinstance(call.func, ast.Name) else None
                )
                if leaf != "Thread":
                    continue
                for kw in call.keywords:
                    if kw.arg != "target":
                        continue
                    tgt_attr = self_attr(kw.value)
                    if tgt_attr is not None and tgt_attr in self.methods:
                        self.thread_entries[tgt_attr] = self.methods[tgt_attr]
                    elif isinstance(kw.value, ast.Name) and kw.value.id in nested:
                        entry = nested[kw.value.id]
                        self.thread_entries[f"{mname}.<{entry.name}>"] = entry
                        self.nested_entry_defs.add(entry)

    def _find_acquire_wrappers(self) -> dict[str, set[str]]:
        out: dict[str, set[str]] = {}
        for mname, body_fn in self.methods.items():
            acquired: set[str] = set()
            released: set[str] = set()
            for call in ast.walk(body_fn):
                if not isinstance(call, ast.Call) or not isinstance(
                    call.func, ast.Attribute
                ):
                    continue
                lock = self_attr(call.func.value)
                if lock in self.lock_attrs:
                    if call.func.attr == "acquire":
                        acquired.add(lock)
                    elif call.func.attr == "release":
                        released.add(lock)
            net = acquired - released
            if net:
                out[mname] = net
        return out


def _scan_unit(cls: _ClassAnalysis, unit_name: str, fn: ast.FunctionDef) -> _MethodScan:
    scan = _MethodScan(cls, unit_name, cls.nested_entry_defs)
    for stmt in fn.body:
        scan.visit(stmt)
    return scan


def analyse_units(
    cls: _ClassAnalysis,
) -> tuple[dict[str, "_MethodScan"], dict[str, set[frozenset]]]:
    """Scan every unit (method or thread entry) of one class and
    propagate entry lock states interprocedurally: public methods and
    thread entries start lock-free, ``__init__`` gets the INIT
    pseudo-state (pre-publication), and each call edge forwards
    caller-entry ∪ call-site lexical locks to the callee. Shared by
    LOCK001 (guard inference) and LOCK002/003 (order/blocking)."""
    units: dict[str, ast.FunctionDef] = dict(cls.methods)
    units.update(cls.thread_entries)
    scans = {name: _scan_unit(cls, name, fn) for name, fn in units.items()}

    entry_states: dict[str, set[frozenset]] = {name: set() for name in units}
    for name in units:
        if name in cls.thread_entries or not name.startswith("_"):
            entry_states[name].add(frozenset())
    if "__init__" in entry_states:
        entry_states["__init__"] = {frozenset({INIT})}

    # propagate: caller entry-state ∪ call-site lexical locks -> callee
    changed = True
    guard = 0
    while changed and guard < 10_000:
        changed = False
        guard += 1
        for name, scan in scans.items():
            for entry in list(entry_states[name]):
                for edge in scan.edges:
                    if edge.callee not in entry_states:
                        continue
                    state = frozenset(entry | edge.held)
                    if state not in entry_states[edge.callee]:
                        entry_states[edge.callee].add(state)
                        changed = True
    return scans, entry_states


def _analyse_class(mod: ModuleInfo, node: ast.ClassDef) -> Iterator[Finding]:
    cls = _ClassAnalysis(mod, node)
    if not cls.lock_attrs:
        return

    scans, entry_states = analyse_units(cls)

    # guarded attributes: attr -> set of locks it was written under
    guards: dict[str, set[str]] = {}
    for name, scan in scans.items():
        for entry in entry_states[name]:
            if INIT in entry:
                continue
            for acc in scan.accesses:
                if acc.kind in ("write", "call"):
                    held = entry | acc.held
                    if held:
                        guards.setdefault(acc.attr, set()).update(held)

    # flag accesses reachable with none of the attribute's guards held
    seen: set[tuple[int, str]] = set()
    kind_word = {"read": "read of", "write": "write to", "call": "call on"}
    for name, scan in scans.items():
        for entry in entry_states[name]:
            if INIT in entry:
                continue  # pre-publication
            for acc in scan.accesses:
                locks = guards.get(acc.attr)
                if not locks:
                    continue
                held = entry | acc.held
                if held & locks:
                    continue
                key = (acc.line, acc.attr)
                if key in seen:
                    continue
                seen.add(key)
                lock_name = "/".join(sorted(f"self.{l}" for l in locks))
                yield Finding(
                    mod.rel,
                    acc.line,
                    RULE,
                    f"unguarded {kind_word[acc.kind]} self.{acc.attr} in "
                    f"{cls.name}.{name}: attribute is written under {lock_name} "
                    f"elsewhere but this path can run without it",
                )


def check_lock_discipline(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for mod in project.modules.values():
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                findings.extend(_analyse_class(mod, node))
    return findings
