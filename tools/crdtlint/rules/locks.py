"""LOCK001 — lock discipline over ``self._*`` attributes.

For every class that owns a ``threading.Lock``/``RLock`` attribute, the
rule infers which underscore-prefixed instance attributes are *guarded*
(written at a program point where some lock is held — lexically inside
``with self._lock:``, after ``self._lock.acquire()``, or, propagated
interprocedurally, inside a private helper only ever called with the
lock held) and then flags every access to a guarded attribute at a
program point reachable **without** that attribute's guarding lock.

Entry points that start lock-free: public methods (no ``_`` prefix) and
thread entries (anything passed as ``target=`` to ``Thread(...)`` —
bound methods or nested defs). ``__init__`` is exempt: the object is
not yet published to other threads, so it neither mints guards nor
produces findings. Private methods never reached from any entry are
assumed to be called under the lock (the class's documented
convention); external callers violating that convention are out of
scope for a static pass.

Attributes holding synchronisation primitives or thread-safe containers
(``Lock``/``Event``/``Queue``/``Thread``/…) are exempt — serialising
access to an object whose whole job is cross-thread signalling would be
noise, not discipline.

"Write" means: plain/augmented assignment, ``del``, item assignment
through the attribute (``self._x[k] = v``), or any method call rooted
at the attribute (``self._x.append(...)``, ``self._x.commit()`` — a
static pass cannot prove a method pure, so calls count as mutation for
guard inference). Reads count when flagging — a torn read of
lock-guarded state is as much a race as a write.

The scanning/entry-state machinery this rule pioneered now lives in
:mod:`tools.crdtlint.rules.threadgraph`, shared with LOCK002/003 and
the RACE happens-before family.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.crdtlint.engine import Finding, ModuleInfo, Project
from tools.crdtlint.rules.threadgraph import (
    INIT,
    ClassAnalysis,
    analyse_units,
    infer_guards,
)

RULE = "LOCK001"


def _analyse_class(mod: ModuleInfo, node: ast.ClassDef) -> Iterator[Finding]:
    cls = ClassAnalysis(mod, node)
    if not cls.lock_attrs:
        return

    scans, entry_states = analyse_units(cls)

    # guarded attributes: attr -> set of locks it was written under
    guards = infer_guards(scans, entry_states)

    # flag accesses reachable with none of the attribute's guards held
    seen: set[tuple[int, str]] = set()
    kind_word = {"read": "read of", "write": "write to", "call": "call on"}
    for name, scan in scans.items():
        for entry in entry_states[name]:
            if INIT in entry:
                continue  # pre-publication
            for acc in scan.accesses:
                locks = guards.get(acc.attr)
                if not locks:
                    continue
                held = entry | acc.held
                if held & locks:
                    continue
                key = (acc.line, acc.attr)
                if key in seen:
                    continue
                seen.add(key)
                lock_name = "/".join(sorted(f"self.{l}" for l in locks))
                yield Finding(
                    mod.rel,
                    acc.line,
                    RULE,
                    f"unguarded {kind_word[acc.kind]} self.{acc.attr} in "
                    f"{cls.name}.{name}: attribute is written under {lock_name} "
                    f"elsewhere but this path can run without it",
                )


def check_lock_discipline(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for mod in project.modules.values():
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                findings.extend(_analyse_class(mod, node))
    return findings
