"""LOCK002/LOCK003 — lock-acquisition order and blocking-under-lock.

Builds on LOCK001's class analysis (scans + interprocedural entry lock
states) and extends it across classes through *constructed* member
objects (``self._wal = WalLog(...)`` — the replica owns its WAL, so a
``self._wal.commit()`` under the replica lock reaches ``os.fsync``
inside :class:`WalLog`):

- **LOCK002** — the acquisition-order graph has a cycle: some path
  acquires lock B while holding A, another acquires A while holding B.
  Two threads interleaving those paths deadlock; no test catches it
  until the scheduler does. Reentrant re-acquisition of the SAME lock
  (RLock) is not an ordering edge.
- **LOCK003** — a call that can block the thread (``os.fsync``, socket
  I/O, ``time.sleep``, ``Thread.join``, ``Event.wait``,
  ``block_until_ready`` device sync, WAL segment roll) is reachable
  while a lock is held. Every other thread contending on that lock —
  the sync tick, mutators, reads — stalls for the blocking call's full
  duration. Sites that block *by contract* (the WAL group-commit
  durability point) carry ``allow[LOCK003]`` comments stating the why.

Boundary (documented, deliberate): member classes are resolved only
through direct constructor assignments. Injected collaborators (the
replica's ``transport=`` parameter) are analysed in their own class
context — their internal discipline is checked, the cross-object edge
is not inferable statically.
"""

from __future__ import annotations

import ast

from tools.crdtlint.engine import Finding, ModuleInfo, Project
from tools.crdtlint.rules.threadgraph import (
    INIT,
    ClassAnalysis,
    analyse_units,
)

RULE_ORDER = "LOCK002"
RULE_BLOCKING = "LOCK003"


class _ClassInfo:
    """One analysed class plus its per-method transitive summaries."""

    def __init__(self, mod: ModuleInfo, node: ast.ClassDef):
        self.mod = mod
        self.node = node
        self.cls = ClassAnalysis(mod, node)
        self.scans, self.entry_states = analyse_units(self.cls)
        self._reach: dict[str, set[str]] = {}

    def reachable(self, method: str) -> set[str]:
        """Methods transitively reachable from ``method`` through
        self-calls inside this class (including itself)."""
        cached = self._reach.get(method)
        if cached is not None:
            return cached
        seen: set[str] = set()
        stack = [method]
        while stack:
            m = stack.pop()
            if m in seen or m not in self.scans:
                continue
            seen.add(m)
            stack.extend(e.callee for e in self.scans[m].edges)
        self._reach[method] = seen
        return seen

    def blocking_summary(self, method: str) -> list[tuple[str, str, int]]:
        """``(what, via_method, line)`` blocking events reachable from
        calling ``method`` on an instance of this class."""
        out = []
        for m in sorted(self.reachable(method)):
            for b in self.scans[m].blocking:
                out.append((b.what, m, b.line))
        return out

    def acquire_summary(self, method: str) -> list[tuple[str, int]]:
        """Locks (of this class) acquired on any path from ``method``."""
        out = []
        for m in sorted(self.reachable(method)):
            for a in self.scans[m].acquires:
                out.append((a.lock, a.line))
        return out


def _resolve_attr_class(
    project: Project, info: _ClassInfo, classes: dict[tuple[str, str], "_ClassInfo"]
) -> dict[str, "_ClassInfo"]:
    """attr name -> _ClassInfo for members with a statically known
    project class: ``self._wal = WalLog(...)`` (plain name, defined
    locally or from-imported) and ``self._wal = wal.WalLog(...)``
    (constructor through a module import)."""
    out: dict[str, _ClassInfo] = {}
    mod = info.mod
    for attr, chain in info.cls.attr_ctors.items():
        target: tuple[str, str] | None = None
        if "." in chain:
            head, _, rest = chain.partition(".")
            imp = mod.imports.get(head)
            if imp is not None and imp[0] in ("mod", "modroot"):
                modname = (
                    imp[1] if imp[0] == "mod"
                    else chain.rsplit(".", 1)[0]
                )
                target = (modname, chain.rsplit(".", 1)[-1])
        elif chain in mod.classes:
            target = (mod.name, chain)
        else:
            imp = mod.imports.get(chain)
            if imp and imp[0] == "sym":
                target = (imp[1], imp[2])
        if target is not None and target in classes:
            out[attr] = classes[target]
    return out


def check_lock_order(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    classes: dict[tuple[str, str], _ClassInfo] = {}
    for name in sorted(project.modules):
        mod = project.modules[name]
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                classes[(mod.name, node.name)] = _ClassInfo(mod, node)

    # acquisition-order edges: (class, lock) -> (class, lock), each with
    # one witness site for reporting
    edges: dict[tuple, dict[tuple, tuple[str, int]]] = {}

    def add_edge(a: tuple, b: tuple, where: tuple[str, int]) -> None:
        if a != b:  # reentrant same-lock re-acquisition is not ordering
            edges.setdefault(a, {}).setdefault(b, where)

    seen_block: set[tuple[str, int, str]] = set()
    for key in sorted(classes):
        info = classes[key]
        cname = info.node.name
        members = _resolve_attr_class(project, info, classes)
        for unit in sorted(info.scans):
            scan = info.scans[unit]
            for entry in info.entry_states[unit]:
                if INIT in entry:
                    continue  # pre-publication: single-threaded
                for acq in scan.acquires:
                    for h in entry | acq.held_before:
                        add_edge(
                            (cname, h), (cname, acq.lock),
                            (info.mod.rel, acq.line),
                        )
                for b in scan.blocking:
                    held = entry | b.held
                    if not held:
                        continue
                    fp = (info.mod.rel, b.line, b.what)
                    if fp in seen_block:
                        continue
                    seen_block.add(fp)
                    locks = "/".join(sorted(f"self.{l}" for l in held))
                    findings.append(Finding(
                        info.mod.rel, b.line, RULE_BLOCKING,
                        f"blocking call ({b.what}) in {cname}.{unit} while "
                        f"holding {locks} — every thread contending on the "
                        f"lock stalls for its full duration",
                    ))
                for call in scan.attr_calls:
                    target = members.get(call.attr)
                    if target is None:
                        continue
                    held = entry | call.held
                    tname = target.node.name
                    for lock2, _line2 in target.acquire_summary(call.callee):
                        for h in held:
                            add_edge(
                                (cname, h), (tname, lock2),
                                (info.mod.rel, call.line),
                            )
                    if not held:
                        continue
                    for what, via, _bline in target.blocking_summary(call.callee):
                        fp = (info.mod.rel, call.line, what)
                        if fp in seen_block:
                            continue
                        seen_block.add(fp)
                        locks = "/".join(sorted(f"self.{l}" for l in held))
                        deep = "" if via == call.callee else f" -> {via}"
                        findings.append(Finding(
                            info.mod.rel, call.line, RULE_BLOCKING,
                            f"blocking call ({what}, via {tname}."
                            f"{call.callee}{deep}) in {cname}.{unit} while "
                            f"holding {locks} — every thread contending on "
                            f"the lock stalls for its full duration",
                        ))

    findings.extend(_report_cycles(edges))
    return findings


def _report_cycles(
    edges: dict[tuple, dict[tuple, tuple[str, int]]]
) -> list[Finding]:
    """Strongly connected components of the order graph with >1 node
    (or any 2-cycle) are deadlock-capable; report each once, at the
    lexically first witness site among the component's edges."""
    index: dict[tuple, int] = {}
    low: dict[tuple, int] = {}
    on_stack: set[tuple] = set()
    stack: list[tuple] = []
    sccs: list[list[tuple]] = []
    counter = [0]

    def strongconnect(v: tuple) -> None:
        # iterative Tarjan (the graph is tiny; recursion would be fine,
        # but an explicit stack keeps pathological inputs safe)
        work = [(v, iter(sorted(edges.get(v, {}))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(edges.get(w, {})))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(comp)

    for v in sorted(edges):
        if v not in index:
            strongconnect(v)

    findings = []
    for comp in sccs:
        comp_set = set(comp)
        witnesses = [
            edges[a][b]
            for a in comp for b in edges.get(a, {})
            if b in comp_set
        ]
        path, line = min(witnesses, key=lambda w: (w[0], w[1]))
        cycle = " -> ".join(f"{c}.{l}" for c, l in sorted(comp))
        findings.append(Finding(
            path, line, RULE_ORDER,
            f"lock acquisition-order cycle ({cycle}): two threads taking "
            f"these locks in opposite orders deadlock",
        ))
    return findings
