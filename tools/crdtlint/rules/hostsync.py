"""SYNC001/SYNC002 — JAX host-sync leaks.

A traced value forced to host (``.item()``, ``.tolist()``,
``int()``/``float()`` coercion, ``np.asarray``, ``block_until_ready()``)
inside a ``jax.jit``/``shard_map``/``pallas_call`` program either fails
tracing or — worse, on the host-driver side — serialises the dispatch
pipeline and kills compute/transfer overlap (DrJAX-style collectives
lose exactly the overlap they exist for).

- **SYNC001**: a sync-forcing expression inside a function reachable
  from a jit entry point. Entry points are discovered by scanning every
  module for ``jax.jit(f)`` / ``@jax.jit`` / ``@partial(jax.jit, …)`` /
  ``shard_map(f, …)`` / ``pl.pallas_call(kernel, …)`` and resolving
  ``f`` through the project import graph (unwrapping ``vmap`` /
  ``partial`` / ``checkpoint``); reachability then closes over direct
  calls. Nested defs of a reachable function are reachable (they trace
  with it).
- **SYNC002**: ``block_until_ready()`` anywhere in an op-library module
  (``ops/``, ``parallel/``) even outside traced code — op bodies must
  leave synchronisation to the caller/bench harness, or carry an
  explicit ``# crdtlint: allow[host-sync]`` justification.

Pure-transition modules (``runtime/transition*`` — the replica split's
device half, ISSUE 6) are jit-reachable BY CONTRACT: every function
defined there is treated as a jit entry root whether or not something
currently wraps it, so a host sync snuck into the fleet's batched
transition path turns the gate red even before any caller traces it.

``int()``/``float()`` on static-shape arithmetic (constants, ``len()``,
``.shape``/``.ndim``/``.size`` reads) is exempt — those are Python
values at trace time, not device reads.
"""

from __future__ import annotations

import ast

from tools.crdtlint.engine import Finding, ModuleInfo, Project, _dotted
from tools.crdtlint.rules import iter_function_defs

RULE_JIT = "SYNC001"
RULE_OP = "SYNC002"

_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
#: ``named_jit`` is the jitcache wrapper (``utils/jitcache.py``):
#: ``named_jit(f, ...)`` IS ``jax.jit(f, ...)`` plus a compile-cache
#: audit registration, so it is an entry root for exactly the same
#: reasons
_JIT_NAMES = {"jit", "named_jit"}
_ENTRY_WRAPPERS = {"shard_map", "pallas_call", "pmap"}
_OP_MODULE_MARKERS = (".ops.", ".parallel.")
#: modules whose every function is a jit entry root by contract (the
#: pure state-transition layer of the replica split — "jit-able, no
#: host syncs" is its definition, so the gate must not depend on some
#: caller happening to wrap each function today). The hash-store kernel
#: module (ISSUE 8) carries the same contract: its host-side policy
#: wrappers live in ``models/hash_store.py``, everything in
#: ``ops/hash_map.py`` must trace clean.
_TRANSITION_MODULE_MARKERS = (".runtime.transition", ".ops.hash_map")


def _is_jit_call(node: ast.Call) -> bool:
    chain = _dotted(node.func) or ""
    return chain.rsplit(".", 1)[-1] in _JIT_NAMES


def _is_partial_jit(node: ast.Call) -> bool:
    """``partial(jax.jit, ...)`` / ``functools.partial(jax.jit, ...)``."""
    chain = _dotted(node.func) or ""
    if chain.rsplit(".", 1)[-1] != "partial" or not node.args:
        return False
    inner = _dotted(node.args[0]) or ""
    return inner.rsplit(".", 1)[-1] in _JIT_NAMES


def _entry_exprs(mod: ModuleInfo) -> list[ast.AST]:
    """Expressions that become device-traced entry functions."""
    out: list[ast.AST] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            chain = _dotted(node.func) or ""
            leaf = chain.rsplit(".", 1)[-1]
            if leaf in _JIT_NAMES and node.args:
                out.append(node.args[0])
            elif leaf in _ENTRY_WRAPPERS and node.args:
                out.append(node.args[0])
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                chain = _dotted(dec) or ""
                if chain.rsplit(".", 1)[-1] in _JIT_NAMES:
                    out.append(ast.Name(id=node.name, ctx=ast.Load()))
                    out[-1].lineno = node.lineno  # resolvable marker
                elif isinstance(dec, ast.Call) and (
                    _is_jit_call(dec) or _is_partial_jit(dec)
                ):
                    marker = ast.Name(id=node.name, ctx=ast.Load())
                    marker.lineno = node.lineno
                    out.append(marker)
    return out


def _local_defs(fn: ast.FunctionDef) -> dict[str, ast.FunctionDef]:
    return {
        n.name: n
        for n in ast.walk(fn)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n is not fn
    }


def _reachable_functions(project: Project) -> set[int]:
    """Node-identity closure of everything a trace can enter (keyed by
    ``id(FunctionDef)``, not name — an untraced host-side function that
    happens to share a name with a jit entry must not be flagged)."""
    work: list[tuple[ModuleInfo, ast.FunctionDef]] = []
    reach: set[int] = set()

    def push(mod: ModuleInfo, fn: ast.FunctionDef) -> None:
        if id(fn) in reach:
            return
        reach.add(id(fn))
        work.append((mod, fn))

    for mod in project.modules.values():
        for expr in _entry_exprs(mod):
            resolved = project.resolve_function(mod, expr)
            if resolved is not None:
                push(*resolved)
        # pure-transition modules: every function is an entry root by
        # contract (see module docstring) — including methods of
        # top-level classes, or a class-based kernel helper would get a
        # silent gate bypass
        if any(m in mod.name + "." for m in _TRANSITION_MODULE_MARKERS):
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    push(mod, node)
                elif isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            push(mod, sub)

    while work:
        mod, fn = work.pop()
        local = _local_defs(fn)
        for nested in local.values():
            push(mod, nested)  # nested defs trace with their parent
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            # shard_map(step, ...)/jit inside a reachable fn: resolve arg
            chain = _dotted(node.func) or ""
            leaf = chain.rsplit(".", 1)[-1]
            if leaf in (_ENTRY_WRAPPERS | _JIT_NAMES) and node.args:
                tgt = node.args[0]
                if isinstance(tgt, ast.Name) and tgt.id in local:
                    push(mod, local[tgt.id])
                    continue
            if isinstance(node.func, ast.Name) and node.func.id in local:
                push(mod, local[node.func.id])
                continue
            resolved = project.resolve_function(mod, node.func)
            if resolved is not None:
                push(*resolved)
    return reach


def _static_shape_only(node: ast.AST) -> bool:
    """True when the expression is trace-time Python arithmetic: built
    from constants, ``len()``, and ``.shape``/``.ndim``/``.size`` reads."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        return node.attr in ("shape", "ndim", "size", "dtype", "nbytes")
    if isinstance(node, ast.Subscript):
        return _static_shape_only(node.value)
    if isinstance(node, ast.Call):
        # bare builtins only: x.sum() is a device reduction, len(x) the
        # trace-time leading dim
        if isinstance(node.func, ast.Name):
            if node.func.id == "len":
                return True
            if node.func.id in ("min", "max"):
                return bool(node.args) and all(
                    _static_shape_only(a) for a in node.args
                )
        return False
    if isinstance(node, ast.BinOp):
        return _static_shape_only(node.left) and _static_shape_only(node.right)
    if isinstance(node, ast.UnaryOp):
        return _static_shape_only(node.operand)
    return False


def _numpy_aliases(mod: ModuleInfo) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name in ("numpy", "numpy.ma"):
                    out.add(alias.asname or "numpy")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "numpy":
                for alias in node.names:
                    if alias.name in ("asarray", "array"):
                        out.add(alias.asname or alias.name)
    return out


def _sync_findings_in(
    mod: ModuleInfo, fn: ast.FunctionDef, np_aliases: set[str]
) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_METHODS:
            findings.append(
                Finding(
                    mod.rel,
                    node.lineno,
                    RULE_JIT,
                    f".{node.func.attr}() forces a host sync inside jit-traced "
                    f"code ({mod.name}.{fn.name} is reachable from a jit/"
                    f"shard_map entry point)",
                )
            )
            continue
        chain = _dotted(node.func) or ""
        if chain in ("jax.device_get",) or chain.endswith(".device_get"):
            findings.append(
                Finding(
                    mod.rel, node.lineno, RULE_JIT,
                    f"device_get forces a host transfer inside jit-traced code "
                    f"({mod.name}.{fn.name})",
                )
            )
            continue
        head = chain.split(".", 1)[0] if chain else ""
        if (
            chain
            and head in np_aliases
            and (chain.endswith(".asarray") or chain.endswith(".array")
                 or chain in np_aliases)
        ):
            findings.append(
                Finding(
                    mod.rel, node.lineno, RULE_JIT,
                    f"{chain}(...) materialises a numpy array (host sync) inside "
                    f"jit-traced code ({mod.name}.{fn.name})",
                )
            )
            continue
        if isinstance(node.func, ast.Name) and node.func.id in ("int", "float"):
            if len(node.args) == 1 and not _static_shape_only(node.args[0]):
                findings.append(
                    Finding(
                        mod.rel, node.lineno, RULE_JIT,
                        f"{node.func.id}() coercion forces a host sync inside "
                        f"jit-traced code ({mod.name}.{fn.name}); compute with "
                        f"jnp dtypes or hoist to the host driver",
                    )
                )
    return findings


def check_host_sync(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    reach = _reachable_functions(project)

    for mod in project.modules.values():
        np_aliases = _numpy_aliases(mod)
        flagged_lines: set[int] = set()
        for _parts, fn in iter_function_defs(mod.tree):
            if id(fn) in reach:
                for f in _sync_findings_in(mod, fn, np_aliases):
                    if f.line not in flagged_lines:
                        flagged_lines.add(f.line)
                        findings.append(f)
        # SYNC002: block_until_ready anywhere in op-library modules
        if any(marker in mod.name + "." for marker in _OP_MODULE_MARKERS):
            for node in ast.walk(mod.tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "block_until_ready"
                    and node.lineno not in flagged_lines
                ):
                    findings.append(
                        Finding(
                            mod.rel,
                            node.lineno,
                            RULE_OP,
                            "block_until_ready() in an op-library module: "
                            "synchronisation belongs to the caller/bench "
                            "harness, not the op body",
                        )
                    )
                elif (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "block_until_ready"
                    and node.lineno not in flagged_lines
                ):
                    findings.append(
                        Finding(
                            mod.rel, node.lineno, RULE_OP,
                            "block_until_ready() in an op-library module: "
                            "synchronisation belongs to the caller/bench "
                            "harness, not the op body",
                        )
                    )
    return findings
