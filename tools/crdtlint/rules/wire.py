"""WIRE001–WIRE005 — wire-protocol drift.

The anti-entropy protocol is only correct when three surfaces stay in
lockstep: the ``*Msg`` dataclasses in ``runtime/sync.py`` (the wire
vocabulary), the replica's isinstance dispatch ladder (every message
kind must have a handler arm), and the transport codecs (every field
must serialise; every frame kind must decode). Nothing but convention
kept them aligned — these rules make the convention checkable:

- **WIRE001** — a protocol-module ``*Msg`` dataclass with no arm in any
  dispatch ladder: the message is sent (or will be) and silently
  unhandled, which on the current ladder means ``TypeError: unknown
  message`` at the receiver, mid-sync-round.
- **WIRE002** — a ladder arm that can never fire: its class does not
  exist in the module the arm names (renamed/removed message), or a
  duplicate arm for a class already handled earlier in the same ladder.
- **WIRE003** — a message field whose annotated type is not
  wire-serializable (sockets, locks, callables, device arrays…):
  pickling fails — or worse, "works" in-process and fails only on the
  TCP transport, i.e. only in production topologies.
- **WIRE004** — a frame-kind constant in a codec module that is sent
  but never compared on the receive path: the peer drops the frame as
  unknown, so the feature silently degrades (the codec's documented
  forward-compat behaviour — fine for *newer peers*, a bug in the
  *same build*).
- **WIRE005** — the wire-compat lock: the protocol module's per-message
  ordered ``(field, type)`` lists are hashed against the checked-in
  ``protocol_manifest.json``. Adding/removing/reordering/retyping a
  wire field without regenerating the manifest
  (``--write-protocol-manifest``) turns the gate red, forcing the
  mixed-version-cluster conversation (MIGRATING.md) at review time.
"""

from __future__ import annotations

import ast
import hashlib
import json
from pathlib import Path

from tools.crdtlint.engine import Finding, ModuleInfo, Project

#: the checked-in manifest, anchored beside the linter like the baseline
DEFAULT_MANIFEST = Path(__file__).resolve().parent.parent / "protocol_manifest.json"

#: leaf type names a wire message field may be built from. The codecs
#: pickle whole messages, so this is the *contract* subset: plain data
#: + numpy containers. Anything else (Callable, Lock, socket, a project
#: class, a bare jax Array) either fails to pickle or smuggles
#: process-local state onto the wire.
_WIRE_SAFE_LEAVES = {
    "int", "float", "bool", "str", "bytes", "bytearray", "complex",
    "None", "NoneType", "Any", "Hashable", "Optional", "Union",
    "list", "dict", "tuple", "set", "frozenset",
    "List", "Dict", "Tuple", "Set", "FrozenSet", "Sequence", "Mapping",
    "Iterable", "ndarray", "generic", "int64", "int32", "float32",
    "float64", "uint64", "bool_",
}


def _dataclass_messages(mod: ModuleInfo) -> dict[str, ast.ClassDef]:
    """``*Msg``-named dataclasses defined at this module's top level."""
    out: dict[str, ast.ClassDef] = {}
    for node in mod.tree.body:
        if not isinstance(node, ast.ClassDef) or not node.name.endswith("Msg"):
            continue
        for dec in node.decorator_list:
            leaf = dec
            if isinstance(leaf, ast.Call):
                leaf = leaf.func
            name = leaf.attr if isinstance(leaf, ast.Attribute) else (
                leaf.id if isinstance(leaf, ast.Name) else None
            )
            if name == "dataclass":
                out[node.name] = node
                break
    return out


def protocol_module(project: Project) -> tuple[ModuleInfo, dict[str, ast.ClassDef]] | None:
    """The module carrying the wire vocabulary: the one defining the
    most ``*Msg`` dataclasses (at least two — a single incidental Msg
    class does not make a protocol)."""
    best: tuple[ModuleInfo, dict[str, ast.ClassDef]] | None = None
    for name in sorted(project.modules):
        mod = project.modules[name]
        msgs = _dataclass_messages(mod)
        if len(msgs) >= 2 and (best is None or len(msgs) > len(best[1])):
            best = (mod, msgs)
    return best


def message_fields(cls: ast.ClassDef) -> list[tuple[str, str]]:
    """Ordered ``(name, type_source)`` wire fields of one dataclass."""
    fields: list[tuple[str, str]] = []
    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            fields.append((stmt.target.id, ast.unparse(stmt.annotation)))
    return fields


def _annotation_leaves(node: ast.AST):
    """Every leaf type name referenced by an annotation expression:
    ``dict[tuple[int, int], np.ndarray | None]`` -> dict, tuple, int,
    ndarray, None. Dotted names contribute their final attribute (the
    ``np.``/``typing.`` prefix is namespacing, not the type)."""
    if isinstance(node, ast.Name):
        yield node.id
    elif isinstance(node, ast.Attribute):
        yield node.attr
    elif isinstance(node, ast.Constant):
        if node.value is None:
            yield "None"
        elif isinstance(node.value, str):  # string annotation: reparse
            try:
                yield from _annotation_leaves(ast.parse(node.value, mode="eval").body)
            except SyntaxError:
                yield node.value
    elif isinstance(node, ast.Subscript):
        yield from _annotation_leaves(node.value)
        yield from _annotation_leaves(node.slice)
    elif isinstance(node, ast.Tuple):
        for elt in node.elts:
            yield from _annotation_leaves(elt)
    elif isinstance(node, ast.BinOp):  # X | Y unions
        yield from _annotation_leaves(node.left)
        yield from _annotation_leaves(node.right)
    elif isinstance(node, ast.Index):  # pragma: no cover - py<3.9 AST
        yield from _annotation_leaves(node.value)


# ----------------------------------------------------------------------
# dispatch ladders


def _isinstance_classes(test: ast.AST) -> list[tuple[str, ast.AST]] | None:
    """``isinstance(x, C)`` / ``isinstance(x, (C, D))`` ->
    [(subject_name, class_expr), ...]; None for any other test."""
    if not (
        isinstance(test, ast.Call)
        and isinstance(test.func, ast.Name)
        and test.func.id == "isinstance"
        and len(test.args) == 2
        and isinstance(test.args[0], ast.Name)
    ):
        return None
    subject = test.args[0].id
    cls_expr = test.args[1]
    elts = cls_expr.elts if isinstance(cls_expr, ast.Tuple) else [cls_expr]
    return [(subject, e) for e in elts]


def _resolve_class(
    project: Project, mod: ModuleInfo, expr: ast.AST
) -> tuple[str | None, str | None]:
    """Resolve a ladder arm's class expression to ``(module_name,
    class_name)``. ``(None, None)`` = not project-related (builtin or
    third-party — ignored); ``(modname, None)`` = names a project
    module but the class is missing there (a WIRE002 broken arm)."""
    if isinstance(expr, ast.Name):
        if expr.id in mod.classes:
            return mod.name, expr.id
        imp = mod.imports.get(expr.id)
        if imp and imp[0] == "sym":
            target = project.modules.get(imp[1])
            if target is not None:
                return (imp[1], imp[2]) if imp[2] in target.classes else (imp[1], None)
        return None, None
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
        imp = mod.imports.get(expr.value.id)
        if imp and imp[0] == "mod":
            target = project.modules.get(imp[1])
            if target is not None:
                return (imp[1], expr.attr) if expr.attr in target.classes else (imp[1], None)
    return None, None


def _ladders(project: Project, mod: ModuleInfo):
    """Yield dispatch ladders: if/elif chains with >= 2 isinstance arms
    over one subject, as ``[(line, modname, clsname|None), ...]``."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.If):
            continue
        # only the HEAD of a chain (an elif is the sole If in its
        # parent's orelse — we detect heads by walking every If and
        # skipping those that appear as a single-orelse child)
        arms: list[tuple[int, str, str | None]] = []
        subjects: set[str] = set()
        cur: ast.stmt | None = node
        while isinstance(cur, ast.If):
            tests = _isinstance_classes(cur.test)
            if tests is not None:
                for subject, cls_expr in tests:
                    modname, clsname = _resolve_class(project, mod, cls_expr)
                    if modname is not None:
                        subjects.add(subject)
                        arms.append((cur.test.lineno, modname, clsname))
            cur = cur.orelse[0] if len(cur.orelse) == 1 else None
        if len(arms) >= 2 and len(subjects) == 1:
            yield arms


def _all_ladders(project: Project) -> list[tuple[ModuleInfo, list]]:
    out = []
    seen_heads: set[int] = set()
    for name in sorted(project.modules):
        mod = project.modules[name]
        for arms in _ladders(project, mod):
            # an elif chain re-yields its suffixes (ast.walk visits the
            # nested Ifs too); keep only maximal ladders by dropping any
            # whose first arm line we already covered
            if arms[0][0] in seen_heads:
                continue
            seen_heads.update(a[0] for a in arms)
            out.append((mod, arms))
    return out


# ----------------------------------------------------------------------
# manifest


def compute_manifest(project: Project) -> dict | None:
    """The manifest stanza for this project's protocol module (None
    when the project has no protocol module)."""
    proto = protocol_module(project)
    if proto is None:
        return None
    mod, msgs = proto
    messages = {}
    for name in sorted(msgs):
        fields = message_fields(msgs[name])
        blob = json.dumps(fields, separators=(",", ":")).encode()
        messages[name] = {
            "fields": fields,
            "sha256": hashlib.sha256(blob).hexdigest(),
        }
    return {"module": mod.rel, "messages": messages}


def load_manifest(path: Path) -> dict:
    return json.loads(path.read_text(encoding="utf-8"))


def write_manifest(path: Path, packages: dict[str, dict]) -> None:
    path.write_text(
        json.dumps({"version": 1, "packages": packages}, indent=2,
                   sort_keys=True) + "\n",
        encoding="utf-8",
    )


# ----------------------------------------------------------------------
# the rules


def check_wire(project: Project) -> list[Finding]:
    # the codec check stands alone: a package can have a frame codec
    # without (or before) a message protocol module
    findings: list[Finding] = list(_check_frame_kinds(project))
    proto = protocol_module(project)
    if proto is None:
        return findings
    mod, msgs = proto
    ladders = _all_ladders(project)

    # WIRE001: every message class needs a dispatch arm somewhere
    handled: set[str] = set()
    for lmod, arms in ladders:
        for _line, modname, clsname in arms:
            if modname == mod.name and clsname is not None:
                handled.add(clsname)
    for name in sorted(msgs):
        if name not in handled:
            findings.append(Finding(
                mod.rel, msgs[name].lineno, "WIRE001",
                f"wire message {name} has no isinstance arm in any "
                f"dispatch ladder — receivers will raise on it",
            ))

    # WIRE002: broken or duplicate arms within one ladder
    for lmod, arms in ladders:
        seen: set[tuple[str, str]] = set()
        for line, modname, clsname in arms:
            if clsname is None:
                findings.append(Finding(
                    lmod.rel, line, "WIRE002",
                    f"dispatch arm names a class missing from {modname} "
                    f"— renamed or removed wire message?",
                ))
            elif (modname, clsname) in seen:
                findings.append(Finding(
                    lmod.rel, line, "WIRE002",
                    f"unreachable dispatch arm: {clsname} already "
                    f"handled earlier in this ladder",
                ))
            else:
                seen.add((modname, clsname))

    # WIRE003: wire-serializable field types only
    for name in sorted(msgs):
        for stmt in msgs[name].body:
            if not (isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name)):
                continue
            bad = sorted(
                leaf for leaf in set(_annotation_leaves(stmt.annotation))
                if leaf not in _WIRE_SAFE_LEAVES
            )
            if bad:
                findings.append(Finding(
                    mod.rel, stmt.lineno, "WIRE003",
                    f"{name}.{stmt.target.id}: type {ast.unparse(stmt.annotation)!r} "
                    f"is not wire-serializable ({', '.join(bad)}) — wire "
                    f"messages carry plain data + numpy arrays only",
                ))

    findings.extend(_check_manifest(project, mod, msgs))
    return findings


def _check_frame_kinds(project: Project) -> list[Finding]:
    """WIRE004 over every codec module: a module-level ``_UPPER = <int>``
    constant used as a frame kind on the send side (arg of a
    ``*send_frame`` call, first arg of ``.enqueue``, or head of a frame
    tuple) must appear in at least one equality comparison — the
    receive-path decode. Sent-but-undecodable = every such frame is
    dropped as unknown by the peer."""
    findings: list[Finding] = []
    for name in sorted(project.modules):
        mod = project.modules[name]
        # codec modules only: anything defining a frame writer. Plain
        # modules may use _UPPER int constants in tuples for their own
        # reasons — that is not wire traffic.
        if not any(
            isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name.endswith("send_frame")
            for n in mod.tree.body
        ):
            continue
        consts: dict[str, int] = {}
        for node in mod.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)
                and not isinstance(node.value.value, bool)
            ):
                cname = node.targets[0].id
                if cname.startswith("_") and cname[1:].replace("_", "").isupper():
                    consts[cname] = node.lineno
        if not consts:
            continue
        sent: set[str] = set()
        compared: set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                leaf = (
                    node.func.attr if isinstance(node.func, ast.Attribute)
                    else node.func.id if isinstance(node.func, ast.Name) else ""
                )
                if leaf.endswith("send_frame"):
                    for arg in node.args:
                        if isinstance(arg, ast.Name) and arg.id in consts:
                            sent.add(arg.id)
                elif leaf == "enqueue" and node.args:
                    a0 = node.args[0]
                    if isinstance(a0, ast.Name) and a0.id in consts:
                        sent.add(a0.id)
            elif isinstance(node, ast.Tuple) and node.elts:
                head = node.elts[0]
                if isinstance(head, ast.Name) and head.id in consts:
                    sent.add(head.id)
            elif isinstance(node, ast.Compare):
                for side in [node.left, *node.comparators]:
                    if isinstance(side, ast.Name) and side.id in consts:
                        compared.add(side.id)
        for cname in sorted(sent - compared):
            findings.append(Finding(
                mod.rel, consts[cname], "WIRE004",
                f"frame kind {cname} is sent but never compared on a "
                f"receive path — peers drop it as an unknown frame",
            ))
    return findings


def _check_manifest(
    project: Project, mod: ModuleInfo, msgs: dict[str, ast.ClassDef]
) -> list[Finding]:
    findings: list[Finding] = []
    path = project.manifest_path or DEFAULT_MANIFEST
    try:
        manifest = load_manifest(path)
    except FileNotFoundError:
        manifest = None
    except (ValueError, KeyError) as e:
        return [Finding(mod.rel, 1, "WIRE005", f"unreadable protocol manifest {path}: {e}")]
    packages = manifest.get("packages") if isinstance(manifest, dict) else None
    if manifest is not None and not isinstance(packages, dict):
        # JSON-valid but structurally mangled (e.g. a bad merge-conflict
        # resolution): a finding, never a lint crash
        return [Finding(
            mod.rel, 1, "WIRE005",
            f"malformed protocol manifest {path}: 'packages' must be an "
            f"object — regenerate with --write-protocol-manifest",
        )]
    if manifest is None or project.package_name not in packages:
        # no wire-compat lock recorded for this package (fixture
        # packages, fresh adoptions): nothing to drift from. The gate
        # test pins the REAL package's presence in the manifest.
        return findings
    entry = packages[project.package_name]
    recorded = entry.get("messages", {}) if isinstance(entry, dict) else {}
    if not isinstance(recorded, dict):
        recorded = {}
    recorded = {k: v for k, v in recorded.items() if isinstance(v, dict)}
    current = compute_manifest(project)["messages"]
    for name in sorted(set(recorded) | set(current)):
        if name not in current:
            findings.append(Finding(
                mod.rel, 1, "WIRE005",
                f"wire message {name} is in the protocol manifest but no "
                f"longer defined — removing a message is a wire-compat "
                f"break; regenerate with --write-protocol-manifest",
            ))
        elif name not in recorded:
            findings.append(Finding(
                mod.rel, msgs[name].lineno, "WIRE005",
                f"wire message {name} is not in the protocol manifest — "
                f"new messages must be recorded (--write-protocol-manifest) "
                f"so field drift is locked from day one",
            ))
        elif recorded[name].get("sha256") != current[name]["sha256"]:
            old = [f for f, _t in recorded[name].get("fields", [])]
            new = [f for f, _t in current[name]["fields"]]
            findings.append(Finding(
                mod.rel, msgs[name].lineno, "WIRE005",
                f"wire fields of {name} drifted from the protocol manifest "
                f"(recorded {old}, current {new} — order and types count): "
                f"adding/removing/reordering wire fields changes what "
                f"peers deserialize; review mixed-version compat "
                f"(MIGRATING.md) then --write-protocol-manifest",
            ))
    return findings
