"""OBS001/OBS002 — observability-plane coverage and hot-path guards.

The metrics plane (ISSUE 9) only works if two structural invariants
hold, and both silently rot without a gate:

- **OBS001** — every event tuple declared in ``runtime/telemetry.py``
  must have ≥1 ``telemetry.execute`` emission site somewhere in the
  package AND a subscription row in the metrics bridge's ``_table``
  (``runtime/metrics.py``). A declared-but-never-emitted event is a
  dead contract; a declared-but-unbridged event means the one
  always-attached consumer drops it on the floor — its metrics read
  zero forever while the emitting code pays full price. Also fires
  when events are declared but NO bridge table exists at all (a rename
  must not disarm the rule).
- **OBS002** — a ``telemetry.execute`` call in a hot-path module
  (replica / fleet / serve / transports) not guarded by
  ``telemetry.has_handlers(...)``: with telemetry disabled the call
  still builds its measurement/metadata dicts (and often pays a device
  readback) on every merge. Guards may be inline
  (``if telemetry.has_handlers(E):``), hoisted through a local
  (``want = telemetry.has_handlers(E)`` … ``if want:``), or enclose a
  nested ``def`` — a closure whose *definition* sits under the guard
  can only ever be called when the guard held, so its body inherits
  the guarded state (the deferred-emission idiom on the drain path).

Discovery is structural, not name-listed: declared events are the
UPPERCASE module-level tuple-of-strings assignments in a module whose
dotted name ends in ``telemetry``; the bridge table is any ``_table``
function returning a list of ``(event, handler)`` tuples; hot modules
are those whose last dotted part is ``replica`` / ``fleet`` /
``serve`` / ``transport`` / ``tcp_transport``.
"""

from __future__ import annotations

import ast

from tools.crdtlint.engine import Finding, ModuleInfo, Project
from tools.crdtlint.rules import call_leaf, iter_function_defs

RULE_COVERAGE = "OBS001"
RULE_GUARD = "OBS002"

#: ``serve`` (ISSUE 14): the serving front door emits per-commit and
#: per-read telemetry — the client hot path pays for unguarded dict
#: builds exactly like the ingest path does
#: ``treesync`` (ISSUE 15): the relay module's helpers run on the
#: drain/tick hot paths like replica/fleet code
_HOT_LEAVES = {
    "replica", "fleet", "serve", "transport", "tcp_transport", "treesync",
}


def _telemetry_module(project: Project) -> ModuleInfo | None:
    for name in sorted(project.modules):
        if name.rsplit(".", 1)[-1] == "telemetry":
            return project.modules[name]
    return None


def _declared_events(mod: ModuleInfo) -> dict[str, int]:
    """UPPERCASE module-level ``NAME = ("a", "b", ...)`` assignments —
    the declared event vocabulary, with declaration lines."""
    out: dict[str, int] = {}
    for node in mod.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not (isinstance(t, ast.Name) and t.id.isupper()):
            continue
        v = node.value
        if (
            isinstance(v, ast.Tuple)
            and v.elts
            and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in v.elts
            )
        ):
            out[t.id] = node.lineno
    return out


def _event_name(node: ast.AST, declared: dict[str, int]) -> str | None:
    """``telemetry.SYNC_DONE`` / bare ``SYNC_DONE`` -> "SYNC_DONE"."""
    if isinstance(node, ast.Attribute) and node.attr in declared:
        return node.attr
    if isinstance(node, ast.Name) and node.id in declared:
        return node.id
    return None


def _is_telemetry_call(
    node: ast.Call, mod: ModuleInfo, project: Project, leaf: str
) -> bool:
    """Is this a ``telemetry.<leaf>(...)`` / imported ``<leaf>(...)``
    call on the project's telemetry module? Resolution goes through the
    import table, with a literal ``telemetry.`` receiver accepted as a
    fallback (the universal idiom in this tree)."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == leaf:
        if isinstance(f.value, ast.Name):
            imp = mod.imports.get(f.value.id)
            if imp is not None and imp[0] in ("mod", "modroot"):
                if imp[1].rsplit(".", 1)[-1] == "telemetry":
                    return True
            return f.value.id == "telemetry"
        return False
    if isinstance(f, ast.Name) and f.id == leaf:
        imp = mod.imports.get(f.id)
        return (
            imp is not None
            and imp[0] == "sym"
            and imp[1].rsplit(".", 1)[-1] == "telemetry"
        )
    return False


_EMIT_LEAVES = ("execute", "execute_many")


def _is_emit_call(node: ast.Call, mod: ModuleInfo, project: Project) -> bool:
    leaf = call_leaf(node)
    return (
        leaf in _EMIT_LEAVES
        and bool(node.args)
        and _is_telemetry_call(node, mod, project, leaf)
    )


def _emitted_events(project: Project, declared: dict[str, int]) -> set[str]:
    out: set[str] = set()
    for name in sorted(project.modules):
        mod = project.modules[name]
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and _is_emit_call(node, mod, project):
                ev = _event_name(node.args[0], declared)
                if ev is not None:
                    out.add(ev)
    return out


def _bridge_tables(
    project: Project, declared: dict[str, int]
) -> list[tuple[str, set[str]]]:
    """Every ``_table`` function returning a list of tuples whose first
    elements are declared events — ``(qualname, subscribed events)``."""
    tables: list[tuple[str, set[str]]] = []
    for name in sorted(project.modules):
        mod = project.modules[name]
        for qual, fn in iter_function_defs(mod.tree):
            if qual[-1] != "_table":
                continue
            subscribed: set[str] = set()
            rows = 0
            for node in ast.walk(fn):
                if not isinstance(node, ast.Return) or not isinstance(
                    node.value, (ast.List, ast.Tuple)
                ):
                    continue
                for elt in node.value.elts:
                    if isinstance(elt, ast.Tuple) and elt.elts:
                        rows += 1
                        ev = _event_name(elt.elts[0], declared)
                        if ev is not None:
                            subscribed.add(ev)
            if rows:
                tables.append((f"{mod.rel}:{'.'.join(qual)}", subscribed))
    return tables


def _outer_function_defs(tree: ast.AST):
    """(qualname_parts, fn) for functions NOT nested inside another
    function — nested defs are analysed within their parent by
    ``_unguarded_executes`` so enclosing guards carry into closures."""
    def walk(node: ast.AST, stack: tuple[str, ...]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield stack + (child.name,), child
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, stack + (child.name,))
            else:
                yield from walk(child, stack)
    yield from walk(tree, ())


def _guard_locals(fn: ast.FunctionDef, mod, project) -> set[str]:
    """Names bound from a ``has_handlers(...)`` call in this function —
    the hoisted-guard idiom (``want = telemetry.has_handlers(E)``)."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and call_leaf(node.value) == "has_handlers"
            and _is_telemetry_call(node.value, mod, project, "has_handlers")
        ):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _test_guards(test: ast.AST, mod, project, guard_names: set[str]) -> bool:
    for n in ast.walk(test):
        if (
            isinstance(n, ast.Call)
            and call_leaf(n) == "has_handlers"
            and _is_telemetry_call(n, mod, project, "has_handlers")
        ):
            return True
        if isinstance(n, ast.Name) and n.id in guard_names:
            return True
    return False


def _unguarded_executes(
    fn: ast.FunctionDef, mod: ModuleInfo, project: Project,
    declared: dict[str, int],
) -> list[tuple[int, str, tuple[str, ...]]]:
    guard_names = _guard_locals(fn, mod, project)
    out: list[tuple[int, str, tuple[str, ...]]] = []

    def walk(node: ast.AST, guarded: bool, names: tuple[str, ...]) -> None:
        if isinstance(node, ast.If):
            g = guarded or _test_guards(node.test, mod, project, guard_names)
            for c in node.body:
                walk(c, g, names)
            for c in node.orelse:
                walk(c, guarded, names)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a closure defined under the guard can only ever run when
            # the guard held — its body inherits the def site's state
            # (guard locals are shared: closures read enclosing names)
            for c in node.body:
                walk(c, guarded, names + (node.name,))
            return
        if isinstance(node, ast.Call) and _is_emit_call(node, mod, project):
            ev = _event_name(node.args[0], declared)
            if ev is not None and not guarded:
                out.append((node.lineno, ev, names))
        for c in ast.iter_child_nodes(node):
            walk(c, guarded, names)

    for stmt in fn.body:
        walk(stmt, False, ())
    return out


def check_obs(project: Project) -> list[Finding]:
    tmod = _telemetry_module(project)
    if tmod is None:
        return []
    declared = _declared_events(tmod)
    if not declared:
        return []
    findings: list[Finding] = []

    # -- OBS001: emission + bridge coverage ----------------------------
    emitted = _emitted_events(project, declared)
    tables = _bridge_tables(project, declared)
    if not tables:
        first = min(declared.values())
        findings.append(Finding(
            tmod.rel, first, RULE_COVERAGE,
            "telemetry events are declared but no metrics-bridge "
            "subscription table was found (a `_table` function returning "
            "(event, handler) rows) — the always-attached consumer is "
            "gone and every metric it fed reads zero",
        ))
    subscribed = set().union(*(s for _q, s in tables)) if tables else set()
    for ev in sorted(declared):
        line = declared[ev]
        if ev not in emitted:
            findings.append(Finding(
                tmod.rel, line, RULE_COVERAGE,
                f"telemetry event {ev} is declared but never emitted "
                f"(no telemetry.execute({ev}, ...) site in the package) "
                f"— dead contract: delete it or emit it",
            ))
        if tables and ev not in subscribed:
            findings.append(Finding(
                tmod.rel, line, RULE_COVERAGE,
                f"telemetry event {ev} has no subscription row in the "
                f"metrics bridge table ({tables[0][0]}) — the "
                f"always-attached consumer drops it and its metrics "
                f"read zero forever",
            ))

    # -- OBS002: hot-path guard discipline -----------------------------
    for name in sorted(project.modules):
        mod = project.modules[name]
        if name.rsplit(".", 1)[-1] not in _HOT_LEAVES:
            continue
        for qual, fn in _outer_function_defs(mod.tree):
            for line, ev, names in _unguarded_executes(
                fn, mod, project, declared
            ):
                findings.append(Finding(
                    mod.rel, line, RULE_GUARD,
                    f"unguarded telemetry.execute({ev}) in hot-path "
                    f"module function {'.'.join(qual + names)} — disabled "
                    f"telemetry still builds the measurement dicts "
                    f"here; wrap it in `if telemetry.has_handlers"
                    f"({ev}):`",
                ))
    return findings
