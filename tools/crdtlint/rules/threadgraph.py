"""Shared thread-graph / happens-before engine (LOCK + RACE families).

This module owns the concurrency model every lock/race rule builds on,
factored out of the original ``rules/locks.py`` (ISSUE 7):

- **Per-unit scanning** (:class:`MethodScan`): one ordered pass over a
  method (or module-level function) body collecting attribute accesses
  with the lexical lock state, call edges, lock acquisitions, blocking
  calls — and, new for the RACE family, *sequence-numbered
  synchronisation events* (``Thread.start/join``, ``Event.set/wait``,
  per-object ``Queue.put/get``) plus iteration accesses (``for x in
  self._coll`` and snapshot idioms like ``list(self._coll)``).
- **Per-class analysis** (:class:`ClassAnalysis`): lock/exempt attr
  inference, thread-entry discovery (``Thread(target=...)`` bound
  methods AND nested defs — the fleet tick loop, the replica event
  loop, the TCP accept/heartbeat/serve threads), acquire-wrapper
  recognition, and interprocedural entry lock states
  (:func:`analyse_units`).
- **Module pseudo-class** (:class:`ModuleAnalysis`): a module's top
  level viewed through the same lens — underscore module globals are
  the "attributes", module-level ``Lock()`` assignments the locks,
  module functions the methods (the telemetry handler table and the
  native lazy-loader cache are real instances).
- **Thread roots over the real import graph**
  (:func:`thread_called_functions`, :func:`build_models`): every
  thread-entry unit's body is walked and its calls resolved through the
  project import table, so a module-level function reached from a
  replica/fleet/transport thread (``telemetry.execute`` from the event
  loop) is known to run on a non-caller root even though the module
  itself starts no threads.
- **Happens-before edges** (:func:`hb_ordered`): ``Thread.start``
  (writes before ``start()`` are visible to the started thread),
  ``Thread.join`` (everything the thread did is visible after the
  join), ``Event.set → Event.wait`` and ``Queue.put → Queue.get`` on
  the *same* object (message-passing handoff). ``Event.wait(timeout)``
  is deliberately NOT an edge — a timed wait can return with nothing
  set, so code ordered only by one is paced, not synchronised — and
  put/get on *distinct* queue objects never synchronise each other.

Documented model boundaries (shared by every consumer): ``__init__`` is
pre-publication (neither mints guards nor races, except for the
publish-after-``Thread.start`` window RACE004 checks); private methods
never reached from any entry are assumed called under their caller's
discipline; cross-class calls through injected collaborators are
analysed in the collaborator's own class context.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable

from tools.crdtlint.engine import ModuleInfo, Project
from tools.crdtlint.rules import (
    MUTATOR_METHODS,
    THREADSAFE_CONSTRUCTORS,
    call_leaf,
    self_attr,
)

#: pseudo lock-state token for __init__-reachable code (pre-publication:
#: single-threaded by construction, so neither flagged nor guard-minting)
INIT = "<init>"

#: the external-caller thread root: all public units run here (any
#: thread the embedding program calls the object from)
CALLER_ROOT = "<caller>"

#: root for module functions reached from a thread entry in ANOTHER
#: module (import-graph discovery: telemetry.execute from the replica
#: event loop)
XTHREAD_ROOT = "<cross-module-thread>"

#: constructor leaves treated as Event-like (set/wait channel)
EVENT_CTORS = {"Event"}

#: constructor leaves treated as Queue-like (put/get channel)
QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}

#: call leaves that block the calling thread regardless of receiver
BLOCKING_LEAVES = {
    "fsync": "os.fsync",
    "sendall": "socket sendall",
    "recv": "socket recv",
    "accept": "socket accept",
    "connect": "socket connect",
    "create_connection": "socket connect",
    "getaddrinfo": "DNS resolution",
    "sleep": "time.sleep",
    "block_until_ready": "device sync (block_until_ready)",
    "fsync_dir": "os.fsync (directory)",
}

#: leaves that block only for specific receiver types — counted when the
#: receiver is a ``self.`` attribute constructed as one of these
BLOCKING_RECEIVER_LEAVES = {
    "join": ("Thread",),
    "wait": ("Event", "Condition", "Barrier"),
}

#: builtins whose call iterates its first argument (the snapshot idiom
#: ``list(self._conns.values())`` — as much an iteration as a for loop)
_ITERATING_BUILTINS = {
    "list", "dict", "set", "frozenset", "tuple", "sorted", "sum", "min", "max",
}


@dataclasses.dataclass(frozen=True)
class Access:
    method: str
    line: int
    attr: str
    kind: str  # "read" | "write" | "call" | "iter"
    held: frozenset  # lock attrs held lexically at this point
    seq: int = 0  # statement-ordered position within the unit
    leaf: str | None = None  # method name for kind == "call"
    aug: bool = False  # AugAssign-Add write (version-counter shape)


@dataclasses.dataclass(frozen=True)
class CallEdge:
    callee: str  # method name on self (or module-level function)
    held: frozenset


@dataclasses.dataclass(frozen=True)
class AcquireEvent:
    """One lock-acquisition site (``with self._x:`` or ``.acquire()``)
    with the lexical lock state just BEFORE it — the raw material of the
    LOCK002 acquisition-order graph."""

    method: str
    line: int
    lock: str
    held_before: frozenset


@dataclasses.dataclass(frozen=True)
class BlockingEvent:
    """A call that can block the thread (fsync, socket I/O, sleep,
    thread join, device sync…) and the lexical lock state at the call —
    LOCK003 flags those reachable with any lock held."""

    method: str
    line: int
    what: str
    held: frozenset


@dataclasses.dataclass(frozen=True)
class AttrCall:
    """``self.X.m(...)`` — a method call on a member object. When X's
    class is statically known (constructed in this class), LOCK002/003
    follow the edge into that class's methods."""

    method: str
    line: int
    attr: str
    callee: str
    held: frozenset


@dataclasses.dataclass(frozen=True)
class SyncEvent:
    """One happens-before primitive in a unit body.

    ``op`` ∈ {"start", "join"} with ``obj`` naming the target thread
    ROOT (an entry unit name), or ``op`` ∈ {"set", "wait",
    "wait_timeout", "put", "get"} with ``obj`` naming the Event/Queue
    attribute (per-object channels: distinct attrs never synchronise
    each other; a timed wait is pacing, not an edge)."""

    method: str
    line: int
    seq: int
    op: str
    obj: str


def _dotted_chain(node: ast.AST) -> str | None:
    """``a.b.C`` attribute chain -> "a.b.C" (None when not a plain chain)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _thread_target(call: ast.Call) -> ast.AST | None:
    """``Thread(target=X)`` -> the X expression (None otherwise)."""
    if call_leaf(call) != "Thread":
        return None
    for kw in call.keywords:
        if kw.arg == "target":
            return kw.value
    return None


class MethodScan(ast.NodeVisitor):
    """One ordered pass over a unit body collecting attribute accesses,
    call edges, lock state, and synchronisation events.

    Lock state tracking is statement-ordered: a ``with self._lock:``
    holds inside its body; ``self._lock.acquire(...)`` (or a call to an
    acquire-wrapper method) holds until ``self._lock.release()`` in the
    same or an outer suite. Nested function defs are analysed inline at
    their definition point (closures run with whatever lock state their
    caller establishes — conservative for callbacks, exact for the
    immediately-called lambda idiom), except thread-entry defs, which
    the class analysis lifts into separate lock-free entry points.

    Works for class methods (attributes are ``self._*``) and, through
    :class:`ModuleAnalysis`, for module-level functions (attributes are
    underscore module globals; names shadowed by function locals are
    excluded per unit).
    """

    def __init__(self, cls: "ClassAnalysis | ModuleAnalysis", method: str,
                 skip_defs: set[ast.AST]):
        self.cls = cls
        self.method = method
        self.skip_defs = skip_defs
        self.module_mode = getattr(cls, "is_module", False)
        self.held: set[str] = set()
        self.accesses: list[Access] = []
        self.iters: list[Access] = []
        self.edges: list[CallEdge] = []
        self.acquires: list[AcquireEvent] = []
        self.blocking: list[BlockingEvent] = []
        self.attr_calls: list[AttrCall] = []
        self.syncs: list[SyncEvent] = []
        self._seq = 0
        #: local variable -> thread root name (``t = Thread(target=f)``)
        self._local_threads: dict[str, str] = {}
        #: nested-def name -> entry unit name within THIS unit
        self._nested_names: dict[str, str] = {}
        #: module mode: globals shadowed by function locals in this unit
        self._shadowed: set[str] = set()

    # -- attr extraction (class: self._x; module: underscore global) ---

    def _attr(self, node: ast.AST) -> str | None:
        if self.module_mode:
            if isinstance(node, ast.Name) and node.id in self.cls.trackable:
                return node.id if node.id not in self._shadowed else None
            return None
        return self_attr(node)

    def _receiver_root(self, func: ast.AST) -> str | None:
        """Root attr of a call-receiver chain: ``self._x.m(...)``,
        ``self._x[k].m(...)``, ``_g[k].append(...)`` all root at the
        tracked attribute/global."""
        if not isinstance(func, ast.Attribute):
            return None
        node = func.value
        while True:
            got = self._attr(node)
            if got is not None:
                return got
            if isinstance(node, ast.Attribute):
                node = node.value
            elif isinstance(node, ast.Subscript):
                node = node.value
            elif isinstance(node, ast.Call):
                node = node.func
            else:
                return None

    # -- lock state ----------------------------------------------------

    def _is_lock_attr(self, node: ast.AST) -> str | None:
        attr = self._attr(node)
        return attr if attr in self.cls.lock_attrs else None

    def visit_With(self, node: ast.With) -> None:
        entered: list[str] = []
        for item in node.items:
            lock = self._is_lock_attr(item.context_expr)
            if lock is not None:
                self.acquires.append(AcquireEvent(
                    self.method, item.context_expr.lineno, lock,
                    frozenset(self.held),
                ))
                # only locks not already held: a nested reentrant
                # ``with self._lock:`` (RLock) must not release the
                # outer hold when the inner block exits
                if lock not in self.held:
                    entered.append(lock)
            else:
                self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        self.held.update(entered)
        for stmt in node.body:
            self.visit(stmt)
        for lock in entered:
            self.held.discard(lock)

    visit_AsyncWith = visit_With

    @staticmethod
    def _terminates(stmts: list[ast.stmt]) -> bool:
        return bool(stmts) and isinstance(
            stmts[-1], (ast.Raise, ast.Return, ast.Continue, ast.Break)
        )

    def visit_If(self, node: ast.If) -> None:
        # branch-merge: a lock acquired in only one branch is not held
        # after the join (the acquire-then-raise guard idiom keeps its
        # lock because the acquiring branch is the TEST, visited first,
        # and a terminating branch contributes nothing to the join)
        self.visit(node.test)
        pre = set(self.held)
        self.held = set(pre)
        for s in node.body:
            self.visit(s)
        body_held = self.held
        self.held = set(pre)
        for s in node.orelse:
            self.visit(s)
        else_held = self.held
        if self._terminates(node.body):
            self.held = else_held
        elif node.orelse and self._terminates(node.orelse):
            self.held = body_held
        else:
            self.held = body_held & else_held

    def _visit_loop(self, node) -> None:
        # a loop body may run zero times: locks acquired (or released)
        # inside don't survive the loop — intersect with the pre-state
        pre = set(self.held)
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.held &= pre

    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    def visit_For(self, node: ast.For) -> None:
        self._record_iter(node.iter)
        self._visit_loop(node)

    def _comprehension(self, node) -> None:
        for gen in node.generators:
            self._record_iter(gen.iter)
        self.generic_visit(node)

    visit_ListComp = _comprehension
    visit_SetComp = _comprehension
    visit_DictComp = _comprehension
    visit_GeneratorExp = _comprehension

    def _iter_attr(self, node: ast.AST) -> str | None:
        """``self._x`` / ``self._x.items()/.values()/.keys()`` -> attr."""
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in {"items", "values", "keys"}
            and not node.args
        ):
            node = node.func.value
        return self._attr(node)

    def _record_iter(self, iter_expr: ast.AST) -> None:
        attr = self._iter_attr(iter_expr)
        if attr is not None:
            self._record(attr, iter_expr.lineno, "iter")

    def _note_blocking(self, func: "ast.Attribute | ast.Name", line: int) -> None:
        leaf = func.attr if isinstance(func, ast.Attribute) else func.id
        what = BLOCKING_LEAVES.get(leaf)
        if what is None and isinstance(func, ast.Attribute):
            # receiver-typed blockers: thread join, event/condition wait
            ctors = BLOCKING_RECEIVER_LEAVES.get(leaf)
            if ctors:
                recv = self._attr(func.value)
                chain = self.cls.attr_ctors.get(recv) if recv is not None else None
                ctor = chain.rsplit(".", 1)[-1] if chain else None
                if ctor in ctors:
                    what = f"{ctor}.{leaf}"
        if what is not None:
            self.blocking.append(
                BlockingEvent(self.method, line, what, frozenset(self.held))
            )

    # -- sync events ---------------------------------------------------

    def _sync(self, op: str, obj: str, line: int) -> None:
        self._seq += 1
        self.syncs.append(SyncEvent(self.method, line, self._seq, op, obj))

    def _thread_root_of(self, target: ast.AST) -> str | None:
        """``Thread(target=X)`` target expression -> entry unit name."""
        attr = self_attr(target) if not self.module_mode else None
        if attr is not None and attr in self.cls.methods:
            return attr
        if isinstance(target, ast.Name):
            if target.id in self._nested_names:
                return self._nested_names[target.id]
            if self.module_mode and target.id in self.cls.methods:
                return target.id
        return None

    def _note_thread_sync(self, node: ast.Call, func: ast.Attribute) -> bool:
        """``X.start()`` / ``X.join(...)`` where X is a known thread:
        a self attr assigned a Thread, a local assigned one, or an
        inline ``Thread(target=...)``. join(timeout=...) still counts —
        the teardown idiom this tree uses everywhere — but a timed
        ``Event.wait`` does not (see module docstring)."""
        if func.attr not in ("start", "join"):
            return False
        recv = func.value
        root = None
        direct = self_attr(recv) if not self.module_mode else None
        if direct is not None:
            root = self.cls.thread_attr_targets.get(direct)
        elif isinstance(recv, ast.Name):
            root = self._local_threads.get(recv.id)
        elif isinstance(recv, ast.Call):
            target = _thread_target(recv)
            if target is not None:
                root = self._thread_root_of(target)
        if root is None:
            return False
        self._sync(func.attr, root, node.lineno)
        return False  # informational: the call still flows through visit_Call

    def _note_channel_sync(self, node: ast.Call, func: ast.Attribute) -> None:
        direct = self._attr(func.value)
        if direct is None:
            return
        chain = self.cls.attr_ctors.get(direct)
        ctor = chain.rsplit(".", 1)[-1] if chain else None
        if ctor in EVENT_CTORS:
            if func.attr == "set":
                self._sync("set", direct, node.lineno)
            elif func.attr == "wait":
                timed = bool(node.args or node.keywords)
                self._sync("wait_timeout" if timed else "wait", direct, node.lineno)
        elif ctor in QUEUE_CTORS:
            if func.attr in ("put", "put_nowait"):
                self._sync("put", direct, node.lineno)
            elif func.attr in ("get", "get_nowait"):
                self._sync("get", direct, node.lineno)

    def visit_Assign(self, node: ast.Assign) -> None:
        # track ``t = Thread(target=f)`` locals for start/join edges
        if isinstance(node.value, ast.Call):
            target_expr = _thread_target(node.value)
            if target_expr is not None:
                root = self._thread_root_of(target_expr)
                if root is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self._local_threads[t.id] = root
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            self._note_thread_sync(node, func)
            self._note_channel_sync(node, func)
            lock = self._is_lock_attr(func.value)
            if lock is not None:
                if func.attr == "acquire":
                    self.acquires.append(AcquireEvent(
                        self.method, node.lineno, lock, frozenset(self.held)
                    ))
                    self.held.add(lock)
                elif func.attr == "release":
                    self.held.discard(lock)
                for arg in node.args + [kw.value for kw in node.keywords]:
                    self.visit(arg)
                return
            callee = self_attr(func) if not self.module_mode else None
            if callee is not None and callee in self.cls.methods:
                # self.helper(...): record the call edge; an acquire-
                # wrapper helper (net-acquires, e.g. Replica._acquire)
                # flips our lexical state exactly like a raw acquire()
                self.edges.append(CallEdge(callee, frozenset(self.held)))
                for arg in node.args + [kw.value for kw in node.keywords]:
                    self.visit(arg)
                self.held.update(self.cls.acquire_wrappers.get(callee, set()))
                return
            self._note_blocking(func, node.lineno)
            recv = self._receiver_root(func)
            if recv is not None:
                # method call rooted at a tracked attribute: potential
                # in-place mutation of that attribute's object
                self._record(recv, func.lineno, "call", leaf=func.attr)
                direct = self._attr(func.value)
                if direct is not None:
                    self.attr_calls.append(AttrCall(
                        self.method, node.lineno, direct, func.attr,
                        frozenset(self.held),
                    ))
                self.visit(func.value)
                for arg in node.args + [kw.value for kw in node.keywords]:
                    self.visit(arg)
                return
        elif isinstance(func, ast.Name):
            self._note_blocking(func, node.lineno)
            if func.id in _ITERATING_BUILTINS and node.args:
                self._record_iter(node.args[0])
            if self.module_mode and func.id in self.cls.methods:
                self.edges.append(CallEdge(func.id, frozenset(self.held)))
        self.generic_visit(node)

    # -- accesses ------------------------------------------------------

    def _record(self, attr: str, line: int, kind: str,
                leaf: str | None = None, aug: bool = False) -> None:
        if attr in self.cls.exempt_attrs or not attr.startswith("_"):
            return
        if attr in self.cls.methods or attr in self.cls.thread_entries:
            return  # bound-method reference, not state
        self._seq += 1
        acc = Access(self.method, line, attr, kind, frozenset(self.held),
                     self._seq, leaf, aug)
        (self.iters if kind == "iter" else self.accesses).append(acc)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = self._attr(node)
        if attr is not None:
            kind = "write" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
            self._record(attr, node.lineno, kind)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if self.module_mode:
            attr = self._attr(node)
            if attr is not None:
                kind = "write" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
                self._record(attr, node.lineno, kind)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # self._x[k] = v / del self._x[k]: the Attribute itself is Load,
        # but the container is mutated — count a write
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            attr = self._attr(node.value)
            if attr is not None:
                self._record(attr, node.lineno, "write")
                self.visit(node.slice)
                return
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = self._attr(node.target)
        if attr is not None:
            self._record(attr, node.lineno, "write",
                         aug=isinstance(node.op, ast.Add))
            self.visit(node.value)
            return
        self.generic_visit(node)

    # -- nested defs ---------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node in self.skip_defs:
            return  # analysed separately as a thread entry
        for stmt in node.body:  # inline: closures see the caller's locks
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.visit(node.body)


class ClassAnalysis:
    def __init__(self, mod: ModuleInfo, node: ast.ClassDef):
        self.mod = mod
        self.node = node
        self.name = node.name
        self.is_module = False
        # keyed by a UNIQUE unit name: a class may define several defs
        # under one name (property getter + setter/deleter overloads) —
        # a plain name-keyed dict would shadow all but the last, leaving
        # e.g. a property getter's lock region entirely unanalysed
        self.methods: dict[str, ast.FunctionDef] = {}
        for n in node.body:
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            name = n.name
            k = 2
            while name in self.methods:
                name = f"{n.name}#{k}"  # "#k" never collides with real names
                k += 1
            self.methods[name] = n
        self.lock_attrs = self._find_constructed(("Lock", "RLock"))
        self.exempt_attrs = self._find_constructed(tuple(THREADSAFE_CONSTRUCTORS))
        self.exempt_attrs |= self.lock_attrs
        #: attr -> constructor leaf name for attrs assigned a direct
        #: ``self.x = Ctor(...)`` (receiver-typed blocking + the
        #: cross-class edges of the LOCK002/003 order analysis)
        self.attr_ctors: dict[str, str] = self._find_attr_ctors()
        # thread-entry units: entry name -> FunctionDef (bound methods
        # and nested defs passed as Thread(target=...))
        self.thread_entries: dict[str, ast.FunctionDef] = {}
        self.nested_entry_defs: set[ast.AST] = set()
        #: attr -> entry unit name for ``self._t = Thread(target=...)``
        #: (so ``self._t.start()/.join()`` resolve to start/join edges)
        self.thread_attr_targets: dict[str, str] = {}
        self._find_thread_entries()
        # methods that net-acquire a lock for their caller
        self.acquire_wrappers: dict[str, set[str]] = self._find_acquire_wrappers()

    def _find_constructed(self, ctor_names: tuple[str, ...]) -> set[str]:
        out: set[str] = set()
        for body_fn in self.methods.values():
            for stmt in ast.walk(body_fn):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                value = stmt.value
                if not isinstance(value, ast.Call):
                    continue
                leaf = (
                    value.func.attr
                    if isinstance(value.func, ast.Attribute)
                    else value.func.id if isinstance(value.func, ast.Name) else None
                )
                if leaf not in ctor_names:
                    continue
                for t in targets:
                    attr = self_attr(t)
                    if attr is not None:
                        out.add(attr)
        return out

    def _find_attr_ctors(self) -> dict[str, str]:
        """attr -> constructor dotted chain (``WalLog`` / ``wal.WalLog``
        / ``threading.Thread``) for direct ``self.x = Ctor(...)``
        assignments. Consumers compare the LEAF for receiver typing and
        resolve the full chain for cross-class edges."""
        out: dict[str, str] = {}
        for body_fn in self.methods.values():
            for stmt in ast.walk(body_fn):
                if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                value = stmt.value
                if not isinstance(value, ast.Call):
                    continue
                chain = (
                    value.func.id
                    if isinstance(value.func, ast.Name)
                    else _dotted_chain(value.func)
                )
                if chain is None:
                    continue
                for t in targets:
                    attr = self_attr(t)
                    if attr is not None:
                        out[attr] = chain
        return out

    def _find_thread_entries(self) -> None:
        for mname, body_fn in self.methods.items():
            nested = {
                n.name: n
                for n in ast.walk(body_fn)
                if isinstance(n, ast.FunctionDef) and n is not body_fn
            }
            for call in ast.walk(body_fn):
                if not isinstance(call, ast.Call):
                    continue
                target = _thread_target(call)
                if target is None:
                    continue
                entry_name = None
                tgt_attr = self_attr(target)
                if tgt_attr is not None and tgt_attr in self.methods:
                    entry_name = tgt_attr
                    self.thread_entries[tgt_attr] = self.methods[tgt_attr]
                elif isinstance(target, ast.Name) and target.id in nested:
                    entry = nested[target.id]
                    entry_name = f"{mname}.<{entry.name}>"
                    self.thread_entries[entry_name] = entry
                    self.nested_entry_defs.add(entry)
                if entry_name is None:
                    continue
                # ``self._t = Thread(target=...)``: remember the attr so
                # later ``self._t.start()/.join()`` resolve to this root
                parent = self._assign_parent(body_fn, call)
                if parent is not None:
                    for t in parent.targets:
                        attr = self_attr(t)
                        if attr is not None:
                            self.thread_attr_targets[attr] = entry_name

    @staticmethod
    def _assign_parent(body_fn: ast.AST, call: ast.Call) -> ast.Assign | None:
        for stmt in ast.walk(body_fn):
            if isinstance(stmt, ast.Assign) and stmt.value is call:
                return stmt
        return None

    def _find_acquire_wrappers(self) -> dict[str, set[str]]:
        out: dict[str, set[str]] = {}
        for mname, body_fn in self.methods.items():
            acquired: set[str] = set()
            released: set[str] = set()
            for call in ast.walk(body_fn):
                if not isinstance(call, ast.Call) or not isinstance(
                    call.func, ast.Attribute
                ):
                    continue
                lock = self_attr(call.func.value)
                if lock in self.lock_attrs:
                    if call.func.attr == "acquire":
                        acquired.add(lock)
                    elif call.func.attr == "release":
                        released.add(lock)
            net = acquired - released
            if net:
                out[mname] = net
        return out


class ModuleAnalysis:
    """A module's top level through the class lens: underscore globals
    are the attributes, module-level ``Lock()`` assignments the locks,
    module functions the methods. The telemetry handler table
    (``_handlers`` + ``_lock``) and the native lazy-loader cache
    (``_lib``/``_tried`` + ``_lock``) are the real instances this
    models."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.node = mod.tree
        self.name = "<module>"
        self.is_module = True
        self.methods: dict[str, ast.FunctionDef] = dict(mod.functions)
        self.lock_attrs: set[str] = set()
        self.exempt_attrs: set[str] = set()
        self.attr_ctors: dict[str, str] = {}
        self.trackable: set[str] = set()
        self.acquire_wrappers: dict[str, set[str]] = {}
        for stmt in mod.tree.body:
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            names = [t.id for t in targets
                     if isinstance(t, ast.Name) and t.id.startswith("_")]
            if not names:
                continue
            value = stmt.value
            if isinstance(value, ast.Call):
                chain = (
                    value.func.id if isinstance(value.func, ast.Name)
                    else _dotted_chain(value.func)
                )
                if chain is not None:
                    leaf = chain.rsplit(".", 1)[-1]
                    for n in names:
                        self.attr_ctors[n] = chain
                    if leaf in ("Lock", "RLock"):
                        self.lock_attrs.update(names)
                    if leaf in THREADSAFE_CONSTRUCTORS:
                        self.exempt_attrs.update(names)
            self.trackable.update(names)
        # names rebound via ``global`` inside functions are trackable
        # even when the top-level binding is a plain constant
        for fn in self.methods.values():
            for n in ast.walk(fn):
                if isinstance(n, ast.Global):
                    self.trackable.update(
                        g for g in n.names if g.startswith("_"))
        self.exempt_attrs |= self.lock_attrs
        self.trackable -= {n for n in self.trackable if n in self.methods}
        self.thread_entries: dict[str, ast.FunctionDef] = {}
        self.nested_entry_defs: set[ast.AST] = set()
        self.thread_attr_targets: dict[str, str] = {}
        self._find_thread_entries()

    def _find_thread_entries(self) -> None:
        for mname, body_fn in self.methods.items():
            nested = {
                n.name: n
                for n in ast.walk(body_fn)
                if isinstance(n, ast.FunctionDef) and n is not body_fn
            }
            for call in ast.walk(body_fn):
                if not isinstance(call, ast.Call):
                    continue
                target = _thread_target(call)
                if target is None or not isinstance(target, ast.Name):
                    continue
                if target.id in nested:
                    entry = nested[target.id]
                    self.thread_entries[f"{mname}.<{entry.name}>"] = entry
                    self.nested_entry_defs.add(entry)
                elif target.id in self.methods:
                    self.thread_entries[target.id] = self.methods[target.id]


def scan_unit(cls: "ClassAnalysis | ModuleAnalysis", unit_name: str,
              fn: ast.FunctionDef) -> MethodScan:
    scan = MethodScan(cls, unit_name, cls.nested_entry_defs)
    scan._nested_names = {
        n.name: f"{unit_name}.<{n.name}>"
        for n in ast.walk(fn)
        if isinstance(n, ast.FunctionDef) and n is not fn
    }
    if scan.module_mode:
        # function locals shadow same-named globals unless declared
        # ``global`` — their accesses are local state, not shared
        declared = {
            g for n in ast.walk(fn) if isinstance(n, ast.Global) for g in n.names
        }
        assigned: set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store, ast.Del)):
                assigned.add(n.id)
            elif isinstance(n, ast.arg):
                assigned.add(n.arg)
        scan._shadowed = assigned - declared
    for stmt in fn.body:
        scan.visit(stmt)
    return scan


def analyse_units(
    cls: "ClassAnalysis | ModuleAnalysis",
    extra_entries: Iterable[str] = (),
) -> tuple[dict[str, MethodScan], dict[str, set[frozenset]]]:
    """Scan every unit (method or thread entry) of one class and
    propagate entry lock states interprocedurally: public methods and
    thread entries start lock-free, ``__init__`` gets the INIT
    pseudo-state (pre-publication), and each call edge forwards
    caller-entry ∪ call-site lexical locks to the callee. Shared by
    LOCK001 (guard inference), LOCK002/003 (order/blocking) and the
    RACE family. ``extra_entries`` seeds additional lock-free entry
    units (import-graph thread-called module functions)."""
    units: dict[str, ast.FunctionDef] = dict(cls.methods)
    units.update(cls.thread_entries)
    scans = {name: scan_unit(cls, name, fn) for name, fn in units.items()}

    entry_states: dict[str, set[frozenset]] = {name: set() for name in units}
    for name in units:
        if name in cls.thread_entries or not name.startswith("_"):
            entry_states[name].add(frozenset())
    for name in extra_entries:
        if name in entry_states:
            entry_states[name].add(frozenset())
    if "__init__" in entry_states:
        entry_states["__init__"] = {frozenset({INIT})}

    # propagate: caller entry-state ∪ call-site lexical locks -> callee
    changed = True
    guard = 0
    while changed and guard < 10_000:
        changed = False
        guard += 1
        for name, scan in scans.items():
            for entry in list(entry_states[name]):
                for edge in scan.edges:
                    if edge.callee not in entry_states:
                        continue
                    state = frozenset(entry | edge.held)
                    if state not in entry_states[edge.callee]:
                        entry_states[edge.callee].add(state)
                        changed = True
    return scans, entry_states


# ----------------------------------------------------------------------
# thread roots + the concurrency model the RACE family consumes

def infer_guards(
    scans: dict[str, MethodScan],
    entry_states: dict[str, set[frozenset]],
) -> dict[str, set[str]]:
    """Guard inference shared by LOCK001 and RACE003: attr -> set of
    locks it is written under somewhere (post-init, kind write/call,
    any reachable entry state)."""
    guards: dict[str, set[str]] = {}
    for name, scan in scans.items():
        for entry in entry_states.get(name, ()):
            if INIT in entry:
                continue
            for acc in scan.accesses:
                if acc.kind in ("write", "call"):
                    held = entry | acc.held
                    if held:
                        guards.setdefault(acc.attr, set()).update(held)
    return guards


@dataclasses.dataclass
class ConcurrencyModel:
    """One class (or module top level) with its units assigned to
    thread roots and per-unit scans/entry-states resolved.

    ``thread_owning`` is False for lock-owning classes with no thread
    entries of their own: they get no cross-root pairs (single caller
    root), but RACE003's check-then-act still applies — their callers
    can come from any thread."""

    mod: ModuleInfo
    owner: "ClassAnalysis | ModuleAnalysis"
    scans: dict[str, MethodScan]
    entry_states: dict[str, set[frozenset]]
    roots: dict[str, frozenset]  # unit -> thread roots it runs on
    thread_owning: bool = True

    @property
    def owner_name(self) -> str:
        return self.owner.name

    def attr_label(self, attr: str) -> str:
        return attr if self.owner.is_module else f"self.{attr}"

    def effective_locks(self, unit: str, acc: Access) -> frozenset | None:
        """Locks held at this access on EVERY (non-init) path reaching
        its unit — the set a common-lock argument may rely on. None
        when the access is unreachable outside ``__init__``."""
        states = [s for s in self.entry_states.get(unit, ()) if INIT not in s]
        if not states:
            return None
        out: frozenset | None = None
        for s in states:
            held = frozenset(s | acc.held)
            out = held if out is None else out & held
        return out

    def accesses_of(self, attr: str, include_iters: bool = False):
        """Yield ``(unit, Access, roots, locks)`` for every reachable
        post-init access of ``attr`` in a rooted unit."""
        for unit, scan in self.scans.items():
            roots = self.roots.get(unit)
            if not roots:
                continue
            pool = scan.accesses + (scan.iters if include_iters else [])
            for acc in pool:
                if acc.attr != attr:
                    continue
                locks = self.effective_locks(unit, acc)
                if locks is None:
                    continue
                yield unit, acc, roots, locks

    def tracked_attrs(self) -> set[str]:
        out: set[str] = set()
        for scan in self.scans.values():
            out.update(a.attr for a in scan.accesses)
            out.update(a.attr for a in scan.iters)
        return out


def is_race_write(acc: Access) -> bool:
    """The RACE family's write notion: plain/augmented/subscript stores
    and deletes, plus calls of KNOWN mutator methods. Unlike LOCK001's
    guard inference (where any method call conservatively counts — it
    only widens an existing lock's guard set), an unknown method call is
    NOT assumed mutating here: call-counts-as-write would flood
    cross-thread socket/file shutdown idioms with false races."""
    if acc.kind == "write":
        return True
    return acc.kind == "call" and acc.leaf in MUTATOR_METHODS


def unit_roots(
    owner: "ClassAnalysis | ModuleAnalysis",
    scans: dict[str, MethodScan],
    thread_called: set[str] = frozenset(),
) -> dict[str, frozenset]:
    """unit -> set of thread roots it can run on. Seeds: every public
    unit runs on the external-caller root; every thread entry is its
    own root; import-graph thread-called module functions additionally
    run on the cross-module-thread root. BFS over self-call edges.
    ``__init__`` is pre-publication and rootless; private units never
    reached from any root are assumed called under their caller's
    discipline (same convention as LOCK001) and excluded."""
    seeds: dict[str, set[str]] = {}
    for name in scans:
        if name == "__init__" or name.startswith("__init__#"):
            continue
        if name in owner.thread_entries or ".<" in name:
            continue  # a thread body is its own root, not a caller API
        if not name.startswith("_"):
            seeds.setdefault(CALLER_ROOT, set()).add(name)
    for entry in owner.thread_entries:
        seeds.setdefault(entry, set()).add(entry)
    for name in thread_called:
        if name in scans:
            seeds.setdefault(XTHREAD_ROOT, set()).add(name)
    out: dict[str, set] = {}
    for root, seed_units in seeds.items():
        stack = list(seed_units)
        visited: set[str] = set()
        while stack:
            u = stack.pop()
            if u in visited or u not in scans:
                continue
            visited.add(u)
            if u == "__init__":
                continue  # ctor runs pre-publication even when self-called
            out.setdefault(u, set()).add(root)
            stack.extend(e.callee for e in scans[u].edges)
    return {u: frozenset(r) for u, r in out.items()}


def thread_called_functions(
    project: Project,
    class_units: list[tuple[ModuleInfo, ast.FunctionDef]],
) -> dict[str, set[str]]:
    """module name -> module-level function names reachable from a
    thread-entry unit anywhere in the project, resolved over the real
    import graph (``telemetry.execute`` called from the replica event
    loop). Worklist-propagated: a thread-called module function's own
    calls are thread-called too."""
    marked: dict[str, set[str]] = {}
    seen: set[int] = set()
    work: list[tuple[ModuleInfo, ast.FunctionDef]] = list(class_units)
    while work:
        mod, fn = work.pop()
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            resolved = project.resolve_function(mod, call.func)
            if resolved is None:
                continue
            tmod, tfn = resolved
            if tfn.name in marked.setdefault(tmod.name, set()):
                continue
            marked[tmod.name].add(tfn.name)
            work.append((tmod, tfn))
    return marked


def build_models(project: Project) -> list[ConcurrencyModel]:
    """The project's full concurrency picture: one model per class that
    owns thread entries, plus one per module whose underscore globals
    are reachable from more than one thread root (its own entries, or
    import-graph thread-called functions)."""
    class_infos: list[tuple[ModuleInfo, ClassAnalysis]] = []
    for name in sorted(project.modules):
        mod = project.modules[name]
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                class_infos.append((mod, ClassAnalysis(mod, node)))

    models: list[ConcurrencyModel] = []
    for mod, ca in class_infos:
        if not ca.thread_entries and not ca.lock_attrs:
            continue  # single external root, no locks: nothing to check
        scans, entry_states = analyse_units(ca)
        roots = unit_roots(ca, scans)
        models.append(ConcurrencyModel(
            mod, ca, scans, entry_states, roots,
            thread_owning=bool(ca.thread_entries),
        ))

    # seed the import-graph worklist with every unit that runs on a
    # thread root anywhere: the entry bodies themselves plus everything
    # they reach through self-calls (telemetry.execute is called from
    # Replica._commit_entries_group, three self-call hops below the
    # event loop — seeding only entry bodies would miss it)
    seed_units: list[tuple[ModuleInfo, ast.FunctionDef]] = []
    for model in models:
        owner = model.owner
        fns: dict[str, ast.FunctionDef] = dict(owner.methods)
        fns.update(owner.thread_entries)
        for unit, roots in model.roots.items():
            if any(r != CALLER_ROOT for r in roots) and unit in fns:
                seed_units.append((model.mod, fns[unit]))
    module_infos: list[ModuleAnalysis] = []
    for name in sorted(project.modules):
        ma = ModuleAnalysis(project.modules[name])
        module_infos.append(ma)
        seed_units.extend((ma.mod, fn) for fn in ma.thread_entries.values())
    thread_called = thread_called_functions(project, seed_units)
    for ma in module_infos:
        called = thread_called.get(ma.mod.name, set())
        called = {c for c in called if c in ma.methods}
        has_entries = bool(ma.thread_entries)
        # a module matters when its own functions start threads (closure
        # escapes — RACE002 — even with no globals), or when its globals
        # can be reached from a cross-module thread root
        if not has_entries and not (ma.trackable and called):
            continue
        scans, entry_states = analyse_units(ma, extra_entries=called)
        roots = unit_roots(ma, scans, thread_called=called)
        if not has_entries and len(
            {r for rs in roots.values() for r in rs}
        ) < 2:
            continue  # every rooted unit on one root: no cross-thread pairs
        models.append(ConcurrencyModel(ma.mod, ma, scans, entry_states, roots))
    return models


# ----------------------------------------------------------------------
# happens-before

def hb_ordered(
    model: ConcurrencyModel,
    w_unit: str, w_seq: int, w_root: str,
    a_unit: str, a_seq: int, a_root: str,
) -> bool:
    """True when the access at (w_unit, w_seq) running on ``w_root`` is
    ordered BEFORE the access at (a_unit, a_seq) on ``a_root`` by a
    recognised happens-before edge:

    - **start**: w's unit starts thread root ``a_root`` after w — the
      started thread sees everything sequenced before its start().
    - **join**: a's unit joined thread root ``w_root`` before a —
      everything the joined thread did is visible after the join
      (timeout joins included: the teardown idiom; see module doc).
    - **channel**: an ``Event.set``/``Queue.put`` on object C after w
      in w's unit pairs with an untimed ``Event.wait``/``Queue.get`` on
      the SAME object C before a in a's unit. Distinct channel objects
      never synchronise each other, and ``wait(timeout)`` is pacing,
      not an edge.
    """
    w_syncs = model.scans[w_unit].syncs if w_unit in model.scans else []
    a_syncs = model.scans[a_unit].syncs if a_unit in model.scans else []
    for s in w_syncs:
        if s.op == "start" and s.obj == a_root and s.seq > w_seq:
            return True
    for s in a_syncs:
        if s.op == "join" and s.obj == w_root and s.seq < a_seq:
            return True
    # put pairs with get, set with wait — on the SAME channel object
    for rel_op, acq_op in (("set", "wait"), ("put", "get")):
        rel_objs = {s.obj for s in w_syncs if s.op == rel_op and s.seq >= w_seq}
        if rel_objs and any(
            s.op == acq_op and s.seq <= a_seq and s.obj in rel_objs
            for s in a_syncs
        ):
            return True
    return False


def pair_unordered(
    model: ConcurrencyModel,
    w_unit: str, w: Access, w_roots: frozenset,
    a_unit: str, a: Access, a_roots: frozenset,
) -> "tuple[str, str] | None":
    """The first root pair (w on A, a on B, A != B) under which the two
    accesses are concurrent and unordered in both directions — or None
    when every cross-root pairing is happens-before ordered."""
    for ra in sorted(w_roots):
        for rb in sorted(a_roots):
            if ra == rb:
                continue
            if hb_ordered(model, w_unit, w.seq, ra, a_unit, a.seq, rb):
                continue
            if hb_ordered(model, a_unit, a.seq, rb, w_unit, w.seq, ra):
                continue
            return ra, rb
    return None
