"""PURE001–PURE003 — lattice-op purity.

``join``/``merge``/``delta`` functions are the correctness foundation of
the framework: anti-entropy assumes they are pure functions of their
inputs — idempotent, commutative, associative joins over the delta
lattice (Almeida et al.). A join that mutates an argument pytree,
consults module state, or reads a clock produces states that diverge
replica-to-replica in ways no test of a single replica will catch.

Scope: functions (including methods) whose name contains a ``join``,
``merge`` or ``delta`` token, in ``ops/`` and ``models/`` modules and
in the pure-transition modules of the replica split
(``runtime/transition*``, ISSUE 6 — the fleet's vmapped lattice ops
are exactly the functions whose purity anti-entropy stakes itself on).

- **PURE001** — argument mutation: assignment/del through a parameter
  (``arg.x = …``, ``arg[k] = …``) or an in-place mutator call on one
  (``arg.update(…)``). The functional jax idiom ``arg.at[i].set(v)`` is
  of course exempt.
- **PURE002** — module-global writes: ``global X`` declarations.
- **PURE003** — nondeterminism: calls into ``time``/``random``/
  ``np.random``/``secrets``/``datetime.now``/``uuid``.
"""

from __future__ import annotations

import ast
import re

from tools.crdtlint.engine import Finding, ModuleInfo, Project, _dotted
from tools.crdtlint.rules import MUTATOR_METHODS, has_at_indexer, iter_function_defs

RULE_MUT = "PURE001"
RULE_GLOBAL = "PURE002"
RULE_IMPURE = "PURE003"

_NAME_RE = re.compile(r"(^|_)(join|merge|delta)(_|$|s$)")
_SCOPE_MARKERS = (".ops.", ".models.", ".runtime.transition")
#: modules where EVERY function is a lattice op by contract, whatever
#: its name — the hash-store kernel module (ISSUE 8): ``rehash``,
#: ``row_apply``, extraction and probing all feed anti-entropy state
#: that must replicate bit-for-bit, so an impure rehash is exactly as
#: gate-red as an impure merge
_WHOLE_MODULE_MARKERS = (".ops.hash_map",)
_IMPURE_ROOTS = {"time", "random", "secrets", "uuid"}
_IMPURE_CHAINS = ("np.random.", "numpy.random.", "datetime.")


def _in_scope(mod: ModuleInfo) -> bool:
    return any(m in mod.name + "." for m in _SCOPE_MARKERS)


def _param_names(fn: ast.FunctionDef) -> set[str]:
    a = fn.args
    names = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    names.discard("self")
    names.discard("cls")
    return names


def _root_name(node: ast.AST) -> str | None:
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        node = node.func if isinstance(node, ast.Call) else node.value
    return node.id if isinstance(node, ast.Name) else None


def _check_function(mod: ModuleInfo, fn: ast.FunctionDef) -> list[Finding]:
    findings: list[Finding] = []
    params = _param_names(fn)
    qual = f"{mod.name}.{fn.name}"

    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            findings.append(
                Finding(
                    mod.rel, node.lineno, RULE_GLOBAL,
                    f"lattice op {qual} declares global "
                    f"{', '.join(node.names)}: joins must not touch module "
                    f"state",
                )
            )
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target] if isinstance(node, ast.AugAssign) else node.targets
            )
            for t in targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    root = _root_name(t)
                    if root in params and not has_at_indexer(t):
                        findings.append(
                            Finding(
                                mod.rel, t.lineno, RULE_MUT,
                                f"lattice op {qual} mutates argument "
                                f"{root!r} in place: joins must return new "
                                f"values (use .at[...].set or rebuild the "
                                f"pytree)",
                            )
                        )
        elif isinstance(node, ast.Call):
            chain = _dotted(node.func) or ""
            root = chain.split(".", 1)[0] if chain else ""
            if root in _IMPURE_ROOTS or any(
                chain.startswith(p) for p in _IMPURE_CHAINS
            ):
                findings.append(
                    Finding(
                        mod.rel, node.lineno, RULE_IMPURE,
                        f"lattice op {qual} calls {chain}(...): joins must be "
                        f"deterministic pure functions of their inputs",
                    )
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in MUTATOR_METHODS
                and _root_name(node.func) in params
                and not has_at_indexer(node.func)
            ):
                findings.append(
                    Finding(
                        mod.rel, node.lineno, RULE_MUT,
                        f"lattice op {qual} calls in-place "
                        f"{node.func.attr}() on argument "
                        f"{_root_name(node.func)!r}: joins must not mutate "
                        f"their inputs",
                    )
                )
    return findings


def check_purity(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for mod in project.modules.values():
        if not _in_scope(mod):
            continue
        whole = any(m in mod.name + "." for m in _WHOLE_MODULE_MARKERS)
        seen_lines: set[tuple[int, str]] = set()
        defs = list(iter_function_defs(mod.tree))
        # qualnames that are FUNCTIONS (classes never yield their own
        # entry): a nested def is covered by ast.walk of its enclosing
        # function, but a CLASS method has no walked parent and must be
        # its own root — `parts[-2]` alone can't tell the two apart
        fn_parts = {parts for parts, _ in defs}
        for parts, fn in defs:
            if not (whole or _NAME_RE.search(fn.name)):
                continue
            # nested defs of a matching op are covered by ast.walk of the
            # parent; skip them as separate roots to avoid double reports
            if (
                len(parts) >= 2
                and parts[:-1] in fn_parts
                and (whole or _NAME_RE.search(parts[-2]))
            ):
                continue
            for f in _check_function(mod, fn):
                key = (f.line, f.rule)
                if key not in seen_lines:
                    seen_lines.add(key)
                    findings.append(f)
    return findings
