"""SPMD001 — shard_map readiness of transition-contract modules.

The ROADMAP's top open item lifts the fleet's ``fleet_*`` transitions
from ``vmap`` to ``shard_map`` over a replica-sharded mesh (DrJAX-style
mapped anti-entropy). That lift only works if the pure transition layer
is *mesh-liftable*, and three construct classes silently break it:

- **host callbacks** (``io_callback`` / ``pure_callback`` /
  ``jax.debug.print`` / ``jax.debug.callback``): a host round trip per
  shard serialises exactly the dispatch overlap the mesh exists for —
  and some backends cannot lift it at all. Red anywhere in a
  transition-contract module.
- **Python branching on replica-axis-dependent sizes**: an ``if`` /
  ``while`` on ``len(states)`` or ``states.…shape[0]`` in a ``fleet_*``
  function branches on the GLOBAL axis size, which under ``shard_map``
  differs from the per-shard size — the trace silently bakes in
  whichever world compiled first.
- **implicit global reductions over the replica axis**: an axis-free
  ``jnp.sum(x)`` / ``x.max()`` at the top level of a ``fleet_*``
  function reduces across the leading replica axis today; under
  ``shard_map`` it reduces only the local shard — a silent semantic
  change that needs an explicit collective (``psum``/``pmax``).
  Reductions inside vmapped inner functions are per-lane and lift
  unchanged, so nested-def bodies are exempt.

Scope: the transition-contract modules (``runtime/transition*``,
``ops/hash_map`` — the same contract markers SYNC001 uses for its
every-function-is-a-jit-root rule). Findings name the construct so the
mesh-sharding PR starts from a verified-clean seam.
"""

from __future__ import annotations

import ast

from tools.crdtlint.engine import Finding, ModuleInfo, Project, _dotted
from tools.crdtlint.rules import outer_function_defs
from tools.crdtlint.rules.hostsync import _TRANSITION_MODULE_MARKERS

RULE = "SPMD001"

#: call leaves that are host callbacks wherever they appear
_CALLBACK_LEAVES = {"io_callback", "pure_callback", "host_callback"}
#: dotted-chain suffixes that are host callbacks (the jax.debug family)
_DEBUG_SUFFIXES = ("debug.print", "debug.callback", "debug.breakpoint")
#: axis-free reduction leaves that implicitly fold the replica axis
_REDUCTION_LEAVES = {
    "sum", "max", "min", "mean", "prod", "any", "all", "argmax", "argmin",
}
#: functions with a leading replica axis by contract (the mesh seam;
#: ``mesh_`` covers the shard_map twins AND the delivery-plane rotate —
#: ISSUE 13 lifted the kernels, so the lifted forms themselves must
#: stay free of the constructs that would re-break them)
_AXIS_FN_PREFIXES = ("fleet_", "stack_", "mesh_")


def _is_transition_module(mod: ModuleInfo) -> bool:
    return any(m in mod.name + "." for m in _TRANSITION_MODULE_MARKERS)


def _callback_findings(mod: ModuleInfo) -> list[Finding]:
    out: list[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _dotted(node.func) or ""
        leaf = chain.rsplit(".", 1)[-1]
        if leaf in _CALLBACK_LEAVES or any(
            chain.endswith(s) for s in _DEBUG_SUFFIXES
        ):
            out.append(Finding(
                mod.rel, node.lineno, RULE,
                f"host callback {chain or leaf}(...) in transition-contract "
                f"module {mod.name}: shard_map cannot lift a host round "
                f"trip onto a replica-sharded mesh — move it to the I/O "
                f"shell",
            ))
    return out


def _param_names(fn: ast.FunctionDef) -> set[str]:
    a = fn.args
    return {
        p.arg
        for p in (a.posonlyargs + a.args + a.kwonlyargs)
    } | ({a.vararg.arg} if a.vararg else set())


def _rooted_at(node: ast.AST, names: set[str]) -> bool:
    """Is the attribute/subscript/call chain rooted at one of ``names``?"""
    while True:
        if isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Name):
            return node.id in names
        else:
            return False


def _axis_size_read(test: ast.AST, params: set[str]) -> str | None:
    """A ``len(param…)`` / ``param….shape[…]`` read inside a branch
    test — the axis-size-dependent value; returns a description."""
    for n in ast.walk(test):
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Name)
            and n.func.id == "len"
            and n.args
            and _rooted_at(n.args[0], params)
        ):
            return "len() of a replica-axis operand"
        if (
            isinstance(n, ast.Attribute)
            and n.attr == "shape"
            and _rooted_at(n.value, params)
        ):
            return ".shape of a replica-axis operand"
    return None


def _nested_node_ids(fn: ast.FunctionDef) -> set[int]:
    """ids of every node living inside a nested def/lambda of ``fn``."""
    inner: set[int] = set()
    for sub in ast.walk(fn):
        if sub is fn:
            continue
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            for n in ast.walk(sub):
                if n is not sub:
                    inner.add(id(n))
    return inner


def _axis_fn_findings(
    mod: ModuleInfo, qual: tuple, fn: ast.FunctionDef
) -> list[Finding]:
    out: list[Finding] = []
    params = _param_names(fn)
    name = ".".join(qual)
    nested = _nested_node_ids(fn)
    for node in ast.walk(fn):
        if id(node) in nested:
            # vmapped inner functions are per-lane: their branches and
            # reductions lift unchanged
            continue
        if isinstance(node, (ast.If, ast.While)):
            desc = _axis_size_read(node.test, params)
            if desc is not None:
                out.append(Finding(
                    mod.rel, node.lineno, RULE,
                    f"Python {'while' if isinstance(node, ast.While) else 'if'} "
                    f"on {desc} in {mod.name}.{name}: under shard_map the "
                    f"per-shard axis size differs from the global one — "
                    f"the trace bakes in whichever compiled first; make "
                    f"the branch a lax.cond or hoist it to the shell",
                ))
        elif isinstance(node, ast.Call):
            f = node.func
            if not isinstance(f, ast.Attribute) or f.attr not in _REDUCTION_LEAVES:
                continue
            # axis-free forms only: an explicit axis= (or positional
            # axis) names the folded axis and survives the lift
            has_axis = any(kw.arg == "axis" for kw in node.keywords)
            chain = _dotted(f) or ""
            head = chain.split(".", 1)[0]
            if head in ("jnp", "np", "jax", "lax", "numpy"):
                has_axis = has_axis or len(node.args) > 1
                operand = node.args[0] if node.args else None
            else:
                has_axis = has_axis or bool(node.args)
                operand = f.value
            if has_axis or operand is None or not _rooted_at(operand, params):
                continue
            out.append(Finding(
                mod.rel, node.lineno, RULE,
                f"axis-free reduction .{f.attr}() over a replica-axis "
                f"operand in {mod.name}.{name}: under shard_map this "
                f"folds only the local shard — name the axes (axis=...) "
                f"or use an explicit collective (psum/pmax) for the "
                f"replica axis",
            ))
    return out


def check_spmd(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for name in sorted(project.modules):
        mod = project.modules[name]
        if not _is_transition_module(mod):
            continue
        findings.extend(_callback_findings(mod))
        for qual, fn in outer_function_defs(mod.tree):
            if not fn.name.startswith(_AXIS_FN_PREFIXES):
                continue
            findings.extend(_axis_fn_findings(mod, qual, fn))
    return findings
