"""SHAPE001/SHAPE002 — recompile discipline at jit dispatch sites.

The batched-fleet design only pays off while every hot dispatch hits a
warm XLA cache; the tree's discipline for that is structural —
**data-dependent Python sizes** (``len()`` of a drained group, a
message count, a member list) must pass through a **tier/pad function**
(``pow2_tier`` / ``pow4_tier`` / ``stack_entry_slices``'s ``lanes=``)
before they become an operand shape, and **static (hashable) jit
arguments** must come from the closed geometry-key vocabulary. Nothing
enforced either until now; the runtime compile-cache audit
(``utils/jitcache.py``, ``crdt_jit_compiles_total{name=...}``) is the
dynamic cross-check of the same invariant.

- **SHAPE001** — a jit dispatch operand whose shape derives from a raw
  data-dependent size:

  * an array constructor (``np.full``/``zeros``/``ones``/``empty``/
    ``arange``) sized by ``len(...)``-tainted arithmetic, whose result
    flows into a jit dispatch (``jit_*`` / the hash kernel table /
    model kernels);
  * ``stack_entry_slices(...)`` with a raw (or missing) ``lanes=`` —
    the exact member count mints one executable per distinct
    occupancy;
  * a list stacked by ``stack_states``/``stack_pytrees``/
    ``jit_stack_pytrees`` without the pad-to-tier idiom
    (``lst += [lst[0]] * (lanes - len(lst))``).

  ``.shape``/``.size`` reads are NOT raw (they mirror an existing
  operand's compiled geometry); a tier call sanitises its whole
  argument expression.

- **SHAPE002** — a static argument at a ``static_argnames`` jit call
  site outside the geometry-key vocabulary: tier-function results,
  constants, store geometry attributes (``table_size``,
  ``probe_window``, ``num_buckets``, …), arithmetic/min/max over
  those, or values forwarded from a parameter (checked at the caller's
  own site). A novel ad-hoc static value — ``lanes=len(msgs)``,
  ``lanes=self._seq`` — mints a fresh executable per value and is red.

Scope: the jit-dispatch shell modules (replica / fleet / binned_map /
hash_store / transition) for SHAPE001; every module for SHAPE002
(static wrappers are discovered project-wide from
``jax.jit``/``named_jit`` assignments, decorators, and the lazy kernel
table's name→statics dict).
"""

from __future__ import annotations

import ast

from tools.crdtlint.engine import Finding, ModuleInfo, Project, _dotted
from tools.crdtlint.rules import iter_function_defs, outer_function_defs

RULE_SHAPE = "SHAPE001"
RULE_STATIC = "SHAPE002"

#: modules whose jit-dispatch argument construction is SHAPE001-checked
_SHELL_LEAVES = {
    "replica", "fleet", "binned_map", "hash_store", "transition", "meshplane",
    "serve",  # ISSUE 14: snapshot reads dispatch winners_for_keys directly
    "treesync",  # ISSUE 15: the relay module rides the jit-dispatch shell
}

#: tier/pad sanitiser seeds (import-resolved; aliases like ``_pow2``
#: follow the import table). Any call to one of these sanitises its
#: whole argument expression.
_TIER_SEEDS = {"pow2_tier", "pow4_tier"}

#: array constructors whose first argument is a shape
_SHAPE_CTORS = {"full", "zeros", "ones", "empty", "arange"}

#: declared pad functions: name -> (lanes kwarg, positional index)
_PAD_FNS = {"stack_entry_slices": ("lanes", 1)}

#: stacking entry points whose list-argument length is a compile shape
_STACK_FNS = {"stack_states", "stack_pytrees", "jit_stack_pytrees"}

#: store geometry attributes allowed as static-arg vocabulary
_GEOMETRY_ATTRS = {
    "num_buckets", "bin_capacity", "replica_capacity", "table_size",
    "probe_window", "shape", "ndim", "size",
}


def _leaf(chain: str) -> str:
    return chain.rsplit(".", 1)[-1]


def _call_leaf(node: ast.Call) -> str:
    return _leaf(_dotted(node.func) or "") or (
        node.func.attr if isinstance(node.func, ast.Attribute) else ""
    )


# ----------------------------------------------------------------------
# sanitiser discovery: tier seeds + project functions that return them


def _tier_names(project: Project, mod: ModuleInfo) -> set[str]:
    """Names that denote a tier sanitiser in ``mod``: the seeds, any
    import alias of a seed, and any project function every return of
    which is itself a tier call (``_dense_lanes`` style, one level)."""
    names = set(_TIER_SEEDS)
    for alias, imp in mod.imports.items():
        if imp[0] == "sym" and imp[2] in _TIER_SEEDS:
            names.add(alias)
    for fname, fn in mod.functions.items():
        rets = [
            n.value for n in ast.walk(fn)
            if isinstance(n, ast.Return) and n.value is not None
        ]
        if rets and all(
            isinstance(r, ast.Call) and _call_leaf(r) in names for r in rets
        ):
            names.add(fname)
    return names


# ----------------------------------------------------------------------
# raw-size taint (SHAPE001)


class _Taint:
    """Per-function raw-size and hazard-array taint."""

    def __init__(self, mod: ModuleInfo, fn: ast.FunctionDef, tiers: set[str]):
        self.tiers = tiers
        a = fn.args
        self.params = {
            p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)
        }
        if a.vararg:
            self.params.add(a.vararg.arg)
        if a.kwarg:
            self.params.add(a.kwarg.arg)
        self.raw: set[str] = set()  # locals holding raw data-dep sizes
        self.hazard: set[str] = set()  # locals holding raw-shaped arrays
        self.padded: set[str] = set()  # list locals padded to a tier
        self.list_sources: dict[str, ast.AST] = {}  # name -> list expr
        self._scan(fn)

    # -- expression classification -------------------------------------

    def expr_raw(self, expr: ast.AST) -> bool:
        """Does this scalar expression carry a raw data-dependent size?"""
        if isinstance(expr, ast.Call):
            leaf = _call_leaf(expr)
            if leaf in self.tiers:
                return False  # tier call sanitises its whole argument
            if leaf == "len":
                return True
            if leaf in ("min", "max", "abs", "sum", "int", "round"):
                return any(self.expr_raw(a) for a in expr.args)
            return False
        if isinstance(expr, ast.Name):
            return expr.id in self.raw
        if isinstance(expr, ast.BinOp):
            return self.expr_raw(expr.left) or self.expr_raw(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self.expr_raw(expr.operand)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self.expr_raw(e) for e in expr.elts)
        if isinstance(expr, ast.IfExp):
            return self.expr_raw(expr.body) or self.expr_raw(expr.orelse)
        # constants, .shape/.size reads, params, unknown calls: not raw
        return False

    def _ctor_hazard(self, expr: ast.AST) -> bool:
        """An array constructor sized by a raw expression?"""
        return (
            isinstance(expr, ast.Call)
            and _call_leaf(expr) in _SHAPE_CTORS
            and bool(expr.args)
            and self.expr_raw(expr.args[0])
        )

    def expr_hazard(self, expr: ast.AST) -> bool:
        """Does this expression reference (or build) a raw-shaped array?"""
        if self._ctor_hazard(expr):
            return True
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in self.hazard:
                return True
            if n is not expr and self._ctor_hazard(n):
                return True
        return False

    # -- statement scan -------------------------------------------------

    @staticmethod
    def _scalar_kill(expr: ast.AST) -> bool:
        """int()/float()/bool() etc. collapse arrays to scalars — the
        hazard (a shape) does not survive them."""
        return (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Name)
            and expr.func.id in ("int", "float", "bool", "len", "str")
        )

    def _scan(self, fn: ast.FunctionDef) -> None:
        stmts = list(ast.walk(fn))
        changed = True
        while changed:
            changed = False
            for node in stmts:
                if isinstance(node, ast.Assign):
                    value = node.value
                    names = [
                        t.id for t in node.targets if isinstance(t, ast.Name)
                    ]
                    for t in node.targets:
                        if isinstance(t, ast.Tuple):
                            names.extend(
                                e.id for e in t.elts if isinstance(e, ast.Name)
                            )
                    if not names:
                        continue
                    if isinstance(value, (ast.List, ast.ListComp)):
                        for n in names:
                            self.list_sources.setdefault(n, value)
                    if self.expr_raw(value):
                        for n in names:
                            if n not in self.raw:
                                self.raw.add(n)
                                changed = True
                    if not self._scalar_kill(value) and self.expr_hazard(value):
                        for n in names:
                            if n not in self.hazard:
                                self.hazard.add(n)
                                changed = True
                elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name
                ):
                    if self.expr_raw(node.value) and node.target.id not in self.raw:
                        # the pad idiom's own `lst += [..] * (V - len(lst))`
                        # is handled below, not tainted here
                        if not self._pad_stmt(node):
                            self.raw.add(node.target.id)
                            changed = True
        # pad idiom: lst += [..] * (V - len(lst)) / lst.extend(same) with
        # a NON-raw tier V — runs after the taint fix point so a raw V
        # (`lanes = len(members)`) does not count as padding
        for node in stmts:
            tgt = self._pad_stmt(node)
            if tgt is not None:
                self.padded.add(tgt)

    def _pad_stmt(self, node: ast.AST) -> str | None:
        """``lst += [..] * (V - len(lst))`` (or ``.extend`` of the same)
        with a sanitised tier ``V`` — returns the padded list's name."""
        target = width = None
        if isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Add):
            if isinstance(node.target, ast.Name):
                target, width = node.target.id, node.value
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "extend"
            and isinstance(node.func.value, ast.Name)
            and node.args
        ):
            target, width = node.func.value.id, node.args[0]
        if target is None or not (
            isinstance(width, ast.BinOp) and isinstance(width.op, ast.Mult)
        ):
            return None
        if isinstance(width.left, (ast.List, ast.ListComp)):
            count = width.right
        elif isinstance(width.right, (ast.List, ast.ListComp)):
            count = width.left
        else:
            count = width.right
        # the count is typically (V - len(lst)): V must be sanitised —
        # the complementary len() term is the idiom itself, not a taint
        if isinstance(count, ast.BinOp) and isinstance(count.op, ast.Sub):
            return target if not self.expr_raw(count.left) else None
        return target if not self.expr_raw(count) else None


# ----------------------------------------------------------------------
# jit dispatch sites


def _is_jit_dispatch(node: ast.Call) -> bool:
    """A call that launches a jitted executable: ``jit_*`` names, the
    hash backend's lazy kernel table (``jit.<kernel>``), and model
    kernel seams (``model.merge_rows`` / ``model.fleet_*``)."""
    chain = _dotted(node.func) or ""
    leaf = _leaf(chain)
    if leaf.startswith("jit_"):
        return True
    parts = chain.split(".")
    if len(parts) >= 2 and parts[-2] == "jit":
        return True
    if leaf.startswith(("fleet_", "mesh_fleet_")) and len(parts) >= 2:
        return True
    return False


def check_shape001(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for mod_name in sorted(project.modules):
        mod = project.modules[mod_name]
        if mod_name.rsplit(".", 1)[-1] not in _SHELL_LEAVES:
            continue
        tiers = _tier_names(project, mod)
        for qual, fn in outer_function_defs(mod.tree):
            # nested defs are walked within their parent: closures share
            # the enclosing scope's taint
            taint = _Taint(mod, fn, tiers)
            name = ".".join(qual)
            seen_lines: set[int] = set()

            def report(line: int, msg: str) -> None:
                if line in seen_lines:
                    return
                seen_lines.add(line)
                findings.append(Finding(mod.rel, line, RULE_SHAPE, msg))

            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                leaf = _call_leaf(node)
                if leaf in _PAD_FNS:
                    kwname, pos = _PAD_FNS[leaf]
                    lanes = next(
                        (kw.value for kw in node.keywords if kw.arg == kwname),
                        node.args[pos] if len(node.args) > pos else None,
                    )
                    if lanes is None:
                        report(node.lineno, (
                            f"{leaf}(...) without {kwname}= stacks the exact "
                            f"member count — one fresh XLA executable per "
                            f"distinct occupancy; pad through pow2_tier "
                            f"({mod.name}.{name})"
                        ))
                    elif taint.expr_raw(lanes):
                        report(node.lineno, (
                            f"{leaf}(..., {kwname}=...) receives a raw "
                            f"data-dependent size (len()-derived) — an "
                            f"unbounded-recompile hazard; route it through "
                            f"pow2_tier/pow4_tier ({mod.name}.{name})"
                        ))
                    continue
                if leaf in _STACK_FNS:
                    for a in node.args:
                        starred = isinstance(a, ast.Starred)
                        tgt = a.value if starred else a
                        if (
                            isinstance(tgt, ast.Name)
                            and tgt.id in taint.hazard
                        ):
                            report(node.lineno, (
                                f"{leaf}(...) stacks a raw-shaped operand "
                                f"{tgt.id!r} ({mod.name}.{name}) — pad to a "
                                f"lane tier first"
                            ))
                            continue
                        # list-length = the stacked leading axis: the
                        # list must be tier-padded in this scope (or be
                        # a parameter, padded by the caller)
                        list_like = starred or (
                            isinstance(tgt, ast.Name)
                            and isinstance(
                                taint.list_sources.get(tgt.id),
                                (ast.List, ast.ListComp),
                            )
                        )
                        if (
                            list_like
                            and isinstance(tgt, ast.Name)
                            and tgt.id not in taint.padded
                            and tgt.id not in taint.params
                        ):
                            report(node.lineno, (
                                f"{leaf}({'*' if starred else ''}{tgt.id}) "
                                f"stacks a list whose length was never "
                                f"padded to a lane tier (`{tgt.id} += "
                                f"[{tgt.id}[0]] * (lanes - len(...))`) — "
                                f"one executable per distinct member count "
                                f"({mod.name}.{name})"
                            ))
                    continue
                if _is_jit_dispatch(node):
                    for a in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        if taint.expr_hazard(a):
                            report(node.lineno, (
                                f"jit dispatch {leaf}(...) takes an operand "
                                f"built with a raw data-dependent shape "
                                f"(len()-derived, never tiered through "
                                f"pow2_tier/pow4_tier) — every distinct "
                                f"size mints a fresh XLA executable "
                                f"({mod.name}.{name})"
                            ))
    return findings


# ----------------------------------------------------------------------
# SHAPE002: static-arg vocabulary


def _static_wrappers(project: Project) -> dict[str, set[str]]:
    """bare jitted name -> static param names, discovered from
    ``NAME = jax.jit/named_jit(fn, static_argnames=…)`` assignments,
    ``@partial(jax.jit, static_argnames=…)`` decorators, and lazy
    kernel tables ({"kernel": ("lanes",), …} dict feeding a
    ``static_argnames=`` jit call in the same function)."""

    def static_names(call: ast.Call) -> set[str]:
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                if isinstance(kw.value, (ast.Tuple, ast.List)):
                    return {
                        e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant) and isinstance(e.value, str)
                    }
                if isinstance(kw.value, ast.Constant) and isinstance(
                    kw.value.value, str
                ):
                    return {kw.value.value}
        return set()

    out: dict[str, set[str]] = {}
    for mod in project.modules.values():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if _call_leaf(node.value) in ("jit", "named_jit"):
                    statics = static_names(node.value)
                    if statics:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                out.setdefault(t.id, set()).update(statics)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call) and _call_leaf(dec) in (
                        "jit", "partial",
                    ):
                        statics = static_names(dec)
                        if statics:
                            out.setdefault(node.name, set()).update(statics)
        # lazy kernel tables: a dict literal of name -> static tuple in
        # a function that also jits with static_argnames=<that dict>.get
        for _qual, fn in iter_function_defs(mod.tree):
            has_static_jit = any(
                isinstance(n, ast.Call)
                and _call_leaf(n) in ("jit", "named_jit")
                and any(kw.arg == "static_argnames" for kw in n.keywords)
                for n in ast.walk(fn)
            )
            if not has_static_jit:
                continue
            for n in ast.walk(fn):
                if not isinstance(n, ast.Dict):
                    continue
                for k, v in zip(n.keys, n.values):
                    if (
                        isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and isinstance(v, (ast.Tuple, ast.List))
                        and v.elts
                        and all(
                            isinstance(e, ast.Constant)
                            and isinstance(e.value, str)
                            for e in v.elts
                        )
                    ):
                        out.setdefault(k.value, set()).update(
                            e.value for e in v.elts
                        )
    return out


def _vocab_ok(
    expr: ast.AST,
    taint_params: set[str],
    assigns: dict[str, ast.AST],
    tiers: set[str],
    _depth: int = 0,
) -> bool:
    """Is this static-arg expression inside the geometry vocabulary?"""
    if _depth > 8:
        return False
    if isinstance(expr, ast.Constant):
        return True
    if isinstance(expr, ast.Attribute):
        return expr.attr in _GEOMETRY_ATTRS or (
            isinstance(expr.value, ast.Attribute)
            and expr.value.attr in _GEOMETRY_ATTRS
        )
    if isinstance(expr, ast.Subscript):
        return _vocab_ok(expr.value, taint_params, assigns, tiers, _depth + 1)
    if isinstance(expr, ast.Name):
        if expr.id in taint_params:
            return True  # forwarded: checked at the caller's site
        src = assigns.get(expr.id)
        if src is None:
            return False
        return _vocab_ok(src, taint_params, assigns, tiers, _depth + 1)
    if isinstance(expr, ast.Call):
        leaf = _call_leaf(expr)
        if leaf in tiers:
            return True
        if leaf in ("min", "max"):
            return all(
                _vocab_ok(a, taint_params, assigns, tiers, _depth + 1)
                for a in expr.args
            )
        return False
    if isinstance(expr, ast.BinOp):
        return _vocab_ok(
            expr.left, taint_params, assigns, tiers, _depth + 1
        ) and _vocab_ok(expr.right, taint_params, assigns, tiers, _depth + 1)
    if isinstance(expr, (ast.ListComp, ast.GeneratorExp)):
        return _vocab_ok(expr.elt, taint_params, assigns, tiers, _depth + 1)
    if isinstance(expr, ast.IfExp):
        return _vocab_ok(
            expr.body, taint_params, assigns, tiers, _depth + 1
        ) and _vocab_ok(expr.orelse, taint_params, assigns, tiers, _depth + 1)
    return False


def check_shape002(project: Project) -> list[Finding]:
    wrappers = _static_wrappers(project)
    if not wrappers:
        return []
    findings: list[Finding] = []
    for mod_name in sorted(project.modules):
        mod = project.modules[mod_name]
        tiers = _tier_names(project, mod)
        for qual, fn in outer_function_defs(mod.tree):
            a = fn.args
            params = {
                p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)
            }
            # lambdas forward their own params too
            for n in ast.walk(fn):
                if isinstance(n, ast.Lambda):
                    la = n.args
                    params |= {
                        p.arg for p in (la.posonlyargs + la.args + la.kwonlyargs)
                    }
            assigns: dict[str, ast.AST] = {}
            for n in ast.walk(fn):
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            assigns[t.id] = n.value
            name = ".".join(qual)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                leaf = _call_leaf(node)
                statics = wrappers.get(leaf)
                if not statics:
                    continue
                for kw in node.keywords:
                    if kw.arg in statics and not _vocab_ok(
                        kw.value, params, assigns, tiers
                    ):
                        findings.append(Finding(
                            mod.rel, node.lineno, RULE_STATIC,
                            f"static argument {kw.arg}= at jit call site "
                            f"{leaf}(...) is outside the geometry-key "
                            f"vocabulary (tier functions, constants, store "
                            f"geometry fields) — a novel static value "
                            f"mints one executable per value "
                            f"({mod.name}.{name})",
                        ))
    return findings


def check_shapes(project: Project) -> list[Finding]:
    return check_shape001(project) + check_shape002(project)
