"""Rule registry + shared AST helpers for crdtlint rule families."""

from __future__ import annotations

import ast

#: method names treated as in-place mutation of their receiver (for the
#: lock rule's write inference and the purity rule's arg-mutation check)
MUTATOR_METHODS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "add", "discard", "setdefault", "sort", "reverse",
    "appendleft", "popleft", "put", "put_nowait", "write", "truncate",
}

#: constructors whose product is itself a synchronisation/thread-safe
#: primitive — attributes holding one are exempt from the lock rule
#: (guarding a Queue with a lock is the container's job, not ours)
THREADSAFE_CONSTRUCTORS = {
    "Lock", "RLock", "Event", "Condition", "Semaphore", "BoundedSemaphore",
    "Barrier", "Thread", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "local",
}


def call_leaf(node: ast.Call) -> str | None:
    """Terminal name of a call's func: ``a.b.c(...)`` -> "c"."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def self_attr(node: ast.AST) -> str | None:
    """``self.X`` -> "X" (None otherwise)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def has_at_indexer(node: ast.AST) -> bool:
    """True when an attribute/subscript chain goes through ``.at[...]``
    — the functional jax update idiom (``x.at[i].set(v)``), which is NOT
    a mutation of ``x``."""
    while True:
        if isinstance(node, ast.Attribute):
            if node.attr == "at":
                return True
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        else:
            return False


def iter_function_defs(tree: ast.AST):
    """Yield every (qualname_parts, FunctionDef) in a module tree."""
    def walk(node: ast.AST, stack: tuple[str, ...]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield stack + (child.name,), child
                yield from walk(child, stack + (child.name,))
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, stack + (child.name,))
            else:
                yield from walk(child, stack)
    yield from walk(tree, ())


def outer_function_defs(tree: ast.AST):
    """(qualname_parts, fn) for functions NOT nested inside another
    function — rule families that analyse nested defs *within* their
    enclosing scope (closure captures share the parent's locals) use
    this to visit each closure exactly once."""
    def walk(node: ast.AST, stack: tuple[str, ...]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield stack + (child.name,), child
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, stack + (child.name,))
            else:
                yield from walk(child, stack)
    yield from walk(tree, ())


from tools.crdtlint.rules.locks import check_lock_discipline
from tools.crdtlint.rules.lockorder import check_lock_order
from tools.crdtlint.rules.races import check_races
from tools.crdtlint.rules.hostsync import check_host_sync
from tools.crdtlint.rules.purity import check_purity
from tools.crdtlint.rules.donation import check_donation
from tools.crdtlint.rules.wire import check_wire
from tools.crdtlint.rules.walkinds import check_wal_kinds
from tools.crdtlint.rules.obs import check_obs
from tools.crdtlint.rules.shapes import check_shapes
from tools.crdtlint.rules.leaks import check_leaks
from tools.crdtlint.rules.spmd import check_spmd
from tools.crdtlint.rules.transfers import check_transfers
from tools.crdtlint.rules.faults import check_faults

ALL_RULES = [
    check_lock_discipline,
    check_lock_order,
    check_races,
    check_host_sync,
    check_purity,
    check_donation,
    check_wire,
    check_wal_kinds,
    check_obs,
    check_shapes,
    check_leaks,
    check_spmd,
    check_transfers,
    check_faults,
]
