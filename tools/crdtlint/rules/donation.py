"""DONATE001 — donation/aliasing hygiene.

``jax.jit(..., donate_argnums=…)`` hands the argument's buffer to XLA:
after the call the donor array is invalid, and reading it is undefined
behaviour that *often works* on CPU (where donation may be ignored) and
corrupts silently on TPU. The rule finds every jit wrapper with
``donate_argnums``/``donate_argnames``, resolves its call sites through
the import graph, and flags donated arguments that are read again after
the call without being rebound.
"""

from __future__ import annotations

import ast
import dataclasses

from tools.crdtlint.engine import Finding, ModuleInfo, Project, _dotted
from tools.crdtlint.rules import iter_function_defs

RULE = "DONATE001"


@dataclasses.dataclass
class _JitWrapper:
    name: str  # name the wrapper is bound to in its module
    mod: str  # module it is defined in
    donate_argnums: tuple[int, ...]
    donate_argnames: tuple[str, ...]
    param_names: tuple[str, ...]  # of the wrapped fn, when resolvable
    line: int


def _donation_kwargs(call: ast.Call) -> tuple[tuple[int, ...], tuple[str, ...]]:
    nums: tuple[int, ...] = ()
    names: tuple[str, ...] = ()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums = (v.value,)
            elif isinstance(v, (ast.Tuple, ast.List)):
                nums = tuple(
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)
                )
        elif kw.arg == "donate_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                names = (v.value,)
            elif isinstance(v, (ast.Tuple, ast.List)):
                names = tuple(
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                )
    return nums, names


def _wrapped_params(project: Project, mod: ModuleInfo, call: ast.Call) -> tuple[str, ...]:
    if not call.args:
        return ()
    resolved = project.resolve_function(mod, call.args[0])
    if resolved is None:
        return ()
    _m, fn = resolved
    a = fn.args
    return tuple(p.arg for p in a.posonlyargs + a.args)


def _collect_wrappers(project: Project) -> dict[tuple[str, str], _JitWrapper]:
    """(module, bound name) -> wrapper info, for jit calls with donation."""
    out: dict[tuple[str, str], _JitWrapper] = {}

    def jit_call(node: ast.AST) -> ast.Call | None:
        # named_jit (utils/jitcache.py) is jax.jit + audit registration
        # — donation kwargs pass through it unchanged
        if (
            isinstance(node, ast.Call)
            and (_dotted(node.func) or "").rsplit(".", 1)[-1]
            in ("jit", "named_jit")
        ):
            return node
        return None

    for mod in project.modules.values():
        for node in mod.tree.body:
            # name = jax.jit(f, donate_argnums=...)
            if isinstance(node, ast.Assign) and (call := jit_call(node.value)):
                nums, names = _donation_kwargs(call)
                if not nums and not names:
                    continue
                params = _wrapped_params(project, mod, call)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[(mod.name, t.id)] = _JitWrapper(
                            t.id, mod.name, nums, names, params, node.lineno
                        )
        for fn_node in ast.walk(mod.tree):
            # @jax.jit(donate_argnums=...) / @partial(jax.jit, donate_argnums=...)
            if not isinstance(fn_node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in fn_node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                leaf = (_dotted(dec.func) or "").rsplit(".", 1)[-1]
                if leaf == "jit" or (
                    leaf == "partial"
                    and dec.args
                    and (_dotted(dec.args[0]) or "").rsplit(".", 1)[-1] == "jit"
                ):
                    nums, names = _donation_kwargs(dec)
                    if not nums and not names:
                        continue
                    a = fn_node.args
                    params = tuple(p.arg for p in a.posonlyargs + a.args)
                    out[(mod.name, fn_node.name)] = _JitWrapper(
                        fn_node.name, mod.name, nums, names, params, fn_node.lineno
                    )
    return out


def _resolve_callee(
    project: Project, mod: ModuleInfo, func: ast.AST
) -> tuple[str, str] | None:
    """Resolve a call's func expression to a (module, bound-name) pair."""
    if isinstance(func, ast.Name):
        imp = mod.imports.get(func.id)
        if imp and imp[0] == "sym":
            return (imp[1], imp[2])
        return (mod.name, func.id)
    chain = _dotted(func)
    if chain is None:
        return None
    head, _, rest = chain.partition(".")
    imp = mod.imports.get(head)
    if imp and imp[0] == "mod" and rest:
        return (imp[1], rest)
    return None


def _donated_positions(w: _JitWrapper, call: ast.Call) -> list[ast.Name]:
    donated: list[ast.Name] = []
    for i in w.donate_argnums:
        if i < len(call.args) and isinstance(call.args[i], ast.Name):
            donated.append(call.args[i])
    if w.donate_argnames and w.param_names:
        index = {p: i for i, p in enumerate(w.param_names)}
        for nm in w.donate_argnames:
            i = index.get(nm)
            if i is not None and i < len(call.args) and isinstance(call.args[i], ast.Name):
                donated.append(call.args[i])
            for kw in call.keywords:
                if kw.arg == nm and isinstance(kw.value, ast.Name):
                    donated.append(kw.value)
    return donated


def _terminating(stmts: list[ast.stmt]) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue)
    )


def _branch_paths(fn: ast.FunctionDef) -> dict[int, tuple]:
    """Map ``id(node)`` -> chain of ``(id(if_node), branch)`` ancestors,
    so mutually exclusive If branches are distinguishable. Statements
    after an else-less If whose body terminates (the early-return idiom)
    are tagged as that If's implicit else branch."""
    paths: dict[int, tuple] = {}

    def tag(node: ast.AST, path: tuple) -> None:
        paths[id(node)] = path
        if isinstance(node, ast.If):
            tag(node.test, path)
            block(node.body, path + ((id(node), "body"),))
            block(node.orelse, path + ((id(node), "else"),))
            return
        for _field, value in ast.iter_fields(node):
            if (
                isinstance(value, list)
                and value
                and all(isinstance(x, ast.stmt) for x in value)
            ):
                block(value, path)
            elif isinstance(value, list):
                for c in value:
                    if isinstance(c, ast.AST):
                        tag(c, path)
            elif isinstance(value, ast.AST):
                tag(value, path)

    def block(stmts: list[ast.stmt], path: tuple) -> None:
        cur = path
        for s in stmts:
            tag(s, cur)
            if isinstance(s, ast.If) and _terminating(s.body) and not s.orelse:
                cur = cur + ((id(s), "else"),)

    tag(fn, ())
    return paths


def _exclusive(p1: tuple, p2: tuple) -> bool:
    """True when the two nodes sit in different branches of one If —
    i.e. they can never execute in the same pass through the code."""
    d1 = dict(p1)
    return any(d1.get(if_id, branch) != branch for if_id, branch in p2)


def check_donation(project: Project) -> list[Finding]:
    wrappers = _collect_wrappers(project)
    if not wrappers:
        return []
    findings: list[Finding] = []
    for mod in project.modules.values():
        for _parts, fn in iter_function_defs(mod.tree):
            calls: list[tuple[ast.Call, _JitWrapper]] = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    key = _resolve_callee(project, mod, node.func)
                    if key in wrappers:
                        calls.append((node, wrappers[key]))
            if not calls:
                continue
            branch_of = _branch_paths(fn)
            # for each donated name: flag loads after the call line that
            # happen before the name is rebound
            loads = [
                n
                for n in ast.walk(fn)
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
            ]
            stores = [
                n
                for n in ast.walk(fn)
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
            ]
            for call, w in calls:
                # a multi-line call puts the donor argument's own Name on
                # a continuation line: everything inside the call's span
                # is the donation itself, not a read after it
                call_end = call.end_lineno or call.lineno
                for donor in _donated_positions(w, call):
                    rebind = min(
                        (s.lineno for s in stores
                         if s.id == donor.id and s.lineno >= call.lineno),
                        default=None,
                    )
                    for ld in loads:
                        if ld.id != donor.id or ld is donor or ld.lineno <= call_end:
                            continue
                        if rebind is not None and ld.lineno > rebind:
                            continue
                        if _exclusive(
                            branch_of.get(id(call), ()), branch_of.get(id(ld), ())
                        ):
                            continue  # read in a branch the call never takes
                        # no line numbers in the message: baseline
                        # fingerprints are (path, rule, message) and must
                        # not drift with unrelated edits
                        findings.append(
                            Finding(
                                mod.rel,
                                ld.lineno,
                                RULE,
                                f"{donor.id!r} was donated to {w.name} "
                                f"(donate_argnums) and read again after the "
                                f"call: the buffer is invalid after donation",
                            )
                        )
                        break  # one finding per donated call is enough
    return findings
