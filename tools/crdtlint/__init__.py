"""crdtlint — project-specific static analysis for the delta-CRDT runtime.

Rule families over the package's real import graph (no hardcoded
file lists):

- ``LOCK001``   lock discipline: accesses to lock-guarded ``self._*``
  attributes on public or thread-entry paths that can run without the
  guarding lock held (``LOCK002`` acquisition-order deadlocks,
  ``LOCK003`` blocking calls under a lock);
- ``RACE001``–``RACE005`` happens-before races: shared state written on
  one thread root and accessed on another with no common lock and no
  HB edge (``Thread.start/join``, ``Event.set/wait``, per-object
  ``Queue.put/get``), closure escapes across thread boundaries,
  check-then-act on version fields, publication after
  ``Thread.start()``, and lock-free iteration of cross-thread-mutated
  collections;
- ``SYNC001``/``SYNC002`` JAX host-sync leaks: ``.item()``,
  ``.tolist()``, ``int()``/``float()`` coercion, ``np.asarray`` and
  ``block_until_ready()`` inside functions reachable from a
  ``jax.jit``/``shard_map``/``pallas_call`` entry point (SYNC001), and
  ``block_until_ready()`` anywhere in op-library modules (SYNC002 —
  sync belongs to the caller/bench harness, not the op body);
- ``PURE001``–``PURE003`` lattice-op purity: ``join``/``merge``/
  ``delta`` functions in ``ops/``/``models/`` must not mutate argument
  pytrees, write module globals, or call ``time.*``/``random.*``;
- ``DONATE001`` donation hygiene: ``donate_argnums``/``donate_argnames``
  arguments re-read after the jitted call.

Suppression: an inline ``# crdtlint: allow[<tag>] <why>`` comment on the
flagged line (or the line directly above) — tags are ``lock``, ``race``,
``host-sync``, ``purity``, ``donation``, ``wire``, ``wal``, an exact
rule id, or ``all`` —
or a checked-in baseline (``--baseline`` / ``--write-baseline``) that
records pre-existing findings by (path, rule, message) fingerprint.
"""

from tools.crdtlint.engine import Finding, Project, load_baseline, run_lint

__all__ = ["Finding", "Project", "load_baseline", "run_lint"]
