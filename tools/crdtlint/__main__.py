import sys

from tools.crdtlint.cli import main

sys.exit(main())
