from delta_crdt_ex_tpu.parallel.batched_sync import (
    fanout_merge,
    fanout_merge_into,
    fanout_merge_packed,
    pack_states,
    ring_gossip_round,
    stack_states,
    unstack_states,
)
from delta_crdt_ex_tpu.parallel.mesh_gossip import (
    AXIS,
    gossip_delta_drive,
    gossip_delta_step,
    gossip_train_step,
    make_mesh,
    place_states,
    replica_sharding,
    restore_mesh,
    snapshot_mesh,
)

__all__ = [
    "AXIS",
    "fanout_merge",
    "fanout_merge_into",
    "fanout_merge_packed",
    "gossip_delta_drive",
    "gossip_delta_step",
    "gossip_train_step",
    "make_mesh",
    "pack_states",
    "place_states",
    "replica_sharding",
    "restore_mesh",
    "ring_gossip_round",
    "snapshot_mesh",
    "stack_states",
    "unstack_states",
]
