"""Multi-chip replication over a device mesh — the ICI data plane.

The reference scales across machines via distributed Erlang's full-mesh
TCP (SURVEY §5.8). The TPU-native equivalent keeps one replica state
resident per device of a ``jax.sharding.Mesh`` and moves whole delta
states device↔device over ICI with ``lax.ppermute`` inside ``shard_map``
— no host hop, XLA schedules the collective. A gossip *step* is:

1. (optional) apply a per-replica local mutation batch (vmapped
   ``apply_batch`` — the "compute" of the step);
2. ``ppermute`` the full state pytree one hop around the ring;
3. join the received state shard-locally;
4. rebuild digest-tree roots (the observability/convergence probe).

Ring gossip converges every replica in ≤ N-1 steps (each state travels
the whole ring); anti-entropy idempotence makes over-delivery harmless —
semantically this is the reference's neighbour gossip with a ring
topology, executed as one SPMD program.

The entry-slice (bounded-divergence) variant over ICI is layered the
same way — extract on device, ppermute fixed-size slices, join — and is
what :mod:`delta_crdt_ex_tpu.parallel.batched_sync` does within a chip.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from delta_crdt_ex_tpu.models.state import DotStore
from delta_crdt_ex_tpu.ops.apply import apply_batch
from delta_crdt_ex_tpu.ops.hashtree import digest_tree
from delta_crdt_ex_tpu.ops.join import join

AXIS = "replicas"


def make_mesh(devices=None) -> Mesh:
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (AXIS,))


def replica_sharding(mesh: Mesh) -> NamedSharding:
    """Leading axis = replica, one per device."""
    return NamedSharding(mesh, P(AXIS))


def place_states(states: list[DotStore], mesh: Mesh) -> DotStore:
    """Stack N replica states and shard one-per-device over the mesh."""
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)
    return jax.device_put(stacked, replica_sharding(mesh))


def _squeeze(tree):
    return jax.tree_util.tree_map(lambda x: x[0], tree)


def _unsqueeze(tree):
    return jax.tree_util.tree_map(lambda x: x[None], tree)


@partial(jax.jit, static_argnames=("mesh", "depth"))
def gossip_train_step(
    mesh: Mesh,
    stacked: DotStore,
    self_slot: jnp.ndarray,  # int32[N]   each replica's own ctx slot
    op: jnp.ndarray,  # int32[N, K]  per-replica mutation batches
    key: jnp.ndarray,  # uint64[N, K]
    valh: jnp.ndarray,  # uint32[N, K]
    ts: jnp.ndarray,  # int64[N, K]
    depth: int = 6,
):
    """One SPMD step: local mutation batch → ring ppermute → join → roots.

    This is the framework's "training step" shape: per-device compute
    (batched mutation kernels), one ICI collective (ppermute of the full
    state pytree), then shard-local lattice math. Returns the new stacked
    states and each replica's digest-tree root (uint32[N]) for
    convergence monitoring.
    """
    n = mesh.devices.size
    perm = [(i, (i + 1) % n) for i in range(n)]
    spec = P(AXIS)

    def step(local, slot, op_b, key_b, valh_b, ts_b):
        local = _squeeze(local)
        applied = apply_batch(
            local, slot[0], op_b[0], key_b[0], valh_b[0], ts_b[0]
        ).state
        received = jax.tree_util.tree_map(
            lambda x: jax.lax.ppermute(x, AXIS, perm), applied
        )
        merged, _ok2, _ins, _kill = join(applied, received, None)
        root = digest_tree(merged, depth)[0][0]
        return _unsqueeze(merged), root[None]

    return shard_map(
        step,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec, spec),
        out_specs=(spec, spec),
        check_vma=False,
    )(stacked, self_slot, op, key, valh, ts)
