"""Multi-chip replication over a device mesh — the ICI data plane.

The reference scales across machines via distributed Erlang's full-mesh
TCP (SURVEY §5.8). The TPU-native equivalent keeps one replica state
resident per device of a ``jax.sharding.Mesh`` and moves whole state
pytrees device↔device over ICI with ``lax.ppermute`` inside ``shard_map``
— no host hop, XLA schedules the collective. A gossip *step* is:

1. (optional) apply a per-replica local mutation batch (the bucket-
   grouped ``row_apply`` kernel — the "compute" of the step);
2. ``ppermute`` the full state pytree one hop around the ring;
3. merge the received state's full-row slice shard-locally;
4. rebuild digest-tree roots from the maintained leaf digests (the
   observability/convergence probe).

Ring gossip converges every replica in ≤ N-1 steps (each state travels
the whole ring); anti-entropy idempotence makes over-delivery harmless —
semantically this is the reference's neighbour gossip with a ring
topology, executed as one SPMD program.

The bounded-divergence variant over ICI is layered the same way —
extract row slices on device, ppermute fixed-size slices, merge — and is
what :mod:`delta_crdt_ex_tpu.parallel.batched_sync` does within a chip.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # jax < 0.6 ships shard_map under experimental,
    # with the replication check spelled check_rep instead of check_vma
    from jax.experimental.shard_map import shard_map as _shard_map_compat

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_compat(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
        )

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from delta_crdt_ex_tpu.models.binned import BinnedStore
from delta_crdt_ex_tpu.ops.binned import (
    extract_rows,
    flagged_first_order,
    merge_rows,
    row_apply,
    tree_from_leaves,
)

AXIS = "replicas"


def make_mesh(devices=None) -> Mesh:
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (AXIS,))


def replica_sharding(mesh: Mesh) -> NamedSharding:
    """Leading axis = replica, one per device."""
    return NamedSharding(mesh, P(AXIS))


def place_states(states: list[BinnedStore], mesh: Mesh) -> BinnedStore:
    """Stack N replica states and shard one-per-device over the mesh."""
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)
    return jax.device_put(stacked, replica_sharding(mesh))


def _squeeze(tree):
    return jax.tree_util.tree_map(lambda x: x[0], tree)


def _unsqueeze(tree):
    return jax.tree_util.tree_map(lambda x: x[None], tree)


@partial(jax.jit, static_argnames=("mesh", "frontier"))
def gossip_delta_step(
    mesh: Mesh,
    stacked: BinnedStore,
    self_slot: jnp.ndarray,  # int32[N]
    rows: jnp.ndarray,  # int32[N, U]  bucket-grouped mutation batches
    op: jnp.ndarray,  # int32[N, U, M]
    key: jnp.ndarray,  # uint64[N, U, M]
    valh: jnp.ndarray,  # uint32[N, U, M]
    ts: jnp.ndarray,  # int64[N, U, M]
    frontier: int = 64,
):
    """One bounded-divergence SPMD gossip step — ICI bytes ∝ divergence.

    The host sync walk ships digest blocks level-by-level because digests
    are expensive to move over a slow control plane (the reference's
    8-levels-per-round partial diff, ``causal_crdt.ex:96,255``). Over ICI
    the whole leaf-digest vector is cheap (``L`` × 4 bytes ≪ one bucket
    slice), so the walk collapses to a single exchange; the bytes that
    matter — entry slices — ship only for buckets that actually differ:

    1. apply the per-replica local mutation batch (``row_apply``);
    2. ppermute **leaf digests** one hop forward (i → i+1): the receiver
       compares against its own leaves and selects up to ``frontier``
       differing buckets (fixed-size padded frontier — the
       ``max_sync_size`` analog, ``causal_crdt.ex:206-214``);
    3. ppermute the **frontier request** one hop backward (the receiver
       asks its ring predecessor — the ``{:get_diff, …}`` analog,
       ``causal_crdt.ex:112-123``);
    4. the predecessor extracts exactly those bucket rows and ppermutes
       the **slice** forward; the receiver joins it shard-locally.

    Per-step ICI traffic = L·4 (digests) + frontier·4 (request) + one
    fixed ``frontier``-row slice — the slice is the only term that scales,
    and it is bounded by the actual divergence (padded rows are -1 and
    merge as no-ops). Divergence beyond ``frontier`` buckets heals over
    subsequent steps (``n_diff`` reports the true differing-bucket count;
    sync is idempotent).

    Returns ``(stacked, roots, ok, n_diff, flags)``; ``ok[i]`` folds the
    local apply's bin-capacity flag AND the merge's tier flags — a False
    means replica i's step is invalid and the host must grow that tier
    and replay from the pre-step state (growth cannot happen inside the
    SPMD program; :func:`gossip_delta_drive` is that recovery loop).
    ``flags[i] = [apply_fill, gid_grow, merge_fill]`` names the
    offending tier (the row-granular merge has no kill or insert tiers).
    """
    n = mesh.devices.size
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]
    spec = P(AXIS)

    def step(local, slot, rows_b, op_b, key_b, valh_b, ts_b):
        local = _squeeze(local)
        applied = row_apply(
            local, slot[0], rows_b[0], op_b[0], key_b[0], valh_b[0], ts_b[0]
        )
        st = applied.state

        # 2. digest exchange: predecessor's leaves arrive here
        prev_leaf = jax.lax.ppermute(st.leaf, AXIS, fwd)
        diff = prev_leaf != st.leaf
        n_diff = jnp.sum(diff.astype(jnp.int32))
        # differing buckets first, ascending index (truncation beyond
        # the frontier is healed by later steps; n_diff reports it)
        order = flagged_first_order(diff, frontier)
        want = jnp.where(diff[order], order.astype(jnp.int32), -1)

        # 3. frontier request travels backward to the predecessor
        asked = jax.lax.ppermute(want, AXIS, bwd)

        # 4. predecessor gathers its rows; slice travels forward
        sl_local = extract_rows(st, asked)
        sl = jax.tree_util.tree_map(
            lambda x: jax.lax.ppermute(x, AXIS, fwd), sl_local
        )
        res = merge_rows(st, sl)
        root = tree_from_leaves(res.state.leaf)[0][0]
        ok = applied.ok & res.ok
        flags = jnp.stack([~applied.ok, res.need_gid_grow, res.need_fill_grow])
        return _unsqueeze(res.state), root[None], ok[None], n_diff[None], flags[None]

    return shard_map(
        step,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec, spec, spec),
        out_specs=(spec, spec, spec, spec, spec),
        check_vma=False,
    )(stacked, self_slot, rows, op, key, valh, ts)


def gossip_delta_drive(
    mesh: Mesh,
    stacked: BinnedStore,
    self_slot: jnp.ndarray,
    rows: jnp.ndarray,
    op: jnp.ndarray,
    key: jnp.ndarray,
    valh: jnp.ndarray,
    ts: jnp.ndarray,
    frontier: int = 64,
    on_grow=None,
    gather=None,
):
    """Host recovery loop around :func:`gossip_delta_step`: a failed step
    (any ``ok=False``) discards that step's states, grows the offending
    tier on the PRE-step states, and replays — mutation batches re-apply
    idempotently because the failed result was never kept. Growth policy:
    gid table ×2, bin capacity ×2 after one compact; each retier
    recompiles the step for the new shapes.

    ``gather`` (multi-controller meshes): a callable returning the FULL
    ``oks``/``flags`` arrays as host values — e.g.
    ``partial(multihost_utils.process_allgather, tiled=True)``. Without
    it ``np.asarray`` on a process-spanning array would fail; with it
    every controller sees identical values and takes the same
    grow/replay decisions, keeping the SPMD programs in lockstep.

    Returns ``(stacked, roots, n_diff, n_retiers)``.
    """
    import numpy as np

    retiers = 0
    while True:
        out, roots, oks, n_diff, flags = gossip_delta_step(
            mesh, stacked, self_slot, rows, op, key, valh, ts,
            frontier=frontier,
        )
        oks_h = np.asarray(gather(oks) if gather else oks)
        if bool(oks_h.all()):
            return out, roots, n_diff, retiers
        # gather flags only on the (rare) failure path — identical on
        # every controller, so the branch above stays in lockstep
        flags_h = np.asarray(gather(flags) if gather else flags)
        retiers += 1
        f = flags_h.any(axis=0)  # [3] any replica
        apply_fill, gid_grow, merge_fill = map(bool, f)
        if gid_grow:
            stacked = stacked.grow(replica_capacity=stacked.replica_capacity * 2)
            if on_grow:
                on_grow(stacked)
        if apply_fill or merge_fill:
            # no compact-first step: the row-granular merge reclaims holes
            # in-row, and row_apply counts dead slots as free — a fill
            # overflow is genuine, so go straight to growth
            stacked = stacked.grow(bin_capacity=stacked.bin_capacity * 2)
            if on_grow:
                on_grow(stacked)


@partial(jax.jit, static_argnames=("mesh",))
def gossip_train_step(
    mesh: Mesh,
    stacked: BinnedStore,
    self_slot: jnp.ndarray,  # int32[N]     each replica's own ctx slot
    rows: jnp.ndarray,  # int32[N, U]  bucket-grouped mutation batches
    op: jnp.ndarray,  # int32[N, U, M]   (see binned_map.group_batch)
    key: jnp.ndarray,  # uint64[N, U, M]
    valh: jnp.ndarray,  # uint32[N, U, M]
    ts: jnp.ndarray,  # int64[N, U, M]
):
    """One SPMD step: local mutation batch → ring ppermute → merge → roots.

    This is the framework's "training step" shape: per-device compute
    (row-local mutation kernels), one ICI collective (ppermute of the full
    state pytree), then shard-local lattice math. Returns the new stacked
    states, each replica's digest-tree root (uint32[N]) for convergence
    monitoring, and per-replica merge ``ok`` flags (bool[N]) — a False
    flag means that replica's merge overflowed a tier and its state for
    this step is invalid (callers must check; growth cannot happen
    inside the SPMD program).
    """
    n = mesh.devices.size
    perm = [(i, (i + 1) % n) for i in range(n)]
    spec = P(AXIS)

    def step(local, slot, rows_b, op_b, key_b, valh_b, ts_b):
        local = _squeeze(local)
        applied = row_apply(
            local, slot[0], rows_b[0], op_b[0], key_b[0], valh_b[0], ts_b[0]
        )
        received = jax.tree_util.tree_map(
            lambda x: jax.lax.ppermute(x, AXIS, perm), applied.state
        )
        all_rows = jnp.arange(applied.state.num_buckets, dtype=jnp.int32)
        sl = extract_rows(received, all_rows)
        res = merge_rows(applied.state, sl)
        root = tree_from_leaves(res.state.leaf)[0][0]
        # ok folds the mutation batch's bin-capacity flag too: a dropped
        # insert (scatter mode='drop') must be as loud as a merge overflow
        ok = applied.ok & res.ok
        return _unsqueeze(res.state), root[None], ok[None]

    return shard_map(
        step,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec, spec, spec),
        out_specs=(spec, spec, spec),
        check_vma=False,
    )(stacked, self_slot, rows, op, key, valh, ts)


def snapshot_mesh(stacked: BinnedStore) -> dict:
    """Device→host image of a mesh-stacked replica set (the SPMD analog
    of the replica Storage snapshot, SURVEY §5.4): one gathered pytree of
    numpy arrays plus the engine layout tag, suitable for pickling. The
    gather crosses the ICI/host boundary once per column."""
    import dataclasses as _dc

    import numpy as np

    from delta_crdt_ex_tpu.runtime.storage import CURRENT_LAYOUT

    return {
        "layout": CURRENT_LAYOUT,
        "arrays": {
            f.name: np.asarray(getattr(stacked, f.name))
            for f in _dc.fields(BinnedStore)
        },
    }


def restore_mesh(snap: dict, mesh: Mesh) -> BinnedStore:
    """Re-place a :func:`snapshot_mesh` image onto a mesh (same replica
    count; the device set may differ — elasticity across restarts)."""
    from delta_crdt_ex_tpu.runtime.storage import require_layout

    require_layout(snap.get("layout", "<untagged>"), "mesh snapshot")
    arrays = snap["arrays"]
    n = arrays["key"].shape[0]
    if mesh.devices.size != n:
        raise ValueError(
            f"snapshot holds {n} replicas but the mesh has "
            f"{mesh.devices.size} devices"
        )
    stacked = BinnedStore(**{k: jnp.asarray(v) for k, v in arrays.items()})
    return jax.device_put(stacked, replica_sharding(mesh))
