"""Batched neighbour anti-entropy — the vmapped fan-out axis.

The reference loops over neighbours one message at a time
(``causal_crdt.ex:264-283``); on TPU the neighbour axis becomes a batch
dimension (SURVEY §2.2): replica states are stacked on a leading axis and
one device call joins a delta into **all** neighbour states at once — the
BASELINE north-star's 64-neighbour fan-in. The same shape also batches a
whole gossip round among N chip-resident replicas (each joins its ring
predecessor) in one call.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from delta_crdt_ex_tpu.models.state import DotStore
from delta_crdt_ex_tpu.ops.join import JoinResult, join


def stack_states(states: list[DotStore]) -> DotStore:
    """Stack equally-shaped replica states on a leading neighbour axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)


def unstack_states(stacked: DotStore) -> list[DotStore]:
    n = stacked.key.shape[0]
    return [jax.tree_util.tree_map(lambda x: x[i], stacked) for i in range(n)]


def fanout_join(
    stacked: DotStore, delta: DotStore, bucket_mask: jnp.ndarray | None = None
) -> JoinResult:
    """Join one delta into N stacked neighbour states in one device call.

    The reference's per-neighbour sync loop, collapsed into a vmap: each
    neighbour performs its own context remap + dot-set join against the
    shared delta (states may know different replica sets — the remap is
    per-neighbour).
    """
    return jax.vmap(join, in_axes=(0, None, None))(stacked, delta, bucket_mask)


def ring_gossip_round(stacked: DotStore) -> JoinResult:
    """One full-state gossip round among N chip-resident replicas: replica
    i joins replica (i-1) mod N. One device call, N joins."""
    rolled = jax.tree_util.tree_map(lambda x: jnp.roll(x, 1, axis=0), stacked)
    return jax.vmap(join, in_axes=(0, 0, None))(stacked, rolled, None)


jit_fanout_join = jax.jit(fanout_join)
jit_ring_gossip_round = jax.jit(ring_gossip_round)
