"""Batched neighbour anti-entropy — the vmapped fan-out axis.

The reference loops over neighbours one message at a time
(``causal_crdt.ex:264-283``); on TPU the neighbour axis becomes a batch
dimension (SURVEY §2.2): replica states are stacked on a leading axis and
one device call merges a delta slice into **all** neighbour states at
once — the BASELINE north-star's 64-neighbour fan-in. The same shape also
batches a whole gossip round among N chip-resident replicas (each merges
its ring predecessor's full-row slice) in one call.

Cost models per path: ``fanout_merge`` uses the element-scatter merge
(O(slice entries) per neighbour — the bench's sparse 8192-row delta
groups); ``ring_gossip_round`` merges FULL states (O(L·B) per replica
per round — the simple whole-state flavour; the bounded-divergence
``gossip_delta_step`` in :mod:`.mesh_gossip` is the O(divergence)
alternative).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from delta_crdt_ex_tpu.models.binned import BinnedStore, pow2_tier
from delta_crdt_ex_tpu.models.binned_map import tier_retry_merge
from delta_crdt_ex_tpu.ops.binned import (
    MergeResult,
    MergeRowsResult,
    RowSlice,
    compact_rows,
    extract_rows,
    merge_rows,
    merge_slice,
)
from delta_crdt_ex_tpu.ops.packed import (
    PackedStore,
    compact_rows_packed,
    merge_slice_packed,
    pack,
)


def stack_states(states: list[BinnedStore]) -> BinnedStore:
    """Stack equally-shaped replica states on a leading neighbour axis
    (layout-agnostic: works on column and packed stores alike)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)


def unstack_states(stacked: BinnedStore) -> list[BinnedStore]:
    n = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    return [jax.tree_util.tree_map(lambda x: x[i], stacked) for i in range(n)]


@partial(jax.jit, static_argnames=("kill_budget", "max_inserts"))
def fanout_merge(
    stacked: BinnedStore,
    sl: RowSlice,
    kill_budget: int = 64,
    max_inserts: int | None = None,
) -> MergeResult:
    """Merge one slice into N stacked neighbour states in one device call.

    The reference's per-neighbour sync loop, collapsed into a vmap: each
    neighbour performs its own gid remap + interval join against the
    shared slice (states may know different replica sets — the remap is
    per-neighbour).
    """
    return jax.vmap(merge_slice, in_axes=(0, None, None, None))(
        stacked, sl, kill_budget, max_inserts
    )


@partial(
    jax.jit,
    static_argnames=("kill_budget", "max_inserts", "scatter_compact", "rows_sorted"),
)
def fanout_merge_packed(
    stacked: PackedStore,
    sl: RowSlice,
    kill_budget: int = 64,
    max_inserts: int | None = None,
    scatter_compact: bool = True,
    rows_sorted: bool = False,
) -> MergeResult:
    """:func:`fanout_merge` over the packed entry layout — the chip-
    measured fast path (north-star A/B on TPU v5e: packed 8,852.8 vs
    columns 4,211.9 merges/s; BASELINE.md "Merge-kernel roofline"). Same
    per-neighbour remap + interval-join semantics, one ``[k, 8]`` vector
    scatter per neighbour instead of 7 scalar-column scatters.

    ``scatter_compact`` (the promoted default — CPU full-config 1,060 →
    2,024 merges/s over the top_k path; ``False`` restores top_k insert
    compaction for A/Bs) replaces the per-neighbour top_k with the
    cumsum+scatter form. ``rows_sorted=True`` vouches the slice's valid
    rows are strictly ascending, unlocking that path's scatter hints —
    see :func:`~delta_crdt_ex_tpu.ops.packed.merge_slice_packed_scomp`;
    a false claim is XLA UB, so the default stays off."""
    fn = partial(
        merge_slice_packed,
        scatter_compact=scatter_compact,
        rows_sorted=rows_sorted,
    )
    return jax.vmap(fn, in_axes=(0, None, None, None))(
        stacked, sl, kill_budget, max_inserts
    )


jit_fanout_compact = jax.jit(jax.vmap(compact_rows))
jit_fanout_compact_packed = jax.jit(jax.vmap(compact_rows_packed))


def fanout_merge_into(
    stacked: BinnedStore | PackedStore,
    sl: RowSlice,
    kill_budget: int = 16,
    on_grow=None,
    n_alive: int | None = None,
    scatter_compact: bool | None = None,
    rows_sorted: bool = False,
):
    """The vmapped analog of ``merge_into``: merge one slice into N
    stacked neighbour states, escalating tiers via the shared
    :func:`~delta_crdt_ex_tpu.models.binned_map.tier_retry_merge` policy.
    Tiers are uniform across the stack (a grow applies to all N states),
    so a single overflowing neighbour retiers everyone — the price of
    the one-call fan-out; each retier is one fresh jit compile.

    Accepts either layout: pass a :class:`PackedStore` stack (see
    :func:`pack_states`) to run the chip-measured fast path; growth and
    compaction escalate through the same tier policy on both.
    ``scatter_compact`` selects the top_k-free insert compaction —
    packed-only (the column kernel has no such variant), and the
    PROMOTED default there (``None`` → on for packed stacks, off for
    column stacks); passing ``True`` on a column stack raises so an A/B
    can't silently time the wrong kernel. ``rows_sorted=True`` vouches
    ascending valid slice rows (scatter-hint fast path; false claims
    are XLA UB — see :func:`fanout_merge_packed`).

    Returns ``(stacked, last_result, n_retries)``."""
    if n_alive is None:
        n_alive = int(np.asarray(sl.alive).sum())
    packed = isinstance(stacked, PackedStore)
    if scatter_compact is None:
        scatter_compact = packed
    if scatter_compact and not packed:
        raise TypeError(
            "scatter_compact=True requires a PackedStore stack "
            "(pack_states); the column kernel has no scomp variant"
        )
    if packed:
        merge = partial(
            fanout_merge_packed,
            scatter_compact=scatter_compact,
            rows_sorted=rows_sorted,
        )
    else:
        merge = fanout_merge
    return tier_retry_merge(
        stacked,
        sl,
        merge,
        jit_fanout_compact_packed if packed else jit_fanout_compact,
        kill_budget,
        pow2_tier(max(n_alive, 1)),
        on_grow=on_grow,
    )


def pack_states(stacked: BinnedStore) -> PackedStore:
    """Column → packed layout for a neighbour stack (``pack`` is rank-
    agnostic, this alias just names the fan-out-side entry point)."""
    return pack(stacked)


@jax.jit
def ring_gossip_round(stacked: BinnedStore) -> MergeRowsResult:
    """One full-state gossip round among N chip-resident replicas: replica
    i merges replica (i-1) mod N's full-row slice (row-granular merge —
    no kill/insert tiers). One device call, N merges."""
    rolled = jax.tree_util.tree_map(lambda x: jnp.roll(x, 1, axis=0), stacked)
    all_rows = jnp.arange(stacked.num_buckets, dtype=jnp.int32)
    slices = jax.vmap(extract_rows, in_axes=(0, None))(rolled, all_rows)
    return jax.vmap(merge_rows)(stacked, slices)
