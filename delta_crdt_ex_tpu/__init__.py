"""delta_crdt_ex_tpu — a TPU-native delta-CRDT framework.

A ground-up JAX/XLA re-design of the capabilities of the Elixir library
``delta_crdt`` v0.5.10 (reference: /root/reference — Almeida et al. 2016
anti-entropy Algorithm 2 + Enes et al. 2018 join decomposition, see
reference ``lib/delta_crdt.ex:9``).

Architecture (TPU-first, NOT a translation of the actor design):

- **Lattice** (:mod:`delta_crdt_ex_tpu.models.binned_map`): the replica's
  dot store lives in HBM as a bucket-binned struct-of-arrays tensor state;
  join / LWW read / batched mutation are fused row-local XLA kernels
  (:mod:`delta_crdt_ex_tpu.ops.binned`). The superseded flat engine
  (:mod:`delta_crdt_ex_tpu.models.aw_lww_map`) survives only as the
  cross-validation oracle in the lattice property tests.
- **Sync index** (:mod:`delta_crdt_ex_tpu.ops.hashtree`): the merkle tree
  becomes a device-resident digest tree with commutative per-bucket
  digests; the reference's continuation ping-pong becomes a
  bounded-frontier level walk (static shapes, cost ∝ divergence).
- **Runtime** (:mod:`delta_crdt_ex_tpu.runtime`): host-side replica
  drivers issue compiled kernel calls; anti-entropy scheduling, ack
  bookkeeping, failure detection, storage and telemetry mirror the
  reference's capability surface (``causal_crdt.ex``).
- **Parallel** (:mod:`delta_crdt_ex_tpu.parallel`): neighbour fan-out is
  a vmapped batch axis (one device call syncs all neighbours); multi-chip
  replication rides ``shard_map`` + collectives over a ``jax.sharding.Mesh``.

64-bit integers (key hashes, dot ids, timestamps) are first-class in this
framework, so x64 is enabled at import. All arrays use explicit dtypes;
nothing relies on default-dtype promotion.
"""

import jax

jax.config.update("jax_enable_x64", True)

__version__ = "0.4.0"

__all__ = [
    "AWLWWMap",
    "AWSet",
    "DeltaCrdt",
    "HashAWLWWMap",
    "HashAWSet",
    "FileStorage",
    "Fleet",
    "FleetFrontdoor",
    "Frontdoor",
    "MemoryStorage",
    "Observability",
    "ObsServer",
    "Overloaded",
    "Replica",
    "Storage",
    "WalLog",
    "child_spec",
    "frontdoor",
    "mutate",
    "mutate_async",
    "mutate_batch",
    "read",
    "set_neighbours",
    "start_fleet",
    "start_link",
]

# PEP 562 lazy exports: several modules build jnp constants at import
# time, which initialises the XLA backend — too early for helpers like
# utils.devices.force_cpu_devices that must run before first backend
# init. Resolving the public surface on first attribute access keeps
# `import delta_crdt_ex_tpu` backend-free.
_EXPORTS = {
    "AWLWWMap": ("delta_crdt_ex_tpu.models.binned_map", "BinnedAWLWWMap"),
    "AWSet": ("delta_crdt_ex_tpu.models.binned_map", "AWSet"),
    "DeltaCrdt": ("delta_crdt_ex_tpu.api", "DeltaCrdt"),
    "HashAWLWWMap": ("delta_crdt_ex_tpu.models.hash_store", "HashAWLWWMap"),
    "HashAWSet": ("delta_crdt_ex_tpu.models.hash_store", "HashAWSet"),
    "Fleet": ("delta_crdt_ex_tpu.runtime.fleet", "Fleet"),
    "FleetFrontdoor": ("delta_crdt_ex_tpu.runtime.serve", "FleetFrontdoor"),
    "Frontdoor": ("delta_crdt_ex_tpu.runtime.serve", "Frontdoor"),
    "MemoryStorage": ("delta_crdt_ex_tpu.runtime.storage", "MemoryStorage"),
    "Observability": ("delta_crdt_ex_tpu.runtime.metrics", "Observability"),
    "ObsServer": ("delta_crdt_ex_tpu.runtime.obs_server", "ObsServer"),
    "Overloaded": ("delta_crdt_ex_tpu.runtime.serve", "Overloaded"),
    "FileStorage": ("delta_crdt_ex_tpu.runtime.storage", "FileStorage"),
    "Replica": ("delta_crdt_ex_tpu.runtime.replica", "Replica"),
    "Storage": ("delta_crdt_ex_tpu.runtime.storage", "Storage"),
    "WalLog": ("delta_crdt_ex_tpu.runtime.wal", "WalLog"),
    "child_spec": ("delta_crdt_ex_tpu.api", "child_spec"),
    "frontdoor": ("delta_crdt_ex_tpu.api", "frontdoor"),
    "mutate": ("delta_crdt_ex_tpu.api", "mutate"),
    "mutate_async": ("delta_crdt_ex_tpu.api", "mutate_async"),
    "mutate_batch": ("delta_crdt_ex_tpu.api", "mutate_batch"),
    "read": ("delta_crdt_ex_tpu.api", "read"),
    "set_neighbours": ("delta_crdt_ex_tpu.api", "set_neighbours"),
    "start_fleet": ("delta_crdt_ex_tpu.api", "start_fleet"),
    "start_link": ("delta_crdt_ex_tpu.api", "start_link"),
}


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))


def __getattr__(name):
    import importlib

    try:
        module, attr = _EXPORTS[name]
    except KeyError:
        try:
            value = importlib.import_module(f"{__name__}.{name}")
        except ModuleNotFoundError as e:
            if e.name != f"{__name__}.{name}":
                raise  # real failure inside an existing submodule
            raise AttributeError(
                f"module {__name__!r} has no attribute {name!r}"
            ) from None
    else:
        value = getattr(importlib.import_module(module), attr)
    globals()[name] = value
    return value
