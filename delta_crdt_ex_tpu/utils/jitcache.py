"""Compile-cache audit for named jit entry roots (ISSUE 12).

The batched-fleet design only pays off while every hot dispatch hits a
warm XLA cache: the shape-bucketing/padding discipline (pow2/pow4 lane
tiers, geometry keys) exists precisely so the distinct-compile count
per entry root stays bounded by the distinct bucket-geometry count.
crdtlint's SHAPE family enforces that discipline *statically*; this
module is the *runtime* half of the cross-check — every hot jit entry
root is created through :func:`named_jit`, which registers the jitted
callable in a process-wide table, and :func:`compile_counts` reads each
root's tracing-cache size (one cache entry per distinct operand
geometry / static-arg combination, i.e. per XLA compile).

The audit surfaces three ways:

- ``crdt_jit_compiles_total{name=...}`` through the metrics bridge
  (:func:`audit` emits ``JIT_COMPILE`` telemetry for roots whose count
  moved; the observability plane runs the audit as a scrape-time
  collector) and the ``/varz`` ``jitcache`` source;
- ``bench.py --ingest`` / ``--fleet`` gate IN-RUN on **zero
  steady-state compiles after warmup** per shape bucket;
- ``tests/test_jitcache.py`` drives a fleet through mixed-occupancy
  tick cycles and asserts the compile count is bounded by the distinct
  bucket-geometry count.

``named_jit`` returns the ``jax.jit`` product unchanged (zero call
overhead — the registry holds a reference, it does not wrap). crdtlint
recognises ``named_jit(fn, ...)`` as a SYNC001 jit entry exactly like
``jax.jit(fn)``.
"""

from __future__ import annotations

import threading

import jax

_lock = threading.Lock()
#: root name -> jitted callable (insertion = module import order)
_roots: dict[str, object] = {}


def named_jit(fn, *, name: str | None = None, **jit_kw):
    """``jax.jit`` + registration under ``name`` (default: the
    function's ``__name__``) for the compile-cache audit. Keyword
    arguments pass through to ``jax.jit`` unchanged."""
    jitted = jax.jit(fn, **jit_kw)
    register(name or getattr(fn, "__name__", repr(fn)), jitted)
    return jitted


def register(name: str, jitted) -> None:
    """Register an already-jitted callable (lazily built kernel tables
    like ``models/hash_store._Jit`` register here on first use).

    A name collision with a DIFFERENT callable raises: silently
    evicting the earlier root would blind the compile-cache audit (and
    the bench zero-steady-state gates) to every recompile of whichever
    object keeps being dispatched. Re-registering the same object is
    idempotent."""
    with _lock:
        prior = _roots.get(name)
        if prior is not None and prior is not jitted:
            raise ValueError(
                f"jitcache: root name {name!r} already registered to a "
                f"different jitted callable — pass an explicit unique "
                f"name= to named_jit"
            )
        _roots[name] = jitted


def _cache_size_of(jitted) -> int | None:
    """Tracing-cache entry count of one jitted callable — one entry per
    compiled executable (distinct shapes/dtypes/static args). ``None``
    when the jax build does not expose the counter."""
    probe = getattr(jitted, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:
        return None


def supported() -> bool:
    """Does this jax build expose per-function tracing-cache sizes?
    (The bench gates assert this so an unsupported build cannot turn
    the zero-steady-state-compiles gate vacuously green.)"""
    return _cache_size_of(jax.jit(lambda: 0)) is not None


def compile_counts() -> dict[str, int]:
    """``{root name: compiles so far}`` for every registered root, in
    sorted name order. Roots whose cache size cannot be read are
    omitted (so a gate diffing two snapshots never sees phantom
    deltas)."""
    with _lock:
        items = sorted(_roots.items())
    out: dict[str, int] = {}
    for name, jitted in items:
        n = _cache_size_of(jitted)
        if n is not None:
            out[name] = n
    return out


def total_compiles() -> int:
    return sum(compile_counts().values())


def audit() -> dict[str, int]:
    """Read every root's compile count and emit ``JIT_COMPILE``
    telemetry carrying the ABSOLUTE per-root count — the metrics
    bridge's subscription row folds those into
    ``crdt_jit_compiles_total{name=...}`` with an idempotent gauge set,
    so any number of planes (each with its own registry) can audit
    independently and a bridge attaching mid-process still exports the
    true totals. The observability plane runs this as a scrape-time
    collector; the bench gates call it around their timed phases.
    Returns the current counts."""
    # deferred import: this module sits below the model layer in the
    # import graph (models register their kernels here at import time),
    # so a top-level runtime import would cycle through runtime/__init__
    from delta_crdt_ex_tpu.runtime import telemetry

    counts = compile_counts()
    if not telemetry.has_handlers(telemetry.JIT_COMPILE):
        return counts
    for name, n in counts.items():
        telemetry.execute(
            telemetry.JIT_COMPILE, {"compiles": n}, {"name": name}
        )
    return counts


def varz() -> dict:
    """``/varz`` source: the audit's unified snapshot envelope."""
    return {"kind": "jitcache", "stats": {"compiles": compile_counts()}}
