"""Stable, replica-independent hashing of arbitrary Python terms.

The reference supports arbitrary Elixir terms as keys and values
(``aw_lww_map.ex:99-112``; ``README.md:39`` warns only about atom leakage).
On TPU the device sees only fixed-width hashes/ids, so the host must map
terms to integers **deterministically across replicas and hosts**: when two
replicas independently write the same key, the device-side key ids must
collide (same 64-bit hash → same bucket → same LWW group).

We canonically encode terms (type-tagged, recursive, order-normalised for
sets/dicts) and hash with BLAKE2b. Key ids are 64 bits (birthday bound ~2^32
keys — fine for the 1M-key north star); value hashes are 32 bits and are only
used for digest/equality hints, never for value identity (values travel by
dot, see ``runtime/replica.py``).
"""

from __future__ import annotations

import pickle
import struct
from hashlib import blake2b

_TAG_NONE = b"\x00"
_TAG_TRUE = b"\x01"
_TAG_FALSE = b"\x02"
_TAG_INT = b"\x03"
_TAG_FLOAT = b"\x04"
_TAG_STR = b"\x05"
_TAG_BYTES = b"\x06"
_TAG_TUPLE = b"\x07"
_TAG_LIST = b"\x08"
_TAG_SET = b"\x09"
_TAG_DICT = b"\x0a"
_TAG_PICKLE = b"\x0b"


def canonical_bytes(term) -> bytes:
    """Deterministic byte encoding of a Python term.

    Containers are encoded recursively; sets and dicts are normalised by
    sorting their encoded elements so iteration order cannot leak in.
    Unknown types fall back to pickle (deterministic within one Python
    version for most types; documented caveat, mirroring the reference's
    own "arbitrary term" looseness).
    """
    t = type(term)
    if term is None:
        return _TAG_NONE
    if t is bool:
        return _TAG_TRUE if term else _TAG_FALSE
    if t is int:
        raw = term.to_bytes((term.bit_length() + 8) // 8 or 1, "big", signed=True)
        return _TAG_INT + struct.pack(">I", len(raw)) + raw
    if t is float:
        return _TAG_FLOAT + struct.pack(">d", term)
    if t is str:
        raw = term.encode("utf-8")
        return _TAG_STR + struct.pack(">I", len(raw)) + raw
    if t is bytes:
        return _TAG_BYTES + struct.pack(">I", len(term)) + term
    if t is tuple or t is list:
        tag = _TAG_TUPLE if t is tuple else _TAG_LIST
        parts = [canonical_bytes(x) for x in term]
        return tag + struct.pack(">I", len(parts)) + b"".join(parts)
    if t is set or t is frozenset:
        parts = sorted(canonical_bytes(x) for x in term)
        return _TAG_SET + struct.pack(">I", len(parts)) + b"".join(parts)
    if t is dict:
        parts = sorted(
            canonical_bytes(k) + canonical_bytes(v) for k, v in term.items()
        )
        return _TAG_DICT + struct.pack(">I", len(parts)) + b"".join(parts)
    raw = pickle.dumps(term, protocol=4)
    return _TAG_PICKLE + struct.pack(">I", len(raw)) + raw


def key_hash64(term) -> int:
    """64-bit key id. Replicas agree on this without coordination."""
    d = blake2b(canonical_bytes(term), digest_size=8).digest()
    h = int.from_bytes(d, "big")
    return h or 1  # 0 is reserved as the empty-slot sentinel


def value_hash32(term) -> int:
    """32-bit value digest (content hint for the sync index)."""
    d = blake2b(canonical_bytes(term), digest_size=4).digest()
    return int.from_bytes(d, "big")


def key_hash64_batch(terms: list):
    """uint64 key ids for a term batch — native batch hasher when built
    (bit-identical to the hashlib path, enforced by tests/test_native.py),
    per-term hashlib otherwise."""
    import numpy as np

    from delta_crdt_ex_tpu import native

    blobs = [canonical_bytes(t) for t in terms]
    out = native.hash64_batch(blobs)
    if out is None:
        out = np.empty(len(terms), np.uint64)
        for i, b in enumerate(blobs):
            out[i] = int.from_bytes(blake2b(b, digest_size=8).digest(), "big") or 1
    return out


def value_hash32_batch(terms: list):
    """uint32 value digests for a term batch (see key_hash64_batch)."""
    import numpy as np

    from delta_crdt_ex_tpu import native

    blobs = [canonical_bytes(t) for t in terms]
    out = native.hash32_batch(blobs)
    if out is None:
        out = np.empty(len(terms), np.uint32)
        for i, b in enumerate(blobs):
            out[i] = int.from_bytes(blake2b(b, digest_size=4).digest(), "big")
    return out
