"""Deterministic fault-point injection (crdtlint v6, FAULT family runtime half).

The anti-entropy algorithm is only correct if replicas survive
crash-recovery, message loss, and mid-commit failure without tearing
the seq/WAL/state/ack invariants — and "survives" is only evidence
when the failure can be *reproduced*. This module is the
transfer-ledger pattern applied to failure paths: every interesting
failure boundary in the runtime (commit tails, WAL append/fsync/roll,
transport send/recv, thread-loop tops) is a **labelled fault point** —
a :func:`faultpoint` call whose label comes from the closed
:data:`SITES` vocabulary — and a seeded :class:`FaultPlan`
deterministically trips raise / delay / partial-write / crash-before /
crash-after at the Nth hit of a labelled site. crdtlint FAULT005 makes
label hygiene static (non-literal labels, collisions, ghost vocabulary
entries red), exactly the way TRANSFER002 guards the transfer ledger.

Zero overhead when disarmed: :func:`faultpoint` is one module-global
load and an ``is None`` compare — no lock, no dict lookup, no
allocation. Bench gates (``bench.py --ingest``) hold the disabled hot
path to its existing numbers; ``bench.py --chaos`` is the armed
consumer (seeded kill+recover schedules converging to canonical
bit-parity with a fault-free twin).

Trips are counted per site and exported two ways: :func:`trips` for
chaos drivers diffing schedules, and ``FAULT_TRIP`` telemetry (emitted
per trip, only when a handler is attached) which the metrics bridge
folds into ``crdt_fault_trips_total{site=...}``.
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager

#: the closed fault-point vocabulary. One label == one call site
#: (crdtlint FAULT005: a non-literal label, a label used from two call
#: sites, or a vocabulary entry with no call site is red) — so a chaos
#: schedule naming a site pins exactly one program point.
SITES = (
    "fleet.loop",
    "replica.commit.batch",
    "replica.commit.entries",
    "replica.durable",
    "replica.loop",
    "replica.relay.flush",
    "transport.recv",
    "transport.send",
    "wal.append",
    "wal.fsync",
    "wal.rotate",
    "wal.write",
)

#: actions a :class:`FaultRule` may take at its Nth hit
ACTIONS = ("raise", "delay", "crash_before", "crash_after", "partial_write")


class FaultError(Exception):
    """Common base of injected failures (so harnesses can catch both)."""


class FaultInjected(FaultError):
    """A transient injected failure: the component is expected to
    surface it to its caller and stay recoverable in-process."""


class CrashInjected(FaultError):
    """A process-death injection: the chaos driver catches this and
    kills the replica (``Replica.crash()``) — nothing in the runtime
    may swallow it."""


class FaultRule:
    """Trip ``action`` at the ``nth`` hit of ``site`` (1-based)."""

    __slots__ = ("site", "nth", "action", "arg", "fired")

    def __init__(self, site: str, nth: int, action: str, arg=None):
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r}; pick one of {SITES}")
        if action not in ACTIONS:
            raise ValueError(f"unknown fault action {action!r}; pick one of {ACTIONS}")
        if nth < 1:
            raise ValueError(f"nth must be >= 1, got {nth}")
        self.site = site
        self.nth = int(nth)
        self.action = action
        self.arg = arg
        self.fired = False

    def __repr__(self) -> str:
        return f"FaultRule({self.site!r}, nth={self.nth}, action={self.action!r})"


class FaultPlan:
    """A deterministic fault schedule: an ordered rule list plus the
    per-site hit counters it consumes. Each rule fires at most once —
    re-arming the same plan object resets its counters, so a seed
    replays the identical schedule."""

    def __init__(self, rules, seed: int = 0):
        self.seed = int(seed)
        self.rules = [
            r if isinstance(r, FaultRule) else FaultRule(*r) for r in rules
        ]
        self.hits: dict[str, int] = {}
        #: site label that armed a pending crash-after (the NEXT hit of
        #: ANY site raises — the points sit at every boundary, so "next
        #: hit" is "immediately after the guarded operation")
        self.pending_crash: str | None = None

    @classmethod
    def seeded(
        cls,
        seed: int,
        sites=None,
        n_rules: int = 3,
        window: tuple = (1, 24),
        actions=("raise", "crash_before", "crash_after", "delay"),
    ) -> "FaultPlan":
        """Mint a deterministic plan from a seed: ``n_rules`` rules over
        ``sites``, each at a hit count drawn from ``window``. The same
        seed always yields the same schedule (the chaos bench's replay
        contract)."""
        rng = random.Random(seed)
        sites = list(sites if sites is not None else SITES)
        rules = []
        for _ in range(n_rules):
            site = rng.choice(sites)
            action = rng.choice(tuple(actions))
            rules.append(FaultRule(site, rng.randint(*window), action))
        return cls(rules, seed=seed)

    def reset(self) -> None:
        self.hits.clear()
        self.pending_crash = None
        for r in self.rules:
            r.fired = False

    def exhausted(self) -> bool:
        """True when every rule has fired (a chaos leg may pump until
        the whole schedule has been delivered)."""
        return self.pending_crash is None and all(r.fired for r in self.rules)


_lock = threading.Lock()
#: the armed plan. ``faultpoint`` reads this WITHOUT the lock — a plain
#: global load — so the disarmed hot path pays one compare; arming /
#: disarming happens on the chaos driver's thread and publication of
#: the object is an atomic reference store.
_plan: "FaultPlan | None" = None
#: per-site trip totals (monotone across plans — the telemetry export)
_trips: dict[str, int] = {}


def arm(plan: FaultPlan) -> FaultPlan:
    """Install ``plan`` (resetting its counters) and return it."""
    global _plan
    with _lock:
        plan.reset()
        _plan = plan
    return plan


def disarm() -> None:
    global _plan
    with _lock:
        _plan = None


@contextmanager
def armed(plan: FaultPlan):
    """``with faults.armed(plan):`` — scoped arming for tests/benches."""
    arm(plan)
    try:
        yield plan
    finally:
        disarm()


@contextmanager
def suspended():
    """Temporarily disarm WITHOUT resetting counters — chaos drivers
    wrap crash-recovery in this so replaying the WAL (which walks the
    same commit/append code paths) does not consume schedule hits, and
    the plan resumes exactly where it left off."""
    global _plan
    with _lock:
        plan, _plan = _plan, None
    try:
        yield plan
    finally:
        with _lock:
            _plan = plan


def active() -> "FaultPlan | None":
    return _plan


def trips() -> dict:
    """``{site: trip_count}`` across every plan ever armed, sorted —
    the ledger image chaos drivers diff and ``varz`` surfaces."""
    with _lock:
        return dict(sorted(_trips.items()))


def _record_trip(site: str) -> None:
    with _lock:
        _trips[site] = _trips.get(site, 0) + 1
    # deferred import: utils sits below the runtime layer (runtime
    # modules call faultpoint at import-adjacent paths), so a top-level
    # runtime import would cycle through runtime/__init__
    from delta_crdt_ex_tpu.runtime import telemetry

    if telemetry.has_handlers(telemetry.FAULT_TRIP):
        telemetry.execute(
            telemetry.FAULT_TRIP, {"trips": 1}, {"site": site}
        )


def faultpoint(label: str):
    """One labelled fault point. Disarmed: a global load + compare.
    Armed: count the hit and trip any rule scheduled for it.

    Returns ``None`` normally. A ``partial_write`` trip returns the
    rule's fraction (0 < f < 1) instead of raising — the WAL's write
    path is the cooperating consumer: it writes that fraction of its
    staged bytes and raises :class:`CrashInjected` itself, minting a
    deterministic torn tail for the recovery legs."""
    # crdtlint: allow[RACE001]  lock-free by design: a stale None read
    # only delays arming by one call — the disarmed fast path must stay
    # a single global load so production pays nothing for fault hooks
    plan = _plan
    if plan is None:
        return None
    return _trip(plan, label)


def _trip(plan: FaultPlan, label: str):
    crashed_site = None
    with _lock:
        if plan.pending_crash is not None:
            crashed_site = plan.pending_crash
            plan.pending_crash = None
    if crashed_site is not None:
        _record_trip(crashed_site)
        raise CrashInjected(
            f"crash_after armed at {crashed_site!r}, tripped at {label!r}"
        )
    with _lock:
        n = plan.hits.get(label, 0) + 1
        plan.hits[label] = n
        rule = None
        for r in plan.rules:
            if not r.fired and r.site == label and r.nth == n:
                rule = r
                r.fired = True
                break
    if rule is None:
        return None
    if rule.action == "crash_after":
        # arm only — the trip is recorded when the pending crash fires
        with _lock:
            plan.pending_crash = label
        return None
    _record_trip(label)
    if rule.action == "raise":
        raise FaultInjected(f"injected failure at {label!r} (hit {rule.nth})")
    if rule.action == "crash_before":
        raise CrashInjected(f"injected crash before {label!r} (hit {rule.nth})")
    if rule.action == "delay":
        time.sleep(float(rule.arg) if rule.arg is not None else 0.002)
        return None
    # partial_write: hand the fraction to the cooperating caller
    frac = float(rule.arg) if rule.arg is not None else 0.5
    return min(max(frac, 0.01), 0.99)


def varz() -> dict:
    """``/varz`` source: the fault-injection trip ledger."""
    return {"kind": "faults", "armed": _plan is not None, "trips": trips()}
