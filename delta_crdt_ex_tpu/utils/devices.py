"""Virtual-device forcing for CPU-hosted multi-chip validation.

Multi-chip shardings (`parallel/`) are validated without TPU hardware by
running on ``n`` virtual CPU devices. Two environment quirks make this
non-trivial (and worth centralising):

- the ambient image's boot hook pins ``jax_platforms`` ahead of env
  vars, so ``JAX_PLATFORMS=cpu`` alone is ignored;
- ``XLA_FLAGS`` is parsed once at FIRST backend initialisation, so the
  device count must be forced before any ``jax.devices()`` call.
"""

from __future__ import annotations

import os
import re

_FLAG = "xla_force_host_platform_device_count"


def forced_device_count() -> int | None:
    """The virtual host-device count requested via ``XLA_FLAGS``, or
    None when the flag is absent — the public read-side of the flag this
    module owns (callers must not parse ``XLA_FLAGS`` themselves)."""
    m = re.search(rf"--{_FLAG}=(\d+)", os.environ.get("XLA_FLAGS", ""))
    return int(m.group(1)) if m else None


def backend_initialised(default: bool = True) -> bool:
    """Whether any XLA backend has been created in this process.

    Probes a jax-internal cache; ``default`` is returned if the private
    API moves in a future jax version.
    """
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        return default


def pin_cpu_platform() -> None:
    """Pin this process (and its children) to the CPU platform.

    Both quirks from the module docstring in one place: the env vars
    alone are NOT enough on the ambient image (the boot hook pins
    ``jax_platforms`` ahead of them), and the config update alone does
    not propagate to subprocesses — so set both, and clear the pool
    address so the accelerator boot hook never fires either way.
    Call before the first backend initialisation."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""

    import jax

    jax.config.update("jax_platforms", "cpu")


def force_cpu_devices(n: int) -> None:
    """Force jax onto the CPU platform with ``n`` virtual devices.

    Must run before the first backend initialisation: XLA parses the
    host-device count exactly once, so afterwards the count cannot
    change in-process (jax itself raises on the config update) and this
    raises rather than silently yielding the wrong mesh size.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if _FLAG in flags:
        flags = re.sub(rf"--{_FLAG}=\d+", rf"--{_FLAG}={n}", flags)
    else:
        flags = f"{flags} --{_FLAG}={n}".strip()
    os.environ["XLA_FLAGS"] = flags
    pin_cpu_platform()
    if not backend_initialised(default=True):  # unknown — verify via reset
        return

    # A backend may predate the settings above: reset and re-check. XLA
    # parses the host-device count once per process, so if a previous
    # backend consumed the old value the recreated one keeps it — raise
    # rather than hand back a silently wrong mesh size.
    import jax.extend.backend

    jax.extend.backend.clear_backends()
    devices = jax.devices()
    if len(devices) < n or devices[0].platform != "cpu":
        raise RuntimeError(
            f"force_cpu_devices({n}) called after the XLA backend was already "
            f"initialised ({len(devices)} {devices[0].platform} device(s)); "
            "the host device count is parsed once per process. Call this "
            "before any jax operation (or run in a fresh process)."
        )


def enable_compilation_cache(path: str | None = None) -> str:
    """Point JAX's persistent compilation cache at a repo-local directory
    so repeated runs (benches, test sessions, a bench retry after a
    wedged device claim) reuse compiled executables instead of paying
    the compile again — critical on remote-compile backends where one
    compile can cost minutes. Returns the cache directory used."""
    import os

    import jax

    if path is None:
        path = os.environ.get(
            "DELTA_CRDT_JAX_CACHE",
            os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), ".jax_cache"),
        )
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    return path
