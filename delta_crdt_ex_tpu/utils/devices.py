"""Virtual-device forcing for CPU-hosted multi-chip validation.

Multi-chip shardings (`parallel/`) are validated without TPU hardware by
running on ``n`` virtual CPU devices. Two environment quirks make this
non-trivial (and worth centralising):

- the ambient image's boot hook pins ``jax_platforms`` ahead of env
  vars, so ``JAX_PLATFORMS=cpu`` alone is ignored;
- ``XLA_FLAGS`` is parsed once at FIRST backend initialisation, so the
  device count must be forced before any ``jax.devices()`` call.
"""

from __future__ import annotations

import os
import re

_FLAG = "xla_force_host_platform_device_count"


def forced_device_count() -> int | None:
    """The virtual host-device count requested via ``XLA_FLAGS``, or
    None when the flag is absent — the public read-side of the flag this
    module owns (callers must not parse ``XLA_FLAGS`` themselves)."""
    m = re.search(rf"--{_FLAG}=(\d+)", os.environ.get("XLA_FLAGS", ""))
    return int(m.group(1)) if m else None


def backend_initialised(default: bool = True) -> bool:
    """Whether any XLA backend has been created in this process.

    Probes a jax-internal cache; ``default`` is returned if the private
    API moves in a future jax version.
    """
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        return default


def pin_cpu_platform() -> None:
    """Pin this process (and its children) to the CPU platform.

    Both quirks from the module docstring in one place: the env vars
    alone are NOT enough on the ambient image (the boot hook pins
    ``jax_platforms`` ahead of them), and the config update alone does
    not propagate to subprocesses — so set both, and clear the pool
    address so the accelerator boot hook never fires either way.
    Call before the first backend initialisation."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""

    import jax

    jax.config.update("jax_platforms", "cpu")


def force_cpu_devices(n: int) -> None:
    """Force jax onto the CPU platform with ``n`` virtual devices.

    Must run before the first backend initialisation: XLA parses the
    host-device count exactly once, so afterwards the count cannot
    change in-process (jax itself raises on the config update) and this
    raises rather than silently yielding the wrong mesh size.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if _FLAG in flags:
        flags = re.sub(rf"--{_FLAG}=\d+", rf"--{_FLAG}={n}", flags)
    else:
        flags = f"{flags} --{_FLAG}={n}".strip()
    os.environ["XLA_FLAGS"] = flags
    pin_cpu_platform()
    if not backend_initialised(default=True):  # unknown — verify via reset
        return

    # A backend may predate the settings above: reset and re-check. XLA
    # parses the host-device count once per process, so if a previous
    # backend consumed the old value the recreated one keeps it — raise
    # rather than hand back a silently wrong mesh size.
    import jax.extend.backend

    jax.extend.backend.clear_backends()
    devices = jax.devices()
    if len(devices) < n or devices[0].platform != "cpu":
        raise RuntimeError(
            f"force_cpu_devices({n}) called after the XLA backend was already "
            f"initialised ({len(devices)} {devices[0].platform} device(s)); "
            "the host device count is parsed once per process. Call this "
            "before any jax operation (or run in a fresh process)."
        )


def detected_topology() -> dict:
    """The detected device/mesh shape, in the exact field vocabulary the
    ``test_multihost_spmd`` capability probe prints (``PROBE_SHAPE``):
    platform, global/local device counts, process count. Recorded into
    ``Fleet.stats()`` and every benchmark artifact so CPU-vs-TPU
    evidence under ``benchmarks/results/`` is self-describing.

    Initialises the XLA backend if nothing has yet (callers that need
    a forced device count must call :func:`force_cpu_devices` first).
    """
    import jax

    return {
        "platform": jax.default_backend(),
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
        "processes": jax.process_count(),
    }


def mesh_shard_count(n_devices: int | None = None) -> int:
    """Largest power-of-two shard count the detected (or given) device
    count supports — the default width of :func:`fleet_mesh`. Power-of-
    two only: the fleet's lane tiers are pow2, and lanes must divide
    evenly into shards for the ``shard_map`` lift."""
    if n_devices is None:
        import jax

        n_devices = len(jax.devices())
    if n_devices < 1:
        raise ValueError("no devices detected")
    return 1 << (int(n_devices).bit_length() - 1)


def fleet_mesh(shards: int | None = None, devices=None):
    """A 1-D ``Mesh(devices[:shards], ("replicas",))`` for
    ``Fleet(mesh=...)`` / ``start_fleet(..., mesh=...)`` (ISSUE 13).

    ``shards`` defaults to the largest power of two the detected device
    set supports (:func:`mesh_shard_count`); non-pow2 counts raise —
    the fleet pads replica-lane tiers to pow2, and the lane axis must
    split evenly across shards. On CPU the mesh is exercisable by
    forcing virtual devices (:func:`force_cpu_devices`, honouring
    ``--xla_force_host_platform_device_count``), which is how tier-1
    and ``bench.py --fleet --mesh`` drive the whole plane without a
    TPU claim."""
    import jax
    import numpy as _np
    from jax.sharding import Mesh

    devices = list(jax.devices()) if devices is None else list(devices)
    if shards is None:
        shards = mesh_shard_count(len(devices))
    shards = int(shards)
    if shards < 1 or shards & (shards - 1):
        raise ValueError(f"mesh shard count must be a power of two: {shards}")
    if shards > len(devices):
        raise ValueError(
            f"{shards} shards requested but only {len(devices)} device(s) "
            f"detected (force more with force_cpu_devices on CPU)"
        )
    return Mesh(_np.array(devices[:shards]), ("replicas",))


def enable_compilation_cache(path: str | None = None) -> str:
    """Point JAX's persistent compilation cache at a repo-local directory
    so repeated runs (benches, test sessions, a bench retry after a
    wedged device claim) reuse compiled executables instead of paying
    the compile again — critical on remote-compile backends where one
    compile can cost minutes. Returns the cache directory used."""
    import os

    import jax

    if path is None:
        path = os.environ.get(
            "DELTA_CRDT_JAX_CACHE",
            os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), ".jax_cache"),
        )
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    return path
