"""Synthetic workload builders (benchmarks, graft entry, bulk loads).

Host-side numpy construction of binned states and writer delta streams.
The synthetic writer issues **per-bucket contiguous counters** so its
delta stream ships exact delta-intervals (``RowSlice.ctx_lo``): each
delta claims precisely the dots it carries, older dots stay unclaimed,
and in-order merging never gaps. Dot identity is (writer gid, bucket,
counter) — globally unique because a dot's bucket is a function of its
key. (The replica runtime instead issues one global counter sequence per
writer — a sparse special case of the same scheme; both are valid dot
namespaces for the lattice.)
"""

from __future__ import annotations

import numpy as np

from delta_crdt_ex_tpu.models.binned import BinnedStore, pow2_tier
from delta_crdt_ex_tpu.ops.binned import RowSlice, init_from_columns


def build_state(
    gid: int,
    keys: np.ndarray,
    num_buckets: int,
    bin_capacity: int,
    replica_capacity: int = 8,
    ts_start: int = 1,
):
    """A single-writer BinnedStore holding ``keys`` (uint64, distinct),
    with per-bucket contiguous counters. Returns (state, next_ctr[L])
    where ``next_ctr[b] - 1`` is the writer's top counter in bucket b.
    Invariants (ehash/fill/amin/amax/leaf) are rebuilt on device by
    :func:`~delta_crdt_ex_tpu.ops.binned.init_from_columns`."""
    import jax.numpy as jnp

    L, B = num_buckets, bin_capacity
    n = len(keys)
    bucket = (keys & np.uint64(L - 1)).astype(np.int64)
    order = np.argsort(bucket, kind="stable")
    sk = keys[order]
    sb = bucket[order]
    # rank within bucket = per-bucket slot and counter-1
    starts = np.searchsorted(sb, np.arange(L))
    rank = np.arange(n) - starts[sb]
    if rank.max(initial=0) >= B:
        raise ValueError(
            f"bucket overflow: max occupancy {rank.max() + 1} > bin capacity {B}"
        )

    key = np.zeros((L, B), np.uint64)
    valh = np.zeros((L, B), np.uint32)
    ts = np.zeros((L, B), np.int64)
    ctr = np.zeros((L, B), np.uint32)
    alive = np.zeros((L, B), bool)
    key[sb, rank] = sk
    valh[sb, rank] = (sk & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    ts[sb, rank] = ts_start + np.arange(n)
    ctr[sb, rank] = rank + 1
    alive[sb, rank] = True

    counts = np.bincount(bucket, minlength=L).astype(np.uint32)
    ctx_max = np.zeros((L, replica_capacity), np.uint32)
    ctx_max[:, 0] = counts
    ctx_gid = np.zeros(replica_capacity, np.uint64)
    ctx_gid[0] = gid

    raw = BinnedStore(
        key=jnp.asarray(key),
        valh=jnp.asarray(valh),
        ts=jnp.asarray(ts),
        node=jnp.zeros((L, B), jnp.int32),
        ctr=jnp.asarray(ctr),
        alive=jnp.asarray(alive),
        ehash=jnp.zeros((L, B), jnp.uint32),
        fill=jnp.zeros(L, jnp.int32),
        amin=jnp.zeros((L, replica_capacity), jnp.uint32),
        amax=jnp.zeros((L, replica_capacity), jnp.uint32),
        leaf=jnp.zeros(L, jnp.uint32),
        ctx_gid=jnp.asarray(ctx_gid),
        ctx_max=jnp.asarray(ctx_max),
    )
    import jax

    return jax.jit(init_from_columns)(raw), counts.astype(np.uint32) + 1


def interval_delta_stream(
    gid: int,
    rng: np.random.Generator,
    num_deltas: int,
    delta_size: int,
    num_buckets: int,
    next_ctr: np.ndarray | None = None,
    ts_start: int = 1 << 20,
    bin_width: int = 8,
):
    """``num_deltas`` sequential RowSlices from one writer: fresh random
    keys, per-bucket counters continuing from ``next_ctr``, exact
    delta-interval contexts. All slices share the static shape
    [U, bin_width] (U = delta_size padded to a power of two) so a scan
    over the stream compiles once."""
    import jax.numpy as jnp

    L = num_buckets
    next_ctr = (
        next_ctr.astype(np.uint32) if next_ctr is not None else np.ones(L, np.uint32)
    )
    u = pow2_tier(delta_size)
    s = bin_width
    slices = []
    ts = ts_start
    for _ in range(num_deltas):
        keys = rng.integers(1, 1 << 63, size=delta_size, dtype=np.uint64)
        bucket = (keys & np.uint64(L - 1)).astype(np.int64)
        rows_u, inv = np.unique(bucket, return_inverse=True)
        # the rows_sorted=True scatter vouch in __graft_entry__.py /
        # bench.py rests on this strict ascent; a producer change that
        # breaks it must fail loudly, not become a false XLA hint
        assert (np.diff(rows_u) > 0).all(), "delta slice rows must strictly ascend"
        nrows = len(rows_u)
        cols = np.zeros(delta_size, np.int64)
        seen: dict[int, int] = {}
        for i in range(delta_size):
            r = int(inv[i])
            cols[i] = seen.get(r, 0)
            seen[r] = cols[i] + 1
        if max(seen.values()) > s:
            raise ValueError(
                f"delta has {max(seen.values())} same-bucket keys > bin_width {s}"
            )

        sl = dict(
            rows=np.full(u, -1, np.int32),
            key=np.zeros((u, s), np.uint64),
            valh=np.zeros((u, s), np.uint32),
            ts=np.zeros((u, s), np.int64),
            node=np.zeros((u, s), np.int32),
            ctr=np.zeros((u, s), np.uint32),
            alive=np.zeros((u, s), bool),
            ctx_rows=np.zeros((u, 1), np.uint32),
            ctx_lo=np.zeros((u, 1), np.uint32),
            ctx_gid=np.array([gid], np.uint64),
        )
        sl["rows"][:nrows] = rows_u
        lo = next_ctr[rows_u] - 1  # interval lower bound (exclusive)
        sl["ctx_lo"][:nrows, 0] = lo
        counts = np.bincount(inv, minlength=nrows).astype(np.uint32)
        sl["ctx_rows"][:nrows, 0] = lo + counts
        sl["key"][inv, cols] = keys
        sl["valh"][inv, cols] = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        sl["ts"][inv, cols] = ts + np.arange(delta_size)
        sl["ctr"][inv, cols] = lo[inv] + cols + 1
        sl["alive"][inv, cols] = True
        next_ctr[rows_u] += counts
        ts += delta_size
        slices.append(RowSlice(**{k: jnp.asarray(v) for k, v in sl.items()}))
    return slices, next_ctr
