"""Pure-Python executable specification of the AWLWWMap semantics.

A from-scratch Python mirror of the reference lattice semantics
(``aw_lww_map.ex``) over the same structures the paper uses — nested
dot-store ``{key: {(value, ts): set(dots)}}`` plus a causal context in
dual representation (compressed ``{node: max}`` state form / explicit dot
set delta form, ``aw_lww_map.ex:13-28``).

Used two ways:

- as the **oracle** in property tests (the reference's model-vs-lattice
  pattern, ``aw_lww_map_test.exs:51-86``);
- as the **baseline stand-in** in ``bench.py``: Elixir/BEAM is not
  available in this image and the reference publishes no numbers
  (BASELINE.md), so per-element host-language dot-store math — the same
  asymptotic work the BEAM implementation does — is the measured
  comparison point.
"""

from __future__ import annotations

from typing import Any, Hashable


def _ctx_member(ctx, dot) -> bool:
    """Polymorphic dot membership (``Dots.member?``, ``aw_lww_map.ex:67-73``)."""
    if isinstance(ctx, set):
        return dot in ctx
    node, ctr = dot
    return ctx.get(node, 0) >= ctr


def _ctx_union(a, b):
    """Context union (``Dots.union``, ``aw_lww_map.ex:39-52``)."""
    if isinstance(a, set) and isinstance(b, set):
        return a | b
    if isinstance(a, set):
        a, b = b, a
    out = dict(a)
    for node, ctr in b.items() if isinstance(b, dict) else b:
        if out.get(node, 0) < ctr:
            out[node] = ctr
    return out


class PyAWLWWMap:
    """One lattice state/delta. ``dots`` is the causal context (dict =
    compressed state form, set = delta form); ``value`` the dot store."""

    def __init__(self, dots=None, value=None, compressed: bool = True):
        self.dots = dots if dots is not None else ({} if compressed else set())
        self.value: dict[Hashable, dict[tuple, set]] = value if value is not None else {}

    # -- mutators (return deltas, reference aw_lww_map.ex:99-150) --------

    def add(self, key, val, node, ts) -> "PyAWLWWMap":
        observed = set()
        for dotset in self.value.get(key, {}).values():
            observed |= dotset
        if isinstance(self.dots, dict):
            next_ctr = self.dots.get(node, 0) + 1
        else:  # delta form ("inefficient next_dot", aw_lww_map.ex:30-33)
            next_ctr = max((c for n, c in self.dots if n == node), default=0) + 1
        dot = (node, next_ctr)
        return PyAWLWWMap(dots=observed | {dot}, value={key: {(val, ts): {dot}}})

    def remove(self, key) -> "PyAWLWWMap":
        observed = set()
        for dotset in self.value.get(key, {}).values():
            observed |= dotset
        return PyAWLWWMap(dots=observed, value={})

    def clear(self) -> "PyAWLWWMap":
        observed = set()
        for sub in self.value.values():
            for dotset in sub.values():
                observed |= dotset
        return PyAWLWWMap(dots=observed, value={})

    # -- lattice join (reference aw_lww_map.ex:153-209) -------------------

    def join(self, other: "PyAWLWWMap", keys) -> "PyAWLWWMap":
        new_dots = _ctx_union(self.dots, other.dots)
        new_value = {k: v for k, v in self.value.items() if k not in keys}
        for k, v in other.value.items():
            if k not in keys:
                new_value[k] = v
        for key in keys:
            s1 = self.value.get(key, {})
            s2 = other.value.get(key, {})
            merged: dict[tuple, set] = {}
            for pair in set(s1) | set(s2):
                d1 = s1.get(pair, set())
                d2 = s2.get(pair, set())
                keep = (d1 & d2)
                keep |= {d for d in d1 if not _ctx_member(other.dots, d)}
                keep |= {d for d in d2 if not _ctx_member(self.dots, d)}
                if keep:
                    merged[pair] = keep
            if merged:
                new_value[key] = merged
            elif key in new_value:
                del new_value[key]
        return PyAWLWWMap(dots=new_dots, value=new_value)

    # -- reads (reference aw_lww_map.ex:211-224) --------------------------

    def read(self) -> dict[Hashable, Any]:
        out = {}
        for key, pairs in self.value.items():
            (val, _ts) = max(pairs, key=lambda p: p[1])
            out[key] = val
        return out
