"""Device↔host transfer ledger (crdtlint v5, TRANSFER family runtime half).

The "device-resident data plane" campaign (ROADMAP) retires host
round-trips one measured step at a time. Before any retirement can be
*evidence* rather than vibes, every crossing needs to be counted: this
module is the jitcache-registry pattern applied to transfers — every
hot-path device↔host crossing goes through an **audited site**
(:func:`register` returns a :class:`TransferSite` handle whose
:meth:`~TransferSite.get`/:meth:`~TransferSite.put` wrap
``jax.device_get``/``jax.device_put``), and the ledger counts crossings
and bytes per site label. crdtlint TRANSFER001 makes any *raw* crossing
in a hot module red; TRANSFER002 cross-checks the declared site labels
against use (ghost labels, collisions) the way OBS001 cross-checks
declared telemetry events.

Zero overhead when observability is disabled: the hot path pays two
integer adds under an uncontended lock (negligible next to the transfer
it accounts); telemetry export is deferred to scrape time —
:func:`audit` emits ``TRANSFER`` events carrying ABSOLUTE per-site
totals only when a handler is attached, and the metrics bridge folds
them into ``crdt_transfers_total{site=...}`` /
``crdt_transfer_bytes_total{site=...}`` with idempotent gauge sets
(the ``crdt_jit_compiles_total`` precedent: monotone by construction,
hence the ``_total`` names despite the set-to-absolute primitive).

Site labels are registered once, at module import, and a label
collision from a **different** call site raises (the jitcache
name-collision guard): two sites silently merging counts would blind
every bench gate diffing ledger snapshots. Re-evaluating the same
``register`` statement (module reload) is idempotent.

Bench gates (`bench.py --ingest/--fleet/--mesh/--serve/--tree`) pin
steady-state per-round crossing deltas by diffing :func:`snapshot`
around their measured rounds, exactly like the zero-compile gates diff
``jitcache.compile_counts()``.
"""

from __future__ import annotations

import sys
import threading

import jax

_lock = threading.Lock()
#: site label -> TransferSite (insertion = module import order)
_sites: dict[str, "TransferSite"] = {}


class TransferSite:
    """One audited crossing site: a label, the registering call site,
    and the running (crossings, bytes) tally. Handles are module-level
    constants by convention — crdtlint TRANSFER001 recognises
    ``<handle>.get/.put`` as the audited crossing form."""

    __slots__ = ("label", "origin", "count", "bytes")

    def __init__(self, label: str, origin: tuple) -> None:
        self.label = label
        self.origin = origin
        self.count = 0
        self.bytes = 0

    def note(self, n_bytes: int, crossings: int = 1) -> None:
        """Manual accounting for a crossing the wrappers cannot wrap
        (e.g. an implicit operand transfer that is contractual)."""
        with _lock:
            self.count += crossings
            self.bytes += int(n_bytes)

    def get(self, value):
        """Audited ``jax.device_get``: one counted crossing for the
        whole pytree (host leaves pass through, like ``device_get``)."""
        self.note(_nbytes(value))
        return jax.device_get(value)

    def put(self, value, device=None):
        """Audited ``jax.device_put`` (``device`` may be a Device or a
        Sharding, forwarded verbatim; None = default placement)."""
        self.note(_nbytes(value))
        if device is None:
            return jax.device_put(value)
        return jax.device_put(value, device)


def _nbytes(value) -> int:
    total = 0
    for leaf in jax.tree_util.tree_leaves(value):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


def register(label: str) -> TransferSite:
    """Register ``label`` and return its :class:`TransferSite` handle.

    The same label registered again from the SAME file:line returns the
    existing handle (module reload); from a DIFFERENT call site it
    raises — silently merging two sites' counts would corrupt every
    ledger delta a bench gate diffs (the jitcache collision guard)."""
    if not isinstance(label, str) or not label:
        raise ValueError(f"transfer site label must be a non-empty str, got {label!r}")
    frame = sys._getframe(1)
    origin = (frame.f_code.co_filename, frame.f_lineno)
    with _lock:
        prior = _sites.get(label)
        if prior is not None:
            if prior.origin != origin:
                raise ValueError(
                    f"transfers: site label {label!r} already registered at "
                    f"{prior.origin[0]}:{prior.origin[1]} — pick a unique "
                    f"label per call site (ledger counts must not merge)"
                )
            return prior
        site = _sites[label] = TransferSite(label, origin)
        return site


def audited_get(value, site: TransferSite):
    """Function form of :meth:`TransferSite.get` (call-through sugar
    for sites threaded as parameters)."""
    return site.get(value)


def audited_put(value, site: TransferSite, device=None):
    """Function form of :meth:`TransferSite.put`."""
    return site.put(value, device)


def snapshot() -> dict:
    """``{label: {"count": crossings, "bytes": bytes_moved}}`` for every
    registered site, in sorted label order — the ledger image bench
    gates diff and ``Replica.stats()``/``Fleet.stats()`` surface."""
    with _lock:
        return {
            label: {"count": s.count, "bytes": s.bytes}
            for label, s in sorted(_sites.items())
        }


def counts() -> dict:
    with _lock:
        return {label: s.count for label, s in sorted(_sites.items())}


def delta(before: dict, after: dict) -> dict:
    """Per-site ``{label: {"count": Δ, "bytes": Δ}}`` between two
    :func:`snapshot` images, zero-delta sites omitted."""
    out: dict = {}
    for label, cur in after.items():
        prev = before.get(label, {"count": 0, "bytes": 0})
        dc = cur["count"] - prev["count"]
        db = cur["bytes"] - prev["bytes"]
        if dc or db:
            out[label] = {"count": dc, "bytes": db}
    return out


def audit() -> dict:
    """Read every site's tally and emit ``TRANSFER`` telemetry carrying
    the ABSOLUTE per-site totals — the metrics bridge folds those into
    ``crdt_transfers_total{site=...}`` / ``crdt_transfer_bytes_total``
    with idempotent gauge sets, so a bridge attaching mid-process still
    exports true totals. The observability plane runs this as a
    scrape-time collector; with no handler attached it is a snapshot
    read and nothing more (zero-overhead-when-disabled). Returns the
    current snapshot."""
    # deferred import: utils sits below the runtime layer in the import
    # graph (runtime modules register their sites at import time), so a
    # top-level runtime import would cycle through runtime/__init__
    from delta_crdt_ex_tpu.runtime import telemetry

    snap = snapshot()
    if not telemetry.has_handlers(telemetry.TRANSFER):
        return snap
    for label, tally in snap.items():
        telemetry.execute(
            telemetry.TRANSFER,
            {"crossings": tally["count"], "bytes": tally["bytes"]},
            {"site": label},
        )
    return snap


def varz() -> dict:
    """``/varz`` source: the ledger's unified snapshot envelope."""
    return {"kind": "transfers", "stats": snapshot()}
