"""Open-addressing hash-table dot store — the second dot-store backend.

Layout (ISSUE 8; WarpSpeed-style bucketed probing, PAPERS.md): every
entry lives in ONE flat table of ``H`` lanes, its slot found by probing
a bounded window from its key's group-aligned base
(:mod:`delta_crdt_ex_tpu.ops.hash_map`). Where the binned store's
``[L, B]`` rows pay tier-promotion repacking when a bucket outgrows its
lane tier — and split fleet batch buckets exactly at those tier
boundaries — this table's only growth event is a load-factor-driven ×2
REHASH of the whole table, so fleets of hash-store members stay batched
through growth and wire slices ship dense (content-sized, not
bin-tier-sized).

Slot lanes (all device-resident, [H]):

    key   : uint64   64-bit key hash (probe target)
    valh  : uint32   value content digest
    ts    : int64    LWW timestamp
    node  : int32    writer replica as LOCAL slot into ctx tables
    ctr   : uint32   dot counter (dot = (gid, sync bucket, ctr))
    alive : bool     entry liveness — a dead lane is FREE (lookups scan
                     the whole fixed probe window, so there are no
                     tombstones; update churn reuses the killed dot's
                     lane and steady state never grows the table)
    ehash : uint32   maintained entry content hash (digest term)
    arr   : uint32   per-sync-bucket arrival stamp (extraction order)

Sync-index bookkeeping — the CLUSTER-AGREED geometry, unchanged from
the binned store so the two backends are protocol-identical (same
digest trees, same walk traffic, same per-bucket causal contexts, same
``CtxGapError`` gap semantics):

    leaf    : uint32[L]    maintained leaf digests (wrapping ehash sums)
    rowseq  : uint32[L]    next arrival stamp per sync bucket
    ctx_gid : uint64[R]    slot → global replica id (0 = empty)
    ctx_max : uint32[L, R] per-bucket per-replica max observed counter

``probe_window`` is STATIC metadata (a jit shape key): the lane count a
lookup scans from its base. Growth policy (:func:`grow_table`): double
``H`` when lanes run out; widen the window too when doubling alone
cannot help (more concurrent dots of one key than window lanes).

:class:`HashAWLWWMap` plugs into the same replica-runtime ``crdt_module``
seam as ``BinnedAWLWWMap`` — grouped ingest (``combine_entry_arrays`` /
``merge_group_into`` reuse the shared wire fan-in), WAL snapshot/replay,
log-shipping serving, and vmapped fleet transitions all work unchanged;
``bench.py --hashstore`` and ``tests/test_hash_store.py`` pin the
bit-for-bit read/state/WAL-bytes/ack parity gate against the binned
store.
"""

from __future__ import annotations

import dataclasses
import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from delta_crdt_ex_tpu.models.binned import pow2_tier as _pow2

#: lanes per probe group (window bases are group-aligned)
GROUP = 8
#: default probe window lanes
DEFAULT_PROBE_WINDOW = 32
#: grow ×2 when the FULLEST probe window passes 3/4 of its lanes —
#: window overflow (never global load) is what forces a rehash, so the
#: advisory watches per-window pressure; the threshold margin absorbs a
#: batch of inserts landing in the hot window before the next advisory
LOAD_NUM, LOAD_DEN = 3, 4


@partial(
    jax.tree_util.register_dataclass,
    data_fields=[
        "key", "valh", "ts", "node", "ctr", "alive", "ehash", "arr",
        "leaf", "rowseq", "ctx_gid", "ctx_max",
    ],
    meta_fields=["probe_window"],
)
@dataclasses.dataclass(frozen=True)
class HashStore:
    key: jax.Array  # uint64[H]
    valh: jax.Array  # uint32[H]
    ts: jax.Array  # int64[H]
    node: jax.Array  # int32[H]
    ctr: jax.Array  # uint32[H]
    alive: jax.Array  # bool[H]
    ehash: jax.Array  # uint32[H]
    arr: jax.Array  # uint32[H]
    leaf: jax.Array  # uint32[L]
    rowseq: jax.Array  # uint32[L]
    ctx_gid: jax.Array  # uint64[R]
    ctx_max: jax.Array  # uint32[L, R]
    probe_window: int = DEFAULT_PROBE_WINDOW

    @property
    def table_size(self) -> int:
        return self.key.shape[-1]

    @property
    def capacity(self) -> int:
        return self.key.shape[-1]

    @property
    def num_buckets(self) -> int:
        return self.leaf.shape[-1]

    @property
    def replica_capacity(self) -> int:
        return self.ctx_gid.shape[-1]

    @staticmethod
    def new(
        num_buckets: int = 64,
        bin_capacity: int = 16,
        replica_capacity: int = 8,
        probe_window: int = DEFAULT_PROBE_WINDOW,
    ) -> "HashStore":
        """Empty state. The signature matches ``BinnedStore.new`` so the
        replica's ``model.new(L, bin_cap, R)`` call serves both backends:
        the table sizes to the same total capacity (``L × bin_capacity``
        lanes, pow2, ≥ 2 probe windows)."""
        L, R = num_buckets, replica_capacity
        H = _pow2(max(num_buckets * bin_capacity, 2 * probe_window, 64))
        return HashStore(
            key=jnp.zeros(H, jnp.uint64),
            valh=jnp.zeros(H, jnp.uint32),
            ts=jnp.zeros(H, jnp.int64),
            node=jnp.zeros(H, jnp.int32),
            ctr=jnp.zeros(H, jnp.uint32),
            alive=jnp.zeros(H, bool),
            ehash=jnp.zeros(H, jnp.uint32),
            arr=jnp.zeros(H, jnp.uint32),
            leaf=jnp.zeros(L, jnp.uint32),
            rowseq=jnp.zeros(L, jnp.uint32),
            ctx_gid=jnp.zeros(R, jnp.uint64),
            ctx_max=jnp.zeros((L, R), jnp.uint32),
            probe_window=probe_window,
        )

    def grow(self, replica_capacity: int | None = None) -> "HashStore":
        """Pad the WRITER tables to a larger tier (unknown-gid growth —
        shared semantics with the binned store). Table growth is NOT a
        pad: it is a rehash (:func:`grow_table`). Rank-agnostic like
        ``BinnedStore.grow`` (works on fleet-stacked states)."""
        r_new = replica_capacity or self.replica_capacity
        dr = r_new - self.replica_capacity
        assert dr >= 0
        if not dr:
            return self
        last = lambda a: jnp.pad(a, ((0, 0),) * (a.ndim - 1) + ((0, dr),))
        return dataclasses.replace(
            self, ctx_gid=last(self.ctx_gid), ctx_max=last(self.ctx_max)
        )

    def entry_gid(self) -> jax.Array:
        """uint64[H]: global replica id of each entry's writer."""
        return self.ctx_gid[self.node]

    def global_ctx(self) -> jax.Array:
        return jnp.max(self.ctx_max, axis=0)

    def own_counter(self, slot) -> jax.Array:
        return jnp.max(self.ctx_max[:, slot])

    def num_alive(self) -> jax.Array:
        return jnp.sum(self.alive.astype(jnp.int32))

    def bucket_of(self, key: jax.Array) -> jax.Array:
        return (key & jnp.uint64(self.num_buckets - 1)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# host-side wrappers: growth policy, dense-tier sizing, model class
# (kernels live in ops/hash_map.py — a pure jit-entry-root module; the
# data-dependent control flow below is host code by design)


def _ops():
    # deferred: ops/hash_map imports this module for HashStore
    from delta_crdt_ex_tpu.ops import hash_map

    return hash_map


class _Jit:
    """Lazily-jitted kernel table (import cycle + first-use compile)."""

    _build_lock = threading.Lock()

    def __getattr__(self, name):
        from delta_crdt_ex_tpu.utils.jitcache import named_jit

        # build + cache + audit-register under one lock: two threads
        # first-touching the same kernel concurrently must not register
        # one jit object while dispatch caches the other — the audit's
        # compile counts would silently read 0 for that root forever
        with _Jit._build_lock:
            fn = self.__dict__.get(name)
            if fn is None:
                ops = _ops()
                static = {
                    "extract_rows_packed": ("lanes",),
                    "extract_own_delta_packed": ("lanes",),
                    "winner_rows_packed": ("lanes",),
                    "rehash": ("table_size", "probe_window"),
                }.get(name, ())
                # named_jit: compile-cache audit registration, prefixed
                # so the hash kernels never collide with the binned
                # roots' names
                fn = named_jit(
                    getattr(ops, name),
                    name=f"hash_{name}",
                    static_argnames=static,
                )
                setattr(self, name, fn)
        return fn


jit = _Jit()


def grow_table(state: HashStore, on_grow=None) -> HashStore:
    """THE growth event: rehash ×2 (at least — a nearly-empty table
    whose hot window overflowed still doubles, because the alive count
    does not see the pending inserts that overflowed, and an in-place
    rehash would hand the retry the same full windows). A rehash that
    still cannot place every entry (e.g. more concurrent dots of one
    hot key than window lanes) doubles again and, every second attempt,
    widens the probe window."""
    n_alive = int(np.asarray(state.num_alive()))
    w = state.probe_window
    h_new = max(_pow2(max(2 * n_alive, 2 * w, 64)), 2 * state.table_size)
    attempt = 0
    while True:
        st2, ok = jit.rehash(state, table_size=h_new, probe_window=w)
        if bool(ok):
            if on_grow:
                on_grow(st2)
            return st2
        attempt += 1
        if attempt % 2 == 0 and w < h_new:
            w *= 2
        else:
            h_new *= 2


def maybe_rehash(state: HashStore, max_window_fill: int, on_grow=None) -> HashStore:
    """Growth advisory: grow once the fullest probe window passes
    ``LOAD_NUM/LOAD_DEN`` of its lanes — the precise overflow precursor
    at any table size (doubling the table splits every probe group in
    two, halving window pressure). Update churn never raises it: a kill
    frees its lane for the replacing insert — no tombstones."""
    if max_window_fill * LOAD_DEN > LOAD_NUM * state.probe_window:
        return grow_table(state, on_grow=on_grow)
    return state


def merge_rows_into(state: HashStore, sl, on_grow=None):
    """Merge a RowSlice via the open-addressing kernel — the hash
    backend's runtime merge path (the ``merge_rows_into`` contract of
    ``models/binned_map.py``: same escapes, same :class:`CtxGapError`
    semantics, growth handled here on the host). Returns
    ``(new_state, result)``."""
    from delta_crdt_ex_tpu.models.binned_map import CtxGapError, _CTX_GAP_MSG

    while True:
        res = jit.merge_rows(state, sl)
        ok, wfill = jax.device_get((res.ok, res.max_window_fill))
        if bool(ok):
            return maybe_rehash(res.state, int(wfill), on_grow=on_grow), res
        if bool(np.asarray(res.need_ctx_gap)):
            err = CtxGapError(_CTX_GAP_MSG)
            err.gap_rows = np.asarray(res.gap_row)
            raise err
        if bool(np.asarray(res.need_gid_grow)):
            state = state.grow(replica_capacity=state.replica_capacity * 2)
            if on_grow:
                on_grow(state)
        if bool(np.asarray(res.need_fill_grow)):
            state = grow_table(state, on_grow=on_grow)


def merge_group_into(state: HashStore, arrays_list: list, on_grow=None):
    """Grouped fan-in merge over the hash table — the
    ``merge_group_into`` contract (one combined slice, one kernel
    dispatch, ``gapped_members`` mapped through member offsets)."""
    from delta_crdt_ex_tpu.models.binned_map import CtxGapError, combine_entry_arrays

    sl, offsets = combine_entry_arrays(arrays_list)
    try:
        new_state, res = merge_rows_into(state, sl, on_grow=on_grow)
    except CtxGapError as err:
        if err.gap_rows is not None:
            err.gapped_members = {
                i
                for i, (lo, hi) in enumerate(offsets)
                if bool(err.gap_rows[lo:hi].any())
            }
        raise
    return new_state, res, offsets


def _dense_lanes(counts) -> int:
    """pow2 wire tier of the fullest requested row — dense slices vary
    by CONTENT, so tiering bounds the distinct merge compiles. pow2 (not
    the pow4 wire tier): lane padding is exactly the byte overhead this
    store exists to shed, and the extra tier count is bounded by
    log2(max bucket fill)."""
    return _pow2(max(int(np.asarray(counts).max(initial=0)), 1), floor=4)


def extract_rows(state: HashStore, rows) -> "object":
    """Dense full-row slice for the wire/WAL/log-ship path: a counting
    pass sizes the pow2 lane tier, the packed gather fills it (two
    dispatches; the binned store reads its static bin tier instead and
    ships the padding — the byte gap ``bench.py --hashstore`` and the
    catch-up stats quantify)."""
    counts = jit.row_counts(state, rows)
    return jit.extract_rows_packed(state, rows, lanes=_dense_lanes(counts))


def extract_own_delta(state: HashStore, rows, self_slot, gid_self, lo):
    counts = jit.own_delta_counts(state, rows, self_slot, lo)
    return jit.extract_own_delta_packed(
        state, rows, self_slot, gid_self, lo, lanes=_dense_lanes(counts)
    )


def winner_rows(state: HashStore, rows):
    counts = jit.row_counts(state, rows)
    return jit.winner_rows_packed(state, rows, lanes=_dense_lanes(counts))


#: ``(impl | None, tag)`` from ``probed_lookup_fn`` — resolved on the
#: first point read so a Mosaic lowering failure surfaces there with
#: its reason, then cached for the process (the ``ops/pallas_tree.py``
#: selection pattern)
_PROBED: "tuple | None" = None


def winners_for_keys(state: HashStore, khash):
    """LWW point lookup: the HBM-resident Pallas probe kernel where it
    lowers (TPU) and the table fits its two-row cover, the jitted jnp
    reference everywhere else (CPU tier-1 runs the jnp path by
    construction — the two agree bit-for-bit, pinned in
    ``tests/test_hash_store.py``)."""
    global _PROBED
    if _PROBED is None:
        _PROBED = _ops().probed_lookup_fn()
    fn, _tag = _PROBED
    if fn is not None and state.probe_window <= 128 and state.table_size >= 256:
        return fn(state, khash)
    return jit.winners_for_keys(state, khash)


class HashAWLWWMap:
    """Model class: the AWLWWMap op vocabulary over :class:`HashStore` —
    a drop-in ``crdt_module`` for the replica runtime, selected via
    ``api.start_link(..., store="hash")``."""

    from delta_crdt_ex_tpu.ops.apply import OP_ADD as _A, OP_CLEAR as _C, OP_REMOVE as _R

    OPS = {
        "add": (_A, 2),
        "remove": (_R, 1),
        "clear": (_C, 0),
    }

    #: snapshot/batch-compat backend tag (recorded in snapshots;
    #: cross-backend restore must go through extraction — MIGRATING.md)
    backend = "hash"
    #: static (non-array) Store fields — snapshot as plain ints
    STORE_META = ("probe_window",)

    Store = HashStore
    new = staticmethod(HashStore.new)
    merge_rows_into = staticmethod(merge_rows_into)
    merge_group_into = staticmethod(merge_group_into)
    extract_rows = staticmethod(extract_rows)
    extract_own_delta = staticmethod(extract_own_delta)
    winner_rows = staticmethod(winner_rows)

    @staticmethod
    def group_batch(num_buckets, op, key, valh, ts):
        from delta_crdt_ex_tpu.models.binned_map import group_batch

        return group_batch(num_buckets, op, key, valh, ts)

    @staticmethod
    def combine_entry_arrays(arrays_list, to_device: bool = True):
        from delta_crdt_ex_tpu.models.binned_map import combine_entry_arrays

        return combine_entry_arrays(arrays_list, to_device=to_device)

    # raw jitted kernels (tests, fleet lanes, deterministic drives)
    row_apply = staticmethod(lambda *a: jit.row_apply(*a))
    merge_rows = staticmethod(lambda *a: jit.merge_rows(*a))
    clear_all = staticmethod(lambda *a: jit.clear_all(*a))
    compact_rows = staticmethod(lambda *a: jit.compact_rows(*a))
    #: point reads select the Pallas probe kernel where it lowers
    winners_for_keys = staticmethod(winners_for_keys)
    winner_all = staticmethod(lambda *a: jit.winner_all(*a))

    @staticmethod
    def tree_from_leaves(leaf):
        # leaf digests are bit-identical across backends: one tree fold
        from delta_crdt_ex_tpu.models.binned_map import jit_tree_from_leaves

        return jit_tree_from_leaves(leaf)

    # the wire slice shape is SHARED with the binned backend (one
    # protocol: either store merges either store's slices)
    from delta_crdt_ex_tpu.ops.binned import RowSlice  # noqa: F401

    @staticmethod
    def read_view(d: dict):
        return d

    # -- replica/fleet seam: growth + batch-compatibility ---------------

    @staticmethod
    def grow_for_apply(state: HashStore) -> HashStore:
        """Local-mutation overflow escape: rehash ×2 (the binned
        backend grows its bin tier here)."""
        return grow_table(state)

    @staticmethod
    def post_apply(state: HashStore, res, on_grow=None) -> HashStore:
        """Post-commit growth advisory: the apply result already carries
        the max window fill, so the advisory costs no extra device
        readback."""
        return maybe_rehash(
            state, int(np.asarray(res.max_window_fill)), on_grow=on_grow
        )

    @staticmethod
    def load_high(max_window_fill: int, probe_window: int) -> bool:
        """Fleet post-commit advisory (the vmapped merge result carries
        per-lane max window fills): a lane whose hot window nears
        overflow should grow OFF the batch path, before it escapes
        mid-batch."""
        return max_window_fill * LOAD_DEN > LOAD_NUM * probe_window

    @staticmethod
    def store_load_high(state: HashStore) -> bool:
        """The same advisory recomputed from a live state (the
        replica's under-lock re-check before an advised growth)."""
        return bool(
            int(np.asarray(jit.max_window_fill(state))) * LOAD_DEN
            > LOAD_NUM * state.probe_window
        )

    @staticmethod
    def geometry(state: HashStore) -> tuple:
        """Batch-compatibility key (ISSUE 8 satellite: each backend
        declares its own — the fleet buckets hash members by TABLE
        CAPACITY, which moves only on rehash, instead of the binned
        store's per-bucket lane tier that splits batches at every tier
        boundary)."""
        return (
            "hash",
            state.num_buckets,
            state.table_size,
            state.replica_capacity,
            state.probe_window,
        )

    @staticmethod
    def geometry_stacked(stacked) -> tuple:
        """Same key read from a fleet-stacked pytree's SHAPES (must not
        materialise a lane)."""
        return (
            "hash",
            stacked.leaf.shape[-1],
            stacked.key.shape[-1],
            stacked.ctx_gid.shape[-1],
            stacked.probe_window,
        )

    @classmethod
    def fleet_merge_rows(cls, states, slices):
        from delta_crdt_ex_tpu.runtime import transition

        return transition.jit_fleet_hash_merge_rows(states, slices)

    # -- batched fleet egress (ISSUE 10): dense extraction sizes its
    # lane tier by CONTENT, so the bucket runs at the max of the
    # members' own pow2 tiers and each lane trims back to its solo tier
    # (``s_tiers``) — ragged members share one compile, shipped bytes
    # stay bit-for-bit the solo extraction's.

    @classmethod
    def fleet_extract_rows(cls, states, rows):
        from delta_crdt_ex_tpu.runtime import transition

        counts = np.asarray(transition.jit_fleet_hash_row_counts(states, rows))
        tiers = [_dense_lanes(c) for c in counts]
        sl = transition.jit_fleet_hash_extract_rows(
            states, rows, lanes=max(tiers)
        )
        return sl, tiers

    @classmethod
    def fleet_extract_own_delta(cls, states, rows, self_slots, gid_selfs, lo):
        from delta_crdt_ex_tpu.runtime import transition

        counts = np.asarray(
            transition.jit_fleet_hash_own_delta_counts(states, rows, self_slots, lo)
        )
        tiers = [_dense_lanes(c) for c in counts]
        sl = transition.jit_fleet_hash_interval_slices(
            states, rows, self_slots, gid_selfs, lo, lanes=max(tiers)
        )
        return sl, tiers
    # (no fleet_tree_from_leaves seam: leaf digests are bit-identical
    # across backends — the fleet's batched tree build is model-agnostic)

    # -- mesh-sharded fleet seam (ISSUE 13): the dense sizing pass rides
    # the mesh too, and the bucket-wide static lane tier is computed
    # from the gathered counts exactly as the vmap forms do — padding
    # lanes count zero alive entries, so the tier never moves with the
    # shard padding and lane trims stay bit-for-bit the solo tiers.

    @classmethod
    def mesh_fleet_merge_rows(cls, mesh, states, slices):
        from delta_crdt_ex_tpu.runtime import transition

        return transition.jit_mesh_fleet_hash_merge_rows(mesh, states, slices)

    @classmethod
    def mesh_fleet_extract_rows(cls, mesh, states, rows):
        from delta_crdt_ex_tpu.runtime import transition

        counts = np.asarray(
            transition.jit_mesh_fleet_hash_row_counts(mesh, states, rows)
        )
        tiers = [_dense_lanes(c) for c in counts]
        sl = transition.jit_mesh_fleet_hash_extract_rows(
            mesh, states, rows, lanes=max(tiers)
        )
        return sl, tiers

    @classmethod
    def mesh_fleet_extract_own_delta(
        cls, mesh, states, rows, self_slots, gid_selfs, lo
    ):
        from delta_crdt_ex_tpu.runtime import transition

        counts = np.asarray(
            transition.jit_mesh_fleet_hash_own_delta_counts(
                mesh, states, rows, self_slots, lo
            )
        )
        tiers = [_dense_lanes(c) for c in counts]
        sl = transition.jit_mesh_fleet_hash_interval_slices(
            mesh, states, rows, self_slots, gid_selfs, lo, lanes=max(tiers)
        )
        return sl, tiers


class HashAWSet(HashAWLWWMap):
    """Add-wins observed-remove set over the hash store (the
    ``AWSet``/``BinnedAWLWWMap`` relationship, hash backend)."""

    from delta_crdt_ex_tpu.ops.apply import OP_ADD as _A, OP_CLEAR as _C, OP_REMOVE as _R

    OPS = {
        "add": (_A, 1),
        "remove": (_R, 1),
        "clear": (_C, 0),
    }

    @staticmethod
    def read_view(d: dict):
        return set(d)
