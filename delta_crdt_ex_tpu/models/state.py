"""Device-resident dot-store state — the tensorised AWLWWMap lattice.

The reference stores a replica as a 2-level nested map
``%{key => %{{value, ts} => MapSet(dots)}}`` plus a causal context
(``aw_lww_map.ex:2-3``). Every add creates exactly one dot attached to one
``{value, ts}`` pair (``aw_lww_map.ex:119-122``), so the whole state
flattens losslessly into a struct-of-arrays of *entries*, one per live dot:

    key   : uint64[C]  64-bit key hash (host keeps hash → term dict)
    valh  : uint32[C]  value content digest (for the sync index)
    ts    : int64[C]   LWW timestamp, nanoseconds
    node  : int32[C]   writer replica as LOCAL slot index into ctx tables
    ctr   : uint32[C]  dot counter (dot = (gid_of(node), ctr), globally unique)
    alive : bool[C]    slot occupancy mask (dead slots are reused)

The causal context is kept in compressed state form — per-replica max
counter, exactly the reference's ``Dots.compress`` representation
(``aw_lww_map.ex:13-20``) — but **decomposed per leaf bucket of the sync
index**:

    ctx_gid : uint64[R]     slot → global 64-bit replica id (0 = empty)
    ctx_max : uint32[L, R]  per-bucket per-replica max observed counter

Bucket decomposition is a deliberate strengthening over the reference:
the reference ships its *global* context alongside *partial* (truncated)
key slices (``causal_crdt.ex:105,259``), which lets a receiver's context
leap ahead of the keys it actually incorporated — after which the skipped
keys' entries test as "already seen" and can never be delivered (a latent
unsoundness its test suite never exercises, since no test syncs more than
``max_sync_size`` divergent keys). Here the sync atom is a leaf bucket:
a slice carries all entries **and the context rows** of exactly the
synced buckets, so coverage can never outrun content. Within a bucket
the compression semantics (per-node max ⇒ covered) are identical to the
reference's.

Slot indices are replica-LOCAL; when two states meet their gid tables are
merged on device and incoming ``node`` columns are remapped
(:func:`delta_crdt_ex_tpu.ops.dots.merge_contexts`). Values never live on
device — the host keeps a ``dot → (key_term, value)`` payload store.

Capacity C and replica capacity R are static (power-of-two tiers) so every
kernel compiles once per tier; growth pads with dead slots (no data moves).
L is fixed per cluster (all replicas must agree on the sync-index depth).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["key", "valh", "ts", "node", "ctr", "alive", "ctx_gid", "ctx_max"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class DotStore:
    key: jax.Array  # uint64[C]
    valh: jax.Array  # uint32[C]
    ts: jax.Array  # int64[C]
    node: jax.Array  # int32[C]
    ctr: jax.Array  # uint32[C]
    alive: jax.Array  # bool[C]
    ctx_gid: jax.Array  # uint64[R]
    ctx_max: jax.Array  # uint32[L, R]

    @property
    def capacity(self) -> int:
        return self.key.shape[-1]

    @property
    def replica_capacity(self) -> int:
        return self.ctx_gid.shape[-1]

    @property
    def num_buckets(self) -> int:
        return self.ctx_max.shape[-2]

    @staticmethod
    def new(
        capacity: int = 1024, replica_capacity: int = 64, num_buckets: int = 4096
    ) -> "DotStore":
        """Empty lattice state (reference ``AWLWWMap.new/0`` + ``compress_dots``,
        ``causal_crdt.ex:72``: the context starts in compressed state form)."""
        return DotStore(
            key=jnp.zeros(capacity, jnp.uint64),
            valh=jnp.zeros(capacity, jnp.uint32),
            ts=jnp.zeros(capacity, jnp.int64),
            node=jnp.zeros(capacity, jnp.int32),
            ctr=jnp.zeros(capacity, jnp.uint32),
            alive=jnp.zeros(capacity, bool),
            ctx_gid=jnp.zeros(replica_capacity, jnp.uint64),
            ctx_max=jnp.zeros((num_buckets, replica_capacity), jnp.uint32),
        )

    def grow(self, capacity: int | None = None, replica_capacity: int | None = None) -> "DotStore":
        """Pad to a larger tier (recompile-free w.r.t. data: dead slots only)."""
        c_new = capacity or self.capacity
        r_new = replica_capacity or self.replica_capacity
        dc = c_new - self.capacity
        dr = r_new - self.replica_capacity
        assert dc >= 0 and dr >= 0
        pad = lambda a, d: jnp.pad(a, (0, d)) if d else a
        return DotStore(
            key=pad(self.key, dc),
            valh=pad(self.valh, dc),
            ts=pad(self.ts, dc),
            node=pad(self.node, dc),
            ctr=pad(self.ctr, dc),
            alive=pad(self.alive, dc),
            ctx_gid=pad(self.ctx_gid, dr),
            ctx_max=jnp.pad(self.ctx_max, ((0, 0), (0, dr))) if dr else self.ctx_max,
        )

    def entry_gid(self) -> jax.Array:
        """uint64[C]: global replica id of each entry's writer (dot identity)."""
        return self.ctx_gid[self.node]

    def global_ctx(self) -> jax.Array:
        """uint32[R]: the reference's global compressed context view
        (per-replica max over all buckets)."""
        return jnp.max(self.ctx_max, axis=0)

    def own_counter(self, slot) -> jax.Array:
        """uint32: highest dot counter this replica has issued."""
        return jnp.max(self.ctx_max[:, slot])

    def num_alive(self) -> jax.Array:
        return jnp.sum(self.alive.astype(jnp.int32))

    def free_slots(self) -> jax.Array:
        return jnp.sum((~self.alive).astype(jnp.int32))
