"""AWLWWMap (flat engine) — SUPERSEDED; kept as a cross-validation oracle.

The production engine is :class:`delta_crdt_ex_tpu.models.binned_map.
BinnedAWLWWMap`; this flat dot-store engine is retained *only* so the
lattice property suite (``tests/test_lattice.py``) can cross-check two
independent kernel implementations against the Python oracle. It is not
exported on any public path (import explicitly as
``delta_crdt_ex_tpu.models.FlatAWLWWMap``).

This was the original TPU-native counterpart of the reference's pluggable
``crdt_module`` (``DeltaCrdt.AWLWWMap``, ``aw_lww_map.ex``): it bundles the
empty state constructor, the mutation-op vocabulary, and jit-compiled
entry points for the lattice kernels. The replica runtime
(:mod:`delta_crdt_ex_tpu.runtime.replica`) is generic over this model
class, mirroring the reference's ``crdt_module`` indirection
(``causal_crdt.ex:50,72,189,339,384``) — an alternative CRDT model only
needs to provide the same surface.

Semantic contract carried over (SURVEY §7 non-negotiables):

- add-wins / observed-remove: a remove kills only observed dots
  (``aw_lww_map.ex:133-146``); concurrent adds survive;
- LWW among surviving values by timestamp (``:211-216``), ties broken
  deterministically by (ts, writer gid, counter);
- causal join per key ``(s1∩s2) ∪ (s1∖c2) ∪ (s2∖c1)`` (``:196-209``);
- context union = per-replica max (``:45-52``).
"""

from __future__ import annotations

from functools import partial

import jax

from delta_crdt_ex_tpu.models.state import DotStore
from delta_crdt_ex_tpu.ops import apply as apply_ops
from delta_crdt_ex_tpu.ops import hashtree, join as join_ops, read as read_ops

# jit-compiled kernel entry points (shared compilation caches).
jit_join = jax.jit(join_ops.join)
jit_extract = jax.jit(join_ops.extract_buckets, static_argnames=("out_size",))
jit_apply = jax.jit(apply_ops.apply_batch)
jit_digest_tree = jax.jit(hashtree.digest_tree, static_argnames=("depth",))
jit_winners_for_keys = jax.jit(read_ops.winners_for_keys)
jit_winner_mask = jax.jit(read_ops.winner_mask)
jit_winner_slice = jax.jit(read_ops.winner_slice, static_argnames=("out_size",))


class AWLWWMap:
    """Model class: op vocabulary + kernels over :class:`DotStore`."""

    #: mutation name → (op code, arity of user args)
    OPS = {
        "add": (apply_ops.OP_ADD, 2),  # add(key, value)    aw_lww_map.ex:99
        "remove": (apply_ops.OP_REMOVE, 1),  # remove(key)  aw_lww_map.ex:133
        "clear": (apply_ops.OP_CLEAR, 0),  # clear()        aw_lww_map.ex:148
    }

    new = staticmethod(DotStore.new)
    join = staticmethod(jit_join)
    extract_buckets = staticmethod(jit_extract)
    slice_to_store = staticmethod(join_ops.slice_to_store)
    apply_batch = staticmethod(jit_apply)
    digest_tree = staticmethod(jit_digest_tree)
    winners_for_keys = staticmethod(jit_winners_for_keys)
    winner_mask = staticmethod(jit_winner_mask)
    winner_slice = staticmethod(jit_winner_slice)
