"""Model classes. The production engine is the bucket-binned
``BinnedAWLWWMap`` (exported as ``AWLWWMap``, matching the package-level
export in :mod:`delta_crdt_ex_tpu` and :mod:`delta_crdt_ex_tpu.api`).

The superseded flat engine is kept *only* as a cross-validation oracle
for the lattice property tests (``tests/test_lattice.py``); it loads
lazily as ``FlatAWLWWMap`` so production imports never pull in the flat
kernel chain.
"""

from delta_crdt_ex_tpu.models.binned import BinnedStore

__all__ = [
    "AWLWWMap",
    "BinnedAWLWWMap",
    "BinnedStore",
    "DotStore",
    "FlatAWLWWMap",
    "HashAWLWWMap",
    "HashAWSet",
    "HashStore",
]

# All model classes resolve lazily: ``binned_map`` imports ``ops.binned``
# which imports ``models.binned`` — an eager import here would re-enter
# this package mid-initialisation (circular import) whenever ``ops.binned``
# is the first module loaded.
_LAZY = {
    "BinnedAWLWWMap": ("delta_crdt_ex_tpu.models.binned_map", "BinnedAWLWWMap"),
    "AWLWWMap": ("delta_crdt_ex_tpu.models.binned_map", "BinnedAWLWWMap"),
    "FlatAWLWWMap": ("delta_crdt_ex_tpu.models.aw_lww_map", "AWLWWMap"),
    "DotStore": ("delta_crdt_ex_tpu.models.state", "DotStore"),
    "HashAWLWWMap": ("delta_crdt_ex_tpu.models.hash_store", "HashAWLWWMap"),
    "HashAWSet": ("delta_crdt_ex_tpu.models.hash_store", "HashAWSet"),
    "HashStore": ("delta_crdt_ex_tpu.models.hash_store", "HashStore"),
}


def __getattr__(name):
    import importlib

    try:
        module, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}") from None
    value = getattr(importlib.import_module(module), attr)
    globals()[name] = value
    return value
