from delta_crdt_ex_tpu.models.aw_lww_map import AWLWWMap
from delta_crdt_ex_tpu.models.state import DotStore

__all__ = ["AWLWWMap", "DotStore"]
