"""Bucket-binned dot-store — the TPU-native state layout of the lattice.

The reference stores a replica as a 2-level nested map
``%{key => %{{value, ts} => MapSet(dots)}}`` plus a causal context
(``aw_lww_map.ex:2-3``) and pays O(log n) per *touched* key on merges.
The first tensorisation here (a flat entry heap, ``models/state.py``)
preserved the semantics but made every merge O(capacity): joining a
512-entry delta into a 1M-key state re-masked and re-scattered the whole
heap. This layout restores the reference's O(touched) cost model while
staying fully static-shaped for XLA:

    entries live in dense rows ``[L, B]`` — row = 64-bit key-hash bucket
    (== leaf of the sync-index digest tree), B slots per bucket.

Because an entry's row is a pure function of its key, every operation
reduces to *row-local* work over a gathered row subset — dense gathers,
vector math along the bin axis, and small element scatters; no
full-state pass, no large scatter-adds (TPU scatters serialize; gathers
and dense reductions don't).

Columns (all device-resident):

    key   : uint64[L, B]   64-bit key hash (host keeps hash → term)
    valh  : uint32[L, B]   value content digest
    ts    : int64[L, B]    LWW timestamp
    node  : int32[L, B]    writer replica as LOCAL slot into ctx tables
    ctr   : uint32[L, B]   dot counter (dot = (gid_of(node), ctr))
    alive : bool[L, B]     slot occupancy
    ehash : uint32[L, B]   maintained entry content hash (digest term)

Maintained summaries (the O(delta) machinery):

    fill : int32[L]        per-row append pointer (alive ⊆ [0, fill))
    amin : uint32[L, R]    min ctr among ALIVE entries per (bucket,
                           writer-slot); U32_MAX when none. A remote
                           context interval can only kill here if it
                           reaches this minimum — the O(R) kill-pruning
                           test that lets merges skip un-killable rows.
    amax : uint32[L, R]    max ctr among ALIVE entries per (bucket,
                           writer-slot); 0 when none. The other half of
                           the pruning test: an interval ``(lo, hi]``
                           with ``lo >= amax`` cannot kill either (all
                           alive dots predate the claim).
    leaf : uint32[L]       leaf digests, updated incrementally (the
                           ``MerkleMap.put`` analog, ``causal_crdt.ex:
                           390-394``): wrapping sum of alive ehash.

Causal context, exactly as before (compressed per-replica max, decomposed
per bucket so partial syncs stay bucket-atomic — see ``models/state.py``
for why that strengthens the reference):

    ctx_gid : uint64[R]    slot → global replica id (0 = empty)
    ctx_max : uint32[L, R] per-bucket per-replica max observed counter

L is fixed per cluster (it is the sync-index depth: ``L = 2**tree_depth``).
B and R are power-of-two tiers; kernels signal overflow via ``ok`` flags
and the host grows the tier and retries (the only data-dependent control
flow, and it lives on the host).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

U32_MAX = jnp.uint32(0xFFFFFFFF)


def pow2_tier(n: int, floor: int = 1) -> int:
    """Round up to the power-of-two capacity tier (tiers bound kernel
    recompiles; every static shape in the engine comes through here)."""
    c = floor
    while c < n:
        c *= 2
    return c


def pow4_tier(n: int, floor: int = 8) -> int:
    """Round up in ×4 steps — the WIRE tier. Sync slices vary per message
    (row count, alive count), and every distinct tier combination is a
    fresh jit compile; on a remote-compile backend a compile can cost
    minutes, so wire shapes trade up to 4× padding for ~half the tier
    count (profiled: the 2-replica convergence bench spent 18.8s of 24.4s
    in 15 tier compiles before this)."""
    c = floor
    while c < n:
        c *= 4
    return c


@partial(
    jax.tree_util.register_dataclass,
    data_fields=[
        "key", "valh", "ts", "node", "ctr", "alive", "ehash",
        "fill", "amin", "amax", "leaf", "ctx_gid", "ctx_max",
    ],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class BinnedStore:
    key: jax.Array  # uint64[L, B]
    valh: jax.Array  # uint32[L, B]
    ts: jax.Array  # int64[L, B]
    node: jax.Array  # int32[L, B]
    ctr: jax.Array  # uint32[L, B]
    alive: jax.Array  # bool[L, B]
    ehash: jax.Array  # uint32[L, B]
    fill: jax.Array  # int32[L]
    amin: jax.Array  # uint32[L, R]
    amax: jax.Array  # uint32[L, R]
    leaf: jax.Array  # uint32[L]
    ctx_gid: jax.Array  # uint64[R]
    ctx_max: jax.Array  # uint32[L, R]

    @property
    def num_buckets(self) -> int:
        return self.key.shape[-2]

    @property
    def bin_capacity(self) -> int:
        return self.key.shape[-1]

    @property
    def capacity(self) -> int:
        return self.key.shape[-2] * self.key.shape[-1]

    @property
    def replica_capacity(self) -> int:
        return self.ctx_gid.shape[-1]

    @staticmethod
    def new(
        num_buckets: int = 64, bin_capacity: int = 16, replica_capacity: int = 8
    ) -> "BinnedStore":
        """Empty lattice state (reference ``AWLWWMap.new/0`` +
        ``compress_dots``, ``causal_crdt.ex:72``)."""
        L, B, R = num_buckets, bin_capacity, replica_capacity
        return BinnedStore(
            key=jnp.zeros((L, B), jnp.uint64),
            valh=jnp.zeros((L, B), jnp.uint32),
            ts=jnp.zeros((L, B), jnp.int64),
            node=jnp.zeros((L, B), jnp.int32),
            ctr=jnp.zeros((L, B), jnp.uint32),
            alive=jnp.zeros((L, B), bool),
            ehash=jnp.zeros((L, B), jnp.uint32),
            fill=jnp.zeros(L, jnp.int32),
            amin=jnp.full((L, R), U32_MAX, jnp.uint32),
            amax=jnp.zeros((L, R), jnp.uint32),
            leaf=jnp.zeros(L, jnp.uint32),
            ctx_gid=jnp.zeros(R, jnp.uint64),
            ctx_max=jnp.zeros((L, R), jnp.uint32),
        )

    def grow(
        self, bin_capacity: int | None = None, replica_capacity: int | None = None
    ) -> "BinnedStore":
        """Pad to a larger tier. L never changes (it is the cluster-agreed
        sync-index geometry); rows and context tables pad with dead slots.
        Rank-agnostic: works on a single state ([L, B] columns) and on a
        neighbour-stacked state ([N, L, B]) alike — padding is always on
        the last axis."""
        b_new = bin_capacity or self.bin_capacity
        r_new = replica_capacity or self.replica_capacity
        db = b_new - self.bin_capacity
        dr = r_new - self.replica_capacity
        assert db >= 0 and dr >= 0
        last = lambda a, d, **kw: jnp.pad(
            a, ((0, 0),) * (a.ndim - 1) + ((0, d),), **kw
        )
        padb = lambda a: last(a, db) if db else a
        return BinnedStore(
            key=padb(self.key),
            valh=padb(self.valh),
            ts=padb(self.ts),
            node=padb(self.node),
            ctr=padb(self.ctr),
            alive=padb(self.alive),
            ehash=padb(self.ehash),
            fill=self.fill,
            amin=last(self.amin, dr, constant_values=U32_MAX) if dr else self.amin,
            amax=last(self.amax, dr) if dr else self.amax,
            leaf=self.leaf,
            ctx_gid=last(self.ctx_gid, dr) if dr else self.ctx_gid,
            ctx_max=last(self.ctx_max, dr) if dr else self.ctx_max,
        )

    def entry_gid(self) -> jax.Array:
        """uint64[L, B]: global replica id of each entry's writer."""
        return self.ctx_gid[self.node]

    def global_ctx(self) -> jax.Array:
        """uint32[R]: the reference's global compressed context view."""
        return jnp.max(self.ctx_max, axis=0)

    def own_counter(self, slot) -> jax.Array:
        """uint32: highest dot counter this replica has issued."""
        return jnp.max(self.ctx_max[:, slot])

    def num_alive(self) -> jax.Array:
        return jnp.sum(self.alive.astype(jnp.int32))

    def bucket_of(self, key: jax.Array) -> jax.Array:
        return (key & jnp.uint64(self.num_buckets - 1)).astype(jnp.int32)
