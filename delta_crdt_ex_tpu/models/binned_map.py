"""BinnedAWLWWMap — the AWLWWMap model over the bucket-binned store.

The TPU-native counterpart of the reference's pluggable ``crdt_module``
(``DeltaCrdt.AWLWWMap``, ``aw_lww_map.ex``), backed by the row-local
kernels of :mod:`delta_crdt_ex_tpu.ops.binned` (see
:mod:`delta_crdt_ex_tpu.models.binned` for the layout). The replica
runtime is generic over this class — the reference's ``crdt_module``
indirection (``causal_crdt.ex:50,72,189,339,384``).

Semantic contract (SURVEY §7 non-negotiables) is identical to the flat
model: add-wins observed-remove; LWW by (ts, writer gid, ctr); causal
join ``(s1∩s2) ∪ (s1∖c2) ∪ (s2∖c1)``; context union = per-replica max.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from delta_crdt_ex_tpu.models.binned import BinnedStore, pow2_tier as _pow2, pow4_tier as _pow4
from delta_crdt_ex_tpu.ops import binned as binned_ops
from delta_crdt_ex_tpu.ops.apply import OP_ADD, OP_CLEAR, OP_PAD, OP_REMOVE
from delta_crdt_ex_tpu.utils.jitcache import named_jit

# named_jit = jax.jit + compile-cache audit registration under the
# kernel's own name (``crdt_jit_compiles_total{name=...}``)
jit_row_apply = named_jit(binned_ops.row_apply)
jit_clear_all = named_jit(binned_ops.clear_all)
jit_merge_slice = named_jit(
    binned_ops.merge_slice, static_argnames=("kill_budget", "max_inserts")
)
jit_merge_rows = named_jit(binned_ops.merge_rows)
jit_extract_rows = named_jit(binned_ops.extract_rows)
jit_extract_own_delta = named_jit(binned_ops.extract_own_delta)
jit_winners_for_keys = named_jit(binned_ops.winners_for_keys)
jit_winner_rows = named_jit(binned_ops.winner_rows)
jit_winner_all = named_jit(binned_ops.winner_all)
jit_compact_rows = named_jit(binned_ops.compact_rows)
jit_tree_from_leaves = named_jit(binned_ops.tree_from_leaves)


class GroupedBatch:
    """A local mutation batch grouped by bucket row for :func:`row_apply`.

    ``index`` maps each original batch position to its (row, col) in the
    grouped arrays so callers can recover per-op results (assigned dot
    counters). Shapes are padded to power-of-two tiers to bound kernel
    recompiles.
    """

    def __init__(self, rows, op, key, valh, ts, index):
        self.rows = rows
        self.op = op
        self.key = key
        self.valh = valh
        self.ts = ts
        self.index = index


def group_batch(num_buckets: int, op, key, valh, ts) -> GroupedBatch:
    """Group flat batch arrays (numpy, batch order) by bucket row.

    Ops for the same key keep their relative order inside a row, which is
    all the sequential batch semantics need (ops on different keys
    commute; see :func:`delta_crdt_ex_tpu.ops.binned.row_apply`).
    ``clear`` must be split out by the caller (``clear_all``).
    """
    n = len(op)
    bucket = (key & np.uint64(num_buckets - 1)).astype(np.int64)
    order: dict[int, int] = {}
    cols = np.zeros(n, np.int64)
    counts: dict[int, int] = {}
    urow_of = np.zeros(n, np.int64)
    for i in range(n):
        b = int(bucket[i])
        if b not in order:
            order[b] = len(order)
            counts[b] = 0
        urow_of[i] = order[b]
        cols[i] = counts[b]
        counts[b] += 1
    u = _pow2(max(len(order), 1))
    m = _pow2(max(counts.values(), default=1))
    rows = np.full(u, -1, np.int32)
    for b, r in order.items():
        rows[r] = b
    g_op = np.full((u, m), OP_PAD, np.int32)
    g_key = np.zeros((u, m), np.uint64)
    g_valh = np.zeros((u, m), np.uint32)
    g_ts = np.zeros((u, m), np.int64)
    g_op[urow_of, cols] = op
    g_key[urow_of, cols] = key
    g_valh[urow_of, cols] = valh
    g_ts[urow_of, cols] = ts
    return GroupedBatch(rows, g_op, g_key, g_valh, g_ts, (urow_of, cols))


_CTX_GAP_MSG = (
    "delta-interval slice is not contiguous with the local context; "
    "re-sync with a full-row slice (ctx_lo=0)"
)


class CtxGapError(ValueError):
    """A delta-interval slice is not contiguous with the local context
    (``need_ctx_gap``): growth cannot heal this — the *sender* must fall
    back to a full-row (state-form, ``ctx_lo=0``) slice. A distinct type
    so sync layers that ship delta-intervals can catch it and request the
    fallback — the replica runtime's eager delta pushes do exactly that
    (``runtime/replica.py``: ``_push_deltas`` sends intervals, the
    ``_handle_entries_inner`` catcher answers a gap with a ``GetDiffMsg``
    full-row repair).

    ``gap_rows`` (numpy bool[U], when the row-granular kernel raised) is
    the per-row gap mask; ``gapped_members`` (set[int], when a grouped
    fan-in merge raised) maps those rows back to the offending member
    slices so the caller replays only the gapped senders solo and keeps
    the clean members in one grouped dispatch."""

    gap_rows = None  # numpy bool[U] from the row-granular kernel
    gapped_members: "set[int] | None" = None  # member indices of a grouped merge


def tier_retry_merge(
    state: BinnedStore,
    sl,
    merge,
    compact,
    kill_budget: int,
    max_inserts: int,
    on_grow=None,
):
    """The tier-escalation policy shared by the single-state and the
    vmapped fan-out merge paths: run ``merge(state, sl, kill_budget,
    max_inserts)``, and on overflow grow the offending tier and retry —
    gid table ×2, kill budget ×4 (capped at the slice's row count),
    insert tier ×4 (capped at the slice grid), and for fill overflow one
    compact first, then bin capacity ×2. ``merge`` may be vmapped: flags
    are reduced with any()/all(), so one overflowing neighbour retiers
    the whole stack.

    Returns ``(new_state, last_result, n_retries)``; each retry is one
    fresh jit compile of the new tier combination. Worst case retries ≤
    ``log4(U/kb0) + 1 + log2(B_end/B0) + log2(R_end/R0)`` (holes cannot
    reappear between a compact and the next retry — only successful
    merges create them — so after one compact further fill overflows go
    straight to bin growth).

    Raises :class:`CtxGapError` on a non-contiguous delta-interval:
    growth cannot heal that — the *sender* must fall back to a full-row
    (state-form, ``ctx_lo=0``) slice.
    """
    compacted = False
    retries = 0
    mi = max_inserts
    while True:
        res = merge(state, sl, kill_budget, mi)
        if bool(np.asarray(res.ok).all()):
            return res.state, res, retries
        retries += 1
        if bool(np.asarray(res.need_ctx_gap).any()):
            raise CtxGapError(_CTX_GAP_MSG)
        if bool(np.asarray(res.need_gid_grow).any()):
            state = state.grow(replica_capacity=state.replica_capacity * 2)
            if on_grow:
                on_grow(state)
        if bool(np.asarray(res.need_kill_tier).any()):
            kill_budget = min(kill_budget * 4, int(sl.rows.shape[0]))
        if bool(np.asarray(res.need_ins_tier).any()):
            mi = min(mi * 4, int(sl.alive.size))
        if bool(np.asarray(res.need_fill_compact).any()):
            if not compacted:
                state = compact(state)
                compacted = True
            else:
                state = state.grow(bin_capacity=state.bin_capacity * 2)
                if on_grow:
                    on_grow(state)


def merge_rows_into(state: BinnedStore, sl, on_grow=None):
    """Merge a RowSlice via the row-granular kernel
    (:func:`~delta_crdt_ex_tpu.ops.binned.merge_rows`) — the runtime's
    merge path: slices there are at most ``max_sync_size`` rows, where
    row-granular cost equals the element-scatter path but needs no
    kill-budget or insert tiers (the only escapes left are genuine
    growth). Returns ``(new_state, last_result)``; raises
    :class:`CtxGapError` on a non-contiguous delta-interval."""
    while True:
        res = jit_merge_rows(state, sl)
        if bool(res.ok):
            return res.state, res
        if bool(res.need_ctx_gap):
            err = CtxGapError(_CTX_GAP_MSG)
            # per-row gap mask (host copy: error path, cost irrelevant)
            # so grouped callers can isolate the gapped member slices
            err.gap_rows = np.asarray(res.gap_row)
            raise err
        if bool(res.need_gid_grow):
            state = state.grow(replica_capacity=state.replica_capacity * 2)
            if on_grow:
                on_grow(state)
        if bool(res.need_fill_grow):
            state = state.grow(bin_capacity=state.bin_capacity * 2)
            if on_grow:
                on_grow(state)


#: entry columns of the EntriesMsg wire dict, in RowSlice order
_WIRE_ENTRY_COLS = ("key", "valh", "ts", "ctr", "alive")


def combine_entry_arrays(
    arrays_list: list, to_device: bool = True
) -> "tuple[binned_ops.RowSlice, list]":
    """Combine k host-plane ``EntriesMsg`` column dicts into ONE
    :class:`~delta_crdt_ex_tpu.ops.binned.RowSlice` — the ingress
    coalescing fan-in: instead of k sequential ``merge_rows`` dispatches,
    the runtime merges the whole group with one.

    Safety preconditions (the caller's grouping rules):

    - bucket rows are pairwise DISJOINT across messages — ``merge_rows``
      is row-local, so the combined merge then equals the sequential
      merges bit-for-bit (insert/kill/pack decisions per row see exactly
      the state the sequential merge would);
    - entry lane tiers are EQUAL (``key.shape[1]``) — the row-compact
      sort width is then identical to the per-message merges, so even
      dead-slot bytes match.

    Writer tables are unioned in first-appearance order (message order,
    slot order within a message) — the same order sequential
    ``merge_gid_tables`` calls would append unknown gids in, keeping
    ``ctx_gid`` bit-identical. Each message's ``node`` column and context
    columns are remapped into the union table; zero-gid (empty) slots
    map to a guaranteed-empty padding column so they stay "no local
    slot" (-1) through the kernel's remap, exactly as before combining.

    Returns ``(slice, offsets)`` where ``offsets[i] = (lo, hi)`` is
    message i's row range in the combined slice (for per-message
    accounting over the kernel's per-row counts). ``to_device=False``
    keeps the combined columns as host numpy (the fleet scheduler
    stacks many replicas' combined slices along a leading axis first
    and moves the stack to device in one hop — see
    :func:`stack_entry_slices`).
    """
    # union writer table, first-appearance order
    union_idx: dict[int, int] = {}
    for a in arrays_list:
        for g in np.asarray(a["ctx_gid"]).tolist():
            if g != 0 and g not in union_idx:
                union_idx[g] = len(union_idx)
    rr_u = len(union_idx)
    rp = _pow2(rr_u + 1, floor=2)  # ≥1 trailing zero column, tiered
    null_col = rr_u  # first padding column: gid 0, remaps to -1
    ctx_gid = np.zeros(rp, np.uint64)
    if rr_u:
        ctx_gid[:rr_u] = np.array(list(union_idx), dtype=np.uint64)

    parts: dict[str, list] = {c: [] for c in _WIRE_ENTRY_COLS}
    rows_parts: list = []
    node_parts: list = []
    ctx_rows_parts: list = []
    ctx_lo_parts: list = []
    offsets: list[tuple[int, int]] = []
    off = 0
    for a in arrays_list:
        table = np.asarray(a["ctx_gid"])
        rr_i = table.shape[0]
        remap = np.full(rr_i, null_col, np.int64)
        nz = np.nonzero(table)[0]
        remap[nz] = [union_idx[int(g)] for g in table[nz].tolist()]
        node = np.asarray(a["node"])
        node_parts.append(remap[np.clip(node, 0, rr_i - 1)].astype(np.int32))
        u_i = node.shape[0]
        crows = np.zeros((u_i, rp), np.uint32)
        clo = np.zeros((u_i, rp), np.uint32)
        crows[:, remap[nz]] = np.asarray(a["ctx_rows"])[:, nz]
        clo[:, remap[nz]] = np.asarray(a["ctx_lo"])[:, nz]
        ctx_rows_parts.append(crows)
        ctx_lo_parts.append(clo)
        rows_parts.append(np.asarray(a["rows"], np.int32))
        for c in _WIRE_ENTRY_COLS:
            parts[c].append(np.asarray(a[c]))
        offsets.append((off, off + u_i))
        off += u_i

    cols = {c: np.concatenate(parts[c], axis=0) for c in _WIRE_ENTRY_COLS}
    rows = np.concatenate(rows_parts)
    node = np.concatenate(node_parts, axis=0)
    ctx_rows = np.concatenate(ctx_rows_parts, axis=0)
    ctx_lo = np.concatenate(ctx_lo_parts, axis=0)

    # pad the row axis to the wire tier (bounds distinct compiles); -1
    # rows are dropped by the kernel's valid mask
    u_pad = _pow4(max(off, 1))
    if u_pad != off:
        pad = u_pad - off
        rows = np.concatenate([rows, np.full(pad, -1, np.int32)])
        node = np.concatenate([node, np.zeros((pad,) + node.shape[1:], node.dtype)])
        ctx_rows = np.concatenate([ctx_rows, np.zeros((pad, rp), np.uint32)])
        ctx_lo = np.concatenate([ctx_lo, np.zeros((pad, rp), np.uint32)])
        cols = {
            c: np.concatenate([v, np.zeros((pad,) + v.shape[1:], v.dtype)])
            for c, v in cols.items()
        }

    put = jnp.asarray if to_device else (lambda a: a)
    sl = binned_ops.RowSlice(
        rows=put(rows),
        key=put(cols["key"]),
        valh=put(cols["valh"]),
        ts=put(cols["ts"]),
        node=put(node),
        ctr=put(cols["ctr"]),
        alive=put(cols["alive"]),
        ctx_rows=put(ctx_rows),
        ctx_lo=put(ctx_lo),
        ctx_gid=put(ctx_gid),
    )
    return sl, offsets


#: RowSlice fields padded along the writer-table (Rr) axis by
#: :func:`stack_entry_slices`'s ragged masking
_SLICE_CTX_FIELDS = ("ctx_rows", "ctx_lo")


def stack_entry_slices(
    slices: list, lanes: int | None = None
) -> "tuple[binned_ops.RowSlice, int]":
    """``combine_entry_arrays`` generalised to a replica axis: stack k
    per-replica combined slices (HOST numpy form, ``to_device=False``)
    into one :class:`~delta_crdt_ex_tpu.ops.binned.RowSlice` with a
    leading replica axis, for ONE ``fleet_merge_rows`` dispatch
    (:mod:`delta_crdt_ex_tpu.runtime.transition`).

    Ragged fan-in is handled by PER-REPLICA MASKING, not truncation:

    - row counts pad to the stack's max row tier with ``-1`` rows (the
      kernel's valid mask drops them — bit-for-bit no-ops);
    - writer-table widths pad to the max with zero gids (zero = empty
      slot: ``merge_gid_tables`` skips them and an all-zero interval
      column claims nothing);
    - entry-lane tiers (``key.shape[1]``) must be EQUAL — lane padding
      would change the row-compact sort width and with it dead-slot
      bytes, breaking bit parity (the same-lane-tier rule grouped
      ingest already enforces); the fleet buckets unequal tiers into
      separate dispatches instead.

    ``lanes`` pads the REPLICA axis (compile-shape tiering for the
    batched dispatch) with all-padding lanes that merge nothing; padded
    lanes replicate lane 0's geometry. Returns ``(stacked slice,
    real_rows)`` where ``real_rows`` counts non-padding bucket rows
    across real lanes — the ragged-mask fill-ratio numerator.
    """
    n = len(slices)
    lanes = n if lanes is None else lanes
    s_widths = {s.key.shape[1] for s in slices}
    if len(s_widths) > 1:
        raise ValueError(f"unequal entry-lane tiers in one stack: {s_widths}")
    u_to = max(s.rows.shape[0] for s in slices)
    rp_to = max(s.ctx_gid.shape[0] for s in slices)
    real_rows = 0

    def pad(sl: binned_ops.RowSlice) -> binned_ops.RowSlice:
        du = u_to - sl.rows.shape[0]
        drp = rp_to - sl.ctx_gid.shape[0]
        out = {}
        for c in binned_ops.RowSlice._fields:
            a = np.asarray(getattr(sl, c))
            if c == "rows":
                if du:
                    a = np.concatenate([a, np.full(du, -1, a.dtype)])
            elif c == "ctx_gid":
                if drp:
                    a = np.concatenate([a, np.zeros(drp, a.dtype)])
            else:
                if c in _SLICE_CTX_FIELDS and drp:
                    a = np.concatenate(
                        [a, np.zeros((a.shape[0], drp), a.dtype)], axis=1
                    )
                if du:
                    a = np.concatenate(
                        [a, np.zeros((du,) + a.shape[1:], a.dtype)]
                    )
            out[c] = a
        return binned_ops.RowSlice(**out)

    padded = []
    for sl in slices:
        rows = np.asarray(sl.rows)
        real_rows += int((rows >= 0).sum())
        padded.append(pad(sl))
    if lanes > n:
        blank = binned_ops.RowSlice(
            rows=np.full(u_to, -1, np.int32),
            **{
                c: np.zeros_like(np.asarray(getattr(padded[0], c)))
                for c in binned_ops.RowSlice._fields
                if c != "rows"
            },
        )
        padded.extend([blank] * (lanes - n))
    stacked = binned_ops.RowSlice(
        **{
            c: jnp.asarray(np.stack([np.asarray(getattr(s, c)) for s in padded]))
            for c in binned_ops.RowSlice._fields
        }
    )
    return stacked, real_rows


def merge_group_into(state: BinnedStore, arrays_list: list, on_grow=None):
    """Grouped fan-in merge: combine k compatible host-plane entry
    slices (:func:`combine_entry_arrays`) and join them with ONE
    row-granular kernel dispatch (:func:`merge_rows_into`) — the
    bench-proven grouped-merge amortisation (one device call for the
    whole group) landed on the runtime ingress path. Returns
    ``(new_state, result, offsets)``; raises :class:`CtxGapError` when
    ANY member's delta-interval gaps, with ``gapped_members`` set to the
    offending member indices (mapped from the kernel's per-row gap mask
    through ``offsets``) so the caller replays only those solo and keeps
    the clean members in one grouped dispatch.
    """
    sl, offsets = combine_entry_arrays(arrays_list)
    try:
        new_state, res = merge_rows_into(state, sl, on_grow=on_grow)
    except CtxGapError as err:
        if err.gap_rows is not None:
            err.gapped_members = {
                i
                for i, (lo, hi) in enumerate(offsets)
                if bool(err.gap_rows[lo:hi].any())
            }
        raise
    return new_state, res, offsets


def merge_into(
    state: BinnedStore, sl, kill_budget: int = 16, on_grow=None, n_alive: int | None = None
):
    """Merge a :class:`~delta_crdt_ex_tpu.ops.binned.RowSlice` into
    ``state`` via :func:`tier_retry_merge` over the element-scatter
    kernel — the bulk fan-in path (cost ∝ slice entries, best for
    sparse many-row slices like the bench's 8192-row delta groups).
    Returns ``(new_state, last_result)``. ``on_grow(state)`` fires after
    each capacity growth (telemetry hook)."""
    # compact the insert scatter to a power-of-two tier of the slice's
    # alive count (scatter cost is per index entry; the [U, S] grid is
    # mostly padding); callers that built the slice from host arrays pass
    # n_alive to avoid a device->host readback here
    if n_alive is None:
        n_alive = int(np.asarray(sl.alive).sum())
    new_state, res, _ = tier_retry_merge(
        state,
        sl,
        lambda st, s, kb, mi: jit_merge_slice(st, s, kill_budget=kb, max_inserts=mi),
        jit_compact_rows,
        kill_budget,
        _pow4(max(n_alive, 1)),
        on_grow=on_grow,
    )
    return new_state, res


class BinnedAWLWWMap:
    """Model class: op vocabulary + kernels over :class:`BinnedStore`."""

    #: mutation name → (op code, arity of user args)
    OPS = {
        "add": (OP_ADD, 2),  # add(key, value)    aw_lww_map.ex:99
        "remove": (OP_REMOVE, 1),  # remove(key)  aw_lww_map.ex:133
        "clear": (OP_CLEAR, 0),  # clear()        aw_lww_map.ex:148
    }

    Store = BinnedStore
    new = staticmethod(BinnedStore.new)
    group_batch = staticmethod(group_batch)
    row_apply = staticmethod(jit_row_apply)
    clear_all = staticmethod(jit_clear_all)
    merge_slice = staticmethod(jit_merge_slice)
    merge_rows = staticmethod(jit_merge_rows)
    extract_rows = staticmethod(jit_extract_rows)
    extract_own_delta = staticmethod(jit_extract_own_delta)
    winners_for_keys = staticmethod(jit_winners_for_keys)
    winner_rows = staticmethod(jit_winner_rows)
    winner_all = staticmethod(jit_winner_all)
    compact_rows = staticmethod(jit_compact_rows)
    tree_from_leaves = staticmethod(jit_tree_from_leaves)
    merge_into = staticmethod(merge_into)
    merge_rows_into = staticmethod(merge_rows_into)
    merge_group_into = staticmethod(merge_group_into)
    combine_entry_arrays = staticmethod(combine_entry_arrays)
    RowSlice = binned_ops.RowSlice

    @staticmethod
    def read_view(d: dict):
        """Shape the resolved winner dict into this model's read form
        (the map: identity). Models sharing the kernel table override
        this (e.g. :class:`AWSet` below)."""
        return d

    # -- replica/fleet backend seam (ISSUE 8): each dot-store backend
    # declares its own growth escape and batch-compatibility key instead
    # of the runtime hardcoding binned geometry

    #: snapshot backend tag (recorded in snapshots; cross-backend
    #: restore must go through extraction — MIGRATING.md)
    backend = "binned"
    #: static (non-array) Store fields — none for this backend
    STORE_META = ()

    @staticmethod
    def grow_for_apply(state: BinnedStore) -> BinnedStore:
        """Local-mutation overflow escape: bin tier ×2."""
        return state.grow(bin_capacity=state.bin_capacity * 2)

    @staticmethod
    def post_apply(state: BinnedStore, res, on_grow=None) -> BinnedStore:
        """Post-commit hook (no load advisory for the binned store)."""
        return state

    @staticmethod
    def load_high(max_window_fill: int, probe_window: int) -> bool:
        """No fleet growth advisory: bin growth happens via the
        per-merge ``need_fill_grow`` escape only."""
        return False

    @staticmethod
    def store_load_high(state: BinnedStore) -> bool:
        return False

    @staticmethod
    def geometry(state: BinnedStore) -> tuple:
        """Batch-compatibility key: fleet batch buckets require equal
        state geometry — for this backend the per-bucket lane tier B,
        which is why binned fleets split batches at tier boundaries."""
        return (
            "binned",
            state.num_buckets,
            state.bin_capacity,
            state.replica_capacity,
        )

    @staticmethod
    def geometry_stacked(stacked) -> tuple:
        """Same key read from a fleet-stacked pytree's shapes (must not
        materialise a lane)."""
        return (
            "binned",
            stacked.key.shape[1],
            stacked.key.shape[2],
            stacked.ctx_gid.shape[1],
        )

    @classmethod
    def fleet_merge_rows(cls, states, slices):
        from delta_crdt_ex_tpu.runtime import transition

        return transition.jit_fleet_merge_rows(states, slices)

    # -- batched fleet egress (ISSUE 10): one vmapped extraction serves
    # a whole sync-tick bucket. Each returns ``(stacked_slice, s_tiers)``
    # where ``s_tiers`` trims each lane's entry-lane axis back to the
    # member's own solo tier (None = the backend's lane axis is static
    # state geometry — nothing to trim; lane k of the stack IS the solo
    # extraction bit-for-bit).

    @classmethod
    def fleet_extract_rows(cls, states, rows):
        from delta_crdt_ex_tpu.runtime import transition

        return transition.jit_fleet_extract_rows(states, rows), None

    @classmethod
    def fleet_extract_own_delta(cls, states, rows, self_slots, gid_selfs, lo):
        from delta_crdt_ex_tpu.runtime import transition

        return (
            transition.jit_fleet_interval_slices(
                states, rows, self_slots, gid_selfs, lo
            ),
            None,
        )

    # NB: no fleet_tree_from_leaves seam — leaf digests are bit-identical
    # across backends, so the fleet's batched tree build groups members
    # by leaf length alone (possibly mixing backends in one stack) and
    # calls transition.jit_fleet_tree_from_leaves directly.

    # -- mesh-sharded fleet seam (ISSUE 13): the same batched forms
    # lifted onto a replica-sharded device mesh. Lane k of the sharded
    # dispatch is bit-for-bit the vmapped (and therefore the solo)
    # kernel on lane k's inputs — the fleet's mesh mode swaps these in
    # without touching any bookkeeping.

    @classmethod
    def mesh_fleet_merge_rows(cls, mesh, states, slices):
        from delta_crdt_ex_tpu.runtime import transition

        return transition.jit_mesh_fleet_merge_rows(mesh, states, slices)

    @classmethod
    def mesh_fleet_extract_rows(cls, mesh, states, rows):
        from delta_crdt_ex_tpu.runtime import transition

        return transition.jit_mesh_fleet_extract_rows(mesh, states, rows), None

    @classmethod
    def mesh_fleet_extract_own_delta(
        cls, mesh, states, rows, self_slots, gid_selfs, lo
    ):
        from delta_crdt_ex_tpu.runtime import transition

        return (
            transition.jit_mesh_fleet_interval_slices(
                mesh, states, rows, self_slots, gid_selfs, lo
            ),
            None,
        )


class AWSet(BinnedAWLWWMap):
    """Add-wins observed-remove set — the second δ-CRDT of the reference
    family (shipped by pre-0.4 versions of the Elixir library; v0.5.10
    kept only AWLWWMap and the pluggable ``crdt_module`` seam this class
    plugs into, ``delta_crdt.ex:56``).

    Presence-only semantics over the identical kernel table: an element
    is a key whose stored value is the constant ``True``; adds/removes
    keep full add-wins observed-remove behaviour (a concurrent add
    survives a remove that did not observe it), and ``read`` returns the
    member set. Diffs feed as ``("add", elem, True)`` / ``("remove",
    elem)``.
    """

    OPS = {
        "add": (OP_ADD, 1),  # add(elem)
        "remove": (OP_REMOVE, 1),  # remove(elem)
        "clear": (OP_CLEAR, 0),  # clear()
    }

    @staticmethod
    def read_view(d: dict):
        return set(d)
