"""LWW read kernels (reference ``AWLWWMap.read``, ``aw_lww_map.ex:211-224``).

The reference resolves every key with ``Enum.max_by`` on the timestamp,
leaving ties to map iteration order. Here ties break deterministically by
``(ts, writer gid, counter)`` so reads are replica- and order-independent
(a strict improvement, documented in SURVEY §7 "Hard parts").

Three paths:

- :func:`winners_for_keys` — O(k·C) masked lexicographic argmax, vmapped
  over a small key batch (the per-mutation diff path);
- :func:`winner_mask` — sort-based winner selection over all (or a bucket
  subset of) entries in one ``lax.sort``;
- :func:`winner_slice` — winners of a bucket subset compacted into a
  fixed-size output (the sync-round diff/callback path: bounded like the
  sync itself, no O(C) host transfer).

Winners carry the writer's **global** id so the host can look up value
payloads by dot without mirroring device slot tables.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from delta_crdt_ex_tpu.models.state import DotStore

_SENTINEL = jnp.uint64(0xFFFFFFFFFFFFFFFF)
_TS_MIN = jnp.int64(-(2**62))


class Winners(NamedTuple):
    found: jnp.ndarray  # bool[k]
    gid: jnp.ndarray  # uint64[k] writer global id
    ctr: jnp.ndarray  # uint32[k]
    valh: jnp.ndarray  # uint32[k]
    ts: jnp.ndarray  # int64[k]


def winners_for_keys(state: DotStore, keys: jnp.ndarray) -> Winners:
    """Current LWW winner entry for each queried key hash."""
    gid = state.entry_gid()

    def one(k):
        m = state.alive & (state.key == k)
        found = jnp.any(m)
        ts_m = jnp.where(m, state.ts, _TS_MIN)
        m1 = m & (ts_m == jnp.max(ts_m))
        gid_m = jnp.where(m1, gid, 0)
        m2 = m1 & (gid_m == jnp.max(gid_m))
        ctr_m = jnp.where(m2, state.ctr, 0)
        m3 = m2 & (ctr_m == jnp.max(ctr_m))
        idx = jnp.argmax(m3)
        return found, gid[idx], state.ctr[idx], state.valh[idx], state.ts[idx]

    f, g, c, v, t = jax.vmap(one)(keys)
    return Winners(f, g, c, v, t)


def winner_mask(state: DotStore, participate: jnp.ndarray | None = None) -> jnp.ndarray:
    """bool[C]: marks the LWW-winning entry of every (participating) key.

    One multi-operand ``lax.sort`` by (key, ts, gid, ctr); the last entry
    of each key run wins.
    """
    c = state.capacity
    mask = state.alive if participate is None else (state.alive & participate)
    skey = jnp.where(mask, state.key, _SENTINEL)
    idx = jnp.arange(c, dtype=jnp.int32)
    skey_s, _, _, _, idx_s = jax.lax.sort(
        (skey, state.ts, state.entry_gid(), state.ctr, idx), num_keys=4
    )
    last_of_run = jnp.concatenate([skey_s[1:] != skey_s[:-1], jnp.ones(1, bool)])
    win_sorted = last_of_run & (skey_s != _SENTINEL)
    return jnp.zeros(c, bool).at[idx_s].set(win_sorted)


class WinnerSlice(NamedTuple):
    count: jnp.ndarray  # int32 (valid prefix length)
    ok: jnp.ndarray  # bool (out_size sufficed)
    key: jnp.ndarray  # uint64[S]
    gid: jnp.ndarray  # uint64[S]
    ctr: jnp.ndarray  # uint32[S]
    valh: jnp.ndarray  # uint32[S]
    ts: jnp.ndarray  # int64[S]


def winner_slice(
    state: DotStore,
    bucket_mask: jnp.ndarray | None,
    out_size: int,
) -> WinnerSlice:
    """Per-key LWW winners within a bucket subset, compacted to ``out_size``."""
    if bucket_mask is None:
        participate = None
    else:
        bucket = (state.key & jnp.uint64(state.num_buckets - 1)).astype(jnp.int32)
        participate = bucket_mask[bucket]
    win = winner_mask(state, participate)

    rank = jnp.cumsum(win.astype(jnp.int32)) - 1
    count = jnp.sum(win.astype(jnp.int32))
    ok = count <= out_size
    tgt = jnp.where(win, rank, out_size)

    def compact(col, dtype):
        return jnp.zeros(out_size, dtype).at[tgt].set(col, mode="drop")

    return WinnerSlice(
        count=count,
        ok=ok,
        key=compact(state.key, jnp.uint64),
        gid=compact(state.entry_gid(), jnp.uint64),
        ctr=compact(state.ctr, jnp.uint32),
        valh=compact(state.valh, jnp.uint32),
        ts=compact(state.ts, jnp.int64),
    )
