"""Device-resident digest tree — the sync index (reference: ``merkle_map`` dep).

The reference indexes its state with a MerkleMap: a hash tree over keys
whose level-by-level comparison locates divergent keys in O(diff) work
(call sites ``causal_crdt.ex:94-96,254-255``). Crucially it hashes the
**internal** dot-map representation, so replicas holding the same user
value under different dots still sync (test ``causal_crdt_test.exs:154-171``).

TPU-native redesign: keys land in ``L = 2**depth`` leaf buckets by key
hash; each bucket's digest is the wrapping-u32 **sum** of its alive
entries' content hashes (commutative → order-free scatter-add, and
incrementally updatable). Entry hashes cover (key, value digest, ts, dot)
— the internal representation, preserving the reference property above.
Parent levels combine children through an asymmetric mix so sibling order
matters. The whole tree is (re)built in one fused device call; the
continuation ping-pong of the reference becomes a bounded-frontier level
walk over these arrays (:mod:`delta_crdt_ex_tpu.runtime.sync`).
"""

from __future__ import annotations

import jax.numpy as jnp

from delta_crdt_ex_tpu.models.state import DotStore

_M1 = jnp.uint64(0xBF58476D1CE4E5B9)
_M2 = jnp.uint64(0x94D049BB133111EB)
_P1 = jnp.uint32(0x85EBCA6B)
_P2 = jnp.uint32(0xC2B2AE35)


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    x = (x ^ (x >> jnp.uint64(30))) * _M1
    x = (x ^ (x >> jnp.uint64(27))) * _M2
    return x ^ (x >> jnp.uint64(31))


def _mix32(x: jnp.ndarray) -> jnp.ndarray:
    x = (x ^ (x >> jnp.uint32(16))) * _P1
    x = (x ^ (x >> jnp.uint32(13))) * _P2
    return x ^ (x >> jnp.uint32(16))


def entry_hashes(state: DotStore) -> jnp.ndarray:
    """uint32[C] content hash of each entry (replica-independent: uses the
    writer's global id, not the local slot)."""
    gid = state.entry_gid()
    h = _mix64(
        state.key
        ^ _mix64(gid ^ state.ctr.astype(jnp.uint64))
        ^ _mix64(state.ts.astype(jnp.uint64) ^ (state.valh.astype(jnp.uint64) << jnp.uint64(32)))
    )
    return (h ^ (h >> jnp.uint64(32))).astype(jnp.uint32)


def leaf_digests(state: DotStore, depth: int) -> jnp.ndarray:
    """uint32[2**depth] per-bucket digests (sum of alive entry hashes)."""
    num_buckets = 1 << depth
    bucket = (state.key & jnp.uint64(num_buckets - 1)).astype(jnp.int32)
    h = entry_hashes(state) * state.alive.astype(jnp.uint32)
    return jnp.zeros(num_buckets, jnp.uint32).at[bucket].add(h)


def digest_tree(state: DotStore, depth: int) -> list[jnp.ndarray]:
    """All tree levels, root first: ``[u32[1], u32[2], …, u32[2**depth]]``.

    Level ``d`` node ``i`` covers leaf buckets ``[i*2**(depth-d), …)``.
    """
    levels = [leaf_digests(state, depth)]
    for _ in range(depth):
        cur = levels[-1].reshape(-1, 2)
        left = _mix32(cur[:, 0] ^ _P1)
        right = _mix32(cur[:, 1] ^ _P2)
        levels.append(left + (right << jnp.uint32(1)) + jnp.uint32(0x9E3779B9))
    return levels[::-1]
