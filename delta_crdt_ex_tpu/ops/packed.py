"""Packed-entry merge variant — the roofline's named lever (BASELINE.md
"Merge-kernel roofline").

The column-layout merge (:func:`delta_crdt_ex_tpu.ops.binned.merge_slice`)
pays 7 element scatters per inserted entry — one per entry column — and
TPU scatter cost is per index ENTRY, not per byte (measured ~10 ns/entry
regardless of payload width). Packing the 7 entry columns into one
``uint32[L, B, 8]`` word table turns them into ONE vector-valued scatter:
a ~7× cut of the merge's dominant random-access term (the roofline's
13.9k → ~50k merges/s ceiling move). The read side pays bitcast/unpack
vector ops, which XLA fuses into consumers.

This module is the PROMOTED fast path for bulk fan-in merges (chip A/B
2026-07-31: packed 8,852.8 vs columns 4,211.9 merges/s at the full
north-star config — 2.10×, past the 1.2× promotion bar; BASELINE.md
"Merge-kernel roofline"):

- ``merge_slice_packed`` is bit-parity tested against ``merge_slice``
  (``tests/test_packed_parity.py``) over randomized workloads,
  including tier growth through the shared escalation ladder;
- ``bench.py`` times it as the primary layout (``BENCH_PACKED=0``
  reverts to columns) and still A/Bs both layouts in one run;
- the library fan-out path accepts packed stacks
  (``parallel/batched_sync.py::fanout_merge_into``);
- the full-config CPU A/B measured a wash (the micro-probe's predicted
  CPU loss does not materialise once XLA fuses the whole call), so the
  promotion carries no CPU downside.

Plane layout (all uint32): ``[key_lo, key_hi, ts_lo, ts_hi, valh, ctr,
ehash, meta]`` with ``meta = node | alive << 16`` (writer slots are
< 2^16 by construction — R tiers are small).

Semantics are pinned to the column kernel, which itself pins to the
reference join (``aw_lww_map.ex:153-209``); see ``merge_slice``'s
docstring for the insert/kill/context contract.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from delta_crdt_ex_tpu.models.binned import BinnedStore, U32_MAX
from delta_crdt_ex_tpu.ops.binned import (
    MergeResult,
    _row_amin,
    _row_amax,
    _slice_view,
    _table_lookup,
    compact_rows,
    encode_dot,
    entry_hash,
    flagged_first_order,
)

_PLANES = 8
_META = 7


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["words", "fill", "amin", "amax", "leaf", "ctx_gid", "ctx_max"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class PackedStore:
    """``BinnedStore`` with the 7 entry columns fused into one word
    table. Aux/summary columns are identical; the duck-typed properties
    let :func:`_slice_view` and the shared merge math run unchanged."""

    words: jax.Array  # uint32[L, B, 8]
    fill: jax.Array  # int32[L]
    amin: jax.Array  # uint32[L, R]
    amax: jax.Array  # uint32[L, R]
    leaf: jax.Array  # uint32[L]
    ctx_gid: jax.Array  # uint64[R]
    ctx_max: jax.Array  # uint32[L, R]

    @property
    def num_buckets(self) -> int:
        return self.words.shape[-3]

    @property
    def bin_capacity(self) -> int:
        return self.words.shape[-2]

    @property
    def replica_capacity(self) -> int:
        return self.ctx_gid.shape[-1]

    def grow(
        self, bin_capacity: int | None = None, replica_capacity: int | None = None
    ) -> "PackedStore":
        """Pad to a larger tier — the packed analog of
        :meth:`BinnedStore.grow`, so :func:`tier_retry_merge` escalates
        either layout through the same policy. Growth is a
        fresh-jit-compile event already, so the round-trip through the
        column layout costs nothing that matters."""
        return pack(
            unpack(self).grow(
                bin_capacity=bin_capacity, replica_capacity=replica_capacity
            )
        )


def _b32(a) -> jax.Array:
    return jax.lax.bitcast_convert_type(a, jnp.uint32)


def pack(state: BinnedStore) -> PackedStore:
    """Column → packed layout (rank-agnostic: leading batch axes pass
    through, so a neighbour-stacked state packs in one call)."""
    assert state.replica_capacity < (1 << 16), "meta plane holds node in 16 bits"
    meta = state.node.astype(jnp.uint32) | (
        state.alive.astype(jnp.uint32) << jnp.uint32(16)
    )
    words = jnp.concatenate(
        [
            _b32(state.key),  # [..., L, B, 2]
            _b32(state.ts),  # [..., L, B, 2]
            state.valh[..., None],
            state.ctr[..., None],
            state.ehash[..., None],
            meta[..., None],
        ],
        axis=-1,
    )
    return PackedStore(
        words=words,
        fill=state.fill,
        amin=state.amin,
        amax=state.amax,
        leaf=state.leaf,
        ctx_gid=state.ctx_gid,
        ctx_max=state.ctx_max,
    )


def unpack(p: PackedStore) -> BinnedStore:
    """Packed → column layout (bitwise inverse of :func:`pack`)."""
    w = p.words
    meta = w[..., _META]
    return BinnedStore(
        key=jax.lax.bitcast_convert_type(w[..., 0:2], jnp.uint64),
        valh=w[..., 4],
        ts=jax.lax.bitcast_convert_type(w[..., 2:4], jnp.int64),
        node=(meta & jnp.uint32(0xFFFF)).astype(jnp.int32),
        ctr=w[..., 5],
        alive=(meta >> jnp.uint32(16)) != 0,
        ehash=w[..., 6],
        fill=p.fill,
        amin=p.amin,
        amax=p.amax,
        leaf=p.leaf,
        ctx_gid=p.ctx_gid,
        ctx_max=p.ctx_max,
    )


def merge_slice_packed_fused(
    state: PackedStore,
    sl,
    kill_budget: int,
    max_inserts: int | None = None,
) -> MergeResult:
    """:func:`merge_slice_packed` with the aux-table updates fused
    (``fused_aux=True``): amin/amax/ctx_max ride one ``[L, R, 3]``
    min-scatter (max via the unsigned-complement identity) and
    fill/leaf one ``[k, 2]`` add-scatter — ~25% fewer random-access
    index entries per merge than the plain packed kernel. Pre-staged
    A/B candidate (``BENCH_FUSED=1``); results on valid merges are
    bit-identical to :func:`merge_slice_packed` (truncated/overflowed
    merges may differ in discarded fields — ``ok`` is False there and
    the tier-retry ladder discards the state)."""
    return merge_slice_packed(
        state, sl, kill_budget, max_inserts, fused_aux=True
    )


def merge_slice_packed_scomp(
    state: PackedStore,
    sl,
    kill_budget: int,
    max_inserts: int | None = None,
    rows_sorted: bool = False,
) -> MergeResult:
    """:func:`merge_slice_packed` with top_k-free insert compaction
    (``scatter_compact=True``): the per-neighbour ``top_k`` over the
    slice grid is replaced by a cumsum rank + one packed ``[G, 9]``
    compaction scatter. Promoted default (CPU full-config 1,060 → 2,024
    merges/s vs the top_k path); bit-identical to the top_k path on
    valid merges (trash-row contents differ only where every consumer
    masks or drops them).

    ``rows_sorted=True`` vouches that the valid prefix of ``sl.rows``
    is strictly ascending (as :func:`~delta_crdt_ex_tpu.ops.binned
    .extract_rows` over an arange and ``interval_delta_stream`` slices
    are), unlocking the sorted/unique scatter hints. A FALSE claim is
    XLA undefined behaviour — leave it off unless the producer
    guarantees ordering."""
    return merge_slice_packed(
        state, sl, kill_budget, max_inserts,
        scatter_compact=True, rows_sorted=rows_sorted,
    )


def compact_rows_packed(p: PackedStore) -> PackedStore:
    """:func:`~delta_crdt_ex_tpu.ops.binned.compact_rows` over the packed
    layout (unpack → dense repack → pack: compaction is a rare
    whole-table event triggered by ``need_fill_compact``, so the layout
    round-trip is noise next to the repack itself)."""
    return pack(compact_rows(unpack(p)))


def merge_slice_packed(
    state: PackedStore,
    sl,
    kill_budget: int,
    max_inserts: int | None = None,
    fused_aux: bool = False,
    scatter_compact: bool = False,
    rows_sorted: bool = False,
) -> MergeResult:
    """:func:`~delta_crdt_ex_tpu.ops.binned.merge_slice` over the packed
    layout: identical insert/kill/context math, but the 7 per-column
    element scatters collapse into ONE ``[k, 8]`` vector scatter and the
    kill pass reads entry rows as word-plane gathers. Returns a
    :class:`MergeResult` whose ``state`` is a :class:`PackedStore`.

    ``rows_sorted`` matters only under ``scatter_compact``: the cumsum
    compaction preserves grid order, so the main scatter's sorted/unique
    hints are valid only when the slice's valid rows are strictly
    ascending — the caller must vouch (see
    :func:`merge_slice_packed_scomp`). The top_k path sorts its indices
    itself and ignores the flag."""
    L = state.num_buckets
    B = state.bin_capacity
    R = state.replica_capacity
    u, s = sl.key.shape

    v = _slice_view(state, sl)
    valid, rows_safe, rows_clip = v.valid, v.rows_safe, v.rows_clip
    gids, rdense, ldense = v.gids, v.rdense, v.ldense
    ln, ln_clip, ins, need_ctx_gap = v.ln, v.ln_clip, v.ins, v.need_ctx_gap

    # --- insert pass (s2 ∖ c1): ONE vector scatter at fill positions ----
    ins_rank = jnp.cumsum(ins.astype(jnp.int32), axis=1) - 1
    n_ins_row = jnp.sum(ins, axis=1, dtype=jnp.int32)
    fill_rows = state.fill[rows_clip]
    need_fill_compact = jnp.any(valid & (fill_rows + n_ins_row > B))
    pos = fill_rows[:, None] + ins_rank  # [U, S] target bin slot

    idx_dtype = jnp.int32 if L * B + u * s < 2**31 else jnp.int64
    pad_idx = L * B + jnp.arange(u * s, dtype=idx_dtype).reshape(u, s)
    flat = jnp.where(
        ins & (pos < B),
        rows_clip[:, None].astype(idx_dtype) * B + jnp.clip(pos, 0, B - 1),
        pad_idx,
    )
    n_inserted = jnp.sum(ins.astype(jnp.int32))

    compacted = False
    if max_inserts is None:
        need_ins_tier = jnp.bool_(False)
        flat_c = flat.reshape(-1)
        sel = slice(None)
        sorted_hint = False
    elif scatter_compact and L * B + u * s < 2**31:
        # top_k-free compaction, v2: the per-neighbour top_k over the
        # [u·s] grid is O(G log G) sort work; a cumsum rank (streaming)
        # replaces it. Only the (flat, grid-index) PAIR is compacted per
        # neighbour ([G, 2] scatter — flat depends on this neighbour's
        # fill and coverage); the payload planes depend on the SLICE
        # alone, so their [G, 7] pack hoists out of the fan-out vmap
        # (built once per call, not per neighbour) and each neighbour
        # just gathers [k, 7] rows at its compacted grid indices. v1
        # scattered all 9 planes per neighbour: 9·G scattered words vs
        # v2's 2·G scattered + 7·k gathered — ~4.5x fewer per-neighbour
        # random-access words at the bench shape (G = 8·k), measured
        # ~40% of the whole CPU merge. The u32 planes limit this branch
        # to L·B + G < 2^31 (every real geometry).
        k = min(max_inserts, flat.size)
        flat_flat = flat.reshape(-1)
        ins_flat = flat_flat < L * B
        # (a two-level short-scan formulation — per-row rank + [u]
        # exclusive row-base scan — was A/B'd on CPU and measured a
        # wash-to-slightly-slower than this single [G] scan; keep the
        # simple form)
        rank = jnp.cumsum(ins_flat.astype(jnp.int32)) - 1
        dest = jnp.where(ins_flat, rank, k)  # k = trash row; >k drops
        gidx = jnp.arange(u * s, dtype=jnp.uint32)
        pair = jnp.stack([flat_flat.astype(jnp.uint32), gidx], axis=-1)
        # dest is NOT sorted (the trash index k interleaves among the
        # ascending ranks wherever a non-insert precedes an insert), so
        # no indices_are_sorted hint here — a false hint is UB in XLA.
        # The LATER flat_c scatter gets its hints only from rows_sorted:
        # compacted flat values (grid order) are ascending+unique iff
        # the valid rows were.
        comp = (
            jnp.zeros((k + 1, 2), jnp.uint32).at[dest].set(pair, mode="drop")
        )[:k]
        planes7 = jnp.concatenate(
            [
                _b32(sl.key.reshape(-1)),  # [G, 2]
                _b32(sl.ts.reshape(-1)),  # [G, 2]
                sl.valh.reshape(-1)[:, None],
                sl.ctr.reshape(-1)[:, None],
                jnp.clip(sl.node, 0, sl.ctx_gid.shape[0] - 1)
                .reshape(-1)
                .astype(jnp.uint32)[:, None],
            ],
            axis=-1,
        )  # [G, 7] — slice-only, shared across the neighbour batch
        pay = planes7[comp[:, 1].astype(jnp.int32)]  # [k, 7] gather
        kpos = jnp.arange(k, dtype=idx_dtype)
        # `real` counts only in-bounds inserts (bin-overflowed entries
        # carry pad flat values and never enter the compaction); the
        # tier flag keeps the top_k path's conservative n_inserted
        real = kpos < jnp.sum(ins_flat.astype(jnp.int32))
        flat_c = jnp.where(real, comp[:, 0].astype(idx_dtype), L * B + kpos)
        key_c = jax.lax.bitcast_convert_type(pay[:, 0:2], jnp.uint64)
        ts_c = jax.lax.bitcast_convert_type(pay[:, 2:4], jnp.int64)
        valh_c = pay[:, 4]
        ctr_c = pay[:, 5]
        node_c = pay[:, 6].astype(jnp.int32)
        # same values as compacting ln_clip directly: ln is a pure
        # [Rr]-table lookup of node, so recomputing it on the k
        # compacted entries beats carrying a G-sized plane through the
        # per-neighbour scatter
        ln_c = jnp.clip(_table_lookup(gids.remap, node_c), 0, R - 1).astype(
            jnp.int32
        )
        need_ins_tier = n_inserted > k
        sorted_hint = rows_sorted
        compacted = True
    else:
        k = min(max_inserts, flat.size)
        neg_vals, sel = jax.lax.top_k(-flat.reshape(-1), k)
        flat_c = -neg_vals
        need_ins_tier = n_inserted > sel.shape[0]
        sorted_hint = True

    if not compacted:
        take = lambda a: a.reshape(-1)[sel]
        key_c = take(sl.key)
        valh_c = take(sl.valh)
        ts_c = take(sl.ts)
        ctr_c = take(sl.ctr)
        ln_c = take(ln_clip).astype(jnp.int32)
        node_c = take(jnp.clip(sl.node, 0, sl.ctx_gid.shape[0] - 1))
    eh_c = entry_hash(key_c, _table_lookup(sl.ctx_gid, node_c), ctr_c, ts_c, valh_c)
    ins_c = flat_c < L * B  # real inserts; padding indices scatter-drop
    rows_c = (flat_c // B).astype(jnp.int32)

    # the packed payload: [k, 8] word records, one scatter
    meta_c = ln_c.astype(jnp.uint32) | (ins_c.astype(jnp.uint32) << jnp.uint32(16))
    vals8 = jnp.concatenate(
        [
            _b32(key_c),  # [k, 2]
            _b32(ts_c),  # [k, 2]
            valh_c[:, None],
            ctr_c[:, None],
            eh_c[:, None],
            meta_c[:, None],
        ],
        axis=-1,
    )
    words2 = (
        state.words.reshape(L * B, _PLANES)
        .at[flat_c]
        .set(
            vals8,
            mode="drop",
            unique_indices=sorted_hint,
            indices_are_sorted=sorted_hint,
        )
        .reshape(L, B, _PLANES)
    )

    Rr = sl.ctx_gid.shape[0]
    if fused_aux:
        # The three [L, R] summary tables (amin: min-combine; amax and
        # ctx_max: max-combine) all update at (row, slot) indices, and
        # for uint32 ``max(x) == ~min(~x)`` — so ONE min-scatter into a
        # ``[L, R, 3]`` stack covers all three (plane value U32_MAX =
        # identity where a plane has no update at that index). Cuts the
        # aux random-access term from 2k+u·Rr to k+u·Rr index entries
        # and 2+Rr scatter ops to 1.
        T = jnp.stack([state.amin, ~state.amax, ~state.ctx_max], axis=-1)
        ident_k = jnp.full_like(ctr_c, U32_MAX)
        ident_u = jnp.full((u,), U32_MAX, jnp.uint32)
        r_idx = jnp.concatenate([rows_c] + [rows_safe] * Rr)
        c_idx = jnp.concatenate(
            [ln_c]
            + [
                jnp.broadcast_to(
                    jnp.where(gids.remap[rr] >= 0, gids.remap[rr], R), (u,)
                )
                for rr in range(Rr)
            ]
        )
        vals3 = jnp.concatenate(
            [
                jnp.stack(
                    [
                        jnp.where(ins_c, ctr_c, U32_MAX),
                        jnp.where(ins_c, ~ctr_c, U32_MAX),
                        ident_k,
                    ],
                    axis=-1,
                )
            ]
            + [
                jnp.stack(
                    [
                        ident_u,
                        ident_u,
                        jnp.where(
                            v.nonempty[:, rr], ~sl.ctx_rows[:, rr], U32_MAX
                        ),
                    ],
                    axis=-1,
                )
                for rr in range(Rr)
            ]
        )
        T = T.at[r_idx, c_idx].min(vals3, mode="drop")
        amin2, amax2, ctx2 = T[..., 0], ~T[..., 1], ~T[..., 2]
        # fill (insert count) and leaf (digest accumulator) are both [L]
        # add-tables updated at the insert rows — one [k, 2] add-scatter.
        # (Per-entry +1 ≡ the per-row n_ins_row add whenever the merge is
        # valid; on a truncated/overflowed merge ok=False and the state
        # is discarded by the tier-retry ladder, so the difference in
        # dead states is unobservable.)
        FL = jnp.stack([state.leaf, state.fill.astype(jnp.uint32)], axis=-1)
        FL = FL.at[rows_c].add(
            jnp.stack(
                [
                    jnp.where(ins_c, eh_c, jnp.uint32(0)),
                    ins_c.astype(jnp.uint32),
                ],
                axis=-1,
            ),
            mode="drop",
        )
        leaf2, fill2 = FL[..., 0], FL[..., 1].astype(jnp.int32)
    else:
        fill2 = state.fill.at[rows_safe].add(n_ins_row, mode="drop")
        amin2 = state.amin.at[rows_c, ln_c].min(
            jnp.where(ins_c, ctr_c, U32_MAX), mode="drop"
        )
        amax2 = state.amax.at[rows_c, ln_c].max(
            jnp.where(ins_c, ctr_c, jnp.uint32(0)), mode="drop"
        )
        if max_inserts is None:
            leaf_add = jnp.sum(
                jnp.where(ins & (pos < B), eh_c.reshape(u, s), jnp.uint32(0)),
                axis=1,
                dtype=jnp.uint32,
            )
            leaf2 = state.leaf.at[rows_safe].add(leaf_add, mode="drop")
        else:
            leaf2 = state.leaf.at[rows_c].add(
                jnp.where(ins_c, eh_c, jnp.uint32(0)), mode="drop"
            )
        ctx2 = state.ctx_max
        for rr in range(Rr):
            colr = jnp.where(gids.remap[rr] >= 0, gids.remap[rr], R)
            vals_r = jnp.where(
                v.nonempty[:, rr], sl.ctx_rows[:, rr], jnp.uint32(0)
            )
            ctx2 = ctx2.at[rows_safe, colr].max(vals_r, mode="drop")

    # --- kill pass ((s1∩s2) ∪ (s1∖c2)), pruned by amin/amax -------------
    amin_rows = state.amin[rows_clip]
    amax_rows = state.amax[rows_clip]
    flagged = valid & jnp.any((rdense >= amin_rows) & (ldense < amax_rows), axis=1)
    n_flagged = jnp.sum(flagged.astype(jnp.int32))
    need_kill_tier = n_flagged > kill_budget

    order = flagged_first_order(flagged, kill_budget)
    kb = order.shape[0]
    k_valid = flagged[order]
    k_rows = jnp.where(k_valid, rows_clip[order], L)
    k_rows_clip = jnp.clip(k_rows, 0, L - 1)

    # local dots of the flagged rows, read as word-plane rows of the
    # post-insert table (same read-through-inserts semantics as the
    # column kernel)
    w_rows = words2[k_rows_clip]  # [KB, B, 8]
    meta_rows = w_rows[..., _META]
    l_node = (meta_rows & jnp.uint32(0xFFFF)).astype(jnp.int32)
    l_ctr = w_rows[..., 5]
    alive_raw = (meta_rows >> jnp.uint32(16)) != 0
    l_alive = alive_raw & k_valid[:, None]
    l_ehash = w_rows[..., 6]

    k_rdense = rdense[order]
    k_ldense = ldense[order]
    covered = (
        jnp.take_along_axis(k_rdense, l_node, axis=1) >= l_ctr
    ) & (jnp.take_along_axis(k_ldense, l_node, axis=1) < l_ctr)
    r_node = ln_clip[order]
    r_ctr = sl.ctr[order]
    r_alive = sl.alive[order] & k_valid[:, None]
    l_dot = encode_dot(l_node, l_ctr)
    r_dot = jnp.where(r_alive, encode_dot(r_node, r_ctr), jnp.uint64(0))
    present = jnp.any(l_dot[:, :, None] == r_dot[:, None, :], axis=2)

    die = l_alive & covered & ~present
    meta_new = (meta_rows & jnp.uint32(0xFFFF)) | (
        (l_alive & ~die).astype(jnp.uint32) << jnp.uint32(16)
    )
    w_rows_new = jnp.concatenate(
        [w_rows[..., : _META], meta_new[..., None]], axis=-1
    )
    words3 = words2.at[k_rows].set(w_rows_new, mode="drop")
    leaf_sub = jnp.sum(jnp.where(die, l_ehash, jnp.uint32(0)), axis=1, dtype=jnp.uint32)
    leaf3 = leaf2.at[k_rows].add(~leaf_sub + jnp.uint32(1), mode="drop")
    amin_k = _row_amin(l_node, l_ctr, l_alive & ~die, kb, R)
    amin3 = amin2.at[k_rows].set(amin_k, mode="drop")
    amax_k = _row_amax(l_node, l_ctr, l_alive & ~die, kb, R)
    amax3 = amax2.at[k_rows].set(amax_k, mode="drop")
    n_killed = jnp.sum(die.astype(jnp.int32))

    ok = ~(
        gids.overflow
        | need_kill_tier
        | need_fill_compact
        | need_ctx_gap
        | need_ins_tier
    )
    new_state = PackedStore(
        words=words3,
        fill=fill2,
        amin=amin3,
        amax=amax3,
        leaf=leaf3,
        ctx_gid=gids.ctx_gid,
        ctx_max=ctx2,
    )
    return MergeResult(
        new_state,
        ok,
        gids.overflow,
        need_kill_tier,
        need_fill_compact,
        need_ctx_gap,
        need_ins_tier,
        n_inserted,
        n_killed,
    )
