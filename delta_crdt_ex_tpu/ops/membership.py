"""Dot-membership kernel: which local entries' dots appear in a delta.

The reference's per-key ``MapSet.intersection`` (``aw_lww_map.ex:196-209``)
reduces, at the dot level, to a set-membership test: dots are globally
unique and each dot determines its entry, so ``e ∈ s1 ∩ s2 ⟺ dot(e) ∈
dots(s2)``. Rather than an O(C·D) compare matrix, dots are packed into u64
keys, the (small) delta side is sorted once, and the state side probes via
``searchsorted`` — O(D log D + C log D), bandwidth-bound at ~O(C).
"""

from __future__ import annotations

import jax.numpy as jnp

from delta_crdt_ex_tpu.ops.dots import encode_dot

_SENTINEL = jnp.uint64(0xFFFFFFFFFFFFFFFF)


def dots_present(
    node_l: jnp.ndarray,
    ctr_l: jnp.ndarray,
    node_r: jnp.ndarray,
    ctr_r: jnp.ndarray,
    mask_r: jnp.ndarray,
) -> jnp.ndarray:
    """bool[C]: local dot (node_l, ctr_l) present among masked remote dots.

    Both sides must already be expressed in the same (local) slot indexing
    (see :func:`delta_crdt_ex_tpu.ops.dots.merge_contexts`).
    """
    d = node_r.shape[0]
    dk_r = jnp.where(mask_r, encode_dot(node_r, ctr_r), _SENTINEL)
    s = jnp.sort(dk_r)
    dk_l = encode_dot(node_l, ctr_l)
    pos = jnp.searchsorted(s, dk_l)
    hit = s[jnp.clip(pos, 0, d - 1)] == dk_l
    return hit & (pos < d) & (dk_l != _SENTINEL)
