"""Local mutation batch kernel.

The reference applies one operation per GenServer message: ``add`` =
remove-delta ⊔ fresh-dot add-delta (``aw_lww_map.ex:99-112``), ``remove``
= delta whose context is the key's observed dots (``:133-146``), then an
immediate join into the state (``causal_crdt.ex:337-342``). One device
call per mutation would waste the chip, so the TPU-native driver batches
queued mutations and this kernel applies a whole batch with
**sequential semantics** in one fused pass:

- each add is assigned the next dot counter in batch order
  (``Dots.next_dot``, ``aw_lww_map.ex:30-37`` — here a cumsum);
- a batch entry survives iff no later batch op touches its key (and no
  later ``clear``);
- pre-batch entries die iff any batch op touches their key (every local
  op observes the whole pre-batch state — the replica sees all its dots)
  or any ``clear`` is present.

``clear`` is implemented properly here; in the reference it exists but is
unreachable through ``mutate`` (``causal_crdt.ex:337`` can't match zero-arg
ops — documented quirk, not replicated).

Op codes: 0 = padding, 1 = add, 2 = remove, 3 = clear.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from delta_crdt_ex_tpu.models.state import DotStore

OP_PAD = 0
OP_ADD = 1
OP_REMOVE = 2
OP_CLEAR = 3

_SENTINEL = jnp.uint64(0xFFFFFFFFFFFFFFFF)


class ApplyResult(NamedTuple):
    state: DotStore
    ok: jnp.ndarray  # bool
    ctr_assigned: jnp.ndarray  # uint32[K]: dot counter per add op (host payload keying)
    n_keys_changed: jnp.ndarray  # int32: unique keys whose dot store changed
    # (the reference's telemetry keys_updated_count, causal_crdt.ex:396-398)


def apply_batch(
    state: DotStore,
    self_slot: jnp.ndarray,  # int32 scalar: local ctx slot of this replica
    op: jnp.ndarray,  # int32[K]
    key: jnp.ndarray,  # uint64[K]
    valh: jnp.ndarray,  # uint32[K]
    ts: jnp.ndarray,  # int64[K]
) -> ApplyResult:
    c = state.capacity
    k = op.shape[0]

    is_add = op == OP_ADD
    is_touch = is_add | (op == OP_REMOVE)
    is_clear = op == OP_CLEAR

    # Fresh dots for adds, sequential within the batch (``Dots.next_dot``:
    # one counter per replica across all buckets).
    base = state.own_counter(self_slot)
    add_seq = jnp.cumsum(is_add.astype(jnp.uint32))
    ctr_assigned = base + add_seq
    # context rows: every created dot is observed in its key's bucket row
    num_buckets = state.num_buckets
    add_bucket = jnp.where(
        is_add, (key & jnp.uint64(num_buckets - 1)).astype(jnp.int32), num_buckets
    )
    ctx_max = state.ctx_max.at[add_bucket, self_slot].max(ctr_assigned, mode="drop")

    # Batch-internal shadowing: op i dies if a later op touches the same key.
    later = jnp.triu(jnp.ones((k, k), bool), 1)  # j > i
    key_eq = key[None, :] == key[:, None]
    shadowed = jnp.any(later & ((key_eq & is_touch[None, :]) | is_clear[None, :]), axis=1)
    ins_alive = is_add & ~shadowed

    # Pre-batch entries: die if any batch op touches their key, or any clear.
    touched_keys = jnp.where(is_touch, key, _SENTINEL)
    s = jnp.sort(touched_keys)
    pos = jnp.searchsorted(s, state.key)
    touched = (s[jnp.clip(pos, 0, k - 1)] == state.key) & (pos < k) & (state.key != _SENTINEL)
    any_clear = jnp.any(is_clear)
    alive1 = state.alive & ~touched & ~any_clear

    # Insert surviving adds into free slots.
    free = ~alive1
    free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1
    slot_of_rank = (
        jnp.full(c, c, jnp.int32)
        .at[jnp.where(free, free_rank, c)]
        .set(jnp.arange(c, dtype=jnp.int32), mode="drop")
    )
    ins_rank = jnp.cumsum(ins_alive.astype(jnp.int32)) - 1
    n_ins = jnp.sum(ins_alive.astype(jnp.int32))
    ok = n_ins <= jnp.sum(free.astype(jnp.int32))
    tgt = jnp.where(ins_alive, slot_of_rank[jnp.clip(ins_rank, 0, c - 1)], c)

    new_state = DotStore(
        key=state.key.at[tgt].set(key, mode="drop"),
        valh=state.valh.at[tgt].set(valh, mode="drop"),
        ts=state.ts.at[tgt].set(ts, mode="drop"),
        node=state.node.at[tgt].set(jnp.full(k, self_slot, jnp.int32), mode="drop"),
        ctr=state.ctr.at[tgt].set(ctr_assigned, mode="drop"),
        alive=alive1.at[tgt].set(True, mode="drop"),
        ctx_gid=state.ctx_gid,
        ctx_max=ctx_max,
    )

    # unique keys whose dot store changed: a pre-batch entry died, or a
    # surviving add inserted. Marks land on the sorted-batch-key axis
    # (searchsorted gives one canonical slot per distinct key).
    kill_mark = touched & state.alive & ~alive1
    changed = jnp.zeros(k, bool).at[jnp.where(kill_mark, jnp.clip(pos, 0, k - 1), k)].set(
        True, mode="drop"
    )
    ins_pos = jnp.searchsorted(s, key)
    changed = changed.at[
        jnp.where(ins_alive, jnp.clip(ins_pos, 0, k - 1), k)
    ].set(True, mode="drop")
    n_keys_changed = jnp.sum(changed.astype(jnp.int32))

    return ApplyResult(new_state, ok, ctr_assigned, n_keys_changed)
