"""Row-local kernels over the bucket-binned dot store.

Every kernel here touches only the rows named by its inputs — dense
gathers, vector math along the bin axis, small element scatters — so the
cost model matches the reference's O(touched keys) merges
(``update_state_with_delta``, ``causal_crdt.ex:383-404``) instead of
O(state capacity). See :mod:`delta_crdt_ex_tpu.models.binned` for the
layout and its maintained invariants (``fill``, ``amin``, ``leaf``,
``ehash``).

Kernels:

- :func:`row_apply` — local mutation batch, grouped by bucket row
  (sequential batch semantics; the reference applies one op per mailbox
  message, ``causal_crdt.ex:337-342``).
- :func:`merge_rows` / :func:`merge_slice` — two anti-entropy merge
  kernels implementing the SAME join (parity-tested bit-for-bit) under
  different cost models. ``merge_rows`` (runtime path): whole-row dense
  math, kill pass on every row, holes reclaimed in-row, no kill/insert
  tiers — best for ≤ ``max_sync_size``-row slices. ``merge_slice``
  (bulk fan-in path): element scatters at ``fill`` positions with an
  ``amin``-pruned kill pass under a ``kill_budget`` tier — cost ∝ slice
  *entries*, best for sparse many-row slices (the bench's 8192-row
  delta groups). Shared preamble: :func:`_slice_view`.
- :func:`winners_for_keys` / :func:`winner_rows` — LWW read resolution
  (``AWLWWMap.read``, ``aw_lww_map.ex:211-224``).
- :func:`extract_rows` — the sync data plane: gather rows + context rows
  (``Map.take`` + ``dots``, ``causal_crdt.ex:115-119``).
- :func:`compact_rows` — full repack (hole reclamation) + invariant
  rebuild; :func:`clear_all` — kill every observed dot.
- :func:`tree_from_leaves` — digest-tree levels above the maintained
  leaf digests (``MerkleMap.update_hashes``, ``causal_crdt.ex:254``).

Dot semantics are unchanged from the flat kernels: add-wins observed
remove, per-key LWW by (ts, writer gid, ctr), causal join
``(s1∩s2) ∪ (s1∖c2) ∪ (s2∖c1)`` per key, context union = per-replica max
(``aw_lww_map.ex:99-209``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from delta_crdt_ex_tpu.models.binned import BinnedStore, U32_MAX
from delta_crdt_ex_tpu.ops.apply import OP_ADD, OP_REMOVE
from delta_crdt_ex_tpu.ops.dots import encode_dot, merge_gid_tables

_M1 = jnp.uint64(0xBF58476D1CE4E5B9)
_M2 = jnp.uint64(0x94D049BB133111EB)
_P1 = jnp.uint32(0x85EBCA6B)
_P2 = jnp.uint32(0xC2B2AE35)


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    x = (x ^ (x >> jnp.uint64(30))) * _M1
    x = (x ^ (x >> jnp.uint64(27))) * _M2
    return x ^ (x >> jnp.uint64(31))


def _mix32(x: jnp.ndarray) -> jnp.ndarray:
    x = (x ^ (x >> jnp.uint32(16))) * _P1
    x = (x ^ (x >> jnp.uint32(13))) * _P2
    return x ^ (x >> jnp.uint32(16))


def entry_hash(key, gid, ctr, ts, valh) -> jnp.ndarray:
    """uint32 content hash of an entry — replica-independent (uses the
    writer's GLOBAL id), covering the internal dot representation so
    same-value/different-dots replicas still diff (the MerkleMap property,
    ``causal_crdt_test.exs:154-171``)."""
    h = _mix64(
        key
        ^ _mix64(gid ^ ctr.astype(jnp.uint64))
        ^ _mix64(ts.astype(jnp.uint64) ^ (valh.astype(jnp.uint64) << jnp.uint64(32)))
    )
    return (h ^ (h >> jnp.uint64(32))).astype(jnp.uint32)


def tree_from_leaves(leaf: jnp.ndarray) -> list[jnp.ndarray]:
    """Digest-tree levels from the maintained leaf digests, root first:
    ``[u32[1], u32[2], …, u32[L]]`` (level combine identical to the flat
    :mod:`~delta_crdt_ex_tpu.ops.hashtree` so the sync walk is shared)."""
    levels = [leaf]
    while levels[-1].shape[0] > 1:
        cur = levels[-1].reshape(-1, 2)
        left = _mix32(cur[:, 0] ^ _P1)
        right = _mix32(cur[:, 1] ^ _P2)
        levels.append(left + (right << jnp.uint32(1)) + jnp.uint32(0x9E3779B9))
    return levels[::-1]


#: table sizes up to this use the unrolled select chain; beyond it the
#: plain gather wins again (select cost scales linearly in table size)
_LOOKUP_UNROLL_MAX = 32


def _table_lookup(table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """``table[idx]`` for a tiny 1-D table. TPU gathers cost ~100
    cycles per element; for an R-sized table an unrolled chain of R
    vector selects is pure VPU work and an order of magnitude faster on
    the [U, S] slice grids (``idx`` must already be clipped to range)."""
    n = table.shape[0]
    if n > _LOOKUP_UNROLL_MAX:
        return table[idx]
    out = jnp.broadcast_to(table[0], idx.shape)
    for i in range(1, n):
        out = jnp.where(idx == i, table[i], out)
    return out


def _row_table_lookup(tbl: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """``take_along_axis(tbl, idx, axis=1)`` for a small trailing dim:
    ``tbl[u, idx[u, s]]`` via R vector selects (same rationale as
    :func:`_table_lookup`; ``idx`` must be clipped to ``[0, R)``)."""
    r = tbl.shape[1]
    if r > _LOOKUP_UNROLL_MAX:
        return jnp.take_along_axis(tbl, idx, axis=1)
    out = jnp.broadcast_to(tbl[:, :1], idx.shape)
    for i in range(1, r):
        out = jnp.where(idx == i, tbl[:, i : i + 1], out)
    return out


def flagged_first_order(flags: jnp.ndarray, budget: int) -> jnp.ndarray:
    """int32[min(budget, n)] indices: the first ``budget`` flagged
    positions in ascending order, unfilled slots holding a guaranteed-
    UNFLAGGED index. Every caller masks slots through ``flags[order]``
    (the kill passes via ``k_valid``, the gossip frontier via ``want``),
    so only the flagged prefix is observable — which lets the selection
    be a cumsum rank + one small scatter instead of the previous
    ``top_k`` over a packed priority key: XLA:CPU lowers ``top_k`` to a
    full O(n log n) sort (~22% of the whole packed merge at the bench
    shape), and TPU pays a multi-pass sort too. Callers that need *all*
    flagged entries must check the flagged count against ``budget``
    themselves.

    The filler is ``argmin(flags)`` — the first unflagged index — NOT an
    arbitrary constant: a filler that aliased a flagged row would enter
    the kill pass unmasked next to the row's real slot, and its
    ``leaf.at[rows].add`` would double-subtract that row's digest. When
    every position is flagged there are no unfilled slots (the ranks
    cover the whole budget), so the degenerate ``argmin`` result is
    never observable."""
    n = flags.shape[0]
    kb = min(budget, n)
    rank = jnp.cumsum(flags.astype(jnp.int32)) - 1
    dest = jnp.where(flags, jnp.minimum(rank, kb), kb)  # kb = trash slot
    filler = jnp.argmin(flags).astype(jnp.int32)
    return (
        jnp.full((kb + 1,), filler, jnp.int32)
        .at[dest]
        .set(jnp.arange(n, dtype=jnp.int32), mode="drop")
    )[:kb]


def _row_amin(node, ctr, alive, u, r):
    """uint32[U, R] min alive counter per (row, writer slot)."""
    uu = jnp.broadcast_to(jnp.arange(u)[:, None], node.shape)
    return (
        jnp.full((u, r), U32_MAX, jnp.uint32)
        .at[uu, node]
        .min(jnp.where(alive, ctr, U32_MAX))
    )


def _row_amax(node, ctr, alive, u, r):
    """uint32[U, R] max alive counter per (row, writer slot); 0 if none."""
    uu = jnp.broadcast_to(jnp.arange(u)[:, None], node.shape)
    return (
        jnp.zeros((u, r), jnp.uint32)
        .at[uu, node]
        .max(jnp.where(alive, ctr, jnp.uint32(0)))
    )


def _row_compact(cols: dict, alive: jnp.ndarray):
    """Stable-pack alive entries to the front of each row; returns
    (packed cols, packed alive, fill per row)."""
    order = jnp.argsort(~alive, axis=1, stable=True)
    packed = {c: jnp.take_along_axis(v, order, axis=1) for c, v in cols.items()}
    alive_p = jnp.take_along_axis(alive, order, axis=1)
    fill = jnp.sum(alive_p, axis=1, dtype=jnp.int32)
    return packed, alive_p, fill


_ROW_COLS = ("key", "valh", "ts", "node", "ctr", "ehash")


def _gather_rows(state: BinnedStore, rows_safe: jnp.ndarray) -> dict:
    return {c: getattr(state, c)[rows_safe] for c in _ROW_COLS}


# ---------------------------------------------------------------------------
# local mutation batch


class RowApplyResult(NamedTuple):
    state: BinnedStore
    ok: jnp.ndarray  # bool: every touched row had bin space
    ctr_assigned: jnp.ndarray  # uint32[U, M] dot counter per add op
    n_keys_changed: jnp.ndarray  # int32 (telemetry keys_updated_count)
    row_killed: jnp.ndarray  # bool[U]: row lost a pre-batch entry (a kill
    # cannot ride a delta-interval push — the host full-row-pushes these)


def row_apply(
    state: BinnedStore,
    self_slot: jnp.ndarray,  # int32 scalar
    rows: jnp.ndarray,  # int32[U] unique bucket rows (-1 = padding)
    op: jnp.ndarray,  # int32[U, M] ops per row, batch order (OP_PAD pads)
    key: jnp.ndarray,  # uint64[U, M]
    valh: jnp.ndarray,  # uint32[U, M]
    ts: jnp.ndarray,  # int64[U, M]
) -> RowApplyResult:
    """Apply a bucket-grouped local mutation batch with sequential
    semantics: within a row, a later op shadows earlier same-key ops, and
    every pre-batch same-key entry dies (a local op observes all local
    dots — the remove-delta half of ``AWLWWMap.add``, ``aw_lww_map.ex:
    99-112``). ``clear`` is handled by :func:`clear_all`, not here."""
    L = state.num_buckets
    B = state.bin_capacity
    R = state.replica_capacity
    u, m = op.shape

    valid = rows >= 0
    rows_safe = jnp.where(valid, rows, L)  # out-of-range: gathers clip, scatters drop
    rows_clip = jnp.clip(rows_safe, 0, L - 1)
    g = _gather_rows(state, rows_clip)
    galive = state.alive[rows_clip] & valid[:, None]

    is_add = (op == OP_ADD) & valid[:, None]
    is_touch = is_add | ((op == OP_REMOVE) & valid[:, None])

    # fresh dot counters: one contiguous sequence per (replica, bucket)
    # (dot identity is (writer, bucket, counter) — unique because a dot's
    # bucket is a function of its key). Per-bucket sequences make every
    # own-delta an exact per-bucket interval (ctx_lo, ctx_max], which is
    # what lets the runtime push delta-interval slices (Almeida et al.'s
    # delta mode) instead of full-row state slices.
    base = state.ctx_max[rows_clip, self_slot]  # [U] own max per bucket
    add_rank = jnp.cumsum(is_add.astype(jnp.uint32), axis=1)
    ctr_assigned = base[:, None] + add_rank

    # batch-internal shadowing: a later same-key touch kills op (u, m)
    later = jnp.triu(jnp.ones((m, m), bool), 1)
    key_eq = key[:, :, None] == key[:, None, :]  # [U, M, M] (m, m')
    shadowed = jnp.any(key_eq & later[None] & is_touch[:, None, :], axis=2)
    ins = is_add & ~shadowed

    # pre-batch kills: every alive entry whose key any batch op touches
    hit = jnp.any(
        (g["key"][:, :, None] == key[:, None, :]) & is_touch[:, None, :], axis=2
    )
    killed = galive & hit
    alive1 = galive & ~hit

    # insert into the lowest free slots of each row
    free = ~alive1
    free_rank = jnp.cumsum(free.astype(jnp.int32), axis=1) - 1
    uu_b = jnp.broadcast_to(jnp.arange(u)[:, None], (u, B))
    slot_of_rank = (
        jnp.full((u, B), B, jnp.int32)
        .at[uu_b, jnp.where(free, free_rank, B)]
        .set(jnp.broadcast_to(jnp.arange(B, dtype=jnp.int32), (u, B)), mode="drop")
    )
    ins_rank = jnp.cumsum(ins.astype(jnp.int32), axis=1) - 1
    n_ins = jnp.sum(ins.astype(jnp.int32), axis=1)
    ok = jnp.all(n_ins <= jnp.sum(free.astype(jnp.int32), axis=1))
    tgt_b = jnp.where(
        ins,
        jnp.take_along_axis(slot_of_rank, jnp.clip(ins_rank, 0, B - 1), axis=1),
        B,
    )

    gid_self = state.ctx_gid[self_slot]
    eh = entry_hash(key, gid_self, ctr_assigned, ts, valh)
    uu_m = jnp.broadcast_to(jnp.arange(u)[:, None], (u, m))
    node_new = jnp.full((u, m), self_slot, jnp.int32)

    def put(col, vals):
        return col.at[uu_m, tgt_b].set(vals, mode="drop")

    cols = {
        "key": put(g["key"], key),
        "valh": put(g["valh"], valh),
        "ts": put(g["ts"], ts),
        "node": put(g["node"], node_new),
        "ctr": put(g["ctr"], ctr_assigned),
        "ehash": put(g["ehash"], eh),
    }
    alive2 = alive1.at[uu_m, tgt_b].set(True, mode="drop")

    # repack rows (free in-row compaction: rows are rewritten anyway)
    packed, alive_p, fill_rows = _row_compact(cols, alive2)

    amin_rows = _row_amin(packed["node"], packed["ctr"], alive_p, u, R)
    amax_rows = _row_amax(packed["node"], packed["ctr"], alive_p, u, R)
    leaf_rows = jnp.sum(
        jnp.where(alive_p, packed["ehash"], jnp.uint32(0)), axis=1, dtype=jnp.uint32
    )
    own_max = jnp.max(jnp.where(ins, ctr_assigned, jnp.uint32(0)), axis=1)

    new_state = BinnedStore(
        **{c: getattr(state, c).at[rows_safe].set(packed[c], mode="drop") for c in _ROW_COLS},
        alive=state.alive.at[rows_safe].set(alive_p, mode="drop"),
        fill=state.fill.at[rows_safe].set(fill_rows, mode="drop"),
        amin=state.amin.at[rows_safe].set(amin_rows, mode="drop"),
        amax=state.amax.at[rows_safe].set(amax_rows, mode="drop"),
        leaf=state.leaf.at[rows_safe].set(leaf_rows, mode="drop"),
        ctx_gid=state.ctx_gid,
        ctx_max=state.ctx_max.at[rows_safe, self_slot].max(own_max, mode="drop"),
    )

    # telemetry: distinct keys whose dot store changed (first-occurrence
    # op marks; key sets of distinct rows are disjoint)
    earlier = jnp.tril(jnp.ones((m, m), bool), -1)
    first_occ = ~jnp.any(key_eq & earlier[None] & is_touch[:, None, :], axis=2)
    killed_any = jnp.any(
        (key[:, :, None] == g["key"][:, None, :]) & galive[:, None, :], axis=2
    )
    changed = is_touch & first_occ & (ins | killed_any)
    n_keys_changed = jnp.sum(changed.astype(jnp.int32))

    return RowApplyResult(
        new_state, ok, ctr_assigned, n_keys_changed, jnp.any(killed, axis=1)
    )


def clear_all(state: BinnedStore) -> BinnedStore:
    """Kill every observed dot (``AWLWWMap.clear``, ``aw_lww_map.ex:
    148-150``): entries die, the context stays — so the clear propagates
    as coverage and unobserved remote dots survive."""
    return BinnedStore(
        key=state.key,
        valh=state.valh,
        ts=state.ts,
        node=state.node,
        ctr=state.ctr,
        alive=jnp.zeros_like(state.alive),
        ehash=state.ehash,
        fill=jnp.zeros_like(state.fill),
        amin=jnp.full_like(state.amin, U32_MAX),
        amax=jnp.zeros_like(state.amax),
        leaf=jnp.zeros_like(state.leaf),
        ctx_gid=state.ctx_gid,
        ctx_max=state.ctx_max,
    )


# ---------------------------------------------------------------------------
# anti-entropy merge


class RowSlice(NamedTuple):
    """Wire format of the sync data plane: gathered rows of the sender's
    store plus the matching context rows — the bucket-atomic analog of the
    reference's ``%{crdt | dots: …, value: Map.take(…)}`` diff payload
    (``causal_crdt.ex:115-119``).

    The context travels as a per-(bucket, writer) counter **interval**
    ``(ctx_lo, ctx_rows]`` — Almeida et al.'s delta-interval: the slice
    claims knowledge of exactly the dots in the interval, so a partial
    (delta) slice cannot over-claim and kill older dots it did not ship.
    Full-row state slices use ``ctx_lo = 0`` (the compressed state form,
    ``Dots.compress``, ``aw_lww_map.ex:13-20``)."""

    rows: jnp.ndarray  # int32[U] bucket indices (-1 = padding)
    key: jnp.ndarray  # uint64[U, S]
    valh: jnp.ndarray  # uint32[U, S]
    ts: jnp.ndarray  # int64[U, S]
    node: jnp.ndarray  # int32[U, S] (sender-local slots)
    ctr: jnp.ndarray  # uint32[U, S]
    alive: jnp.ndarray  # bool[U, S]
    ctx_rows: jnp.ndarray  # uint32[U, Rr] interval upper bounds (inclusive)
    ctx_lo: jnp.ndarray  # uint32[U, Rr] interval lower bounds (exclusive)
    ctx_gid: jnp.ndarray  # uint64[Rr]


def extract_rows(state: BinnedStore, rows: jnp.ndarray) -> RowSlice:
    """Gather the slice for a set of bucket rows (-1 pads)."""
    L = state.num_buckets
    valid = rows >= 0
    rows_clip = jnp.clip(rows, 0, L - 1)
    v = valid[:, None]
    g = _gather_rows(state, rows_clip)
    return RowSlice(
        rows=rows,
        key=g["key"],
        valh=g["valh"],
        ts=g["ts"],
        node=g["node"],
        ctr=g["ctr"],
        alive=state.alive[rows_clip] & v,
        ctx_rows=state.ctx_max[rows_clip] * valid[:, None].astype(jnp.uint32),
        ctx_lo=jnp.zeros_like(state.ctx_max[rows_clip]),
        ctx_gid=state.ctx_gid,
    )


def extract_own_delta(
    state: BinnedStore,
    rows: jnp.ndarray,  # int32[U] bucket rows (-1 pads)
    self_slot: jnp.ndarray,  # int32 scalar
    gid_self: jnp.ndarray,  # uint64 scalar (== ctx_gid[self_slot])
    lo: jnp.ndarray,  # uint32[U] per-row interval lower bound (exclusive)
) -> RowSlice:
    """Gather an OWN-writer delta-interval slice: this replica's alive
    entries with counter in ``(lo, ctx_max]`` per bucket row, claiming
    exactly that interval (Almeida et al.'s delta mode — the delta a
    replica pushes eagerly instead of waiting for a digest walk to
    locate it). Minted-but-superseded counters inside the interval read
    as observed removes, which is their meaning. The slice's writer
    table is just ``[gid_self]``; shipped entries' node column is 0."""
    L = state.num_buckets
    valid = rows >= 0
    rows_clip = jnp.clip(rows, 0, L - 1)
    v = valid[:, None]
    g = _gather_rows(state, rows_clip)
    own = g["node"] == self_slot
    alive = state.alive[rows_clip] & v & own & (g["ctr"] > lo[:, None])
    hi = state.ctx_max[rows_clip, self_slot] * valid.astype(jnp.uint32)
    return RowSlice(
        rows=rows,
        key=g["key"],
        valh=g["valh"],
        ts=g["ts"],
        node=jnp.zeros_like(g["node"]),
        ctr=g["ctr"],
        alive=alive,
        ctx_rows=hi[:, None],
        ctx_lo=(lo * valid.astype(jnp.uint32))[:, None],
        ctx_gid=gid_self[None],
    )


class SliceView(NamedTuple):
    """The interval/insert preamble shared by both merge kernels — one
    implementation so the subtle invariants (empty-interval masking,
    the U32_MAX ldense sentinel, contiguity) cannot drift between them."""

    valid: jnp.ndarray  # bool[U]
    rows_safe: jnp.ndarray  # int32[U] (L where padding — scatters drop)
    rows_clip: jnp.ndarray  # int32[U]
    gids: Any
    rdense: jnp.ndarray  # uint32[U, R] interval upper bounds, local slots
    ldense: jnp.ndarray  # uint32[U, R] interval lower bounds, local slots
    ln: jnp.ndarray  # int32[U, S] remapped writer slots (-1 unknown)
    ln_clip: jnp.ndarray  # int32[U, S]
    local_ctx: jnp.ndarray  # uint32[U, R]
    ins: jnp.ndarray  # bool[U, S]: slice entries to insert (s2 ∖ c1)
    need_ctx_gap: jnp.ndarray  # bool
    gap_row: jnp.ndarray  # bool[U]: the rows whose intervals gap — a
    # grouped fan-in caller maps these back to the member slices so only
    # the gapped sender replays solo (the rest stay one grouped dispatch)
    nonempty: jnp.ndarray  # bool[U, Rr]: interval columns claiming anything


def _slice_view(state: BinnedStore, sl: RowSlice) -> SliceView:
    L = state.num_buckets
    R = state.replica_capacity
    u, s = sl.key.shape

    valid = sl.rows >= 0
    rows_safe = jnp.where(valid, sl.rows, L)
    rows_clip = jnp.clip(rows_safe, 0, L - 1)

    gids = merge_gid_tables(state.ctx_gid, sl.ctx_gid)

    # empty intervals (lo == hi) claim nothing: mask them out of BOTH
    # bounds, or an idle writer's row would read as a (0, hi] state-form
    # claim and kill dots the slice never shipped
    nonempty = sl.ctx_rows > sl.ctx_lo
    rr_n = sl.ctx_gid.shape[0]
    if rr_n * R <= _LOOKUP_UNROLL_MAX * _LOOKUP_UNROLL_MAX:
        # remote context rows in local slot indexing: [U, R]. The remap
        # is a per-slice constant [Rr], so the dense forms are a one-hot
        # max/min over the Rr axis — no scatters (remap < 0 matches no
        # column, the old mode="drop")
        oh = gids.remap[:, None] == jnp.arange(R, dtype=jnp.int32)[None, :]
        sel3 = nonempty[:, :, None] & oh[None]  # [U, Rr, R]
        rdense = jnp.max(
            jnp.where(sel3, sl.ctx_rows[:, :, None], jnp.uint32(0)), axis=1
        )
        ldense = jnp.min(jnp.where(sel3, sl.ctx_lo[:, :, None], U32_MAX), axis=1)
    else:
        # large writer tables: the [U, Rr, R] one-hot intermediates would
        # dwarf the scatter they replace — keep the scatter form there
        uu_r = jnp.broadcast_to(jnp.arange(u)[:, None], sl.ctx_rows.shape)
        remap_cols = jnp.broadcast_to(gids.remap[None, :], sl.ctx_rows.shape)
        rcols = jnp.where(remap_cols >= 0, remap_cols, R)
        rdense = (
            jnp.zeros((u, R), jnp.uint32)
            .at[uu_r, rcols]
            .max(jnp.where(nonempty, sl.ctx_rows, jnp.uint32(0)), mode="drop")
        )
        ldense = (
            jnp.full((u, R), U32_MAX, jnp.uint32)
            .at[uu_r, rcols]
            .min(jnp.where(nonempty, sl.ctx_lo, U32_MAX), mode="drop")
        )
    # interval lower bounds in local slots (0 where nothing shipped)
    ldense = jnp.where(ldense == U32_MAX, jnp.uint32(0), ldense)

    # insert pass (s2 ∖ c1)
    ln = _table_lookup(
        gids.remap, jnp.clip(sl.node, 0, sl.ctx_gid.shape[0] - 1)
    )  # [U, S]
    ln_clip = jnp.clip(ln, 0, R - 1)
    local_ctx = state.ctx_max[rows_clip]  # [U, R]
    covered_local = _row_table_lookup(local_ctx, ln_clip.astype(jnp.int32)) >= sl.ctr
    ins = sl.alive & valid[:, None] & ~covered_local & (ln >= 0)
    # delta-interval contiguity: advancing ctx to hi is only sound if our
    # context already reaches lo (no unobserved gap beneath the interval)
    gap_row = jnp.any(valid[:, None] & (rdense > ldense) & (local_ctx < ldense), axis=1)
    need_ctx_gap = jnp.any(gap_row)
    return SliceView(
        valid, rows_safe, rows_clip, gids, rdense, ldense, ln, ln_clip,
        local_ctx, ins, need_ctx_gap, gap_row, nonempty,
    )


class MergeResult(NamedTuple):
    state: BinnedStore
    ok: jnp.ndarray  # bool: result valid (budgets sufficed)
    need_gid_grow: jnp.ndarray  # bool: unknown writer gids overflowed R
    need_kill_tier: jnp.ndarray  # bool: flagged rows exceeded the kill budget
    need_fill_compact: jnp.ndarray  # bool: some row ran out of bin space
    need_ctx_gap: jnp.ndarray  # bool: delta-interval not contiguous with our
    # context (caller must fall back to a full-row sync; never raised by
    # ctx_lo = 0 state-form slices)
    need_ins_tier: jnp.ndarray  # bool: inserts exceeded the max_inserts tier
    n_inserted: jnp.ndarray  # int32
    n_killed: jnp.ndarray  # int32


def merge_slice(
    state: BinnedStore,
    sl: RowSlice,
    kill_budget: int,
    max_inserts: int | None = None,
) -> MergeResult:
    """Join a received bucket slice into the local state — O(slice) plus
    O(kill_budget · B) for the pruned kill pass.

    Per synced bucket the reference join applies (``aw_lww_map.ex:
    153-209``):
      - insert remote entries not covered by the local context (s2 ∖ c1);
      - kill local entries covered by the remote context interval and
        absent from the remote entries (survivors = (s1∩s2) ∪ (s1∖c2));
      - context union (per-replica max), valid because the interval is
        verified contiguous with the local context (``need_ctx_gap``).
    The kill pass gathers only rows where the ``amin``/``amax`` test
    proves a kill is possible; ``kill_budget`` rows at most (static
    tier), else ``ok=False`` and the host retries with a bigger tier.

    ``max_inserts`` (static tier) compacts the insert scatter: the [U, S]
    slice grid is mostly padding, and TPU scatter cost is per *index
    entry*, so the inserts are sort-compacted to ``max_inserts`` sorted
    unique positions before scattering. ``need_ins_tier`` reports an
    overflowing tier (caller retries a bigger one). ``None`` scatters the
    full grid (no compaction).
    """
    L = state.num_buckets
    B = state.bin_capacity
    R = state.replica_capacity
    u, s = sl.key.shape

    v = _slice_view(state, sl)
    valid, rows_safe, rows_clip = v.valid, v.rows_safe, v.rows_clip
    gids, rdense, ldense = v.gids, v.rdense, v.ldense
    ln, ln_clip, ins, need_ctx_gap = v.ln, v.ln_clip, v.ins, v.need_ctx_gap

    # --- insert pass (s2 ∖ c1): element scatters at fill positions -------
    ins_rank = jnp.cumsum(ins.astype(jnp.int32), axis=1) - 1
    n_ins_row = jnp.sum(ins, axis=1, dtype=jnp.int32)
    fill_rows = state.fill[rows_clip]
    need_fill_compact = jnp.any(valid & (fill_rows + n_ins_row > B))
    pos = fill_rows[:, None] + ins_rank  # [U, S] target bin slot

    # overflowing rows (pos >= B) must not clip into valid slots — drop.
    # Padding indices are DISTINCT out-of-bounds values (L*B + position):
    # the compacted scatter promises unique_indices, and duplicated
    # sentinels would void that promise even though they are dropped.
    # 32-bit index math when the range fits (it always does at real
    # geometries): TPU has no native i64 — a 64-bit argsort/scatter pays
    # emulated two-word compares for nothing
    idx_dtype = jnp.int32 if L * B + u * s < 2**31 else jnp.int64
    pad_idx = L * B + jnp.arange(u * s, dtype=idx_dtype).reshape(u, s)
    flat = jnp.where(
        ins & (pos < B),
        rows_clip[:, None].astype(idx_dtype) * B + jnp.clip(pos, 0, B - 1),
        pad_idx,
    )
    n_inserted = jnp.sum(ins.astype(jnp.int32))

    if max_inserts is None:
        need_ins_tier = jnp.bool_(False)
        flat_c = flat.reshape(-1)
        sel = slice(None)
        sorted_hint = False
    else:
        # sort-compact: real insert positions (ascending) first, padding
        # (L*B) last — scatters then touch max_inserts sorted unique
        # indices instead of the full padded grid. top_k of the negated
        # indices = the k smallest, already sorted: O(n log k) instead of
        # a full O(n log² n) argsort, and it emits the compacted index
        # values directly (flat is duplicate-free by construction)
        k = min(max_inserts, flat.size)
        neg_vals, sel = jax.lax.top_k(-flat.reshape(-1), k)
        flat_c = -neg_vals
        need_ins_tier = n_inserted > sel.shape[0]
        sorted_hint = True

    # compacted payload columns (the compacted branch computes the entry
    # hash AFTER compaction: the grid-wide hash would burn emulated-u64
    # mix rounds on ~90% padding)
    take = lambda a: a.reshape(-1)[sel]
    key_c = take(sl.key)
    valh_c = take(sl.valh)
    ts_c = take(sl.ts)
    ctr_c = take(sl.ctr)
    ln_c = take(ln_clip).astype(jnp.int32)
    node_c = take(jnp.clip(sl.node, 0, sl.ctx_gid.shape[0] - 1))
    eh_c = entry_hash(key_c, _table_lookup(sl.ctx_gid, node_c), ctr_c, ts_c, valh_c)
    ins_c = flat_c < L * B  # real inserts; padding indices scatter-drop
    rows_c = (flat_c // B).astype(jnp.int32)  # == L+ (dropped) for padding

    def put(col, vals_c):
        return (
            col.reshape(-1)
            .at[flat_c]
            .set(
                vals_c,
                mode="drop",
                unique_indices=sorted_hint,
                indices_are_sorted=sorted_hint,
            )
            .reshape(L, B)
        )

    key2 = put(state.key, key_c)
    valh2 = put(state.valh, valh_c)
    ts2 = put(state.ts, ts_c)
    node2 = put(state.node, ln_c)
    ctr2 = put(state.ctr, ctr_c)
    ehash2 = put(state.ehash, eh_c)
    alive2 = put(state.alive, ins_c)
    fill2 = state.fill.at[rows_safe].add(n_ins_row, mode="drop")
    amin2 = state.amin.at[rows_c, ln_c].min(
        jnp.where(ins_c, ctr_c, U32_MAX), mode="drop"
    )
    amax2 = state.amax.at[rows_c, ln_c].max(
        jnp.where(ins_c, ctr_c, jnp.uint32(0)), mode="drop"
    )
    # leaf digests: in the compacted branch, scatter-add the k-bounded
    # inserted hashes by row (duplicates accumulate; padding rows >= L
    # drop; NOT unique — same-row inserts share a row index). The
    # uncompacted branch row-reduces first so the scatter stays U index
    # entries, not the full U*S grid.
    if max_inserts is None:
        leaf_add = jnp.sum(
            jnp.where(ins & (pos < B), eh_c.reshape(u, s), jnp.uint32(0)),
            axis=1,
            dtype=jnp.uint32,
        )
        leaf2 = state.leaf.at[rows_safe].add(leaf_add, mode="drop")
    else:
        leaf2 = state.leaf.at[rows_c].add(
            jnp.where(ins_c, eh_c, jnp.uint32(0)), mode="drop"
        )
    # context union, one scatter per remote writer column (the slice's
    # writer table is small; a [U, R] row scatter would cost U·R index
    # entries for mostly-empty rows)
    ctx2 = state.ctx_max
    for rr in range(sl.ctx_gid.shape[0]):
        colr = jnp.where(gids.remap[rr] >= 0, gids.remap[rr], R)
        vals_r = jnp.where(v.nonempty[:, rr], sl.ctx_rows[:, rr], jnp.uint32(0))
        ctx2 = ctx2.at[rows_safe, colr].max(vals_r, mode="drop")

    # --- kill pass ((s1∩s2) ∪ (s1∖c2)), pruned by amin/amax ---------------
    # the interval (lo, hi] can only kill a local dot if it overlaps the
    # [amin, amax] alive-counter span of some (bucket, writer) — all
    # computed on the PRE-merge state, as the join semantics demand
    amin_rows = state.amin[rows_clip]
    amax_rows = state.amax[rows_clip]
    flagged = valid & jnp.any((rdense >= amin_rows) & (ldense < amax_rows), axis=1)
    n_flagged = jnp.sum(flagged.astype(jnp.int32))
    need_kill_tier = n_flagged > kill_budget

    # flagged rows first (need_kill_tier reports when they exceed the
    # budget, so truncation is never silent)
    order = flagged_first_order(flagged, kill_budget)
    kb = order.shape[0]  # = min(kill_budget, U)
    k_valid = flagged[order]  # [KB]
    k_rows = jnp.where(k_valid, rows_clip[order], L)
    k_rows_clip = jnp.clip(k_rows, 0, L - 1)

    # local dots of the flagged rows — NOTE: read through the post-insert
    # arrays; inserted entries sit at slots >= fill and carry fresh remote
    # dots (present in the slice by construction), so they survive their
    # own coverage test via the presence check below
    l_node = node2[k_rows_clip]
    l_ctr = ctr2[k_rows_clip]
    l_alive = alive2[k_rows_clip] & k_valid[:, None]
    l_ehash = ehash2[k_rows_clip]

    k_rdense = rdense[order]  # [KB, R]
    k_ldense = ldense[order]
    covered = (
        jnp.take_along_axis(k_rdense, l_node.astype(jnp.int32), axis=1) >= l_ctr
    ) & (jnp.take_along_axis(k_ldense, l_node.astype(jnp.int32), axis=1) < l_ctr)
    # presence among remote slice dots of the same rows: [KB, B] vs [KB, S]
    r_node = ln_clip[order]
    r_ctr = sl.ctr[order]
    r_alive = sl.alive[order] & k_valid[:, None]
    l_dot = encode_dot(l_node, l_ctr)
    r_dot = jnp.where(r_alive, encode_dot(r_node, r_ctr), jnp.uint64(0))
    present = jnp.any(l_dot[:, :, None] == r_dot[:, None, :], axis=2)

    die = l_alive & covered & ~present
    alive3 = alive2.at[k_rows].set(l_alive & ~die, mode="drop")
    # wrapping subtract of dead hashes from the leaf digests
    leaf_sub = jnp.sum(jnp.where(die, l_ehash, jnp.uint32(0)), axis=1, dtype=jnp.uint32)
    leaf3 = leaf2.at[k_rows].add(~leaf_sub + jnp.uint32(1), mode="drop")
    amin_k = _row_amin(l_node, l_ctr, l_alive & ~die, kb, R)
    amin3 = amin2.at[k_rows].set(amin_k, mode="drop")
    amax_k = _row_amax(l_node, l_ctr, l_alive & ~die, kb, R)
    amax3 = amax2.at[k_rows].set(amax_k, mode="drop")
    n_killed = jnp.sum(die.astype(jnp.int32))

    ok = ~(
        gids.overflow
        | need_kill_tier
        | need_fill_compact
        | need_ctx_gap
        | need_ins_tier
    )
    new_state = BinnedStore(
        key=key2,
        valh=valh2,
        ts=ts2,
        node=node2,
        ctr=ctr2,
        alive=alive3,
        ehash=ehash2,
        fill=fill2,
        amin=amin3,
        amax=amax3,
        leaf=leaf3,
        ctx_gid=gids.ctx_gid,
        ctx_max=ctx2,
    )
    return MergeResult(
        new_state,
        ok,
        gids.overflow,
        need_kill_tier,
        need_fill_compact,
        need_ctx_gap,
        need_ins_tier,
        n_inserted,
        n_killed,
    )


class MergeRowsResult(NamedTuple):
    state: BinnedStore
    ok: jnp.ndarray  # bool: result valid
    need_gid_grow: jnp.ndarray  # bool: unknown writer gids overflowed R
    need_fill_grow: jnp.ndarray  # bool: survivors + inserts exceed B
    need_ctx_gap: jnp.ndarray  # bool: delta-interval not contiguous
    n_inserted: jnp.ndarray  # int32
    n_killed: jnp.ndarray  # int32
    # per-row counts (int32[U]): a coalesced fan-in merge concatenates
    # several messages' rows into one slice, and per-message accounting
    # (telemetry parity with sequential handling) is a host-side sum over
    # each message's row range — possible only if the kernel reports
    # per-row, not just slice-total, counts. Scalars above stay for
    # existing callers (totals == per-row sums).
    n_ins_row: jnp.ndarray  # int32[U]
    n_kill_row: jnp.ndarray  # int32[U]
    #: bool[U] — WHICH rows' delta-intervals gap. A grouped fan-in merge
    #: concatenates several messages' rows; on ``need_ctx_gap`` the host
    #: maps the flagged rows back to member slices and replays only the
    #: gapped senders solo, keeping clean senders in one grouped dispatch.
    gap_row: jnp.ndarray


def merge_rows(state: BinnedStore, sl: RowSlice) -> MergeRowsResult:
    """Row-granular anti-entropy merge: gather the slice's rows whole,
    do every step as dense row-local vector math, scatter the rows back
    (U row indices — row scatters are index-cheap, unlike the element
    scatters the first merge design compacted and budgeted around).

    Consequences of working on whole rows:

    - the kill pass runs on EVERY synced row for free — no ``amin``
      pruning, no ``kill_budget`` tier, no retry recompiles;
    - inserts need no position bookkeeping or sort-compaction tier:
      survivors and inserts pack together with one in-row sort, which
      also reclaims every hole (merges never fragment rows);
    - the only capacity escape left is genuine bin overflow
      (``need_fill_grow``: alive survivors + inserts > B) plus the gid
      table and interval-gap conditions.

    Semantics are identical to the reference join (``aw_lww_map.ex:
    153-209``): insert s2 ∖ c1, kill s1 dots covered by the remote
    interval and absent from s2, context union = per-(bucket, writer)
    max, delta-interval contiguity enforced (``need_ctx_gap``).
    """
    B = state.bin_capacity
    R = state.replica_capacity
    u, s = sl.key.shape

    v = _slice_view(state, sl)
    valid, rows_safe, rows_clip = v.valid, v.rows_safe, v.rows_clip
    gids, rdense, ldense = v.gids, v.rdense, v.ldense
    ln, ln_clip, ins, need_ctx_gap = v.ln, v.ln_clip, v.ins, v.need_ctx_gap
    local_ctx = v.local_ctx

    g = _gather_rows(state, rows_clip)
    galive = state.alive[rows_clip] & valid[:, None]

    # kill pass ((s1∩s2) ∪ (s1∖c2)) on every row: a local dot dies iff
    # the interval covers it and the slice doesn't carry it
    cov_hi = _row_table_lookup(rdense, g["node"])
    cov_lo = _row_table_lookup(ldense, g["node"])
    covered = (cov_hi >= g["ctr"]) & (cov_lo < g["ctr"])
    r_ok = sl.alive & (ln >= 0)
    present = jnp.any(
        (g["node"][:, :, None] == ln_clip[:, None, :])
        & (g["ctr"][:, :, None] == sl.ctr[:, None, :])
        & r_ok[:, None, :],
        axis=2,
    )
    die = galive & covered & ~present
    alive_surv = galive & ~die

    # pack survivors + inserts into the row's B slots (one stable sort,
    # holes reclaimed as a side effect)
    eh_ins = entry_hash(
        sl.key,
        _table_lookup(sl.ctx_gid, jnp.clip(sl.node, 0, sl.ctx_gid.shape[0] - 1)),
        sl.ctr,
        sl.ts,
        sl.valh,
    )
    wide = {
        "key": jnp.concatenate([g["key"], sl.key], axis=1),
        "valh": jnp.concatenate([g["valh"], sl.valh], axis=1),
        "ts": jnp.concatenate([g["ts"], sl.ts], axis=1),
        "node": jnp.concatenate([g["node"], ln_clip.astype(jnp.int32)], axis=1),
        "ctr": jnp.concatenate([g["ctr"], sl.ctr], axis=1),
        "ehash": jnp.concatenate([g["ehash"], eh_ins], axis=1),
    }
    w_alive = jnp.concatenate([alive_surv, ins], axis=1)
    packed_w, alive_w, n_alive_row = _row_compact(wide, w_alive)
    packed = {c: v[:, :B] for c, v in packed_w.items()}
    alive_p = alive_w[:, :B]
    need_fill_grow = jnp.any(valid & (n_alive_row > B))
    fill_rows = jnp.minimum(n_alive_row, B)

    amin_rows = _row_amin(packed["node"], packed["ctr"], alive_p, u, R)
    amax_rows = _row_amax(packed["node"], packed["ctr"], alive_p, u, R)
    leaf_rows = jnp.sum(
        jnp.where(alive_p, packed["ehash"], jnp.uint32(0)), axis=1, dtype=jnp.uint32
    )
    ctx2 = jnp.maximum(local_ctx, rdense)

    new_state = BinnedStore(
        **{c: getattr(state, c).at[rows_safe].set(packed[c], mode="drop") for c in _ROW_COLS},
        alive=state.alive.at[rows_safe].set(alive_p, mode="drop"),
        fill=state.fill.at[rows_safe].set(fill_rows, mode="drop"),
        amin=state.amin.at[rows_safe].set(amin_rows, mode="drop"),
        amax=state.amax.at[rows_safe].set(amax_rows, mode="drop"),
        leaf=state.leaf.at[rows_safe].set(leaf_rows, mode="drop"),
        ctx_gid=gids.ctx_gid,
        ctx_max=state.ctx_max.at[rows_safe].set(ctx2, mode="drop"),
    )
    ok = ~(gids.overflow | need_fill_grow | need_ctx_gap)
    n_ins_row = jnp.sum(ins.astype(jnp.int32), axis=1)
    n_kill_row = jnp.sum(die.astype(jnp.int32), axis=1)
    return MergeRowsResult(
        new_state,
        ok,
        gids.overflow,
        need_fill_grow,
        need_ctx_gap,
        jnp.sum(n_ins_row),
        jnp.sum(n_kill_row),
        n_ins_row,
        n_kill_row,
        v.gap_row,
    )


# ---------------------------------------------------------------------------
# reads


class KeyWinners(NamedTuple):
    found: jnp.ndarray  # bool[K]
    gid: jnp.ndarray  # uint64[K] winner's writer gid
    ctr: jnp.ndarray  # uint32[K]
    valh: jnp.ndarray  # uint32[K]
    ts: jnp.ndarray  # int64[K]


def _lww_rank(ts, gid, ctr, alive):
    """Lexicographic (ts, gid, ctr) LWW order as a sortable tuple; dead
    entries rank below everything."""
    neg = jnp.int64(-(2**62))
    return (
        jnp.where(alive, ts, neg),
        jnp.where(alive, gid, jnp.uint64(0)),
        jnp.where(alive, ctr, jnp.uint32(0)),
    )


def _argmax_lww(ts, gid, ctr, alive):
    """int32[..., 1] index of the lexicographic (ts, gid, ctr) maximum
    along the last axis: narrow the candidate mask one component at a
    time, then take the first index of the final mask."""
    t, g, c = _lww_rank(ts, gid, ctr, alive)
    m1 = t == jnp.max(t, axis=-1, keepdims=True)
    g1 = jnp.where(m1, g, jnp.uint64(0))
    m2 = m1 & (g1 == jnp.max(g1, axis=-1, keepdims=True))
    c1 = jnp.where(m2, c, jnp.uint32(0))
    m3 = m2 & (c1 == jnp.max(c1, axis=-1, keepdims=True))
    return jnp.argmax(m3, axis=-1, keepdims=True)


def winners_for_keys(state: BinnedStore, khash: jnp.ndarray) -> KeyWinners:
    """LWW winner per queried key hash (``AWLWWMap.read/2``,
    ``aw_lww_map.ex:218-224``)."""
    rows = state.bucket_of(khash)
    g_ts = state.ts[rows]
    g_key = state.key[rows]
    g_alive = state.alive[rows] & (g_key == khash[:, None])
    g_gid = _table_lookup(
        state.ctx_gid, jnp.clip(state.node[rows], 0, state.replica_capacity - 1)
    )
    g_ctr = state.ctr[rows]
    best = _argmax_lww(g_ts, g_gid, g_ctr, g_alive)
    take = lambda a: jnp.take_along_axis(a, best, axis=1)[:, 0]
    return KeyWinners(
        found=take(g_alive),
        gid=take(g_gid),
        ctr=take(g_ctr),
        valh=take(state.valh[rows]),
        ts=take(g_ts),
    )


class RowWinners(NamedTuple):
    win: jnp.ndarray  # bool[U, B]: entry is its key's LWW winner
    key: jnp.ndarray  # uint64[U, B]
    gid: jnp.ndarray  # uint64[U, B]
    ctr: jnp.ndarray  # uint32[U, B]
    valh: jnp.ndarray  # uint32[U, B]
    ts: jnp.ndarray  # int64[U, B]


def _sorted_winners(key, ts, gid, ctr, alive, valh) -> "RowWinners":
    """Shared winner core: one lexicographic multi-operand sort per row
    by (key, ts, gid, ctr); a winner is the last entry of its key-run
    (dead entries rank below everything, so a run whose last entry is
    dead is entirely dead). Returned arrays are in row-sorted order."""
    t, g, c = _lww_rank(ts, gid, ctr, alive)
    key_s, t_s, g_s, c_s, alive_s, valh_s = jax.lax.sort(
        (key, t, g, c, alive, valh), dimension=1, num_keys=4
    )
    run_last = jnp.concatenate(
        [key_s[:, :-1] != key_s[:, 1:], jnp.ones((key_s.shape[0], 1), bool)], axis=1
    )
    return RowWinners(alive_s & run_last, key_s, g_s, c_s, valh_s, t_s)


def winner_all(state: BinnedStore) -> RowWinners:
    """Whole-table LWW winners (:func:`winner_rows` with every row, minus
    the row gather): the full-map read path (``AWLWWMap.read/1``,
    ``aw_lww_map.ex:211-216``) sorts the entry table once instead of
    gathering bucket chunks through an index — one device call for the
    1M-key read."""
    gid = _table_lookup(
        state.ctx_gid, jnp.clip(state.node, 0, state.replica_capacity - 1)
    )
    return _sorted_winners(
        state.key, state.ts, gid, state.ctr, state.alive, state.valh
    )


def winner_rows(state: BinnedStore, rows: jnp.ndarray) -> RowWinners:
    """Per-key LWW winners within the given bucket rows (full-map read =
    all rows, chunked by the host). An entry wins iff no other alive
    same-key entry in its row ranks higher (keys never span rows).

    Implementation: :func:`_sorted_winners` over the gathered rows —
    O(B log B) lanes instead of the O(B²) pairwise compare. Callers
    select by ``win``, never by position."""
    L = state.num_buckets
    valid = rows >= 0
    rows_clip = jnp.clip(rows, 0, L - 1)
    gid = _table_lookup(
        state.ctx_gid, jnp.clip(state.node[rows_clip], 0, state.replica_capacity - 1)
    )
    return _sorted_winners(
        state.key[rows_clip],
        state.ts[rows_clip],
        gid,
        state.ctr[rows_clip],
        state.alive[rows_clip] & valid[:, None],
        state.valh[rows_clip],
    )


# ---------------------------------------------------------------------------
# maintenance


def init_from_columns(state: BinnedStore) -> BinnedStore:
    """Rebuild ``ehash`` from the entry columns, then every maintained
    invariant (:func:`compact_rows`). For host-constructed states
    (benchmarks, bulk loads): the host fills key/valh/ts/node/ctr/alive
    and the context tables; the device derives the rest in one pass."""
    node_c = jnp.clip(state.node, 0, state.replica_capacity - 1)
    ehash = entry_hash(
        state.key, _table_lookup(state.ctx_gid, node_c), state.ctr, state.ts, state.valh
    )
    return compact_rows(dataclasses.replace(state, ehash=ehash))


def compact_rows(state: BinnedStore) -> BinnedStore:
    """Full repack: reclaim holes left by merge kills, rebuild every
    maintained invariant. One dense pass; host calls it when a merge
    reports ``need_fill_compact``."""
    L = state.num_buckets
    R = state.replica_capacity
    cols = {c: getattr(state, c) for c in _ROW_COLS}
    packed, alive_p, fill = _row_compact(cols, state.alive)
    amin = _row_amin(packed["node"], packed["ctr"], alive_p, L, R)
    amax = _row_amax(packed["node"], packed["ctr"], alive_p, L, R)
    leaf = jnp.sum(
        jnp.where(alive_p, packed["ehash"], jnp.uint32(0)), axis=1, dtype=jnp.uint32
    )
    return BinnedStore(
        **packed,
        alive=alive_p,
        fill=fill,
        amin=amin,
        amax=amax,
        leaf=leaf,
        ctx_gid=state.ctx_gid,
        ctx_max=state.ctx_max,
    )
