"""Pallas TPU kernels: fused digest-tree folds over leaf digests.

The digest tree (the ``MerkleMap.update_hashes`` analog,
``causal_crdt.ex:254``) folds the maintained leaf digests into parent
levels. The XLA version materialises every level as a separate HBM
array with one fusion per level — log2(L) kernel launches and HBM round
trips. The whole working set is tiny (a replica's leaf array at
L = 2^14 is 64 KB), so a Pallas kernel can keep the entire fold in VMEM.

**The production kernel is** :func:`batched_roots_pallas` (what
:func:`batched_roots_fn` probes and the bench uses): a roll-based
strided fold over (8, L) blocks that computes one root per tree of a
batch in a single launch. It is shaped around Mosaic's TPU constraints
— 8-row blocks, full-width rolls, no reshapes (a round-1 (1, L)-block
packed-levels kernel never lowered on real TPUs and was removed; this
one exists because of that lesson). The combine mix matches
:func:`delta_crdt_ex_tpu.ops.binned.tree_from_leaves` bit for bit, so
either implementation can serve the sync walk.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# plain ints (not traced jnp scalars): pallas kernels may not capture
# array constants, and uint32 arithmetic promotes python ints exactly
_P1 = 0x85EBCA6B
_P2 = 0xC2B2AE35
_GOLDEN = 0x9E3779B9


def _mix32(x):
    x = (x ^ (x >> 16)) * jnp.uint32(_P1)
    x = (x ^ (x >> 13)) * jnp.uint32(_P2)
    return x ^ (x >> 16)


def _combine(left, right):
    return (
        _mix32(left ^ jnp.uint32(_P1))
        + (_mix32(right ^ jnp.uint32(_P2)) << 1)
        + jnp.uint32(_GOLDEN)
    )


def _roots_kernel(leaf_ref, out_ref):
    """Strided in-place fold: after step s, the value of level-(depth-s)
    node i sits at lane ``i * 2**s`` (other lanes hold garbage that no
    later step reads). Every step is a full-width roll + combine — no
    reshapes or strided slices, which Mosaic's TPU lowering rejects or
    relayouts; the roll is a native lane rotation. Root lands in lane 0."""
    from jax.experimental.pallas import tpu as pltpu

    cur = leaf_ref[...]  # [NB, L]
    L = cur.shape[1]
    k = 1
    while k < L:
        # shifted[i] = cur[i + k]  (pltpu.roll wants non-negative shifts)
        shifted = pltpu.roll(cur, L - k, 1)
        cur = _combine(cur, shifted)
        k *= 2
    out_ref[...] = cur[:, : out_ref.shape[1]]


def batched_roots_pallas(leaf: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Tree roots ``uint32[N]`` for a batch of leaf arrays ``uint32[N, L]``
    in one fused kernel launch, entire fold in VMEM. The batch is padded
    to a multiple of 8 (Mosaic requires the second-to-last block dim be a
    multiple of 8; the round-1 packed-levels kernel used (1, L) blocks and
    never lowered on real TPUs)."""
    import jax
    from jax.experimental import pallas as pl

    n, L = leaf.shape
    if L < 128 or L & (L - 1):
        raise ValueError(
            f"batched_roots_pallas needs a power-of-two L >= 128 (one full "
            f"lane vector), got L={L}; use the XLA fold for smaller trees"
        )
    nb = 8
    n_pad = -(-n // nb) * nb
    if n_pad != n:
        leaf = jnp.concatenate(
            [leaf, jnp.zeros((n_pad - n, L), jnp.uint32)], axis=0
        )
    out = pl.pallas_call(
        _roots_kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad, 128), jnp.uint32),
        grid=(n_pad // nb,),
        in_specs=[pl.BlockSpec((nb, L), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((nb, 128), lambda i: (i, 0)),
        interpret=interpret,
    )(leaf)
    return out[:n, 0]


def batched_roots_fn(num_leaves: int):
    """Probe Pallas availability once and return a jittable
    ``uint32[N, L] -> uint32[N]`` batched-roots function: the fused
    roll-fold kernel where it lowers, the per-level XLA fold elsewhere.
    (L must cover at least one 128-lane vector for the kernel.)"""
    import jax

    from delta_crdt_ex_tpu.ops.binned import tree_from_leaves as xla_tree

    tag = "xla"
    if num_leaves >= 128:
        try:
            # the probe's entire job is to force execution once so a
            # Mosaic lowering failure surfaces here, not in the sync
            # walk; the production kernel path never syncs
            # crdtlint: allow[host-sync] probe must synchronise by design
            jax.jit(batched_roots_pallas)(
                jnp.zeros((2, num_leaves), jnp.uint32)
            ).block_until_ready()
            return batched_roots_pallas, "pallas"
        except Exception as e:
            # the probe's whole job on a new backend is to learn WHY the
            # kernel won't lower — swallowing the Mosaic error here cost
            # round 4 its chip verdict (every session just logged
            # "digest tree: xla"); keep the fallback but surface the
            # reason in the impl tag callers log
            import sys

            msg = " ".join(str(e).split())
            print(
                f"[pallas_tree] batched_roots_pallas probe failed: {msg[:500]}",
                file=sys.stderr,
                flush=True,
            )
            tag = f"xla (pallas probe failed: {type(e).__name__}: {msg[:160]})"
    return jax.vmap(lambda lf: xla_tree(lf)[0][0]), tag
