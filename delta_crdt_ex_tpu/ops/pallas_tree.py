"""Pallas TPU kernels: fused digest-tree folds over leaf digests.

The digest tree (the ``MerkleMap.update_hashes`` analog,
``causal_crdt.ex:254``) folds the maintained leaf digests into parent
levels. The XLA version materialises every level as a separate HBM
array with one fusion per level — log2(L) kernel launches and HBM round
trips. The whole working set is tiny (a replica's leaf array at
L = 2^14 is 64 KB), so a Pallas kernel can keep the entire fold in VMEM.

**The production kernel is** :func:`batched_roots_pallas` (what
:func:`batched_roots_fn` probes and the bench uses): a roll-based
strided fold over (8, L) blocks that computes one root per tree of a
batch in a single launch. It is shaped around Mosaic's TPU constraints
— 8-row blocks, full-width rolls, no reshapes.

:func:`tree_from_leaves_pallas` is the round-1 packed-ALL-levels kernel
(heap order: node i of level d at index ``2^d + i``; index 1 = root).
Its (1, L) block spec never lowered on real TPUs (Mosaic requires the
second-to-last block dim be a multiple of 8); it is kept as an
interpret-mode executable spec of the packed-levels layout should a
future sync walk want on-device levels. Both kernels' combine mix
matches :func:`delta_crdt_ex_tpu.ops.binned.tree_from_leaves` bit for
bit, so any implementation can serve the sync walk.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# plain ints (not traced jnp scalars): pallas kernels may not capture
# array constants, and uint32 arithmetic promotes python ints exactly
_P1 = 0x85EBCA6B
_P2 = 0xC2B2AE35
_GOLDEN = 0x9E3779B9


def _mix32(x):
    x = (x ^ (x >> 16)) * jnp.uint32(_P1)
    x = (x ^ (x >> 13)) * jnp.uint32(_P2)
    return x ^ (x >> 16)


def _combine(left, right):
    return (
        _mix32(left ^ jnp.uint32(_P1))
        + (_mix32(right ^ jnp.uint32(_P2)) << 1)
        + jnp.uint32(_GOLDEN)
    )


def _tree_kernel(leaf_ref, out_ref):
    """One grid program folds one tree entirely in VMEM.

    The fold works on a [1, W] row per level (TPU wants ≥2D vectors);
    splitting even/odd lanes via a reshape to [W/2, 2] keeps every step
    a dense VPU op.
    """
    cur = leaf_ref[0, :]  # [L]
    L = cur.shape[0]
    w = L
    # write packed levels progressively: level sizes L/2, L/4, …, 1
    while w > 1:
        pairs = cur.reshape(w // 2, 2)
        cur = _combine(pairs[:, 0], pairs[:, 1])  # [w/2]
        w //= 2
        out_ref[0, w : 2 * w] = cur
    out_ref[0, 0:1] = cur  # index 0 unused; keep deterministic


def tree_from_leaves_pallas(leaf: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Packed parent levels ``uint32[N, L]`` for a batch of leaf arrays
    ``uint32[N, L]`` (heap order, root at index 1). One kernel launch for
    the whole batch; each grid program folds one tree in VMEM."""
    from jax.experimental import pallas as pl

    n, L = leaf.shape
    return pl.pallas_call(
        _tree_kernel,
        out_shape=jax.ShapeDtypeStruct((n, L), jnp.uint32),
        grid=(n,),
        in_specs=[pl.BlockSpec((1, L), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, L), lambda i: (i, 0)),
        interpret=interpret,
    )(leaf)


def unpack_levels(packed: jnp.ndarray, depth: int) -> list[jnp.ndarray]:
    """Heap-packed parent levels → the list-of-levels shape the sync walk
    consumes (root first), for ONE tree ``uint32[L]``. The leaf level is
    not in ``packed``; append the original leaves."""
    return [packed[(1 << d) : (1 << (d + 1))] for d in range(depth)]


def _roots_kernel(leaf_ref, out_ref):
    """Strided in-place fold: after step s, the value of level-(depth-s)
    node i sits at lane ``i * 2**s`` (other lanes hold garbage that no
    later step reads). Every step is a full-width roll + combine — no
    reshapes or strided slices, which Mosaic's TPU lowering rejects or
    relayouts; the roll is a native lane rotation. Root lands in lane 0."""
    from jax.experimental.pallas import tpu as pltpu

    cur = leaf_ref[...]  # [NB, L]
    L = cur.shape[1]
    k = 1
    while k < L:
        # shifted[i] = cur[i + k]  (pltpu.roll wants non-negative shifts)
        shifted = pltpu.roll(cur, L - k, 1)
        cur = _combine(cur, shifted)
        k *= 2
    out_ref[...] = cur[:, : out_ref.shape[1]]


def batched_roots_pallas(leaf: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Tree roots ``uint32[N]`` for a batch of leaf arrays ``uint32[N, L]``
    in one fused kernel launch, entire fold in VMEM. The batch is padded
    to a multiple of 8 (Mosaic requires the second-to-last block dim be a
    multiple of 8; the round-1 packed-levels kernel used (1, L) blocks and
    never lowered on real TPUs)."""
    import jax
    from jax.experimental import pallas as pl

    n, L = leaf.shape
    if L < 128 or L & (L - 1):
        raise ValueError(
            f"batched_roots_pallas needs a power-of-two L >= 128 (one full "
            f"lane vector), got L={L}; use the XLA fold for smaller trees"
        )
    nb = 8
    n_pad = -(-n // nb) * nb
    if n_pad != n:
        leaf = jnp.concatenate(
            [leaf, jnp.zeros((n_pad - n, L), jnp.uint32)], axis=0
        )
    out = pl.pallas_call(
        _roots_kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad, 128), jnp.uint32),
        grid=(n_pad // nb,),
        in_specs=[pl.BlockSpec((nb, L), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((nb, 128), lambda i: (i, 0)),
        interpret=interpret,
    )(leaf)
    return out[:n, 0]


def batched_roots_fn(num_leaves: int):
    """Probe Pallas availability once and return a jittable
    ``uint32[N, L] -> uint32[N]`` batched-roots function: the fused
    roll-fold kernel where it lowers, the per-level XLA fold elsewhere.
    (L must cover at least one 128-lane vector for the kernel.)"""
    import jax

    from delta_crdt_ex_tpu.ops.binned import tree_from_leaves as xla_tree

    if num_leaves >= 128:
        try:
            jax.jit(batched_roots_pallas)(
                jnp.zeros((2, num_leaves), jnp.uint32)
            ).block_until_ready()
            return batched_roots_pallas, "pallas"
        except Exception:
            pass
    return jax.vmap(lambda lf: xla_tree(lf)[0][0]), "xla"
