"""Pallas TPU kernel: fused digest-tree build from leaf digests.

``tree_from_leaves`` (the ``MerkleMap.update_hashes`` analog,
``causal_crdt.ex:254``) folds the maintained leaf digests into parent
levels. The XLA version materialises every level as a separate HBM
array with one fusion per level — log2(L) kernel launches and HBM round
trips. The whole working set is tiny (a replica's leaf array at
L = 2^14 is 64 KB), so the Pallas kernel keeps the entire fold in VMEM:
one launch computes all levels of a *batch* of trees (the vmapped
neighbour axis of the bench) and writes the packed parent levels once.

Layout: parent levels are packed into one ``uint32[N, L]`` output —
level d (size 2^d, d = depth-1 … 0) lives at offset ``2^d`` … ``2^(d+1)``
(heap order: node i of level d at index ``2^d + i``; index 1 = root,
index 0 unused). The level-combine mix matches
:func:`delta_crdt_ex_tpu.ops.binned.tree_from_leaves` bit for bit, so
either implementation can serve the sync walk.

Falls back to the XLA path transparently where Pallas TPU lowering is
unavailable (CPU tests run the interpreter instead).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# plain ints (not traced jnp scalars): pallas kernels may not capture
# array constants, and uint32 arithmetic promotes python ints exactly
_P1 = 0x85EBCA6B
_P2 = 0xC2B2AE35
_GOLDEN = 0x9E3779B9


def _mix32(x):
    x = (x ^ (x >> 16)) * jnp.uint32(_P1)
    x = (x ^ (x >> 13)) * jnp.uint32(_P2)
    return x ^ (x >> 16)


def _combine(left, right):
    return (
        _mix32(left ^ jnp.uint32(_P1))
        + (_mix32(right ^ jnp.uint32(_P2)) << 1)
        + jnp.uint32(_GOLDEN)
    )


def _tree_kernel(leaf_ref, out_ref):
    """One grid program folds one tree entirely in VMEM.

    The fold works on a [1, W] row per level (TPU wants ≥2D vectors);
    splitting even/odd lanes via a reshape to [W/2, 2] keeps every step
    a dense VPU op.
    """
    cur = leaf_ref[0, :]  # [L]
    L = cur.shape[0]
    w = L
    # write packed levels progressively: level sizes L/2, L/4, …, 1
    while w > 1:
        pairs = cur.reshape(w // 2, 2)
        cur = _combine(pairs[:, 0], pairs[:, 1])  # [w/2]
        w //= 2
        out_ref[0, w : 2 * w] = cur
    out_ref[0, 0:1] = cur  # index 0 unused; keep deterministic


def tree_from_leaves_pallas(leaf: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Packed parent levels ``uint32[N, L]`` for a batch of leaf arrays
    ``uint32[N, L]`` (heap order, root at index 1). One kernel launch for
    the whole batch; each grid program folds one tree in VMEM."""
    from jax.experimental import pallas as pl

    n, L = leaf.shape
    return pl.pallas_call(
        _tree_kernel,
        out_shape=jax.ShapeDtypeStruct((n, L), jnp.uint32),
        grid=(n,),
        in_specs=[pl.BlockSpec((1, L), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, L), lambda i: (i, 0)),
        interpret=interpret,
    )(leaf)


def unpack_levels(packed: jnp.ndarray, depth: int) -> list[jnp.ndarray]:
    """Heap-packed parent levels → the list-of-levels shape the sync walk
    consumes (root first), for ONE tree ``uint32[L]``. The leaf level is
    not in ``packed``; append the original leaves."""
    return [packed[(1 << d) : (1 << (d + 1))] for d in range(depth)]


def batched_roots_fn(num_leaves: int):
    """Probe Pallas availability once and return a jittable
    ``uint32[N, L] -> uint32[N]`` batched-roots function: the fused
    kernel where it lowers, the per-level XLA fold elsewhere."""
    import jax

    from delta_crdt_ex_tpu.ops.binned import tree_from_leaves as xla_tree

    try:
        jax.jit(tree_from_leaves_pallas)(
            jnp.zeros((2, num_leaves), jnp.uint32)
        ).block_until_ready()
        return lambda leaf: tree_from_leaves_pallas(leaf)[:, 1], "pallas"
    except Exception:
        return jax.vmap(lambda lf: xla_tree(lf)[0][0]), "xla"
