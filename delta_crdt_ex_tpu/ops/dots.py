"""Causal-context kernels (the reference's ``AWLWWMap.Dots``, ``aw_lww_map.ex:10-97``).

The context lives in compressed state form — per-replica max counter
(``Dots.compress``, ``aw_lww_map.ex:13-20``) — decomposed per sync-index
bucket: ``(ctx_gid u64[R], ctx_max u32[L, R])`` (see
:mod:`delta_crdt_ex_tpu.models.state` for why bucket rows rather than one
global row). Slot indices are replica-local, so whenever two states meet
their gid tables must be merged and the incoming state's slots remapped.
This happens **on device** (no host round-trip) so the same kernel serves
host-driven sync and in-mesh ``shard_map`` gossip.

Context lattice ops map to the reference like so:

- union (per-replica max, ``Dots.union`` ``aw_lww_map.ex:45-52``) →
  column-scatter max in :func:`merge_contexts` (bucket-rowwise);
- membership ``{i,x} ∈ c ⟺ c[i] ≥ x`` (``Dots.member?`` ``:71-73``) →
  a (bucket, slot) gather + compare (see callers in
  :mod:`delta_crdt_ex_tpu.ops.join`);
- ``next_dot`` (``:35-37``) → counter assignment in
  :mod:`delta_crdt_ex_tpu.ops.apply` (cumsum over the mutation batch).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class MergedContext(NamedTuple):
    ctx_gid: jnp.ndarray  # uint64[R]     merged slot table (local slots preserved)
    ctx_max: jnp.ndarray  # uint32[L, R]  merged per-bucket context (the union)
    remap: jnp.ndarray  # int32[Rr]     remote slot → local slot (-1 for empty)
    remote_dense: jnp.ndarray  # uint32[L, R]  remote context in local slot indexing
    overflow: jnp.ndarray  # bool          not enough free local slots for new gids


def merge_contexts(
    gid_l: jnp.ndarray,
    max_l: jnp.ndarray,
    gid_r: jnp.ndarray,
    max_r: jnp.ndarray,
) -> MergedContext:
    """Merge a remote context into a local one.

    Matching gids keep their local slot; unknown gids are assigned to free
    local slots in remote-slot order. ``remote_dense`` re-expresses the
    remote bucket rows over local slots so dot-membership tests against
    the remote context become a single gather. Rows the remote side did
    not ship (unsynced buckets: all-zero) union as no-ops.
    """
    r_local = gid_l.shape[0]

    occupied_r = gid_r != 0
    # R_l x R_r equality: which local slot holds each remote gid.
    eq = (gid_l[:, None] == gid_r[None, :]) & occupied_r[None, :]
    has_match = jnp.any(eq, axis=0)
    match_idx = jnp.argmax(eq, axis=0).astype(jnp.int32)

    is_new = occupied_r & ~has_match
    free = gid_l == 0
    free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1
    # rank → local slot index (unassigned ranks point out of bounds)
    slot_of_rank = (
        jnp.full(r_local, r_local, jnp.int32)
        .at[jnp.where(free, free_rank, r_local)]
        .set(jnp.arange(r_local, dtype=jnp.int32), mode="drop")
    )
    new_rank = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    n_new = jnp.sum(is_new.astype(jnp.int32))
    n_free = jnp.sum(free.astype(jnp.int32))
    overflow = n_new > n_free

    new_slot = slot_of_rank[jnp.clip(new_rank, 0, r_local - 1)]
    target = jnp.where(is_new, new_slot, match_idx)
    target = jnp.where(occupied_r, target, r_local)  # empty remote slots: drop

    ctx_gid = gid_l.at[target].set(gid_r, mode="drop")
    # scatter remote columns into local slot positions, bucket-rowwise
    remote_dense = (
        jnp.zeros_like(max_l).at[:, target].max(max_r, mode="drop")
    )
    ctx_max = jnp.maximum(max_l, remote_dense)

    remap = jnp.where(occupied_r, target, -1).astype(jnp.int32)
    return MergedContext(ctx_gid, ctx_max, remap, remote_dense, overflow)


def encode_dot(node: jnp.ndarray, ctr: jnp.ndarray) -> jnp.ndarray:
    """Pack a (local-slot, counter) dot into one u64 sort/search key."""
    return (node.astype(jnp.uint64) << jnp.uint64(32)) | ctr.astype(jnp.uint64)


class MergedGids(NamedTuple):
    ctx_gid: jnp.ndarray  # uint64[R]  merged slot table (local slots preserved)
    remap: jnp.ndarray  # int32[Rr]  remote slot → local slot (-1 for empty)
    overflow: jnp.ndarray  # bool       not enough free local slots for new gids


def merge_gid_tables(gid_l: jnp.ndarray, gid_r: jnp.ndarray) -> MergedGids:
    """Merge the remote gid slot table into the local one — the R-sized
    prefix of :func:`merge_contexts`, for callers that handle context rows
    themselves (O(delta) merges must not touch the full ``[L, R]`` table)."""
    r_local = gid_l.shape[0]

    occupied_r = gid_r != 0
    eq = (gid_l[:, None] == gid_r[None, :]) & occupied_r[None, :]
    has_match = jnp.any(eq, axis=0)
    match_idx = jnp.argmax(eq, axis=0).astype(jnp.int32)

    is_new = occupied_r & ~has_match
    free = gid_l == 0
    free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1
    slot_of_rank = (
        jnp.full(r_local, r_local, jnp.int32)
        .at[jnp.where(free, free_rank, r_local)]
        .set(jnp.arange(r_local, dtype=jnp.int32), mode="drop")
    )
    new_rank = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    overflow = jnp.sum(is_new.astype(jnp.int32)) > jnp.sum(free.astype(jnp.int32))

    new_slot = slot_of_rank[jnp.clip(new_rank, 0, r_local - 1)]
    target = jnp.where(is_new, new_slot, match_idx)
    target = jnp.where(occupied_r, target, r_local)

    ctx_gid = gid_l.at[target].set(gid_r, mode="drop")
    # un-placeable new gids (overflow) map to -1 like empties, keeping the
    # "-1 = no local slot" contract callers guard on
    remap = jnp.where(occupied_r & (target < r_local), target, -1).astype(jnp.int32)
    return MergedGids(ctx_gid, remap, overflow)
