"""The lattice join — the hot kernel of the framework.

Implements the reference's causal δ-join (``AWLWWMap.join/3``,
``aw_lww_map.ex:153-209``) over the tensorised dot store, restricted — as
in the reference — to a key subset (`keys` there, a leaf-bucket mask here;
``join_or_maps`` passes non-listed keys through untouched,
``aw_lww_map.ex:185-188``).

Per-key dot-set join ``(s1∩s2) ∪ (s1∖c2) ∪ (s2∖c1)`` (``aw_lww_map.ex:
196-209``) becomes three fused masks over entry arrays:

- keep a participating local entry iff its dot is present in the delta
  (∩) or not covered by the delta's context (s1∖c2);
- insert a delta entry iff its dot is not covered by the local context
  (s2∖c1) — the local context row of the entry's bucket covers every dot
  the replica has ever observed there (alive or removed), so coverage
  alone prevents duplicate or resurrecting inserts;
- context union = per-replica max, bucket-rowwise (``Dots.union``,
  ``aw_lww_map.ex:45-52``). Unsynced buckets' rows arrive as zeros and
  union as no-ops, so a bounded partial sync can never over-advance the
  receiver's context (see :mod:`delta_crdt_ex_tpu.models.state`).

Everything is static-shaped; `ok=False` signals the host to grow a
capacity tier and retry (the only data-dependent escape hatch).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from delta_crdt_ex_tpu.models.state import DotStore
from delta_crdt_ex_tpu.ops.dots import merge_contexts
from delta_crdt_ex_tpu.ops.membership import dots_present


class JoinResult(NamedTuple):
    state: DotStore
    ok: jnp.ndarray  # bool: capacity sufficed (result invalid otherwise)
    n_inserted: jnp.ndarray  # int32
    n_killed: jnp.ndarray  # int32


def _bucket(key: jnp.ndarray, num_buckets: int) -> jnp.ndarray:
    return (key & jnp.uint64(num_buckets - 1)).astype(jnp.int32)


def join(
    local: DotStore,
    remote: DotStore,
    bucket_mask: jnp.ndarray | None = None,
) -> JoinResult:
    """Join a remote delta/state into the local state.

    ``remote`` may be a reconstituted slice (anti-entropy path: entries +
    context rows of exactly the synced buckets, zeros elsewhere) or a full
    peer state (mesh gossip). ``bucket_mask`` (bool[L]) limits
    reconciliation to the synced key buckets; ``None`` reconciles all keys.
    Both states must share the same bucket count L.
    """
    c = local.capacity
    num_buckets = local.num_buckets

    merged = merge_contexts(local.ctx_gid, local.ctx_max, remote.ctx_gid, remote.ctx_max)

    # Remote entries re-expressed in local slot indexing.
    node_r = merged.remap[remote.node]
    node_r_safe = jnp.clip(node_r, 0, local.replica_capacity - 1)

    bucket_l = _bucket(local.key, num_buckets)
    bucket_r = _bucket(remote.key, num_buckets)
    if bucket_mask is None:
        in_bucket_l = jnp.ones(c, bool)
        in_bucket_r = jnp.ones(remote.capacity, bool)
    else:
        in_bucket_l = bucket_mask[bucket_l]
        in_bucket_r = bucket_mask[bucket_r]

    # s2 ∖ c1 — delta entries this replica's bucket row has never covered.
    covered_r = local.ctx_max[bucket_r, node_r_safe] >= remote.ctr
    insert_mask = remote.alive & in_bucket_r & ~covered_r & (node_r >= 0)

    # (s1 ∩ s2) ∪ (s1 ∖ c2) — survivors among participating local entries.
    participating = local.alive & in_bucket_l
    covered_l = merged.remote_dense[bucket_l, local.node] >= local.ctr
    present_l = dots_present(
        local.node, local.ctr, node_r_safe, remote.ctr, remote.alive & in_bucket_r
    )
    alive1 = local.alive & (~participating | ~covered_l | present_l)

    # Scatter inserts into free slots (rank-matched via cumsums).
    free = ~alive1
    free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1
    slot_of_rank = (
        jnp.full(c, c, jnp.int32)
        .at[jnp.where(free, free_rank, c)]
        .set(jnp.arange(c, dtype=jnp.int32), mode="drop")
    )
    ins_rank = jnp.cumsum(insert_mask.astype(jnp.int32)) - 1
    n_ins = jnp.sum(insert_mask.astype(jnp.int32))
    n_free = jnp.sum(free.astype(jnp.int32))
    ok = (n_ins <= n_free) & ~merged.overflow

    tgt = jnp.where(insert_mask, slot_of_rank[jnp.clip(ins_rank, 0, c - 1)], c)

    state = DotStore(
        key=local.key.at[tgt].set(remote.key, mode="drop"),
        valh=local.valh.at[tgt].set(remote.valh, mode="drop"),
        ts=local.ts.at[tgt].set(remote.ts, mode="drop"),
        node=local.node.at[tgt].set(node_r_safe, mode="drop"),
        ctr=local.ctr.at[tgt].set(remote.ctr, mode="drop"),
        alive=alive1.at[tgt].set(True, mode="drop"),
        ctx_gid=merged.ctx_gid,
        ctx_max=merged.ctx_max,
    )
    n_killed = jnp.sum((local.alive & ~alive1).astype(jnp.int32))
    return JoinResult(state, ok, n_ins, n_killed)


class EntrySlice(NamedTuple):
    """Wire format of the anti-entropy data plane: compacted entry columns
    plus the sender's gid table. Context rows for the synced buckets are
    gathered separately (host-driven, bounded by max_sync_size) and the
    receiver reconstitutes a :class:`DotStore` via :func:`slice_to_store`."""

    key: jnp.ndarray  # uint64[S]
    valh: jnp.ndarray  # uint32[S]
    ts: jnp.ndarray  # int64[S]
    node: jnp.ndarray  # int32[S]
    ctr: jnp.ndarray  # uint32[S]
    alive: jnp.ndarray  # bool[S]
    ctx_gid: jnp.ndarray  # uint64[R]


class SliceResult(NamedTuple):
    slice: EntrySlice
    count: jnp.ndarray  # int32: entries selected
    ok: jnp.ndarray  # bool: slice capacity sufficed


def extract_buckets(state: DotStore, bucket_mask: jnp.ndarray, out_size: int) -> SliceResult:
    """Extract the replica's entries for a set of leaf buckets as a slice.

    The anti-entropy data plane: the originator ships exactly its entries
    for the differing buckets, with the matching context rows attached
    (reference: ``Map.take(crdt_state.value, keys)`` + ``dots:
    diff.dots``, ``causal_crdt.ex:115-119`` — except our context travels
    bucket-rowwise, see module docstring).
    """
    sel = state.alive & bucket_mask[_bucket(state.key, state.num_buckets)]
    rank = jnp.cumsum(sel.astype(jnp.int32)) - 1
    count = jnp.sum(sel.astype(jnp.int32))
    ok = count <= out_size
    tgt = jnp.where(sel, rank, out_size)

    def compact(col, dtype):
        return jnp.zeros(out_size, dtype).at[tgt].set(col, mode="drop")

    out = EntrySlice(
        key=compact(state.key, jnp.uint64),
        valh=compact(state.valh, jnp.uint32),
        ts=compact(state.ts, jnp.int64),
        node=compact(state.node, jnp.int32),
        ctr=compact(state.ctr, jnp.uint32),
        alive=jnp.zeros(out_size, bool).at[tgt].set(sel, mode="drop"),
        ctx_gid=state.ctx_gid,
    )
    return SliceResult(out, count, ok)


def slice_to_store(
    entry_cols: dict, ctx_rows: jnp.ndarray, row_buckets: jnp.ndarray, num_buckets: int
) -> DotStore:
    """Reconstitute a received slice as a DotStore: context rows scattered
    into a dense [L, R] (zeros for unsynced buckets → union no-ops)."""
    r = entry_cols["ctx_gid"].shape[0]
    dense = (
        jnp.zeros((num_buckets, r), jnp.uint32)
        .at[row_buckets]
        .set(ctx_rows, mode="drop")
    )
    return DotStore(ctx_max=dense, **entry_cols)
