"""Open-addressing hash-table kernels over the flat dot store.

Second dot-store backend (ISSUE 8): where the binned engine stores
entries in dense ``[L, B]`` bucket rows (one row per sync-index leaf)
and pays tier-promotion repacking when a row outgrows its lane tier,
this engine stores every entry in ONE flat open-addressing table
(WarpSpeed-style bucketed probing, PAPERS.md):

    slot lanes [H] : key/valh/ts/node/ctr/alive/ehash/arr

An entry's slot is found by probing a bounded window of lanes starting
at its key's group-aligned base (``_probe_window``): group size
:data:`GROUP` lanes, window ``probe_window`` lanes, linear at lane
granularity. Placement takes the first DEAD lane of the window — every
lookup scans its whole fixed window masked by ``alive`` (no
early-termination probe chains), so there are no tombstones: a kill
frees its lane immediately and steady-state update churn (kill old
dot, insert new) reuses lanes instead of creeping the table full. A
window with no dead lane signals ``need_fill_grow`` and the host
rehashes the table ×2 — the ONLY growth event (no per-tier repacking,
no lane-tier wire splits). Probing is by KEY, so every concurrent dot
of a key lives in that key's window — point lookups (reads,
kill/present tests) touch ``probe_window`` lanes instead of a bin row.

The sync-index geometry is UNCHANGED from the binned store: the
cluster-agreed ``L`` leaf buckets keep their per-bucket causal context
(``ctx_gid``/``ctx_max``), maintained leaf digests (identical wrapping
ehash sums ⇒ identical digest trees ⇒ identical walk traffic), and
per-(writer, bucket) dot counters. Join semantics are the reference's,
verbatim through the SAME interval preamble the binned kernels use
(:func:`delta_crdt_ex_tpu.ops.binned._slice_view` — one implementation,
so ``CtxGapError`` gap semantics cannot drift between backends).

Every entry carries an ``arr`` arrival stamp (per sync bucket, minted
from ``rowseq``): extraction sorts a bucket's entries by arrival, so
the dense non-padded wire slices this store ships are deterministic —
the canonical order the parity suite compares.

All functions here are pure jit entry roots (crdtlint SYNC001 treats
this module like ``runtime/transition.py``): no host syncs, no
mutation, data-dependent control flow stays on the host (the model
wrapper in :mod:`delta_crdt_ex_tpu.models.hash_store`).

A Pallas TPU kernel serves the probe-window point lookup
(:func:`probe_lookup_pallas`): the table stays in HBM and each query
block DMAs exactly its two 128-lane rows into VMEM — the WarpSpeed
access shape. Selection follows ``ops/pallas_tree.py``: probe once,
fall back to the pure ``jax.numpy`` path (the CPU/tier-1 reference)
with the lowering failure surfaced.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from delta_crdt_ex_tpu.models.binned import U32_MAX
from delta_crdt_ex_tpu.models.hash_store import GROUP, HashStore
from delta_crdt_ex_tpu.ops import binned as binned_ops
from delta_crdt_ex_tpu.ops.apply import OP_ADD, OP_REMOVE
from delta_crdt_ex_tpu.ops.binned import (
    RowSlice,
    _argmax_lww,
    _slice_view,
    _sorted_winners,
    _table_lookup,
    entry_hash,
)

#: probe-hash salt: the window base must be independent of the sync
#: bucket (= low key bits), or every bucket's entries would crowd a
#: correlated table region
_SALT = jnp.uint64(0x9E3779B97F4A7C15)


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def probe_base(key: jnp.ndarray, table_size: int) -> jnp.ndarray:
    """int32[...]: group-aligned first lane of ``key``'s probe window."""
    ng = table_size // GROUP
    h = _mix64(key ^ _SALT)
    return ((h & jnp.uint64(ng - 1)) * jnp.uint64(GROUP)).astype(jnp.int32)


def _window(key: jnp.ndarray, table_size: int, window: int):
    """Candidate lanes ``int32[..., W]`` for ``key`` plus their in-table
    mask. Windows do not wrap: lanes past the table end are masked out,
    so inserts near the end overflow into a rehash instead (placement
    and lookup must agree on one probe sequence)."""
    slots = probe_base(key, table_size)[..., None] + jnp.arange(window, dtype=jnp.int32)
    return slots, slots < table_size


def _place(occupied: jnp.ndarray, want: jnp.ndarray, slots: jnp.ndarray, slot_ok: jnp.ndarray):
    """Place each flagged entry at the first unoccupied lane of its
    candidate window, resolving same-lane collisions *within the batch*:
    rounds of propose → scatter-min claim → winners commit. An entry
    loses a round only when its proposed lane was just taken, so after
    ``W`` rounds it is either placed or its window is provably full.
    ``occupied`` is the POST-KILL alive mask (dead lanes are free — no
    tombstones; see module docstring). Returns ``(placed int32[N] (-1 =
    window full), occupied')`` where ``occupied'`` is the alive mask
    with every placed lane claimed."""
    n, w = slots.shape
    h = occupied.shape[0]
    slots_c = jnp.where(slot_ok, slots, h)  # h = out-of-window sentinel
    used_p = jnp.concatenate([occupied, jnp.ones(1, bool)])  # sentinel lane reads taken
    ids = jnp.arange(n, dtype=jnp.int32)
    placed0 = jnp.full(n, -1, jnp.int32)

    def body(_, carry):
        used_p, placed = carry
        unplaced = want & (placed < 0)
        free = ~used_p[slots_c]  # [N, W]
        has = jnp.any(free, axis=1)
        pos = jnp.argmax(free, axis=1)
        cand = jnp.where(
            unplaced & has,
            jnp.take_along_axis(slots_c, pos[:, None], axis=1)[:, 0],
            h,
        )
        claim = jnp.full(h + 1, n, jnp.int32).at[cand].min(ids)
        win = unplaced & has & (claim[cand] == ids)
        placed = jnp.where(win, cand, placed)
        used_p = used_p.at[jnp.where(win, cand, h)].set(True)
        return used_p, placed

    used_p, placed = jax.lax.fori_loop(0, w, body, (used_p, placed0))
    return placed, used_p[:-1]


def _row_lookup(rows: jnp.ndarray, num_buckets: int):
    """Per-slice row plumbing shared by merge/extract: ``(valid[U],
    rows_safe[U] (L = padding sentinel), rows_clip[U], row_to_u[L])``
    where ``row_to_u`` maps a sync bucket to its position in ``rows``
    (``U`` = not requested)."""
    u = rows.shape[0]
    valid = rows >= 0
    rows_safe = jnp.where(valid, rows, num_buckets)
    rows_clip = jnp.clip(rows_safe, 0, num_buckets - 1)
    row_to_u = (
        jnp.full(num_buckets, u, jnp.int32)
        .at[rows_safe]
        .set(jnp.arange(u, dtype=jnp.int32), mode="drop")
    )
    return valid, rows_safe, rows_clip, row_to_u


def _max_window_fill(alive: jnp.ndarray, table_size: int, window: int) -> jnp.ndarray:
    """int32: alive entries in the fullest probe window. THE growth
    pressure signal: overflow of one window — not global load — is what
    forces a rehash, so the advisory measures per-window fill (one
    cumsum + a strided windowed difference, scale-independent)."""
    cum = jnp.cumsum(alive.astype(jnp.int32))
    bases = jnp.arange(0, table_size, GROUP, dtype=jnp.int32)
    hi = jnp.clip(bases + window - 1, 0, table_size - 1)
    below = cum[bases] - alive[bases].astype(jnp.int32)  # cum[b-1], branch-free
    return jnp.max(cum[hi] - below)


def max_window_fill(state: HashStore) -> jnp.ndarray:
    """Advisory signal recomputed from a state (the fleet's off-batch
    re-check; merge/apply results carry it for free)."""
    return _max_window_fill(state.alive, state.table_size, state.probe_window)


def _entry_rows(state: HashStore) -> jnp.ndarray:
    """int32[H]: the sync bucket of each slot's key (stale for dead
    slots — always mask by ``alive``)."""
    return (state.key & jnp.uint64(state.num_buckets - 1)).astype(jnp.int32)


def _splice_leaf(state: HashStore, alive2, ehash2, rows_safe, rows_clip):
    """Recompute the maintained leaf digests of the touched rows from
    the updated table (wrapping sum of alive ehash — commutative, so it
    lands bit-identical to the binned store's row-local sums)."""
    L = state.num_buckets
    ent_row = _entry_rows(state)
    touched = jnp.zeros(L, bool).at[rows_safe].set(True, mode="drop")
    sel = alive2 & touched[ent_row]
    leaf_all = jnp.zeros(L, jnp.uint32).at[jnp.where(sel, ent_row, L)].add(
        jnp.where(sel, ehash2, jnp.uint32(0)), mode="drop"
    )
    return state.leaf.at[rows_safe].set(leaf_all[rows_clip], mode="drop")


# ---------------------------------------------------------------------------
# local mutation batch


def row_apply(
    state: HashStore,
    self_slot: jnp.ndarray,  # int32 scalar
    rows: jnp.ndarray,  # int32[U] unique bucket rows (-1 = padding)
    op: jnp.ndarray,  # int32[U, M] ops per row, batch order (OP_PAD pads)
    key: jnp.ndarray,  # uint64[U, M]
    valh: jnp.ndarray,  # uint32[U, M]
    ts: jnp.ndarray,  # int64[U, M]
):
    """Apply a bucket-grouped local mutation batch — the open-addressing
    counterpart of :func:`delta_crdt_ex_tpu.ops.binned.row_apply`, with
    IDENTICAL observable semantics (sequential shadowing, per-bucket dot
    counters, kill accounting): kills probe each touched key's window,
    inserts place at the first free window lane. ``ok=False`` means some
    insert's window was full — the host rehashes ×2 and retries."""
    L = state.num_buckets
    H = state.table_size
    W = state.probe_window
    u, m = op.shape
    n = u * m

    valid = rows >= 0
    rows_safe = jnp.where(valid, rows, L)
    rows_clip = jnp.clip(rows_safe, 0, L - 1)

    is_add = (op == OP_ADD) & valid[:, None]
    is_touch = is_add | ((op == OP_REMOVE) & valid[:, None])

    # dot counters: one contiguous sequence per (replica, bucket) — the
    # binned kernel's exact assignment (bit parity of minted dots)
    base = state.ctx_max[rows_clip, self_slot]
    add_rank = jnp.cumsum(is_add.astype(jnp.uint32), axis=1)
    ctr_assigned = base[:, None] + add_rank

    # batch-internal shadowing (binned row_apply, verbatim)
    later = jnp.triu(jnp.ones((m, m), bool), 1)
    key_eq = key[:, :, None] == key[:, None, :]
    shadowed = jnp.any(key_eq & later[None] & is_touch[:, None, :], axis=2)
    ins = is_add & ~shadowed

    # pre-batch kills: probe every touched key's window for alive
    # same-key entries (same key ⇒ same window, so all of them are here)
    key_f = key.reshape(n)
    touch_f = is_touch.reshape(n)
    slots, slot_in = _window(key_f, H, W)
    slots_c = jnp.where(slot_in, slots, H)
    slots_g = jnp.clip(slots, 0, H - 1)
    t_alive = state.alive[slots_g] & slot_in
    match = touch_f[:, None] & t_alive & (state.key[slots_g] == key_f[:, None])
    alive1 = state.alive.at[jnp.where(match, slots_c, H)].set(False, mode="drop")
    killed_any = jnp.any(match, axis=1).reshape(u, m)
    row_killed = jnp.any(killed_any & is_touch, axis=1)

    # inserts: first dead window lane (kills above just freed the old
    # dots' lanes — update churn reuses them), batch collisions resolved
    placed, alive2 = _place(alive1, ins.reshape(n), slots, slot_in)
    ok = ~jnp.any(ins.reshape(n) & (placed < 0))
    tgt = jnp.where(placed >= 0, placed, H)

    gid_self = state.ctx_gid[self_slot]
    eh = entry_hash(key, gid_self, ctr_assigned, ts, valh)
    ins_rank = jnp.cumsum(ins.astype(jnp.uint32), axis=1) - jnp.uint32(1)
    arr_new = state.rowseq[rows_clip][:, None] + ins_rank

    put = lambda col, vals: col.at[tgt].set(vals.reshape(n), mode="drop")
    key2 = put(state.key, key)
    valh2 = put(state.valh, valh)
    ts2 = put(state.ts, ts)
    node2 = put(state.node, jnp.full((u, m), self_slot, jnp.int32))
    ctr2 = put(state.ctr, ctr_assigned)
    ehash2 = put(state.ehash, eh)
    arr2 = put(state.arr, arr_new)

    n_ins_row = jnp.sum(ins.astype(jnp.uint32), axis=1, dtype=jnp.uint32)
    rowseq2 = state.rowseq.at[rows_safe].add(n_ins_row, mode="drop")
    own_max = jnp.max(jnp.where(ins, ctr_assigned, jnp.uint32(0)), axis=1)

    st2 = HashStore(
        key=key2, valh=valh2, ts=ts2, node=node2, ctr=ctr2,
        alive=alive2, ehash=ehash2, arr=arr2,
        leaf=state.leaf, rowseq=rowseq2,
        ctx_gid=state.ctx_gid,
        ctx_max=state.ctx_max.at[rows_safe, self_slot].max(own_max, mode="drop"),
        probe_window=W,
    )
    st2 = dataclasses.replace(
        st2, leaf=_splice_leaf(st2, alive2, ehash2, rows_safe, rows_clip)
    )

    # telemetry count: distinct keys whose dot store changed (binned
    # row_apply, verbatim — first-occurrence marks over the batch)
    earlier = jnp.tril(jnp.ones((m, m), bool), -1)
    first_occ = ~jnp.any(key_eq & earlier[None] & is_touch[:, None, :], axis=2)
    changed = is_touch & first_occ & (ins | killed_any)
    n_keys_changed = jnp.sum(changed.astype(jnp.int32))

    return HashApplyResult(
        st2, ok, ctr_assigned, n_keys_changed, row_killed,
        jnp.sum(alive2.astype(jnp.int32)),
        _max_window_fill(alive2, H, W),
    )


class HashApplyResult(NamedTuple):
    state: HashStore
    ok: jnp.ndarray  # bool: every insert found a free window lane
    ctr_assigned: jnp.ndarray  # uint32[U, M]
    n_keys_changed: jnp.ndarray  # int32
    row_killed: jnp.ndarray  # bool[U]
    #: table pressure, read back alongside ``ok`` so the host's
    #: growth-advisory policy costs no extra device sync
    n_alive: jnp.ndarray  # int32
    max_window_fill: jnp.ndarray  # int32


class HashMergeResult(NamedTuple):
    """Field-compatible superset of
    :class:`delta_crdt_ex_tpu.ops.binned.MergeRowsResult` (the fleet and
    grouped-ingest tails duck-type on these names); ``need_fill_grow``
    here means "some insert's probe window was full — rehash ×2"."""

    state: HashStore
    ok: jnp.ndarray
    need_gid_grow: jnp.ndarray
    need_fill_grow: jnp.ndarray
    need_ctx_gap: jnp.ndarray
    n_inserted: jnp.ndarray
    n_killed: jnp.ndarray
    n_ins_row: jnp.ndarray  # int32[U]
    n_kill_row: jnp.ndarray  # int32[U]
    gap_row: jnp.ndarray  # bool[U]
    n_alive: jnp.ndarray  # int32 (growth advisory, see HashApplyResult)
    max_window_fill: jnp.ndarray  # int32


def clear_all(state: HashStore) -> HashStore:
    """Kill every observed dot (``AWLWWMap.clear``): entries die, the
    context stays. Every lane frees immediately (no tombstones) — the
    next inserts reuse them."""
    return dataclasses.replace(
        state,
        alive=jnp.zeros_like(state.alive),
        leaf=jnp.zeros_like(state.leaf),
    )


# ---------------------------------------------------------------------------
# anti-entropy merge


def merge_rows(state: HashStore, sl: RowSlice) -> HashMergeResult:
    """Join a received bucket slice — the open-addressing counterpart of
    :func:`delta_crdt_ex_tpu.ops.binned.merge_rows`, same join on the
    same wire shape (any ``[U, S]`` slice, padded binned rows or dense
    hash extractions alike):

    - interval/insert preamble via the SHARED ``_slice_view`` (insert
      mask, dense interval bounds, gap detection — gap semantics are the
      binned kernel's by construction);
    - inserts probe-place (cost ∝ slice entries × window);
    - the kill pass tests every alive entry of the synced rows against
      the interval with three O(H) element-wise gathers (bucket → slice
      row → coverage), and presence of local dots in the slice is
      resolved by probing the slice entries' windows — no [H, S] cross
      product, no kill budget, no retry tiers.
    """
    L = state.num_buckets
    H = state.table_size
    W = state.probe_window
    u, s = sl.key.shape

    v = _slice_view(state, sl)
    valid, rows_safe, rows_clip = v.valid, v.rows_safe, v.rows_clip
    gids, rdense, ldense = v.gids, v.rdense, v.ldense
    ln, ln_clip, ins, need_ctx_gap = v.ln, v.ln_clip, v.ins, v.need_ctx_gap

    _, _, _, row_to_u = _row_lookup(sl.rows, L)

    # --- kill pass ((s1∩s2) ∪ (s1∖c2)) over the synced rows ------------
    ent_row = _entry_rows(state)
    u_of = row_to_u[ent_row]  # [H]: position in sl.rows, u = not synced
    in_slice = state.alive & (u_of < u)
    u_clip = jnp.clip(u_of, 0, u - 1)
    node_clip = jnp.clip(state.node, 0, state.replica_capacity - 1)
    cov_hi = rdense[u_clip, node_clip]
    cov_lo = ldense[u_clip, node_clip]
    covered = (cov_hi >= state.ctr) & (cov_lo < state.ctr)

    # presence: probe each slice entry's window for its exact local dot
    r_ok = sl.alive & (ln >= 0) & valid[:, None]
    skey_f = sl.key.reshape(u * s)
    slots, slot_in = _window(skey_f, H, W)
    slots_c = jnp.where(slot_in, slots, H)
    slots_g = jnp.clip(slots, 0, H - 1)
    pmatch = (
        r_ok.reshape(u * s)[:, None]
        & slot_in
        & state.alive[slots_g]
        & (state.key[slots_g] == skey_f[:, None])
        & (state.node[slots_g] == ln_clip.reshape(u * s).astype(jnp.int32)[:, None])
        & (state.ctr[slots_g] == sl.ctr.reshape(u * s)[:, None])
    )
    present = (
        jnp.zeros(H + 1, bool)
        .at[jnp.where(pmatch, slots_c, H)]
        .set(True)[:H]
    )

    die = in_slice & covered & ~present
    alive1 = state.alive & ~die
    n_kill_row = (
        jnp.zeros(u, jnp.int32)
        .at[jnp.where(die, u_of, u)]
        .add(1, mode="drop")
    )

    # --- insert pass (s2 ∖ c1): probe-place the slice entries into
    # dead window lanes (incl. those the kill pass above just freed) ----
    ins_f = ins.reshape(u * s)
    placed, alive2 = _place(alive1, ins_f, slots, slot_in)
    need_fill_grow = jnp.any(ins_f & (placed < 0))
    tgt = jnp.where(placed >= 0, placed, H)

    eh_ins = entry_hash(
        sl.key,
        _table_lookup(sl.ctx_gid, jnp.clip(sl.node, 0, sl.ctx_gid.shape[0] - 1)),
        sl.ctr,
        sl.ts,
        sl.valh,
    )
    ins_rank = jnp.cumsum(ins.astype(jnp.uint32), axis=1) - jnp.uint32(1)
    arr_new = state.rowseq[rows_clip][:, None] + ins_rank

    put = lambda col, vals: col.at[tgt].set(vals.reshape(u * s), mode="drop")
    key2 = put(state.key, sl.key)
    valh2 = put(state.valh, sl.valh)
    ts2 = put(state.ts, sl.ts)
    node2 = put(state.node, ln_clip.astype(jnp.int32))
    ctr2 = put(state.ctr, sl.ctr)
    ehash2 = put(state.ehash, eh_ins)
    arr2 = put(state.arr, arr_new)

    n_ins_row = jnp.sum(ins.astype(jnp.int32), axis=1)
    rowseq2 = state.rowseq.at[rows_safe].add(
        n_ins_row.astype(jnp.uint32), mode="drop"
    )
    ctx2 = jnp.maximum(v.local_ctx, rdense)

    st2 = HashStore(
        key=key2, valh=valh2, ts=ts2, node=node2, ctr=ctr2,
        alive=alive2, ehash=ehash2, arr=arr2,
        leaf=state.leaf, rowseq=rowseq2,
        ctx_gid=gids.ctx_gid,
        ctx_max=state.ctx_max.at[rows_safe].set(ctx2, mode="drop"),
        probe_window=W,
    )
    st2 = dataclasses.replace(
        st2, leaf=_splice_leaf(st2, alive2, ehash2, rows_safe, rows_clip)
    )

    ok = ~(gids.overflow | need_fill_grow | need_ctx_gap)
    return HashMergeResult(
        st2,
        ok,
        gids.overflow,
        need_fill_grow,
        need_ctx_gap,
        jnp.sum(n_ins_row),
        jnp.sum(n_kill_row),
        n_ins_row,
        n_kill_row,
        v.gap_row,
        jnp.sum(alive2.astype(jnp.int32)),
        _max_window_fill(alive2, H, W),
    )


# ---------------------------------------------------------------------------
# extraction (the dense, non-padded wire path)


def row_counts(state: HashStore, rows: jnp.ndarray) -> jnp.ndarray:
    """int32[U]: alive entries per requested sync row — the host sizes
    the dense extraction tier from this before the packed gather."""
    L = state.num_buckets
    u = rows.shape[0]
    _, _, _, row_to_u = _row_lookup(rows, L)
    u_of = row_to_u[_entry_rows(state)]
    sel = state.alive & (u_of < u)
    return (
        jnp.zeros(u, jnp.int32).at[jnp.where(sel, u_of, u)].add(1, mode="drop")
    )


def own_delta_counts(
    state: HashStore, rows: jnp.ndarray, self_slot: jnp.ndarray, lo: jnp.ndarray
) -> jnp.ndarray:
    """int32[U]: own-writer entries with counter in ``(lo, ∞)`` per
    requested row (the delta-interval extraction's sizing pass)."""
    L = state.num_buckets
    u = rows.shape[0]
    _, _, _, row_to_u = _row_lookup(rows, L)
    u_of = row_to_u[_entry_rows(state)]
    u_clip = jnp.clip(u_of, 0, u - 1)
    sel = (
        state.alive
        & (u_of < u)
        & (state.node == self_slot)
        & (state.ctr > lo[u_clip])
    )
    return (
        jnp.zeros(u, jnp.int32).at[jnp.where(sel, u_of, u)].add(1, mode="drop")
    )


def _pack_rows(state: HashStore, rows: jnp.ndarray, sel: jnp.ndarray, lanes: int):
    """Pack the selected entries into a dense ``[U, lanes]`` grid, each
    row in arrival (``arr``) order, dead lanes zeroed — the
    deterministic dense wire form. One sort by ``(row position, arr)``;
    per-row lane = global rank − row start."""
    L = state.num_buckets
    u = rows.shape[0]
    H = state.table_size
    _, _, _, row_to_u = _row_lookup(rows, L)
    u_of = row_to_u[_entry_rows(state)]
    u_clip = jnp.clip(u_of, 0, u - 1)

    sortkey = jnp.where(
        sel,
        (u_of.astype(jnp.uint64) << jnp.uint64(32)) | state.arr.astype(jnp.uint64),
        jnp.uint64(0xFFFFFFFFFFFFFFFF),
    )
    order = jnp.argsort(sortkey)
    sel_s = sel[order]
    u_s = u_clip[order]
    counts = (
        jnp.zeros(u, jnp.int32).at[jnp.where(sel, u_of, u)].add(1, mode="drop")
    )
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1]])
    pos = jnp.arange(H, dtype=jnp.int32) - starts[u_s]
    tgt_u = jnp.where(sel_s & (pos < lanes), u_s, u)
    tgt_p = jnp.clip(pos, 0, lanes - 1)

    def pack(col):
        return (
            jnp.zeros((u, lanes), col.dtype)
            .at[tgt_u, tgt_p]
            .set(col[order], mode="drop")
        )

    cols = {c: pack(getattr(state, c)) for c in ("key", "valh", "ts", "node", "ctr")}
    alive = (
        jnp.zeros((u, lanes), bool).at[tgt_u, tgt_p].set(sel_s, mode="drop")
    )
    return cols, alive


def extract_rows_packed(state: HashStore, rows: jnp.ndarray, lanes: int) -> RowSlice:
    """Dense full-row state slice (``ctx_lo = 0``) for the requested
    sync rows — the wire/WAL/log-ship transfer shape of this store:
    ``lanes`` is the host-chosen pow2 tier of the fullest requested row,
    so shipped bytes track content, not a bin-capacity tier."""
    L = state.num_buckets
    u = rows.shape[0]
    _, _, _, row_to_u = _row_lookup(rows, L)
    u_of = row_to_u[_entry_rows(state)]
    sel = state.alive & (u_of < u)
    cols, alive = _pack_rows(state, rows, sel, lanes)
    valid = rows >= 0
    rows_clip = jnp.clip(rows, 0, L - 1)
    return RowSlice(
        rows=rows,
        key=cols["key"],
        valh=cols["valh"],
        ts=cols["ts"],
        node=cols["node"],
        ctr=cols["ctr"],
        alive=alive,
        ctx_rows=state.ctx_max[rows_clip] * valid[:, None].astype(jnp.uint32),
        ctx_lo=jnp.zeros_like(state.ctx_max[rows_clip]),
        ctx_gid=state.ctx_gid,
    )


def extract_own_delta_packed(
    state: HashStore,
    rows: jnp.ndarray,
    self_slot: jnp.ndarray,
    gid_self: jnp.ndarray,
    lo: jnp.ndarray,
    lanes: int,
) -> RowSlice:
    """Dense own-writer delta-interval slice (Almeida et al.'s delta
    mode), claiming exactly ``(lo, ctx_max]`` per row — the eager-push
    shape, without the binned store's whole-row lane padding."""
    L = state.num_buckets
    u = rows.shape[0]
    valid, _, rows_clip, row_to_u = _row_lookup(rows, L)
    u_of = row_to_u[_entry_rows(state)]
    u_clip = jnp.clip(u_of, 0, u - 1)
    sel = (
        state.alive
        & (u_of < u)
        & (state.node == self_slot)
        & (state.ctr > lo[u_clip])
    )
    cols, alive = _pack_rows(state, rows, sel, lanes)
    hi = state.ctx_max[rows_clip, self_slot] * valid.astype(jnp.uint32)
    return RowSlice(
        rows=rows,
        key=cols["key"],
        valh=cols["valh"],
        ts=cols["ts"],
        node=jnp.zeros_like(cols["node"]),
        ctr=cols["ctr"],
        alive=alive,
        ctx_rows=hi[:, None],
        ctx_lo=(lo * valid.astype(jnp.uint32))[:, None],
        ctx_gid=gid_self[None],
    )


# ---------------------------------------------------------------------------
# reads


def winners_for_keys(state: HashStore, khash: jnp.ndarray):
    """LWW winner per queried key hash: gather each key's probe window
    (``W`` lanes — the O(1) point read this layout exists for) and take
    the lexicographic (ts, gid, ctr) maximum among alive matches."""
    H = state.table_size
    W = state.probe_window
    slots, slot_in = _window(khash, H, W)
    slots_g = jnp.clip(slots, 0, H - 1)
    g_alive = state.alive[slots_g] & slot_in & (state.key[slots_g] == khash[:, None])
    g_gid = _table_lookup(
        state.ctx_gid,
        jnp.clip(state.node[slots_g], 0, state.replica_capacity - 1),
    )
    g_ctr = state.ctr[slots_g]
    g_ts = state.ts[slots_g]
    best = _argmax_lww(g_ts, g_gid, g_ctr, g_alive)
    take = lambda a: jnp.take_along_axis(a, best, axis=1)[:, 0]
    return binned_ops.KeyWinners(
        found=take(g_alive),
        gid=take(g_gid),
        ctr=take(g_ctr),
        valh=take(state.valh[slots_g]),
        ts=take(g_ts),
    )


def winner_all(state: HashStore):
    """Whole-table LWW winners: one lexicographic sort of the flat table
    (the binned ``winner_all`` shape with a single [1, H] row)."""
    gid = _table_lookup(
        state.ctx_gid, jnp.clip(state.node, 0, state.replica_capacity - 1)
    )
    one = lambda a: a[None, :]
    return _sorted_winners(
        one(state.key), one(state.ts), one(gid), one(state.ctr),
        one(state.alive), one(state.valh),
    )


def winner_rows_packed(state: HashStore, rows: jnp.ndarray, lanes: int):
    """Per-key LWW winners within the given sync rows: pack the rows'
    entries dense, then the shared sorted-winner core."""
    L = state.num_buckets
    u = rows.shape[0]
    _, _, _, row_to_u = _row_lookup(rows, L)
    u_of = row_to_u[_entry_rows(state)]
    sel = state.alive & (u_of < u)
    cols, alive = _pack_rows(state, rows, sel, lanes)
    gid = _table_lookup(
        state.ctx_gid, jnp.clip(cols["node"], 0, state.replica_capacity - 1)
    )
    return _sorted_winners(
        cols["key"], cols["ts"], gid, cols["ctr"], alive, cols["valh"]
    )


# ---------------------------------------------------------------------------
# maintenance: rehash (THE growth event) + invariant rebuild


def rehash(state: HashStore, table_size: int, probe_window: int):
    """Rebuild the table at ``table_size`` lanes: every alive entry
    re-places by bucketed linear probing. The
    placement is one sort + one cumulative max — entries sorted by
    (new base, arrival) take ``slot_j = j + cummax(base_j − j)``, which
    IS linear probing's first-free-lane rule evaluated for the whole
    table at once. Returns ``(state', ok)``; ``ok=False`` (an entry
    displaced past the probe window, or off the table end) tells the
    host to grow further / widen the window and retry."""
    H_old = state.table_size
    sel = state.alive
    base = probe_base(state.key, table_size)
    sortkey = jnp.where(
        sel,
        (base.astype(jnp.uint64) << jnp.uint64(32)) | state.arr.astype(jnp.uint64),
        jnp.uint64(0xFFFFFFFFFFFFFFFF),
    )
    order = jnp.argsort(sortkey)
    sel_s = sel[order]
    base_s = base[order].astype(jnp.int64)
    j = jnp.arange(H_old, dtype=jnp.int64)
    slot = j + jax.lax.cummax(jnp.where(sel_s, base_s - j, jnp.int64(-(2**40))))
    disp = slot - base_s
    ok = ~jnp.any(sel_s & ((slot >= table_size) | (disp >= probe_window)))
    tgt = jnp.where(sel_s & (slot < table_size), slot, table_size).astype(jnp.int32)

    def move(col):
        return (
            jnp.zeros(table_size, col.dtype).at[tgt].set(col[order], mode="drop")
        )

    alive_new = jnp.zeros(table_size, bool).at[tgt].set(sel_s, mode="drop")
    st2 = HashStore(
        key=move(state.key),
        valh=move(state.valh),
        ts=move(state.ts),
        node=move(state.node),
        ctr=move(state.ctr),
        alive=alive_new,
        ehash=move(state.ehash),
        arr=move(state.arr),
        leaf=state.leaf,
        rowseq=state.rowseq,
        ctx_gid=state.ctx_gid,
        ctx_max=state.ctx_max,
        probe_window=probe_window,
    )
    return st2, ok


def compact_rows(state: HashStore) -> HashStore:
    """Rebuild the maintained leaf digests from the entry lanes (the
    binned ``compact_rows`` analog for host-constructed states; the
    table itself has nothing to repack — rehash owns reclamation)."""
    L = state.num_buckets
    ent_row = _entry_rows(state)
    sel = state.alive
    leaf = (
        jnp.zeros(L, jnp.uint32)
        .at[jnp.where(sel, ent_row, L)]
        .add(jnp.where(sel, state.ehash, jnp.uint32(0)), mode="drop")
    )
    return dataclasses.replace(state, leaf=leaf)


# ---------------------------------------------------------------------------
# Pallas TPU point-lookup kernel (probe-selected, jnp path is reference)

#: lanes per VMEM row the kernel DMAs; probe windows must fit two rows
_PL_LANES = 128


def _probe_kernel_body(nq: int, window: int, r: int, nrows: int):
    """Build the kernel fn for a static (block, window, R) shape. All
    comparisons run on int32/uint32 bit-halves (TPU has no native i64);
    the LWW order is resolved by lexicographic candidate narrowing over
    (ts_hi, ts_lo, gid_hi, gid_lo, ctr) — the ``_argmax_lww`` scheme on
    word halves. Per query the kernel DMAs exactly the two 128-lane HBM
    rows covering its probe window — the WarpSpeed access shape."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(
        base_ref,  # SMEM int32[NQ]  window base lane per query
        qk_ref,  # VMEM int32[NQ, 2] query key halves (lo, hi)
        tkey_lo_ref, tkey_hi_ref,  # HBM int32[H/128, 128] key bit-halves
        alive_ref,  # HBM int32[H/128, 128]
        node_ref,  # HBM int32[H/128, 128]
        ctr_ref,  # HBM int32[H/128, 128] (uint32 bits)
        tslo_ref, tshi_ref,  # HBM int32[H/128, 128] ts bit-halves
        valh_ref,  # HBM int32[H/128, 128]
        gid_ref,  # VMEM int32[2, R] ctx_gid bit-halves
        out_ref,  # VMEM int32[NQ, 8]
        scratch, sem,
    ):
        def umax(x, m):  # max of uint32-bits x over mask m (0 neutral)
            return jnp.max(jnp.where(m, pltpu.bitcast(x, jnp.uint32), jnp.uint32(0)))

        for q in range(nq):
            b = base_ref[q]
            # two 128-lane rows always cover [b, b+window) for window ≤
            # 128 — except when b sits in the LAST row, where the pair
            # shifts back one row (the tail lanes past b+window are
            # masked off by the window test either way)
            row0 = jnp.minimum(b // _PL_LANES, nrows - 2)
            for ci, ref in enumerate(
                (tkey_lo_ref, tkey_hi_ref, alive_ref, node_ref, ctr_ref,
                 tslo_ref, tshi_ref, valh_ref)
            ):
                dma = pltpu.make_async_copy(
                    ref.at[pl.ds(row0, 2)], scratch.at[ci], sem
                )
                dma.start()
                dma.wait()
            lane = (
                jax.lax.broadcasted_iota(jnp.int32, (2, _PL_LANES), 0) * _PL_LANES
                + jax.lax.broadcasted_iota(jnp.int32, (2, _PL_LANES), 1)
            )
            off = (lane + row0 * _PL_LANES) - b
            in_win = (off >= 0) & (off < window)
            keq = (scratch[0] == qk_ref[q, 0]) & (scratch[1] == qk_ref[q, 1])
            alive = scratch[2] != 0
            m = in_win & keq & alive
            # gid bit-halves via unrolled select chain (R is static)
            node = scratch[3]
            g_lo = jnp.zeros((2, _PL_LANES), jnp.int32)
            g_hi = jnp.zeros((2, _PL_LANES), jnp.int32)
            for i in range(r):
                g_lo = jnp.where(node == i, gid_ref[1, i], g_lo)
                g_hi = jnp.where(node == i, gid_ref[0, i], g_hi)
            # lexicographic narrowing: ts is int64 but non-negative here
            # (LWW clock), so unsigned half-compares order it correctly
            cand = m
            for part in (scratch[6], scratch[5], g_hi, g_lo, scratch[4]):
                u = pltpu.bitcast(part, jnp.uint32)
                cand = cand & (u == umax(part, cand))
            found = jnp.any(m)
            # winner lane: the (unique) surviving candidate's flat index
            pick = lambda col: jnp.max(jnp.where(cand, col, jnp.int32(-(2**30))))
            free = in_win & (scratch[2] == 0)  # dead lanes are free
            free_slot = jnp.min(
                jnp.where(free, lane + row0 * _PL_LANES, jnp.int32(2**30))
            )
            out_ref[q, 0] = found.astype(jnp.int32)
            out_ref[q, 1] = jnp.where(found, pick(lane) + row0 * _PL_LANES, -1)
            out_ref[q, 2] = jnp.where(found, pick(node), 0)
            out_ref[q, 3] = jnp.where(found, pick(scratch[4]), 0)
            out_ref[q, 4] = jnp.where(found, pick(scratch[7]), 0)
            out_ref[q, 5] = jnp.where(found, pick(scratch[5]), 0)
            out_ref[q, 6] = jnp.where(found, pick(scratch[6]), 0)
            out_ref[q, 7] = free_slot

    return kernel


def probe_lookup_pallas(
    khash: jnp.ndarray, state: HashStore, interpret: bool = False
) -> jnp.ndarray:
    """Pallas point lookup: per query key, DMA the two 128-lane HBM rows
    covering its probe window into VMEM and resolve the LWW winner (and
    the first free lane — the upsert probe) on-chip. Returns
    ``int32[Q, 8]`` (found, slot, node, ctr, valh, ts_lo, ts_hi,
    free_slot). 64-bit columns travel as uint32 half-lanes (TPU has no
    native i64)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    H = state.table_size
    W = state.probe_window
    R = state.replica_capacity
    if W > _PL_LANES:
        raise ValueError(f"probe window {W} exceeds the kernel's two-row cover")
    if H < 2 * _PL_LANES:
        raise ValueError(
            f"table of {H} lanes is below the kernel's two-row minimum; "
            "use the jnp reference path for tiny tables"
        )
    q = khash.shape[0]
    nq = 8
    q_pad = -(-q // nq) * nq
    kh = jnp.concatenate([khash, jnp.zeros(q_pad - q, jnp.uint64)])
    base = probe_base(kh, H)
    shape2d = (H // _PL_LANES, _PL_LANES)
    i32 = lambda u32_bits: jax.lax.bitcast_convert_type(u32_bits, jnp.int32)
    lo32 = lambda a64: i32((a64 & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32))
    hi32 = lambda a64: i32((a64 >> jnp.uint64(32)).astype(jnp.uint32))
    ts_u = state.ts.astype(jnp.uint64)  # non-negative LWW stamps
    qk = jnp.stack([lo32(kh), hi32(kh)], axis=1)
    gid2 = jnp.stack([hi32(state.ctx_gid), lo32(state.ctx_gid)])
    out = pl.pallas_call(
        _probe_kernel_body(nq, W, R, H // _PL_LANES),
        out_shape=jax.ShapeDtypeStruct((q_pad, 8), jnp.int32),
        grid=(q_pad // nq,),
        in_specs=[
            pl.BlockSpec((nq,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec((nq, 2), lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec((2, R), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((nq, 8), lambda i: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((8, 2, _PL_LANES), jnp.int32),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )(
        base, qk,
        lo32(state.key).reshape(shape2d), hi32(state.key).reshape(shape2d),
        state.alive.astype(jnp.int32).reshape(shape2d),
        state.node.reshape(shape2d),
        i32(state.ctr).reshape(shape2d),
        lo32(ts_u).reshape(shape2d), hi32(ts_u).reshape(shape2d),
        i32(state.valh).reshape(shape2d),
        gid2,
    )
    return out[:q]


def probe_winners(state: HashStore, khash: jnp.ndarray, interpret: bool = False):
    """:class:`~delta_crdt_ex_tpu.ops.binned.KeyWinners` view of the
    Pallas probe kernel's raw int32 grid — the drop-in accelerated form
    of :func:`winners_for_keys` (the model selects it per read when the
    probe succeeded and the table fits the kernel's two-row cover)."""
    out = probe_lookup_pallas(khash, state, interpret=interpret)
    u32 = lambda col: jax.lax.bitcast_convert_type(col, jnp.uint32)
    node = jnp.clip(out[:, 2], 0, state.replica_capacity - 1)
    ts_lo = u32(out[:, 5]).astype(jnp.uint64)
    ts_hi = u32(out[:, 6]).astype(jnp.uint64)
    return binned_ops.KeyWinners(
        found=out[:, 0] != 0,
        gid=_table_lookup(state.ctx_gid, node),
        ctr=u32(out[:, 3]),
        valh=u32(out[:, 4]),
        ts=((ts_hi << jnp.uint64(32)) | ts_lo).astype(jnp.int64),
    )


def probed_lookup_fn():
    """Probe Pallas availability once (the ``ops/pallas_tree.py``
    selection pattern) and return ``(winners_for_keys_impl, tag)``: the
    HBM-resident probe kernel where Mosaic lowers it, the pure-jnp
    reference everywhere else (CPU tier-1 runs the jnp path by
    construction). The probe executes a tiny lookup so a lowering
    failure surfaces HERE with its reason, not in a read path."""
    tag = "xla"
    try:
        st = HashStore.new(num_buckets=4, bin_capacity=64, replica_capacity=8)
        # crdtlint: allow[host-sync] probe must synchronise by design
        jax.block_until_ready(
            probe_winners(st, jnp.zeros(2, jnp.uint64))
        )
        return probe_winners, "pallas"
    except Exception as e:  # surface WHY (the pallas_tree round-4 lesson)
        import sys

        msg = " ".join(str(e).split())
        print(
            f"[hash_map] probe_lookup_pallas probe failed: {msg[:300]}",
            file=sys.stderr,
            flush=True,
        )
        tag = f"xla (pallas probe failed: {type(e).__name__}: {msg[:120]})"
    return None, tag
