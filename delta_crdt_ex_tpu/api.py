"""Public API facade — parity with ``DeltaCrdt`` (``lib/delta_crdt.ex``).

The reference surface maps 1:1 (``delta_crdt.ex:56,97,117,126,135``):

==========================  ==========================================
reference                   here
==========================  ==========================================
``start_link(module, o)``   :func:`start_link` (spawns the sync thread)
``set_neighbours/2``        :func:`set_neighbours` (one-way edges)
``mutate/4``                :func:`mutate`
``mutate_async/3``          :func:`mutate_async`
``read/2``                  :func:`read`
``child_spec/1``            :func:`child_spec` (supervision metadata)
==========================  ==========================================

Defaults match the reference: ``sync_interval`` 200 ms, ``max_sync_size``
200 (``delta_crdt.ex:31-32``).

TPU-native extensions live on :class:`~delta_crdt_ex_tpu.runtime.replica.
Replica` itself (deterministic ``sync_to_all``/``process_pending`` for
test quiescence, ``read_keys``, ``checkpoint``, ``gc``) and in
:mod:`delta_crdt_ex_tpu.parallel` (batched neighbour sync, mesh gossip).

Example (the reference doctest flow, ``delta_crdt.ex:17-28``)::

    crdt1 = start_link(AWLWWMap, sync_interval=0.003)
    crdt2 = start_link(AWLWWMap, sync_interval=0.003)
    set_neighbours(crdt1, [crdt2])
    set_neighbours(crdt2, [crdt1])
    mutate(crdt1, "add", ["CRDT", "is magic!"])
    ...
    read(crdt2)  # {'CRDT': 'is magic!'}
"""

from __future__ import annotations

from typing import Any

from delta_crdt_ex_tpu.models.binned_map import BinnedAWLWWMap as AWLWWMap
from delta_crdt_ex_tpu.runtime.fleet import Fleet
from delta_crdt_ex_tpu.runtime.replica import Replica


def _resolve_store(crdt_module, store: "str | None"):
    """Map a model class onto the requested dot-store backend
    (ISSUE 8): ``store="hash"`` selects the open-addressing hash-table
    store, ``store="binned"`` the bucket-binned rows (the default).
    Explicit hash model classes pass through unchanged."""
    if store is None:
        return crdt_module
    if store not in ("hash", "binned"):
        raise ValueError(f"unknown store backend {store!r}; use 'hash' or 'binned'")
    backend = getattr(crdt_module, "backend", None)
    if backend == store:
        return crdt_module
    from delta_crdt_ex_tpu.models.binned_map import AWSet, BinnedAWLWWMap
    from delta_crdt_ex_tpu.models.hash_store import HashAWSet, HashAWLWWMap

    mapping = {
        ("hash", BinnedAWLWWMap): HashAWLWWMap,
        ("hash", AWSet): HashAWSet,
        ("binned", HashAWLWWMap): BinnedAWLWWMap,
        ("binned", HashAWSet): AWSet,
    }
    try:
        return mapping[(store, crdt_module)]
    except KeyError:
        raise ValueError(
            f"{crdt_module!r} has no {store!r}-store counterpart; pass a "
            "model class whose backend matches, or omit store="
        ) from None

DEFAULT_SYNC_INTERVAL = 0.2  # seconds (reference: 200 ms, delta_crdt.ex:31)
DEFAULT_MAX_SYNC_SIZE = 200  # items (reference: delta_crdt.ex:32)

DeltaCrdt = Replica  # the handle type users hold


def start_link(
    crdt_module=AWLWWMap,
    *,
    threaded: bool = True,
    store: "str | None" = None,
    **opts,
) -> Replica:
    """Start a replica (reference ``DeltaCrdt.start_link/2``).

    ``store`` selects the dot-store backend (ISSUE 8): ``"binned"``
    (default) is the bucket-binned row engine; ``"hash"`` is the
    device-resident open-addressing hash table — O(1) point upserts
    that reuse killed lanes (steady-state churn never grows the
    table), dense non-padded wire extraction, and a
    window-pressure-advised ×2 rehash as its only growth event (no
    per-tier repacking; fleet batches survive growth, with the
    advisory growing pressured members off the batch path). The two
    backends are protocol-identical
    and bit-for-bit parity-gated (``tests/test_hash_store.py``,
    ``bench.py --hashstore``); snapshots/WALs record their backend, and
    cross-backend restore goes through extraction (MIGRATING.md).

    ``threaded=True`` runs the periodic anti-entropy loop in a background
    thread (the GenServer-process analog). ``threaded=False`` leaves
    driving to the caller (deterministic tests / benches call
    ``sync_to_all()`` + ``transport.pump()``).

    Durability (no reference analog — the reference writes the whole
    replica image through storage on every change,
    ``causal_crdt.ex:402-403``): pass ``wal_dir=<path>`` to switch
    ``storage_mode="every_op"`` from O(state) snapshot writes to an
    O(delta) write-ahead log (:mod:`delta_crdt_ex_tpu.runtime.wal`).
    Knobs ride along to the replica:

    - ``wal_dir`` — directory for segment files (and, when no
      ``storage_module`` is given, compaction snapshots);
    - ``fsync_mode`` — group-commit cadence: ``"record"`` | ``"batch"``
      (default) | ``"interval"`` | ``"none"``;
    - ``segment_bytes`` — roll to a new segment past this size;
    - ``compact_every`` — checkpoint a snapshot and reclaim covered
      segments after this many appended records.

    Recovery is automatic: a restarted replica with the same ``name``
    and ``wal_dir`` loads the newest snapshot and replays the log past
    it (torn tail records are truncated, not crashed on).

    Ingress coalescing (on by default): the replica's event loop
    batch-receives queued sync slices and joins compatible groups with
    one grouped fan-in kernel dispatch — observably identical to
    sequential handling, measurably faster under fan-in load. Knobs:
    ``ingress_coalesce``, ``max_coalesce``, ``ingress_batch``;
    observability via :meth:`Replica.stats`. WAL segment reclaim is
    additionally gated on the monitored-neighbour ack watermark
    (``membership_compaction``) so a lagging peer's catch-up records
    survive compaction — bounded by ``membership_retain`` records
    (default ``4 * compact_every``) so a never-acking peer cannot grow
    the log without limit.

    Log-shipping catch-up (on by default): a replica gaining or
    re-seeing a neighbour requests that peer's delta-log suffix past
    its applied watermark (``GetLogMsg``) instead of paying the full
    digest walk; the server answers bounded ``LogChunkMsg`` runs of
    full-row slices that merge through the grouped-ingest path. Past
    the compaction horizon the peer weighs the servable suffix against
    the walk-bound prefix: a dominant suffix streams as a
    horizon-clamped chunk run (only the prefix walks), a small one
    skips the chunks entirely — the walk must heal it anyway. Knobs:
    ``log_shipping``, ``catchup_chunk_rows``, ``catchup_suffix_ratio``
    (engage the clamped stream when suffix ≥ ratio × prefix, default
    4); observability under ``Replica.stats()["catchup"]``.

    Hierarchical anti-entropy (ISSUE 15, additive — default off):
    ``tree_gossip=True`` replaces flat all-neighbour sync with a
    deterministic membership-derived spanning tree
    (:mod:`delta_crdt_ex_tpu.runtime.treesync`): every replica
    computes the same tree from the sorted member set + ``tree_seed``
    (no coordinator), leaves sync only their parent, and relays
    coalesce inbound children's deltas into ONE merged re-emission per
    link per epoch — O(fanout) links and bytes per member instead of
    O(neighbours), with multi-hop propagation cascading through relays
    instead of waiting a sync interval per generation. Co-located
    members (same process endpoint, same pinned device, or one fleet /
    mesh) cluster as a bottom-tier subtree whose captain alone gossips
    outward. A parent/relay ``Down`` re-parents deterministically on
    every observer; past ``tree_degrade_ratio`` locally-down members
    the replica falls back to flat gossip until membership stabilises.
    Knobs: ``tree_gossip``, ``tree_fanout`` (default 8), ``tree_seed``,
    ``tree_degrade_ratio`` (default 0.25), ``tree_group`` (explicit
    tier-0 cluster key); observability under
    ``Replica.stats()["tree"]`` and the ``crdt_tree_*`` metric family.
    Gated by ``bench.py --tree`` (propagation rounds + bytes-on-wire
    vs flat gossip at 256 simulated peers, bit-for-bit parity).

    Observability plane (ISSUE 9, off by default): ``obs=True`` joins
    the process-wide :class:`~delta_crdt_ex_tpu.runtime.metrics.
    Observability` plane (``obs=<Observability>`` an explicit one) —
    the always-attached metrics bridge folds every telemetry event
    into a Prometheus-style registry, scrape-time collectors poll
    mailbox depth / WAL footprint / transport bytes, a bounded flight
    recorder keeps the replica's recent structured events (dumped on
    :meth:`Replica.crash`), and the dot-provenance lag tracer samples
    local commits so peers' watermark advances yield per-peer
    convergence-lag histograms with ZERO wire changes. Serve
    ``/metrics`` + ``/healthz`` + ``/varz`` with
    ``obs_plane.serve(port=...)``; the existing ``stats()`` dicts are
    unchanged (MIGRATING.md). Disabled (the default) the hot paths pay
    only ``has_handlers`` lock checks.
    """
    opts.setdefault("sync_interval", DEFAULT_SYNC_INTERVAL)
    opts.setdefault("max_sync_size", DEFAULT_MAX_SYNC_SIZE)
    replica = Replica(_resolve_store(crdt_module, store), **opts)
    if threaded:
        replica.start()
    return replica


def start_fleet(
    n: int,
    crdt_module=AWLWWMap,
    *,
    threaded: bool = True,
    names: "list | None" = None,
    min_batch: int = 2,
    store: "str | None" = None,
    mesh=None,
    **opts,
) -> Fleet:
    """Start ``n`` replicas served by ONE batched event loop (ISSUE 6:
    batched replica fleets — no reference analog; the BEAM gives every
    CRDT its own process, which is exactly the per-user-process cost
    this replaces).

    The fleet drains all ``n`` mailboxes per tick and joins compatible
    sync slices across replicas with one ``vmap``-batched kernel
    dispatch over a leading replica axis, instead of one dispatch (and
    one thread) per replica — the served-users-per-host lever
    (``bench.py --fleet``: ≥3× aggregate merges/sec vs per-replica
    loops at 256 replicas, bit-for-bit parity asserted in-run). Sync
    ticks batch the egress half the same way (ISSUE 10): one vmapped
    digest-tree build + one vmapped eager-delta extraction per shape
    bucket serves every due member, and pushes/openers bound for a
    co-located peer process ship as one ``FleetFrameMsg`` TCP frame
    per endpoint per tick (negotiated; legacy peers get per-member
    frames).
    Observable semantics per member are identical to solo replicas:
    WAL records, acks, diffs, and telemetry fan back out per replica
    (``tests/test_fleet.py`` pins state bits, WAL bytes, and ack
    streams against solo runs).

    ``opts`` are per-replica ``start_link`` options (shared by all
    members; pass ``names`` for explicit member names — a shared
    ``wal_dir`` is safe, segments are per-name). Returns the
    :class:`~delta_crdt_ex_tpu.runtime.fleet.Fleet`; its ``.replicas``
    are ordinary :class:`Replica` handles for ``mutate``/``read``/
    ``set_neighbours``. ``threaded=False`` leaves driving to the
    caller (``fleet.tick()`` / ``fleet.drain()`` +
    ``fleet.run_duties()``).

    ``obs=`` wires the whole fleet into ONE observability plane
    (``True`` = the process-wide default, or an explicit
    :class:`~delta_crdt_ex_tpu.runtime.metrics.Observability`): every
    member registers its varz/health/metric sources and the fleet adds
    its own tick-freshness health check plus occupancy / ragged-fill
    gauges — see :func:`start_link` for the plane's full surface.

    ``mesh=`` (ISSUE 13, additive — default off) shards the fleet's
    batched dispatches over a 1-D replica-sharded device mesh: pass a
    ``jax.sharding.Mesh`` over the ``"replicas"`` axis
    (:func:`delta_crdt_ex_tpu.utils.devices.fleet_mesh` builds one), an
    int shard count, or ``True`` for the detected-topology default.
    Hot kernels then run as ``shard_map`` twins, resident stacked
    states stay device-sharded between ticks, and sync-tick messages
    between co-mesh members deliver as device-side ``ppermute``
    rotations (only off-mesh destinations take the TCP/frame path).
    Semantics are bit-for-bit the vmap fleet's — state, WAL bytes,
    acks and wire bytes (``tests/test_mesh_fleet.py``).

    ``tree_gossip=True`` members (ISSUE 15) are stamped with ONE
    shared tier-0 cluster key (the mesh plane's, when ``mesh=`` is on)
    so the whole fleet forms a single bottom-tier subtree of the
    gossip spanning tree: intra-fleet hops are local mailbox (or
    ppermute) deliveries and only the captain gossips outward; relay
    re-emissions ride the tick's frame collector / mesh exchange like
    every other sync send."""
    if names is not None and len(names) != n:
        raise ValueError(f"{len(names)} names for {n} replicas")
    opts.setdefault("sync_interval", DEFAULT_SYNC_INTERVAL)
    opts.setdefault("max_sync_size", DEFAULT_MAX_SYNC_SIZE)
    # resolve the obs knob ONCE so every member and the fleet share one
    # plane (obs=True would resolve identically per member, but an
    # explicit instance must not be re-validated N times either way)
    from delta_crdt_ex_tpu.runtime import metrics as _metrics

    obs_plane = _metrics.resolve_obs(opts.pop("obs", None))
    if obs_plane is not None:
        opts["obs"] = obs_plane
    crdt_module = _resolve_store(crdt_module, store)
    replicas = []
    for i in range(n):
        member = dict(opts)
        if names is not None:
            member["name"] = names[i]
        replicas.append(Replica(crdt_module, **member))
    fleet = Fleet(replicas, min_batch=min_batch, obs=obs_plane, mesh=mesh)
    if threaded:
        fleet.start()
    return fleet


def child_spec(opts: dict | None = None) -> dict:
    """Supervision metadata (reference ``child_spec/1``, ``delta_crdt.ex:68-82``)."""
    opts = dict(opts or {})
    crdt = opts.pop("crdt", None)
    if crdt is None:
        raise ValueError(f"must specify 'crdt' in options, got: {opts!r}")
    name = opts.get("name", "DeltaCrdt")
    shutdown = opts.pop("shutdown", 5.0)
    return {
        "id": name,
        "start": (start_link, (crdt,), opts),
        "shutdown": shutdown,
    }


def set_neighbours(crdt: Replica, neighbours: list) -> None:
    """One-way sync edges; call symmetrically for bidirectional sync
    (reference note, ``delta_crdt.ex:84-95``)."""
    crdt.set_neighbours(neighbours)


#: default call timeout. The reference's GenServer.call default is 5s
#: (``delta_crdt.ex:117-137``) — enough on the BEAM where every op is
#: sub-millisecond, but here a replica's FIRST sync merge jit-compiles
#: for seconds while holding the serialisation lock, so a 5s default
#: would flake on cold starts. Pass ``timeout=5.0`` for strict parity.
DEFAULT_TIMEOUT = 30.0


def mutate(crdt: Replica, f: str, args: list, timeout: float = DEFAULT_TIMEOUT) -> None:
    """Synchronous mutation; raises TimeoutError if the replica stays
    busy past ``timeout`` (``DeltaCrdt.mutate/4``)."""
    crdt.mutate(f, args, timeout)


def mutate_async(crdt: Replica, f: str, args: list) -> None:
    crdt.mutate_async(f, args)


def mutate_batch(
    crdt: Replica, f: str, items: list, timeout: float = DEFAULT_TIMEOUT
) -> None:
    """Bulk mutation (TPU-native extension — no reference analog): one
    ``f`` op per ``items`` entry, applied in order in vectorized batch
    kernels. The natural shape for loads/imports."""
    crdt.mutate_batch(f, items, timeout)


def read(crdt: Replica, timeout: float = DEFAULT_TIMEOUT) -> "dict[Any, Any] | set":
    """Resolved read: a dict for map models, a set for ``AWSet``."""
    return crdt.read(timeout)


def frontdoor(crdt, **opts):
    """The serving front door of a replica or fleet (ISSUE 14) —
    created on first use and cached on the target.

    The front door is the client-facing hot path for heavy traffic:

    - **lock-free snapshot reads** — ``fd.read_keys(keys)`` /
      ``fd.read()`` / ``fd.scan(prefix)`` run off an immutable
      published store generation without EVER taking the replica lock,
      so reads never queue behind the event loop's merges.
      ``Replica.read(timeout)`` keeps its flush-then-read semantics as
      the strong-read mode — serving is additive (MIGRATING.md).
    - **coalesced write admission** — ``fd.mutate(f, args)`` /
      ``fd.mutate_async(f, args)`` fold many concurrent clients' ops
      into one grouped commit per admission window through the SAME
      ``Replica.apply_ops`` entrance ``mutate_batch`` uses: N client
      ops cost one vectorised kernel dispatch + one WAL group commit
      instead of N lock/notify round-trips.
    - **backpressure** — past the admission-queue / mailbox /
      transport ``queue_bytes`` / WAL-backlog limits, ops are shed
      with an explicit :class:`~delta_crdt_ex_tpu.runtime.serve.
      Overloaded`; shedding flips the plane's ``/healthz`` check to
      503 and the ``crdt_serve_*`` metrics family records
      admitted/shed/coalesce-depth/latency.

    ``crdt`` may be a :class:`Replica` (→ :class:`~delta_crdt_ex_tpu.
    runtime.serve.Frontdoor`) or a :class:`~delta_crdt_ex_tpu.runtime.
    fleet.Fleet` (→ one front door per member with key-hash routing).
    Options (``max_commit_ops``, ``max_pending_ops``,
    ``max_mailbox_depth``, ``max_queue_bytes``, ``max_wal_backlog``,
    ``shed_health_hold``, ``journal``) are fixed at first creation.
    Gated by ``bench.py --serve`` (open-loop p50/p99 harness)."""
    return crdt.frontdoor(**opts)
