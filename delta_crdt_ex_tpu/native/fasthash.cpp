// Batch BLAKE2b-64 key hashing for the host runtime.
//
// The device only ever sees 64-bit key ids; the host derives them from
// canonically-encoded terms (utils/hashing.py). Hashing a large mutation
// batch or rebuilding dictionaries for a million-key map pays ~1 us of
// Python/hashlib overhead per key; this extension hashes a packed buffer
// of encodings in one call. It implements RFC 7693 BLAKE2b with
// digest_length=8, no key — bit-for-bit identical to Python's
// hashlib.blake2b(data, digest_size=8), which remains the fallback, so
// native and non-native replicas always agree on key ids (equality is
// enforced by tests/test_native.py).
//
// Build: g++ -O3 -shared -fPIC fasthash.cpp -o libfasthash.so
// (done on demand by delta_crdt_ex_tpu/native/__init__.py)

#include <cstdint>
#include <cstring>

namespace {

constexpr uint64_t IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

constexpr uint8_t SIGMA[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3}};

inline uint64_t rotr64(uint64_t x, unsigned n) {
  return (x >> n) | (x << (64 - n));
}

inline uint64_t load64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86-64 / aarch64)
}

void compress(uint64_t h[8], const uint8_t block[128], uint64_t t, bool last) {
  uint64_t m[16];
  for (int i = 0; i < 16; ++i) m[i] = load64(block + 8 * i);
  uint64_t v[16];
  for (int i = 0; i < 8; ++i) v[i] = h[i];
  for (int i = 0; i < 8; ++i) v[8 + i] = IV[i];
  v[12] ^= t;  // t0 (messages < 2^64 bytes; t1 stays 0)
  if (last) v[14] = ~v[14];

#define G(a, b, c, d, x, y)      \
  v[a] = v[a] + v[b] + (x);      \
  v[d] = rotr64(v[d] ^ v[a], 32); \
  v[c] = v[c] + v[d];            \
  v[b] = rotr64(v[b] ^ v[c], 24); \
  v[a] = v[a] + v[b] + (y);      \
  v[d] = rotr64(v[d] ^ v[a], 16); \
  v[c] = v[c] + v[d];            \
  v[b] = rotr64(v[b] ^ v[c], 63);

  for (int r = 0; r < 12; ++r) {
    const uint8_t* s = SIGMA[r];
    G(0, 4, 8, 12, m[s[0]], m[s[1]]);
    G(1, 5, 9, 13, m[s[2]], m[s[3]]);
    G(2, 6, 10, 14, m[s[4]], m[s[5]]);
    G(3, 7, 11, 15, m[s[6]], m[s[7]]);
    G(0, 5, 10, 15, m[s[8]], m[s[9]]);
    G(1, 6, 11, 12, m[s[10]], m[s[11]]);
    G(2, 7, 8, 13, m[s[12]], m[s[13]]);
    G(3, 4, 9, 14, m[s[14]], m[s[15]]);
  }
#undef G

  for (int i = 0; i < 8; ++i) h[i] ^= v[i] ^ v[8 + i];
}

// BLAKE2b, digest_size bytes (1..64), no key, sequential mode.
void blake2b(const uint8_t* data, uint64_t len, uint8_t* out, unsigned digest_size) {
  uint64_t h[8];
  for (int i = 0; i < 8; ++i) h[i] = IV[i];
  h[0] ^= 0x01010000ULL ^ digest_size;  // param block: fanout=1, depth=1

  uint8_t block[128];
  uint64_t t = 0;
  while (len > 128) {
    std::memcpy(block, data, 128);
    t += 128;
    compress(h, block, t, false);
    data += 128;
    len -= 128;
  }
  std::memset(block, 0, 128);
  std::memcpy(block, data, len);
  t += len;
  compress(h, block, t, true);
  std::memcpy(out, h, digest_size);
}

}  // namespace

extern "C" {

// Hash n concatenated byte strings; offsets has n+1 entries delimiting
// each string in `packed`. Writes one big-endian-interpreted 64-bit key
// id per string (matching int.from_bytes(digest, "big") in Python, with
// 0 mapped to 1 — the empty-slot sentinel).
void hash64_batch(const uint8_t* packed, const uint64_t* offsets, uint64_t n,
                  uint64_t* out) {
  for (uint64_t i = 0; i < n; ++i) {
    uint8_t d[8];
    blake2b(packed + offsets[i], offsets[i + 1] - offsets[i], d, 8);
    uint64_t v = 0;
    for (int j = 0; j < 8; ++j) v = (v << 8) | d[j];
    out[i] = v ? v : 1;
  }
}

// 32-bit value digests, same packing convention.
void hash32_batch(const uint8_t* packed, const uint64_t* offsets, uint64_t n,
                  uint32_t* out) {
  for (uint64_t i = 0; i < n; ++i) {
    uint8_t d[4];
    blake2b(packed + offsets[i], offsets[i + 1] - offsets[i], d, 4);
    out[i] = ((uint32_t)d[0] << 24) | ((uint32_t)d[1] << 16) |
             ((uint32_t)d[2] << 8) | (uint32_t)d[3];
  }
}
}
