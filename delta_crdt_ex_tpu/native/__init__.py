"""Native host-runtime extensions (C++, built on demand, optional).

The TPU compute path is JAX/XLA; the host runtime around it uses native
code where per-element Python overhead matters. Components degrade
gracefully: if no compiler is available the pure-Python paths are used —
and they compute byte-identical results, so mixed native/non-native
clusters stay consistent.

Current components:
- ``fasthash``: batch BLAKE2b key/value hashing (see ``fasthash.cpp``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "fasthash.cpp")
_SO = os.path.join(_DIR, "libfasthash.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _SO + ".tmp"],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(_SO + ".tmp", _SO)
        return True
    except Exception:
        return False


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
            lib.hash64_batch.argtypes = [
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_uint64,
                ctypes.c_void_p,
            ]
            lib.hash32_batch.argtypes = lib.hash64_batch.argtypes
            _lib = lib
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def _pack(blobs: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
    offsets = np.zeros(len(blobs) + 1, np.uint64)
    total = 0
    for i, b in enumerate(blobs):
        total += len(b)
        offsets[i + 1] = total
    packed = np.empty(total, np.uint8)
    pos = 0
    for b in blobs:
        packed[pos : pos + len(b)] = np.frombuffer(b, np.uint8)
        pos += len(b)
    return packed, offsets


def hash64_batch(blobs: list[bytes]) -> np.ndarray | None:
    """uint64 key ids for canonical encodings; None if native unavailable."""
    lib = _load()
    if lib is None or not blobs:
        return None
    packed, offsets = _pack(blobs)
    out = np.empty(len(blobs), np.uint64)
    lib.hash64_batch(
        packed.ctypes.data, offsets.ctypes.data, len(blobs), out.ctypes.data
    )
    return out


def hash32_batch(blobs: list[bytes]) -> np.ndarray | None:
    lib = _load()
    if lib is None or not blobs:
        return None
    packed, offsets = _pack(blobs)
    out = np.empty(len(blobs), np.uint32)
    lib.hash32_batch(
        packed.ctypes.data, offsets.ctypes.data, len(blobs), out.ctypes.data
    )
    return out
