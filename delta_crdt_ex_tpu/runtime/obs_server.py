"""Per-process HTTP observability endpoint (ISSUE 9 tentpole).

Stdlib-only (``http.server``) export of the metrics plane built in
:mod:`delta_crdt_ex_tpu.runtime.metrics`:

- ``GET /metrics`` — Prometheus text exposition 0.0.4 of the plane's
  registry (bridge-fed event metrics + scrape-time collector gauges);
- ``GET /healthz`` — liveness/readiness JSON: every registered health
  check (replica event loop responsive, WAL writable, neighbours
  reachable via the existing monitor/heartbeat state; fleet tick
  freshness). HTTP 200 when every check passes, 503 otherwise — the
  k8s-style probe contract;
- ``GET /varz`` — one JSON snapshot unifying ``Replica.stats()`` /
  ``Fleet.stats()`` / WAL stats under a single schema (each source is
  ``{"kind": ..., "stats": ...}``; the underlying dicts are unchanged
  — this surface is additive, MIGRATING.md).

One :class:`ObsServer` per process is the expected shape (Prometheus
scrapes processes); ``port=0`` binds an ephemeral port for tests. The
server runs on daemon threads (``ThreadingHTTPServer``) and every
handler builds its whole response before writing, holding no runtime
lock across socket I/O (crdtlint LOCK003 discipline).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

logger = logging.getLogger("delta_crdt_ex_tpu")


def _jsonable(obj):
    """Best-effort JSON coercion for stats payloads: addresses may be
    tuples (TCP ``(name, (host, port))``), numpy scalars may leak in —
    neither must 500 the page."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    item = getattr(obj, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    return str(obj)


class _Handler(BaseHTTPRequestHandler):
    server_version = "crdt-obs/1"
    #: set per server class (see ObsServer.start)
    obs = None

    def _reply(self, code: int, content_type: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 - stdlib handler contract
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                body = self.obs.registry.render().encode()
                self._reply(
                    200, "text/plain; version=0.0.4; charset=utf-8", body
                )
            elif path == "/healthz":
                ok, checks = self.obs.health()
                body = json.dumps(
                    {"status": "ok" if ok else "unhealthy",
                     "checks": _jsonable(checks)},
                    indent=2,
                ).encode()
                self._reply(200 if ok else 503, "application/json", body)
            elif path == "/varz":
                snap = self.obs.varz()
                snap["metrics_families"] = self.obs.registry.families()
                body = json.dumps(_jsonable(snap), indent=2).encode()
                self._reply(200, "application/json", body)
            elif path == "/":
                body = (
                    b"crdt observability endpoint: /metrics /healthz /varz\n"
                )
                self._reply(200, "text/plain; charset=utf-8", body)
            else:
                self._reply(404, "text/plain; charset=utf-8", b"not found\n")
        except Exception:  # a scrape must never take the process down
            logger.exception("obs endpoint %s failed", path)
            try:
                self._reply(
                    500, "text/plain; charset=utf-8", b"internal error\n"
                )
            except OSError:
                pass

    def log_message(self, fmt, *args) -> None:  # scrapes are not log news
        logger.debug("obs http: " + fmt, *args)


class ObsServer:
    """The per-process ``/metrics`` + ``/healthz`` + ``/varz`` endpoint
    for one :class:`~delta_crdt_ex_tpu.runtime.metrics.Observability`
    plane. ``port=0`` binds an ephemeral port (tests / several planes
    per host); :attr:`url` names the bound address."""

    def __init__(self, obs, *, host: str = "127.0.0.1", port: int = 0):
        self.obs = obs
        self._host = host
        self._port = int(port)
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple:
        if self._httpd is None:
            raise RuntimeError("obs server not started")
        return self._httpd.server_address

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "ObsServer":
        if self._httpd is not None:
            return self
        handler = type("ObsHandler", (_Handler,), {"obs": self.obs})
        httpd = ThreadingHTTPServer((self._host, self._port), handler)
        httpd.daemon_threads = True
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever,
            name=f"crdt-obs-{httpd.server_address[1]}",
            daemon=True,
        )
        self._thread.start()
        logger.info("observability endpoint at %s", self.url)
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


__all__ = ["ObsServer"]
