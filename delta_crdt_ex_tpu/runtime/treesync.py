"""Hierarchical topology-aware anti-entropy (ISSUE 15): the spanning
tree every replica derives for itself.

Flat gossip syncs every configured neighbour directly, so fleet-scale
propagation pays O(peers) redundant walk rounds and bytes per replica
per tick. Tascade-style in-network combining (PAPERS.md) wants a
reduction tree instead: leaves sync only their parent, intermediate
relays coalesce inbound children's deltas and re-emit ONE merged slice
per link per epoch (``Replica._relay_flush``), and the frame format
(PR 10's ``FleetFrameMsg``) lets an intermediate hop rewrite its
``entries`` without touching the inner messages.

There is NO coordinator: every replica computes the SAME tree from the
same inputs —

- the sorted member set (its configured neighbours plus itself),
- a shared ``tree_seed`` (tie-break shuffling, so the root is not
  always the lexically-smallest name),
- its locally-observed down set (``Down`` messages for tree links;
  re-derived on ``set_neighbours`` and on Down/rejoin),
- a tier-0 GROUP per member (:func:`group_of`): members that resolve
  to the same process endpoint / pinned device / fleet mesh cluster as
  one bottom-tier subtree under a single "captain", because an
  intra-process (or intra-mesh ``ppermute``) hop is free relative to
  TCP — the bottom tier of the tree IS the mesh.

Divergent views (e.g. mid-churn, before every replica observed the
same Down) are SAFE, just transiently suboptimal: every tree link is
an ordinary bidirectional sync edge healed by the digest walk, and the
next shared derivation converges the topology. When a replica's local
down set damages the tree past ``tree_degrade_ratio`` it falls back to
flat gossip outright (every neighbour a direct link) until membership
stabilises — correctness never depends on the tree being intact.

Derivation is pure and host-only; all mutable relay state lives on the
:class:`~delta_crdt_ex_tpu.runtime.replica.Replica` under its lock.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Hashable


def member_key(addr: Hashable) -> str:
    """Canonical, process-independent ordering key for a member address
    (names are strings; TCP canonical addrs are ``(name, (host, port))``
    tuples — ``repr`` is deterministic for both)."""
    return repr(addr)


def _shuffle_rank(addr: Hashable, seed: int) -> bytes:
    """Deterministic pseudo-random rank: the same (addr, seed) ranks
    identically in every process, and a seed change reshuffles the whole
    tree (root rotation without a coordinator)."""
    h = hashlib.blake2b(digest_size=8)
    h.update(member_key(addr).encode())
    h.update(int(seed).to_bytes(8, "little", signed=True))
    return h.digest()


def group_of(transport, addr: Hashable) -> "tuple | None":
    """The tier-0 cluster key for ``addr`` — derivable by EVERY replica
    from wire-visible information, so trees agree without a gossip
    round about the topology itself:

    1. an in-process owner advertising ``tree_group`` (a fleet stamps
       its members with one shared key — the mesh/fleet tier);
    2. the remote process endpoint of a TCP canonical address
       (``(name, (host, port))``) — co-located members cluster so only
       their captain gossips cross-process;
    3. the pinned device (``transport.device_of``) — co-device members
       ride the device data plane between each other;
    4. ``None``: the member is its own singleton group.
    """
    owners = getattr(transport, "_owners", None)
    if owners is not None:
        owner = owners.get(addr)
        if owner is None and isinstance(addr, tuple) and len(addr) == 2:
            owner = owners.get(addr[0])
        if owner is not None:
            tg = getattr(owner, "tree_group", None)
            if tg is not None:
                return ("group", tg)
    if (
        isinstance(addr, tuple)
        and len(addr) == 2
        and isinstance(addr[1], (tuple, list))
        and len(addr[1]) == 2
    ):
        return ("endpoint", tuple(addr[1]))
    device_of = getattr(transport, "device_of", None)
    if device_of is not None:
        dev = device_of(addr)
        if dev is not None:
            return ("device", repr(dev))
    return None


@dataclasses.dataclass(frozen=True)
class TreeTopology:
    """One derived spanning tree: identical on every replica that fed
    :func:`derive_tree` the same inputs. ``epoch`` names the derivation
    (hash of members + seed + fanout) so two replicas can cheaply agree
    they computed the same tree."""

    epoch: str
    fanout: int
    seed: int
    members: tuple
    root: Any
    parent: dict  # addr -> parent addr (root absent)
    children: dict  # addr -> tuple of child addrs (leaves absent)
    tier: dict  # addr -> depth from root (root = 0)
    depth: int

    def links(self, addr: Hashable) -> list:
        """This member's sync edges: its parent (if any) then its
        children — the ONLY peers a tree-mode replica monitors, pushes
        to, walks toward, and relays between."""
        out: list = []
        p = self.parent.get(addr)
        if p is not None:
            out.append(p)
        out.extend(self.children.get(addr, ()))
        return out

    def role(self, addr: Hashable) -> str:
        if addr == self.root:
            return "root"
        return "relay" if self.children.get(addr) else "leaf"


def derive_tree(
    members,
    *,
    fanout: int = 8,
    seed: int = 0,
    down=(),
    group_key=None,
) -> "TreeTopology | None":
    """Derive the deterministic spanning tree over ``members`` minus
    ``down``. ``group_key`` maps addr → tier-0 cluster key (or None for
    a singleton); members sharing a key become ONE bottom-tier subtree:
    their captain (lowest shuffle rank) takes the group's slot in the
    relay tree and the rest hang off it directly, whatever the fanout —
    intra-group links are the free tier. The relay tree over captains
    is heap-shaped (captain i's children are ``i*F+1 .. i*F+F`` in
    shuffle-rank order), so depth is ``ceil(log_F(captains))`` and
    every replica lands on the same layout. Returns ``None`` for an
    empty alive set."""
    if fanout < 2:
        raise ValueError(f"tree fanout must be >= 2, got {fanout}")
    down = set(down)
    alive = sorted(
        {m for m in members if m not in down}, key=member_key
    )
    if not alive:
        return None
    rank = {m: _shuffle_rank(m, seed) for m in alive}

    groups: dict[Any, list] = {}
    for m in alive:
        gk = group_key(m) if group_key is not None else None
        if gk is None:
            gk = ("solo", member_key(m))
        groups.setdefault(gk, []).append(m)
    for g in groups.values():
        g.sort(key=lambda m: (rank[m], member_key(m)))
    # captain order = shuffle rank of each group's captain: the relay
    # tree is over captains, one slot per tier-0 cluster
    captains = sorted(
        (g[0] for g in groups.values()),
        key=lambda m: (rank[m], member_key(m)),
    )
    slot = {c: i for i, c in enumerate(captains)}

    parent: dict = {}
    children: dict = {}
    tier: dict = {}
    for i, c in enumerate(captains):
        if i == 0:
            tier[c] = 0
        else:
            p = captains[(i - 1) // fanout]
            parent[c] = p
            children.setdefault(p, []).append(c)
            tier[c] = tier[p] + 1
    for g in groups.values():
        cap = g[0]
        for m in g[1:]:
            parent[m] = cap
            children.setdefault(cap, []).append(m)
            tier[m] = tier[cap] + 1

    h = hashlib.blake2b(digest_size=8)
    for m in alive:
        h.update(member_key(m).encode())
        h.update(b"\x00")
    # the group PARTITION shapes the tree too, and it is observer-
    # dependent (an in-process observer sees tree_group stamps a remote
    # one cannot): fold it into the digest so two replicas reporting
    # the same epoch really did derive the same tree
    for gk in sorted(groups, key=repr):
        h.update(b"\x01")
        for m in groups[gk]:
            h.update(member_key(m).encode())
            h.update(b"\x00")
    h.update(int(seed).to_bytes(8, "little", signed=True))
    h.update(int(fanout).to_bytes(4, "little"))
    return TreeTopology(
        epoch=h.hexdigest(),
        fanout=int(fanout),
        seed=int(seed),
        members=tuple(alive),
        root=captains[0],
        parent=parent,
        children={k: tuple(v) for k, v in children.items()},
        tier=tier,
        depth=max(tier.values()) if tier else 0,
    )


def too_damaged(n_members: int, n_down: int, ratio: float) -> bool:
    """The flat-gossip degrade decision: with more than ``ratio`` of
    the membership down (or nothing but ourselves left) the tree's
    relay chains are untrustworthy — sync every neighbour directly
    until membership stabilises. Local-view-deterministic: replicas
    that observed the same failures degrade together."""
    if n_members <= 1:
        return True
    return n_down > ratio * n_members


def fleet_group_key(member_addrs) -> tuple:
    """The shared ``tree_group`` a fleet stamps its members with: a
    deterministic digest of the sorted member address set, so any
    process that knows the fleet's membership derives the same tier-0
    cluster (and co-located externals fall back to the endpoint group,
    which clusters the same members)."""
    h = hashlib.blake2b(digest_size=8)
    for m in sorted(member_addrs, key=member_key):
        h.update(member_key(m).encode())
        h.update(b"\x00")
    return ("fleet", h.hexdigest())
