"""Tracing / profiling (SURVEY §5.1).

The reference's profiling story is dev-time ``:fprof`` wrapped in
``bench/basic_operations.exs:9-23`` (trace 1000 mutations, analyse to a
file). The TPU-native equivalents:

- :func:`trace` — context manager around any region, capturing a
  ``jax.profiler`` device trace (view with TensorBoard / xprof);
- :func:`annotate` — cheap named spans (``jax.profiler.TraceAnnotation``)
  used by the replica around its merge/flush hot paths;
- :func:`profile_mutations` — the fprof-analog: run N mutations against a
  replica under a device trace plus host-side phase timers, and return
  the wall-time breakdown.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any

import jax


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a jax.profiler device trace for the enclosed region."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span visible in device traces (no-op cost when not tracing)."""
    return jax.profiler.TraceAnnotation(name)


def profile_mutations(crdt, n: int = 1000, logdir: str | None = None) -> dict[str, Any]:
    """Profile ``n`` add mutations (reference ``bench/basic_operations.exs:
    9-23``): optional device trace + host wall-time split."""
    ctx = trace(logdir) if logdir else contextlib.nullcontext()
    t0 = time.perf_counter()
    with ctx:
        for x in range(n):
            crdt.mutate("add", [f"key{x}", "value"])
        crdt.hibernate()
    total = time.perf_counter() - t0
    return {
        "mutations": n,
        "total_s": total,
        "per_op_us": total / n * 1e6,
        "trace_dir": logdir,
    }
