"""Deterministic simulated network — the race-detector of this framework.

The reference has no sanitizer; its safety argument is the actor model
plus property tests (SURVEY §5.2). The TPU runtime's equivalent is this
seeded, deterministic scheduler: it intercepts every protocol message and
delivers them in adversarial orders — permuted, delayed, duplicated, or
dropped — while the convergence property suite asserts that replicas
still converge (sync is idempotent and commutative, so any delivery
schedule must reach the same fixed point; reference behaviour under
``send/2``'s only guarantee, per-pair FIFO, is a special case).

Duplication exercises idempotence; dropping exercises retry-via-next-tick
(the reference's "syncing is idempotent" rescue, ``causal_crdt.ex:
269-282``); permutation exercises commutativity of merges.
"""

from __future__ import annotations

import random
from typing import Hashable

from delta_crdt_ex_tpu.runtime.transport import LocalTransport


class SimNetwork(LocalTransport):
    """LocalTransport with a seeded adversarial delivery schedule.

    Messages are buffered in a pending pool; :meth:`step` delivers a
    random subset in random order, optionally duplicating or dropping.
    Fully deterministic given the seed.
    """

    def __init__(
        self,
        seed: int = 0,
        drop_rate: float = 0.0,
        dup_rate: float = 0.0,
        reorder: bool = True,
    ):
        super().__init__()
        self.rng = random.Random(seed)
        self.drop_rate = drop_rate
        self.dup_rate = dup_rate
        self.reorder = reorder
        self._pool: list[tuple[Hashable, object]] = []

    def send(self, addr: Hashable, msg) -> bool:
        with self._lock:
            known = addr in self._owners
        if not known:
            return False
        self._pool.append((addr, msg))
        return True

    def step(self, max_deliveries: int | None = None) -> int:
        """Deliver (a prefix of) the pending pool adversarially."""
        pool, self._pool = self._pool, []
        if self.reorder:
            self.rng.shuffle(pool)
        if max_deliveries is not None:
            pool, rest = pool[:max_deliveries], pool[max_deliveries:]
            self._pool.extend(rest)
        delivered = 0
        for addr, msg in pool:
            r = self.rng.random()
            if r < self.drop_rate:
                continue
            if r < self.drop_rate + self.dup_rate:
                self._deliver(addr, msg)
                delivered += 1
            self._deliver(addr, msg)
            delivered += 1
        return delivered

    def _deliver(self, addr, msg) -> None:
        with self._lock:
            owner = self._owners.get(addr)
        if owner is not None:
            owner.handle(msg)

    def run(self, replicas, rounds: int = 50) -> None:
        """Alternate sync ticks and adversarial delivery steps."""
        for _ in range(rounds):
            for rep in replicas:
                rep.sync_to_all()
            self.step()

    @property
    def pending(self) -> int:
        return len(self._pool)
