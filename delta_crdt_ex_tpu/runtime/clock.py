"""LWW timestamp source.

The reference stamps adds with ``System.monotonic_time(:nanosecond)``
(``aw_lww_map.ex:104``) — monotonic per BEAM node, arbitrary offset, so
cross-replica LWW order is essentially meaningless there (SURVEY §7
"Hard parts"). We keep the per-replica monotonicity contract but base the
clock on wall time so cross-replica LWW is at least wall-clock sensible,
and guarantee strict per-replica increase (ties are impossible within a
replica). Deterministic logical clocks are injectable for tests.
"""

from __future__ import annotations

import time

import numpy as np


class Clock:
    """Strictly increasing nanosecond timestamps, wall-clock based."""

    def __init__(self, start: int | None = None):
        self._last = int(start or 0)

    def next(self) -> int:
        now = time.time_ns()
        self._last = now if now > self._last else self._last + 1
        return self._last

    def next_n(self, n: int) -> np.ndarray:
        """``n`` strictly increasing stamps in one call (the bulk flush
        path) — consecutive from max(now, last+1), so interleaving with
        ``next()`` keeps the strict global order."""
        now = time.time_ns()
        start = now if now > self._last else self._last + 1
        out = start + np.arange(n, dtype=np.int64)
        if n:
            self._last = int(out[-1])
        return out

    def observe(self, ts: int) -> None:
        """Fast-forward past a restored/remote timestamp (crash-restart
        continuity: restored LWW entries must not out-rank new writes)."""
        if ts > self._last:
            self._last = ts


class LogicalClock(Clock):
    """Deterministic test clock: 1, 2, 3, …"""

    def next(self) -> int:
        self._last += 1
        return self._last

    def next_n(self, n: int) -> np.ndarray:
        out = self._last + 1 + np.arange(n, dtype=np.int64)
        self._last += n
        return out
