"""Fleet-wide metrics plane (ISSUE 9 tentpole).

The reference's entire observability surface is one ``:telemetry``
event (``[:delta_crdt, :sync, :done]``, ``causal_crdt.ex:396-398``);
ours had grown to ten event tuples plus three ad-hoc ``stats()`` dicts
with no aggregation, no export, and no cross-replica correlation. This
module is the missing aggregation layer:

- :class:`Registry` — a process-wide table of counters / gauges /
  histograms with label sets (replica name, peer, plane, store
  backend), rendered as Prometheus text exposition by
  :meth:`Registry.render` (served by
  :mod:`delta_crdt_ex_tpu.runtime.obs_server`). Every update happens
  under one registry lock and every read is a snapshot — the RACE/LOCK
  gates see exactly the discipline the rest of the runtime follows.
- :class:`MetricsBridge` — THE one always-attached telemetry consumer:
  it subscribes to every event tuple declared in
  :mod:`~delta_crdt_ex_tpu.runtime.telemetry` (the subscription table
  in :meth:`MetricsBridge._table` is cross-checked against the
  declared events by crdtlint OBS001) and folds measurements into the
  registry. With no bridge attached the ``has_handlers`` guards on the
  hot paths keep disabled telemetry at a lock-check — no dict
  building, no handler calls (crdtlint OBS002 keeps it that way).
- :class:`FlightRecorder` — a bounded per-replica ring buffer of
  recent structured events (sync rounds, catch-up, rehash, compaction,
  gap repairs, fallbacks): the black box chaos/soak scenarios read
  after the fact, dumped through the logger on :meth:`Replica.crash`
  and queryable in tests via :meth:`FlightRecorder.events`.
- :class:`LagTracer` — dot-provenance replication-lag tracing with
  ZERO wire changes: deltas already carry ``(writer, seq)`` dots and
  sync openers already stamp seq watermarks, so a sampling tracer
  records local-commit time at the origin (keyed on the originator
  address + seq that are already on the wire) and remote-visibility
  time at each peer (the moment its applied watermark of that origin
  advances), yielding per-peer convergence-lag and propagation-round
  histograms — the instrument hierarchical anti-entropy must read.
- :class:`Observability` — the facade the ``obs=`` knob on
  :func:`~delta_crdt_ex_tpu.api.start_link` /
  :func:`~delta_crdt_ex_tpu.api.start_fleet` resolves to: one registry
  + bridge + lag tracer + flight-recorder factory, plus the varz /
  health source tables the HTTP endpoint serves.

Metric naming scheme: every name is ``crdt_<noun>[_<unit>]`` with the
Prometheus conventions — ``_total`` counters, ``_seconds`` / ``_bytes``
units, histograms exported as ``_bucket``/``_sum``/``_count``. Label
keys are drawn from the closed set ``name`` (replica), ``peer``,
``origin``, ``plane``, ``role``, ``fleet``, ``transport``, ``reason``
(shed signal), ``mode`` (read class), ``tier`` (spanning-tree tier).

Lock order (deadlock-free by construction, LOCK002): replica lock →
tracer/recorder lock → registry lock. Nothing here ever acquires a
replica or fleet lock.
"""

from __future__ import annotations

import bisect
import logging
import re
import threading
import time
from typing import Any, Callable

from delta_crdt_ex_tpu.runtime import telemetry

logger = logging.getLogger("delta_crdt_ex_tpu")

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: wall-time histogram buckets (seconds): spans a 100 µs kernel
#: dispatch to a 30 s catch-up stream
LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)
#: small-count histogram buckets (coalesce depth, batch occupancy,
#: propagation rounds)
COUNT_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0, 64.0, 128.0)


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats render as ints."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Metric:
    """Base: one named metric family with a fixed label-name tuple.
    All value state is guarded by the OWNING registry's lock (one lock
    per registry keeps update cost at a single uncontended acquire on
    the hot path and makes reads whole-registry-consistent)."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str, label_names: tuple, lock):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in label_names:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} for {name!r}")
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self._lock = lock

    def _labels(self, labels) -> tuple:
        if len(labels) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected {len(self.label_names)} label "
                f"value(s) {self.label_names}, got {labels!r}"
            )
        # fast path: a tuple of str (what the bridge always passes) is
        # already canonical — the genexpr re-tuple below costs more than
        # the whole locked update on the ingest hot path
        if type(labels) is tuple:
            for v in labels:
                if type(v) is not str:
                    break
            else:
                return labels
        return tuple(str(v) for v in labels)

    def _series(self, labels: tuple) -> str:
        if not labels:
            return self.name
        pairs = ",".join(
            f'{k}="{_escape(v)}"' for k, v in zip(self.label_names, labels)
        )
        return f"{self.name}{{{pairs}}}"


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_text, label_names, lock):
        super().__init__(name, help_text, label_names, lock)
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, labels: tuple = ()) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up ({amount})")
        labels = self._labels(labels)
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + amount

    def _inc_held(self, labels: tuple, amount: float = 1.0) -> None:
        """Caller HOLDS the registry lock and has canonicalised
        ``labels`` — the bridge's hot path folds a whole event's
        updates under ONE lock acquire instead of one per metric."""
        self._values[labels] = self._values.get(labels, 0.0) + amount

    def value(self, labels: tuple = ()) -> float:
        labels = self._labels(labels)
        with self._lock:
            return self._values.get(labels, 0.0)

    def _render(self) -> list[str]:
        # caller holds the registry lock
        return [
            f"{self._series(lb)} {_fmt(v)}"
            for lb, v in sorted(self._values.items())
        ]

    def _snapshot(self):
        return dict(self._values)


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_text, label_names, lock):
        super().__init__(name, help_text, label_names, lock)
        self._values: dict[tuple, float] = {}

    def set(self, value: float, labels: tuple = ()) -> None:
        labels = self._labels(labels)
        with self._lock:
            self._values[labels] = float(value)

    def _set_held(self, labels: tuple, value: float) -> None:
        """Caller HOLDS the registry lock, ``labels`` canonical (see
        :meth:`Counter._inc_held`)."""
        self._values[labels] = float(value)

    def inc(self, amount: float = 1.0, labels: tuple = ()) -> None:
        labels = self._labels(labels)
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + amount

    def remove(self, labels: tuple = ()) -> None:
        """Drop one label set (a stopped replica's gauges must not scrape
        as a stale last value forever)."""
        labels = self._labels(labels)
        with self._lock:
            self._values.pop(labels, None)

    def value(self, labels: tuple = ()) -> float:
        labels = self._labels(labels)
        with self._lock:
            return self._values.get(labels, 0.0)

    _render = Counter._render
    _snapshot = Counter._snapshot


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_text, label_names, lock, buckets=LATENCY_BUCKETS):
        super().__init__(name, help_text, label_names, lock)
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError(f"{self.name}: at least one bucket required")
        self.buckets = b
        # per label set: [per-bucket counts..., +Inf count], sum
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}

    def observe(self, value: float, labels: tuple = ()) -> None:
        labels = self._labels(labels)
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            counts = self._counts.get(labels)
            if counts is None:
                counts = self._counts[labels] = [0] * (len(self.buckets) + 1)
                self._sums[labels] = 0.0
            counts[i] += 1
            self._sums[labels] += value

    def _observe_held(self, labels: tuple, value: float) -> None:
        """Caller HOLDS the registry lock, ``labels`` canonical (see
        :meth:`Counter._inc_held`)."""
        i = bisect.bisect_left(self.buckets, value)
        counts = self._counts.get(labels)
        if counts is None:
            counts = self._counts[labels] = [0] * (len(self.buckets) + 1)
            self._sums[labels] = 0.0
        counts[i] += 1
        self._sums[labels] += value

    def count(self, labels: tuple = ()) -> int:
        labels = self._labels(labels)
        with self._lock:
            return sum(self._counts.get(labels, ()))

    def sum(self, labels: tuple = ()) -> float:
        labels = self._labels(labels)
        with self._lock:
            return self._sums.get(labels, 0.0)

    def label_sets(self) -> list[tuple]:
        with self._lock:
            return list(self._counts)

    def _render(self) -> list[str]:
        out: list[str] = []
        for lb in sorted(self._counts):
            counts = self._counts[lb]
            cum = 0
            for ub, c in zip(self.buckets, counts):
                cum += c
                le = (_fmt(ub), lb)
                pairs = ",".join(
                    [f'le="{le[0]}"']
                    + [
                        f'{k}="{_escape(v)}"'
                        for k, v in zip(self.label_names, lb)
                    ]
                )
                out.append(f"{self.name}_bucket{{{pairs}}} {cum}")
            cum += counts[-1]
            pairs = ",".join(
                ['le="+Inf"']
                + [f'{k}="{_escape(v)}"' for k, v in zip(self.label_names, lb)]
            )
            out.append(f"{self.name}_bucket{{{pairs}}} {cum}")
            suffix = self._series(lb)
            base, brace, rest = suffix.partition("{")
            out.append(f"{base}_sum{brace}{rest} {_fmt(self._sums[lb])}")
            out.append(f"{base}_count{brace}{rest} {cum}")
        return out

    def _snapshot(self):
        return {
            lb: {"count": sum(c), "sum": self._sums[lb]}
            for lb, c in self._counts.items()
        }


class Registry:
    """Process-wide metric registry.

    ``counter``/``gauge``/``histogram`` are get-or-create (idempotent
    for an identical signature, raising on a conflicting re-register),
    so independent subsystems can share families. Collectors registered
    via :meth:`register_collector` are invoked at snapshot/render time
    OUTSIDE the registry lock (they may take replica/fleet locks to
    poll ``stats()`` — the scrape path never holds the registry lock
    while acquiring a runtime lock, keeping the lock order acyclic).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list[Callable[[], None]] = []

    # -- family registration --------------------------------------------

    def _get_or_create(self, cls, name, help_text, label_names, **kw) -> Any:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or existing.label_names != tuple(label_names):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind} with labels {existing.label_names}"
                    )
                return existing
            metric = cls(name, help_text, tuple(label_names), self._lock, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help_text: str, label_names: tuple = ()) -> Counter:
        return self._get_or_create(Counter, name, help_text, label_names)

    def gauge(self, name: str, help_text: str, label_names: tuple = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, label_names)

    def histogram(
        self, name: str, help_text: str, label_names: tuple = (),
        buckets=LATENCY_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help_text, label_names, buckets=buckets
        )

    def get(self, name: str) -> "_Metric | None":
        with self._lock:
            return self._metrics.get(name)

    # -- collectors ------------------------------------------------------

    def register_collector(self, fn: Callable[[], None]) -> None:
        """``fn()`` runs before every render/snapshot to poll gauges
        from live objects (mailbox depth, WAL segment counts, fleet
        occupancy) — scrape-time cost instead of hot-path cost."""
        with self._lock:
            self._collectors.append(fn)

    def unregister_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:  # a dead source must not kill the scrape
                logger.debug("metrics collector failed", exc_info=True)

    # -- export ----------------------------------------------------------

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        self._run_collectors()
        lines: list[str] = []
        with self._lock:
            for name, m in self._metrics.items():
                samples = m._render()
                if not samples:
                    continue
                lines.append(f"# HELP {name} {m.help}")
                lines.append(f"# TYPE {name} {m.kind}")
                lines.extend(samples)
        return "\n".join(lines) + "\n"

    def families(self) -> int:
        """Registered metric-family count — the cheap header number for
        ``/varz`` (``snapshot()`` would re-run every collector, i.e.
        re-poll every replica/fleet/WAL source, just to be counted)."""
        with self._lock:
            return len(self._metrics)

    def snapshot(self) -> dict:
        """Structured value snapshot (tests / the JSON varz surface)."""
        self._run_collectors()
        out: dict = {}
        with self._lock:
            for name, m in self._metrics.items():
                values = {
                    "|".join(lb) if lb else "": v
                    for lb, v in m._snapshot().items()
                }
                out[name] = {"type": m.kind, "values": values}
        return out


# ----------------------------------------------------------------------
# the telemetry -> metrics bridge

def _with_batch(per_message: Callable, batch: Callable) -> Callable:
    """Wrap a per-message handler with a ``batch`` attribute —
    ``telemetry.execute_many`` dispatches the whole list to ``batch``
    in one call; plain ``execute`` (and non-batch handlers) still see
    per-message calls. A function object because bound methods reject
    attribute assignment."""
    def handler(event, meas, meta):
        per_message(event, meas, meta)
    handler.batch = batch
    return handler


class MetricsBridge:
    """THE always-attached telemetry consumer: every event tuple
    declared in :mod:`~delta_crdt_ex_tpu.runtime.telemetry` has a row
    in :meth:`_table` folding its measurements into registry metrics —
    crdtlint OBS001 turns red when a declared event is missing from
    the table, and the mutation tests prove it."""

    def __init__(self, registry: Registry):
        self.registry = registry
        #: the registry's one lock: each handler folds its whole
        #: event's updates under a single acquire (the ``*_held``
        #: metric primitives) — per-metric ``inc``/``observe`` calls
        #: would pay one acquire each on the ingest hot path
        self._lock = registry._lock
        self._attached = False
        c, g, h = registry.counter, registry.gauge, registry.histogram
        self.sync_done = c(
            "crdt_sync_done_total", "Merges applied (local + remote)", ("name",)
        )
        self.keys_updated = c(
            "crdt_sync_keys_updated_total", "Keys changed by merges", ("name",)
        )
        self.capacity_grown = c(
            "crdt_capacity_grown_total", "Store growth events", ("name",)
        )
        self.capacity = g(
            "crdt_capacity", "Current store entry capacity", ("name",)
        )
        self.sync_rounds = c(
            "crdt_sync_rounds_total", "Entry slices merged", ("name", "plane")
        )
        self.sync_seconds = h(
            "crdt_merge_dispatch_seconds",
            "Per-slice merge wall time (kernel accounting)",
            ("name", "plane"),
        )
        self.sync_entries = c(
            "crdt_sync_entries_total", "Entries received in slices",
            ("name", "plane"),
        )
        self.sync_buckets = c(
            "crdt_sync_buckets_total", "Bucket rows received in slices",
            ("name", "plane"),
        )
        self.ingest_dispatches = c(
            "crdt_ingest_dispatches_total", "Grouped fan-in dispatches", ("name",)
        )
        self.ingest_messages = c(
            "crdt_ingest_coalesced_messages_total",
            "Messages folded into grouped dispatches", ("name",),
        )
        self.ingest_depth = h(
            "crdt_ingest_coalesce_depth", "Messages per grouped dispatch",
            ("name",), buckets=COUNT_BUCKETS,
        )
        self.ingest_seconds = h(
            "crdt_ingest_dispatch_seconds", "Grouped dispatch wall time",
            ("name",),
        )
        self.wal_records = c(
            "crdt_wal_append_records_total", "WAL records appended", ("name",)
        )
        self.wal_bytes = c(
            "crdt_wal_append_bytes_total", "WAL bytes appended", ("name",)
        )
        self.wal_seconds = h(
            "crdt_wal_append_seconds", "WAL append+commit wall time", ("name",)
        )
        self.wal_compactions = c(
            "crdt_wal_compactions_total", "WAL compaction checkpoints", ("name",)
        )
        self.wal_reclaimed = c(
            "crdt_wal_reclaimed_bytes_total", "WAL bytes reclaimed", ("name",)
        )
        self.wal_recover_records = c(
            "crdt_wal_recovered_records_total", "WAL records replayed", ("name",)
        )
        self.wal_recover_seconds = h(
            "crdt_wal_recover_seconds", "WAL recovery wall time", ("name",)
        )
        self.catchup_chunks = c(
            "crdt_catchup_chunks_total", "Log-shipping chunks",
            ("name", "role"),
        )
        self.catchup_bytes = c(
            "crdt_catchup_chunk_bytes_total", "Log-shipping chunk bytes",
            ("name", "role"),
        )
        self.catchup_entries = c(
            "crdt_catchup_chunk_entries_total", "Log-shipping chunk entries",
            ("name", "role"),
        )
        self.catchup_streams = c(
            "crdt_catchup_streams_total", "Completed catch-up streams", ("name",)
        )
        self.catchup_horizon = c(
            "crdt_catchup_horizon_fallbacks_total",
            "Catch-up streams clamped at a compaction horizon", ("name",),
        )
        self.catchup_seconds = h(
            "crdt_catchup_stream_seconds", "Catch-up stream wall time", ("name",)
        )
        self.fleet_dispatches = c(
            "crdt_fleet_dispatches_total", "Fleet batched dispatches", ("fleet",)
        )
        self.fleet_messages = c(
            "crdt_fleet_batched_messages_total",
            "Messages merged by fleet batched dispatches", ("fleet",),
        )
        self.fleet_seconds = h(
            "crdt_fleet_dispatch_seconds", "Fleet batched dispatch wall time",
            ("fleet",),
        )
        self.fleet_occupancy = h(
            "crdt_fleet_dispatch_replicas", "Replicas per fleet dispatch",
            ("fleet",), buckets=COUNT_BUCKETS,
        )
        self.fleet_rows = c(
            "crdt_fleet_rows_total", "Real rows in fleet dispatches", ("fleet",)
        )
        self.fleet_padded_rows = c(
            "crdt_fleet_padded_rows_total",
            "Padded rows launched by fleet dispatches", ("fleet",),
        )
        self.fleet_egress_ticks = c(
            "crdt_fleet_egress_ticks_total",
            "Batched fleet sync-tick egress passes", ("fleet",),
        )
        self.fleet_egress_dispatches = c(
            "crdt_fleet_egress_dispatches_total",
            "Vmapped egress extraction/tree dispatches", ("fleet",),
        )
        self.fleet_egress_members = h(
            "crdt_fleet_egress_members",
            "Members served per batched egress tick", ("fleet",),
            buckets=COUNT_BUCKETS,
        )
        self.fleet_egress_frames = c(
            "crdt_fleet_egress_frames_total",
            "FleetFrameMsg envelopes shipped", ("fleet",),
        )
        self.fleet_egress_frame_members = c(
            "crdt_fleet_egress_frame_members_total",
            "Member replicas carried by shipped FleetFrameMsg envelopes",
            ("fleet",),
        )
        self.fleet_egress_seconds = h(
            "crdt_fleet_egress_seconds",
            "Batched egress tick wall time", ("fleet",),
        )
        self.mesh_exchanges = c(
            "crdt_mesh_exchanges_total",
            "Intra-mesh ppermute exchange dispatches", ("fleet",),
        )
        self.mesh_intra_entries = c(
            "crdt_mesh_intra_entries_total",
            "Sync-tick entries delivered through the intra-mesh plane",
            ("fleet",),
        )
        self.mesh_fallback_entries = c(
            "crdt_mesh_fallback_entries_total",
            "Sync-tick entries that fell back to the host/TCP path",
            ("fleet",),
        )
        self.mesh_permuted_bytes = c(
            "crdt_mesh_permuted_bytes_total",
            "Bytes moved by intra-mesh ppermute rotations (padded buffers)",
            ("fleet",),
        )
        # serving plane (ISSUE 14): admission/shed/read accounting —
        # the front door's client-facing counterpart of the ingest
        # coalescing family (one SERVE_ADMIT per grouped commit, one
        # SERVE_SHED per rejected op, one SERVE_READ per snapshot read)
        self.serve_commits = c(
            "crdt_serve_commits_total", "Admission grouped commits", ("name",)
        )
        self.serve_admitted = c(
            "crdt_serve_admitted_ops_total",
            "Client write ops admitted and committed", ("name",),
        )
        self.serve_depth = h(
            "crdt_serve_coalesce_depth",
            "Client ops folded per admission commit", ("name",),
            buckets=COUNT_BUCKETS,
        )
        self.serve_commit_seconds = h(
            "crdt_serve_commit_seconds",
            "Admission group-commit wall time", ("name",),
        )
        self.serve_shed = c(
            "crdt_serve_shed_ops_total",
            "Client write ops shed by backpressure", ("name", "reason"),
        )
        self.serve_reads = c(
            "crdt_serve_reads_total", "Snapshot reads served", ("name", "mode")
        )
        self.serve_read_seconds = h(
            "crdt_serve_read_seconds", "Snapshot read wall time", ("name",)
        )
        self.serve_read_retries = c(
            "crdt_serve_read_retries_total",
            "Stale-snapshot read retries", ("name",),
        )
        # hierarchical anti-entropy (ISSUE 15): the crdt_tree_* family —
        # relay coalescing histograms (how many inbound frames fold into
        # one re-emission, how many entries each merged re-emission
        # carries), per-tier tx/rx byte counters, and the topology
        # gauges the TREE_TOPOLOGY event keeps fresh (removed on
        # unregister_replica — a stopped replica must not scrape stale)
        self.tree_reemits = c(
            "crdt_tree_reemits_total",
            "Relay coalesced re-emissions shipped", ("name",),
        )
        self.tree_coalesce_depth = h(
            "crdt_tree_relay_coalesce_depth",
            "Inbound frames folded per relay re-emission", ("name",),
            buckets=COUNT_BUCKETS,
        )
        self.tree_entries_per_reemit = h(
            "crdt_tree_entries_per_reemit",
            "Entries carried per merged relay re-emission", ("name",),
            buckets=COUNT_BUCKETS,
        )
        self.tree_tx_bytes = c(
            "crdt_tree_tx_bytes_total",
            "Relay re-emission slice bytes shipped, by tree tier",
            ("name", "tier"),
        )
        self.tree_rx_bytes = c(
            "crdt_tree_rx_bytes_total",
            "Inbound slice bytes folded into relay re-emissions, by tree tier",
            ("name", "tier"),
        )
        self.tree_depth = g(
            "crdt_tree_depth", "Spanning-tree depth of the derived topology",
            ("name",),
        )
        self.tree_fanout = g(
            "crdt_tree_fanout", "Configured relay fanout", ("name",)
        )
        self.tree_role = g(
            "crdt_tree_role",
            "Tree role (0 leaf / 1 relay / 2 root; degraded reads 0)",
            ("name",),
        )
        self.tree_tier = g(
            "crdt_tree_tier", "This replica's tier (distance from root)",
            ("name",),
        )
        self.tree_members = g(
            "crdt_tree_members", "Members in the derived tree", ("name",)
        )
        self.tree_degraded = g(
            "crdt_tree_degraded",
            "1 while degraded to flat gossip (0 tree-routed)", ("name",),
        )
        self._on_tree_relay = _with_batch(
            self._on_tree_relay, self._on_tree_relay_batch
        )
        # monotone by construction (a tracing cache only grows), hence
        # the _total name despite the set-to-absolute gauge primitive:
        # the jitcache audit reports absolute per-root compile counts,
        # not deltas, so a bridge attaching mid-process still exports
        # the true totals
        self.jit_compiles = g(
            "crdt_jit_compiles_total",
            "XLA compiles per named jit entry root (tracing-cache size)",
            ("name",),
        )
        # same monotone-gauge-as-_total convention: the transfer ledger
        # audit reports absolute per-site crossing/byte totals
        self.transfers = g(
            "crdt_transfers_total",
            "Device-host crossings per audited transfer site",
            ("site",),
        )
        self.transfer_bytes = g(
            "crdt_transfer_bytes_total",
            "Bytes moved across the device-host boundary per audited site",
            ("site",),
        )
        # fault-injection trips (crdtlint v6 runtime half): a real
        # counter — the faults registry emits one FAULT_TRIP per trip,
        # not absolute totals, so chaos runs attach mid-process and see
        # only their own schedule's trips
        self.fault_trips = c(
            "crdt_fault_trips_total",
            "Injected-fault trips per labelled fault-point site",
            ("site",),
        )
        # batchable handlers for the two per-message hot families: the
        # grouped ingest path emits them via telemetry.execute_many, and
        # the batch form folds the whole group under ONE registry-lock
        # acquire and one label resolve — per-message handler dispatch
        # is the dominant enabled-telemetry cost at coalesce depth 16
        self._on_sync_done = _with_batch(
            self._on_sync_done, self._on_sync_done_batch
        )
        self._on_sync_round = _with_batch(
            self._on_sync_round, self._on_sync_round_batch
        )

    # -- subscription table ---------------------------------------------

    def _table(self) -> list:
        """Event tuple -> handler. crdtlint OBS001 cross-checks this
        table against every event declared in ``runtime/telemetry.py``
        — dropping a row turns the gate red (mutation-tested)."""
        return [
            (telemetry.SYNC_DONE, self._on_sync_done),
            (telemetry.CAPACITY_GROWN, self._on_capacity_grown),
            (telemetry.SYNC_ROUND, self._on_sync_round),
            (telemetry.INGEST_COALESCE, self._on_ingest_coalesce),
            (telemetry.WAL_APPEND, self._on_wal_append),
            (telemetry.WAL_COMPACT, self._on_wal_compact),
            (telemetry.WAL_RECOVER, self._on_wal_recover),
            (telemetry.CATCHUP_CHUNK, self._on_catchup_chunk),
            (telemetry.CATCHUP_DONE, self._on_catchup_done),
            (telemetry.FLEET_DISPATCH, self._on_fleet_dispatch),
            (telemetry.FLEET_EGRESS, self._on_fleet_egress),
            (telemetry.MESH_EXCHANGE, self._on_mesh_exchange),
            (telemetry.JIT_COMPILE, self._on_jit_compile),
            (telemetry.TRANSFER, self._on_transfer),
            (telemetry.FAULT_TRIP, self._on_fault_trip),
            (telemetry.SERVE_ADMIT, self._on_serve_admit),
            (telemetry.SERVE_SHED, self._on_serve_shed),
            (telemetry.SERVE_READ, self._on_serve_read),
            (telemetry.TREE_RELAY, self._on_tree_relay),
            (telemetry.TREE_TOPOLOGY, self._on_tree_topology),
        ]

    def attach(self) -> "MetricsBridge":
        if not self._attached:
            rows = self._table()
            # runtime mirror of the static OBS001 gate (for deployments
            # that never run tests/lint): a declared event without a
            # subscription row would silently read zero forever
            missing = set(telemetry.declared_events()) - {ev for ev, _h in rows}
            if missing:
                logger.warning(
                    "metrics bridge table misses declared telemetry "
                    "event(s) %s — their metrics will read zero", missing,
                )
            for event, handler in rows:
                telemetry.attach(event, handler)
            self._attached = True
        return self

    def detach(self) -> None:
        if self._attached:
            for event, handler in self._table():
                telemetry.detach(event, handler)
            self._attached = False

    # -- handlers --------------------------------------------------------

    # Handlers run on whatever thread emitted the event (replica loop,
    # fleet tick, TCP serve) — every update happens under the one
    # registry lock, folded per EVENT (one acquire, N ``*_held``
    # updates). Label tuples are built inline as canonical str tuples
    # (``str(meta[...])`` only when a caller passed a non-str).

    @staticmethod
    def _s(v) -> str:
        return v if type(v) is str else str(v)

    def _on_sync_done(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("name")),)
        with self._lock:
            self.sync_done._inc_held(lb)
            self.keys_updated._inc_held(lb, meas.get("keys_updated_count", 0))

    def _on_sync_done_batch(self, _event, meas_list, meta) -> None:
        lb = (self._s(meta.get("name")),)
        keys = 0
        for meas in meas_list:
            keys += meas.get("keys_updated_count", 0)
        with self._lock:
            self.sync_done._inc_held(lb, len(meas_list))
            self.keys_updated._inc_held(lb, keys)

    def _on_capacity_grown(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("name")),)
        with self._lock:
            self.capacity_grown._inc_held(lb)
            self.capacity._set_held(lb, meas.get("capacity", 0))

    def _on_sync_round(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("name")), self._s(meta.get("plane", "host")))
        g = meas.get
        with self._lock:
            self.sync_rounds._inc_held(lb)
            self.sync_seconds._observe_held(lb, g("duration_s", 0.0))
            self.sync_entries._inc_held(lb, g("entries", 0))
            self.sync_buckets._inc_held(lb, g("buckets", 0))

    def _on_sync_round_batch(self, _event, meas_list, meta) -> None:
        lb = (self._s(meta.get("name")), self._s(meta.get("plane", "host")))
        entries = buckets = 0
        with self._lock:
            observe = self.sync_seconds._observe_held
            for meas in meas_list:
                g = meas.get
                observe(lb, g("duration_s", 0.0))
                entries += g("entries", 0)
                buckets += g("buckets", 0)
            self.sync_rounds._inc_held(lb, len(meas_list))
            self.sync_entries._inc_held(lb, entries)
            self.sync_buckets._inc_held(lb, buckets)

    def _on_ingest_coalesce(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("name")),)
        g = meas.get
        with self._lock:
            self.ingest_dispatches._inc_held(lb)
            self.ingest_messages._inc_held(lb, g("depth", 0))
            self.ingest_depth._observe_held(lb, g("depth", 0))
            self.ingest_seconds._observe_held(lb, g("duration_s", 0.0))

    def _on_wal_append(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("name")),)
        g = meas.get
        with self._lock:
            self.wal_records._inc_held(lb, g("records", 1))
            self.wal_bytes._inc_held(lb, g("bytes", 0))
            self.wal_seconds._observe_held(lb, g("duration_s", 0.0))

    def _on_wal_compact(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("name")),)
        with self._lock:
            self.wal_compactions._inc_held(lb)
            self.wal_reclaimed._inc_held(lb, meas.get("bytes_reclaimed", 0))

    def _on_wal_recover(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("name")),)
        with self._lock:
            self.wal_recover_records._inc_held(lb, meas.get("records", 0))
            self.wal_recover_seconds._observe_held(
                lb, meas.get("duration_s", 0.0)
            )

    def _on_catchup_chunk(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("name")), self._s(meta.get("role", "")))
        g = meas.get
        with self._lock:
            self.catchup_chunks._inc_held(lb)
            self.catchup_bytes._inc_held(lb, g("bytes", 0))
            self.catchup_entries._inc_held(lb, g("entries", 0))

    def _on_catchup_done(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("name")),)
        g = meas.get
        with self._lock:
            self.catchup_streams._inc_held(lb)
            self.catchup_seconds._observe_held(lb, g("duration_s", 0.0))
            self.catchup_horizon._inc_held(lb, g("horizon_fallback", 0))

    def _on_fleet_dispatch(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("fleet")),)
        g = meas.get
        with self._lock:
            self.fleet_dispatches._inc_held(lb)
            self.fleet_messages._inc_held(lb, g("messages", 0))
            self.fleet_seconds._observe_held(lb, g("duration_s", 0.0))
            self.fleet_occupancy._observe_held(lb, g("replicas", 0))
            self.fleet_rows._inc_held(lb, g("rows", 0))
            self.fleet_padded_rows._inc_held(lb, g("padded_rows", 0))

    def _on_fleet_egress(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("fleet")),)
        g = meas.get
        with self._lock:
            self.fleet_egress_ticks._inc_held(lb)
            self.fleet_egress_dispatches._inc_held(lb, g("dispatches", 0))
            self.fleet_egress_members._observe_held(lb, g("members", 0))
            self.fleet_egress_frames._inc_held(lb, g("frames", 0))
            self.fleet_egress_frame_members._inc_held(lb, g("frame_members", 0))
            self.fleet_egress_seconds._observe_held(lb, g("duration_s", 0.0))

    def _on_mesh_exchange(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("fleet")),)
        g = meas.get
        with self._lock:
            self.mesh_exchanges._inc_held(lb, g("exchanges", 0))
            self.mesh_intra_entries._inc_held(lb, g("intra_entries", 0))
            self.mesh_fallback_entries._inc_held(lb, g("fallback_entries", 0))
            self.mesh_permuted_bytes._inc_held(lb, g("permuted_bytes", 0))

    def _on_jit_compile(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("name")),)
        with self._lock:
            self.jit_compiles._set_held(lb, meas.get("compiles", 0))

    def _on_transfer(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("site")),)
        with self._lock:
            self.transfers._set_held(lb, meas.get("crossings", 0))
            self.transfer_bytes._set_held(lb, meas.get("bytes", 0))

    def _on_fault_trip(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("site")),)
        with self._lock:
            self.fault_trips._inc_held(lb, meas.get("trips", 1))

    def _on_serve_admit(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("name")),)
        g = meas.get
        with self._lock:
            self.serve_commits._inc_held(lb)
            self.serve_admitted._inc_held(lb, g("ops", 0))
            self.serve_depth._observe_held(lb, g("ops", 0))
            self.serve_commit_seconds._observe_held(lb, g("duration_s", 0.0))

    def _on_serve_shed(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("name")), self._s(meta.get("reason", "")))
        with self._lock:
            self.serve_shed._inc_held(lb, meas.get("ops", 1))

    def _on_tree_relay(self, _event, meas, meta) -> None:
        name = self._s(meta.get("name"))
        lb = (name,)
        tier_lb = (name, self._s(meta.get("tier", "0")))
        g = meas.get
        with self._lock:
            self.tree_reemits._inc_held(lb)
            # continuation emissions of a truncated window carry no
            # depth sample — only completed windows shape the histogram
            depth = g("depth")
            if depth is not None:
                self.tree_coalesce_depth._observe_held(lb, depth)
            self.tree_entries_per_reemit._observe_held(lb, g("entries", 0))
            self.tree_tx_bytes._inc_held(tier_lb, g("tx_bytes", 0))
            rx = g("rx_bytes", 0)
            if rx:
                self.tree_rx_bytes._inc_held(tier_lb, rx)

    def _on_tree_relay_batch(self, _event, meas_list, meta) -> None:
        name = self._s(meta.get("name"))
        lb = (name,)
        tier_lb = (name, self._s(meta.get("tier", "0")))
        tx = rx = 0
        with self._lock:
            depth_obs = self.tree_coalesce_depth._observe_held
            entries_obs = self.tree_entries_per_reemit._observe_held
            for meas in meas_list:
                g = meas.get
                depth = g("depth")
                if depth is not None:
                    depth_obs(lb, depth)
                entries_obs(lb, g("entries", 0))
                tx += g("tx_bytes", 0)
                rx += g("rx_bytes", 0)
            self.tree_reemits._inc_held(lb, len(meas_list))
            self.tree_tx_bytes._inc_held(tier_lb, tx)
            if rx:
                self.tree_rx_bytes._inc_held(tier_lb, rx)

    def _on_tree_topology(self, _event, meas, meta) -> None:
        lb = (self._s(meta.get("name")),)
        g = meas.get
        with self._lock:
            self.tree_depth._set_held(lb, g("depth", 0))
            self.tree_fanout._set_held(lb, g("fanout", 0))
            self.tree_role._set_held(lb, g("role", 0))
            self.tree_tier._set_held(lb, g("tier", 0))
            self.tree_members._set_held(lb, g("members", 0))
            self.tree_degraded._set_held(lb, g("degraded", 0))

    def _on_serve_read(self, _event, meas, meta) -> None:
        name = self._s(meta.get("name"))
        lb = (name, self._s(meta.get("mode", "keys")))
        g = meas.get
        with self._lock:
            self.serve_reads._inc_held(lb, g("reads", 1))
            self.serve_read_seconds._observe_held((name,), g("duration_s", 0.0))
            retries = g("retries", 0)
            if retries:
                self.serve_read_retries._inc_held((name,), retries)


# ----------------------------------------------------------------------
# flight recorder

class FlightRecorder:
    """Bounded ring buffer of recent structured events — the per-replica
    black box. ``record`` is a lock + list append (µs-scale next to a
    merge dispatch); the ring drops the OLDEST event past ``capacity``
    and counts drops so a post-mortem knows how much history it holds.
    """

    def __init__(self, name: str, capacity: int = 256):
        if capacity < 1:
            raise ValueError("flight recorder capacity must be >= 1")
        self.name = name
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._buf: list[tuple] = []
        self._next = 0  # monotone event id (== total events ever recorded)

    def record(self, kind: str, **fields) -> None:
        t = time.time()
        with self._lock:
            self._buf.append((t, self._next, kind, fields))
            self._next += 1
            if len(self._buf) > self.capacity:
                del self._buf[0 : len(self._buf) - self.capacity]

    def events(self, kind: str | None = None) -> list[dict]:
        """Snapshot, oldest first (queryable in tests / chaos drivers)."""
        with self._lock:
            buf = list(self._buf)
        return [
            {"t": t, "id": i, "kind": k, **f}
            for t, i, k, f in buf
            if kind is None or k == kind
        ]

    def dropped(self) -> int:
        with self._lock:
            return self._next - len(self._buf)

    def events_recorded(self) -> int:
        """Total events ever recorded (monotone; the ring holds the
        newest ``capacity`` of them)."""
        with self._lock:
            return self._next

    def dump(self, log=None, path: str | None = None) -> int:
        """Write the ring through the logger (the crash black box);
        returns the number of events dumped.

        Exception-safe per event: a logging handler (or an unprintable
        field value) raising mid-dump must not lose the REMAINING ring
        events — the black box's whole value is the events nearest the
        crash, which are the last ones dumped. With ``path`` the ring
        is also appended to that file as JSON lines (best-effort,
        ``repr`` fallback for non-JSON fields), so chaos runs keep the
        black box after the process dies and the log stream with it."""
        log = log or logger
        events = self.events()
        try:
            log.error(
                "flight recorder %r: %d event(s), %d older dropped",
                self.name, len(events), self.dropped(),
            )
        except Exception:
            pass  # a dying log sink must not stop the event dump below
        for e in events:
            try:
                fields = {k: v for k, v in e.items() if k not in ("t", "id", "kind")}
                log.error("flight %r #%d %.6f %s %s", self.name, e["id"], e["t"], e["kind"], fields)
            except Exception:
                continue  # skip the poison event, keep the rest
        if path is not None:
            try:
                import json as _json

                with open(path, "a", encoding="utf-8") as f:
                    for e in events:
                        f.write(_json.dumps(
                            {"replica": self.name, **e}, default=repr,
                        ) + "\n")
            except OSError:
                logger.debug("flight dump to %r failed", path, exc_info=True)
        return len(events)


# ----------------------------------------------------------------------
# replication-lag tracing

class LagTracer:
    """Per-peer convergence lag from dots already on the wire.

    The origin samples local commits (every ``sample_every``-th seq) as
    ``(origin addr, seq) -> commit time``; a peer reports visibility the
    moment its applied watermark of that origin advances (walk-equality
    ack on a round opener, or an applied log-shipping chunk — both
    existing protocol events carrying the originator address and seq,
    so the trace needs ZERO wire changes). The lag histogram is labeled
    ``(origin, peer)``; a parallel histogram counts the origin's sync
    ROUNDS the sample waited through — the propagation-rounds
    measurement hierarchical anti-entropy topologies are judged by.

    Pending samples are bounded per origin (oldest evicted — a sample
    no peer ever covers must not leak), origins bounded LRU. A sample
    stays pending until evicted so EVERY peer's first coverage of it
    yields one observation; samples are kept as parallel
    ascending-seq lists, so each watermark advance bisects to its
    ``(origin, peer)`` covered floor and touches only the newly
    covered span — O(log pending + newly covered), never a rescan of
    the whole window. All state sits under one tracer lock; histogram
    updates happen after it is released (the registry lock never nests
    inside it).
    """

    MAX_PENDING = 512
    MAX_ORIGINS = 4096

    def __init__(self, registry: Registry, *, sample_every: int = 16):
        if sample_every < 1:
            raise ValueError("sample_every must be >= 1")
        self.sample_every = int(sample_every)
        self._lock = threading.Lock()
        #: origin addr -> parallel ([seq...], [(t_commit, rounds)...])
        #: lists in ascending seq order (commits are monotone per
        #: origin; a backward seq means the origin restarted and the
        #: old incarnation's samples/floors are dropped)
        self._pending: dict[Any, tuple[list, list]] = {}
        #: (origin, peer) -> highest seq this peer already covered; the
        #: skip floor that makes repeat watermark advances cheap (LRU
        #: bounded — an evicted floor can at worst double-count still-
        #: pending old samples for that one pair)
        self._floor: dict[tuple, int] = {}
        #: origin addr -> sync rounds opened by that origin
        self._rounds: dict[Any, int] = {}
        self.lag = registry.histogram(
            "crdt_replication_lag_seconds",
            "Local-commit to remote-visibility lag per (origin, peer)",
            ("origin", "peer"),
        )
        self.rounds = registry.histogram(
            "crdt_propagation_rounds",
            "Origin sync rounds between commit and remote visibility",
            ("origin", "peer"), buckets=COUNT_BUCKETS,
        )

    def note_commit(self, origin, seq: int, now: float | None = None) -> None:
        """Called by the origin after a seq advance (sampled here, so
        the hot path pays one modulo when no sample is due)."""
        if seq % self.sample_every:
            return
        now = time.monotonic() if now is None else now
        with self._lock:
            pend = self._pending.get(origin)
            if pend is None:
                if len(self._pending) >= self.MAX_ORIGINS:
                    self._pending.pop(next(iter(self._pending)))
                pend = self._pending[origin] = ([], [])
            seqs, samples = pend
            s = int(seq)
            if seqs and s <= seqs[-1]:
                # backward seq: the origin restarted (recovery resumes
                # from a snapshot) — the old incarnation's samples and
                # floors describe commits that no longer exist
                seqs.clear()
                samples.clear()
                for k in [k for k in self._floor if k[0] == origin]:
                    del self._floor[k]
            # the sample stays pending until evicted by the bound, so
            # EVERY peer's first coverage of it yields one observation
            # (popping on first match would hand all the lag evidence
            # to whichever peer converges first)
            seqs.append(s)
            samples.append((now, self._rounds.get(origin, 0)))
            if len(seqs) > self.MAX_PENDING:
                excess = len(seqs) - self.MAX_PENDING
                del seqs[:excess]
                del samples[:excess]

    def note_round(self, origin) -> None:
        """Called by the origin when it opens a sync round (the
        propagation-round clock)."""
        with self._lock:
            self._rounds[origin] = self._rounds.get(origin, 0) + 1
            while len(self._rounds) > self.MAX_ORIGINS:
                self._rounds.pop(next(iter(self._rounds)))

    def note_visible(self, peer, origin, seq: int, now: float | None = None) -> None:
        """Called by ``peer`` when its applied watermark of ``origin``
        advances to ``seq``: every pending sample in ``(floor, seq]`` —
        the span this peer has not yet covered — is now remotely
        visible there (the samples stay pending for the OTHER peers;
        each peer's first coverage counts exactly once, and bisecting
        the ascending seq list to the floor makes the usual
        nothing-new advance O(log pending))."""
        if peer == origin:
            return  # self-visibility is not replication lag
        now = time.monotonic() if now is None else now
        with self._lock:
            pend = self._pending.get(origin)
            if pend is None or not pend[0]:
                return
            key = (origin, peer)
            floor = self._floor.get(key, 0)
            if seq <= floor:
                return  # already covered through here
            rounds_now = self._rounds.get(origin, 0)
            seqs, samples = pend
            lo = bisect.bisect_right(seqs, floor)
            hi = bisect.bisect_right(seqs, int(seq))
            matched = samples[lo:hi]
            self._floor.pop(key, None)  # pop+reinsert: LRU recency
            self._floor[key] = seq
            while len(self._floor) > self.MAX_ORIGINS:
                self._floor.pop(next(iter(self._floor)))
        labels = (str(origin), str(peer))
        for t_commit, rounds_at in matched:
            self.lag.observe(max(0.0, now - t_commit), labels)
            self.rounds.observe(rounds_now - rounds_at, labels)

    def peers_seen(self) -> set:
        """Peer label values with at least one lag sample (bench gate:
        the per-peer histogram must be populated for every peer)."""
        return {lb[1] for lb in self.lag.label_sets()}


# ----------------------------------------------------------------------
# the facade behind the ``obs=`` knob

class Observability:
    """One observability plane: registry + always-attached bridge + lag
    tracer + flight-recorder factory + the varz / health source tables
    the HTTP endpoint (``obs_server.ObsServer``) serves. Pass an
    instance (or ``True`` for the process-wide default) as ``obs=`` to
    ``start_link`` / ``start_fleet``.

    ONE plane per process is the expected shape (``obs=True``): the
    telemetry handler table is process-global, so a second plane's
    bridge folds EVERY replica's events — including replicas started
    with ``obs=None``, whose hot paths then also pay enabled-telemetry
    costs while any plane exists in the process. Use distinct planes
    only for isolated registries in tests, and ``close()`` them."""

    def __init__(
        self,
        *,
        registry: Registry | None = None,
        lag_sample_every: int = 16,
        flight_capacity: int = 256,
    ):
        self.registry = registry or Registry()
        self.bridge = MetricsBridge(self.registry).attach()
        self.lag = LagTracer(self.registry, sample_every=lag_sample_every)
        self.flight_capacity = int(flight_capacity)
        self._lock = threading.Lock()
        self._varz_sources: dict[str, Callable[[], dict]] = {}
        self._health_checks: dict[str, Callable[[], dict]] = {}
        self._server = None
        # replica/fleet-polled gauges (collector-fed: scrape-time cost)
        g = self.registry.gauge
        self._g_mailbox = g(
            "crdt_mailbox_depth", "Queued messages in the replica mailbox",
            ("name",),
        )
        self._g_seq = g(
            "crdt_sequence_number", "Replica applied-batch sequence number",
            ("name",),
        )
        self._g_payloads = g(
            "crdt_payloads", "Host payload dict size", ("name",)
        )
        self._g_outstanding = g(
            "crdt_outstanding_syncs", "In-flight sync rounds", ("name",)
        )
        self._g_wal_segments = g(
            "crdt_wal_segments", "WAL segment files on disk", ("name",)
        )
        self._g_wal_bytes = g(
            "crdt_wal_bytes", "WAL bytes on disk", ("name",)
        )
        self._g_wal_horizon = g(
            "crdt_wal_horizon", "WAL log-shipping horizon seq", ("name",)
        )
        self._g_fleet_occupancy = g(
            "crdt_fleet_avg_occupancy", "Mean replicas per fleet dispatch",
            ("fleet",),
        )
        self._g_fleet_fill = g(
            "crdt_fleet_ragged_fill_ratio",
            "Real/padded row ratio of fleet dispatches", ("fleet",),
        )
        self._g_fleet_ticks = g(
            "crdt_fleet_ticks", "Fleet scheduler ticks (polled)", ("fleet",)
        )
        self._g_fleet_egress_mpf = g(
            "crdt_fleet_egress_members_per_frame",
            "Mean member replicas per shipped FleetFrameMsg", ("fleet",),
        )
        self._g_fleet_egress_fpt = g(
            "crdt_fleet_egress_frames_per_tick",
            "Mean FleetFrameMsg envelopes per egress tick", ("fleet",),
        )
        self._g_fleet_egress_occ = g(
            "crdt_fleet_egress_bucket_occupancy",
            "Mean members per batched egress extraction bucket", ("fleet",),
        )
        self._g_serve_pending = g(
            "crdt_serve_pending_ops",
            "Write ops queued or in flight in the serving front door",
            ("name",),
        )
        self._g_serve_overloaded = g(
            "crdt_serve_overloaded",
            "1 while the serving front door is shedding (0 healthy)",
            ("name",),
        )
        self._g_mesh_shards = g(
            "crdt_mesh_shards",
            "Mesh shard count of a mesh-mode fleet (0 = vmap mode)",
            ("fleet",),
        )
        self._g_mesh_mps = g(
            "crdt_mesh_members_per_shard",
            "Mean fleet members per mesh shard", ("fleet",),
        )
        # compile-cache audit (ISSUE 12): a scrape-time collector runs
        # the jitcache audit, which re-publishes EVERY named jit entry
        # root's absolute tracing-cache size through JIT_COMPILE
        # telemetry on each scrape (idempotent gauge sets — so any
        # plane, attached at any point, exports the true totals); the
        # bridge row above folds those into
        # crdt_jit_compiles_total{name=...}. Collector-fed so an idle
        # process pays nothing between scrapes.
        from delta_crdt_ex_tpu.utils import jitcache as _jitcache

        def _collect_jit_compiles() -> None:
            _jitcache.audit()

        self._jit_collector = _collect_jit_compiles
        self.registry.register_collector(_collect_jit_compiles)
        self.add_varz_source("jitcache", _jitcache.varz)
        # transfer-ledger audit (ISSUE 17): same collector-fed shape —
        # each scrape re-publishes every audited site's absolute
        # crossing/byte totals through TRANSFER telemetry; the bridge
        # folds them into crdt_transfers_total{site=...} /
        # crdt_transfer_bytes_total{site=...}
        from delta_crdt_ex_tpu.utils import transfers as _transfers

        def _collect_transfers() -> None:
            _transfers.audit()

        self._transfer_collector = _collect_transfers
        self.registry.register_collector(_collect_transfers)
        self.add_varz_source("transfers", _transfers.varz)
        self._c_drained = self.registry.counter(
            "crdt_drained_messages_total",
            "Messages drained by the replica event loop", ("name",),
        )
        self._h_drain = self.registry.histogram(
            "crdt_drain_seconds", "Wall time of one mailbox drain pass",
            ("name",),
        )
        self._g_tx_bytes = g(
            "crdt_transport_tx_bytes", "Transport bytes sent", ("transport",)
        )
        self._g_rx_bytes = g(
            "crdt_transport_rx_bytes", "Transport bytes received", ("transport",)
        )
        self._g_txq_bytes = g(
            "crdt_transport_queue_bytes", "Bytes queued on sender connections",
            ("transport",),
        )

    # -- factory hooks ---------------------------------------------------

    def recorder(self, name: str) -> FlightRecorder:
        return FlightRecorder(name, capacity=self.flight_capacity)

    def record_drain(self, name: str, messages: int, duration_s: float) -> None:
        """Mailbox drain accounting (one call per ``process_pending``
        batch — never per message; both updates under one registry
        lock acquire)."""
        lb = (name if type(name) is str else str(name),)
        with self.registry._lock:
            self._c_drained._inc_held(lb, messages)
            self._h_drain._observe_held(lb, duration_s)

    # -- source registration ---------------------------------------------

    def add_varz_source(self, key: str, fn: Callable[[], dict]) -> None:
        with self._lock:
            self._varz_sources[key] = fn

    def add_health_check(self, key: str, fn: Callable[[], dict]) -> None:
        with self._lock:
            self._health_checks[key] = fn

    def remove_source(self, key: str) -> None:
        with self._lock:
            self._varz_sources.pop(key, None)
            self._health_checks.pop(key, None)

    def register_replica(self, rep) -> None:
        """Wire one replica into the plane: varz + health sources plus a
        scrape-time collector polling its stats/mailbox/WAL gauges."""
        key = f"replica:{rep.name}"
        self.add_varz_source(key, rep.obs_varz)
        self.add_health_check(key, rep.health)
        name_lb = (rep.name,)

        def collect() -> None:
            st = rep.stats()
            self._g_seq.set(st["sequence_number"], name_lb)
            self._g_payloads.set(st["payloads"], name_lb)
            self._g_outstanding.set(st["outstanding_syncs"], name_lb)
            depth_fn = getattr(rep.transport, "queue_depth", None)
            if depth_fn is not None:
                self._g_mailbox.set(depth_fn(rep.addr), name_lb)
            wal = st.get("wal")
            if wal is not None:
                self._g_wal_segments.set(wal["segments"], name_lb)
                self._g_wal_horizon.set(wal["horizon"], name_lb)
                self._g_wal_bytes.set(rep.wal_size_bytes(), name_lb)
            tstats_fn = getattr(rep.transport, "transport_stats", None)
            if tstats_fn is not None:
                ts = tstats_fn()
                tl = (ts["endpoint"],)
                self._g_tx_bytes.set(ts["tx_bytes"], tl)
                self._g_rx_bytes.set(ts["rx_bytes"], tl)
                self._g_txq_bytes.set(ts["queue_bytes"], tl)

        rep._obs_collector = collect
        self.registry.register_collector(collect)

    def unregister_replica(self, rep) -> None:
        self.remove_source(f"replica:{rep.name}")
        collect = getattr(rep, "_obs_collector", None)
        if collect is not None:
            self.registry.unregister_collector(collect)
            rep._obs_collector = None
        # a still-attached front door unwires with its replica: its
        # collector would otherwise keep re-setting the serve gauges
        # removed below (the crdt_serve_* cleanup contract, ISSUE 14)
        fd = getattr(rep, "_frontdoor", None)
        if fd is not None:
            self.unregister_serve(fd)
        for gauge in (
            self._g_mailbox, self._g_seq, self._g_payloads,
            self._g_outstanding, self._g_wal_segments, self._g_wal_bytes,
            self._g_wal_horizon,
            # tree-topology gauges (ISSUE 15) are event-fed by the
            # bridge but lifecycle-owned here: a stopped tree-mode
            # replica must not scrape its last role/depth forever
            self.bridge.tree_depth, self.bridge.tree_fanout,
            self.bridge.tree_role, self.bridge.tree_tier,
            self.bridge.tree_members, self.bridge.tree_degraded,
        ):
            # serve gauges are NOT in this loop: unregister_serve (the
            # register_serve pair, invoked above and by Frontdoor.close)
            # owns their cleanup unambiguously
            gauge.remove((rep.name,))

    # -- serving plane (ISSUE 14) ----------------------------------------

    def register_serve(self, fd) -> None:
        """Wire one serving front door into the plane: ``serve:{name}``
        varz + health sources (the health check is what flips
        ``/healthz`` to 503 while the plane sheds) plus a scrape-time
        collector polling the pending/overloaded gauges."""
        key = f"serve:{fd.name}"
        self.add_varz_source(key, fd.obs_varz)
        self.add_health_check(key, fd.health)
        name_lb = (fd.name if type(fd.name) is str else str(fd.name),)

        def collect() -> None:
            st = fd.stats()
            self._g_serve_pending.set(st["pending_ops"], name_lb)
            self._g_serve_overloaded.set(
                1.0 if st["overloaded"] else 0.0, name_lb
            )

        fd._obs_collector = collect
        self.registry.register_collector(collect)

    def unregister_serve(self, fd) -> None:
        """Unwire a front door (close / replica teardown): sources,
        collector and gauges all go — a closed plane must not scrape
        as a stale last value (the unregister-cleanup contract)."""
        self.remove_source(f"serve:{fd.name}")
        collect = getattr(fd, "_obs_collector", None)
        if collect is not None:
            self.registry.unregister_collector(collect)
            fd._obs_collector = None
        name_lb = (fd.name if type(fd.name) is str else str(fd.name),)
        self._g_serve_pending.remove(name_lb)
        self._g_serve_overloaded.remove(name_lb)

    def register_fleet(self, fleet) -> None:
        key = f"fleet:{id(fleet):x}"
        self.add_varz_source(key, fleet.obs_varz)
        self.add_health_check(key, fleet.health)
        fleet_lb = (str(id(fleet)),)

        def collect() -> None:
            st = fleet.stats()
            self._g_fleet_occupancy.set(st["avg_occupancy"], fleet_lb)
            self._g_fleet_fill.set(st["ragged_fill_ratio"], fleet_lb)
            self._g_fleet_ticks.set(st["ticks"], fleet_lb)
            eg = st["egress"]
            self._g_fleet_egress_mpf.set(eg["members_per_frame"], fleet_lb)
            self._g_fleet_egress_fpt.set(eg["frames_per_tick"], fleet_lb)
            self._g_fleet_egress_occ.set(eg["avg_bucket_occupancy"], fleet_lb)
            mesh = st.get("mesh")
            if mesh is not None:
                self._g_mesh_shards.set(mesh["shards"], fleet_lb)
                self._g_mesh_mps.set(mesh["members_per_shard"], fleet_lb)

        fleet._obs_collector = collect
        self.registry.register_collector(collect)

    def unregister_fleet(self, fleet) -> None:
        self.remove_source(f"fleet:{id(fleet):x}")
        collect = getattr(fleet, "_obs_collector", None)
        if collect is not None:
            self.registry.unregister_collector(collect)
            fleet._obs_collector = None
        # fleet front door: per-member serve gauges unwire with the
        # fleet (each member Frontdoor registered under its own name)
        fd = getattr(fleet, "_frontdoor", None)
        if fd is not None:
            for member_fd in fd.members:
                self.unregister_serve(member_fd)
        for gauge in (
            self._g_fleet_occupancy, self._g_fleet_fill, self._g_fleet_ticks,
            self._g_fleet_egress_mpf, self._g_fleet_egress_fpt,
            self._g_fleet_egress_occ, self._g_mesh_shards, self._g_mesh_mps,
        ):
            # same contract as unregister_replica: a stopped fleet must
            # not scrape as a stale last value forever
            gauge.remove((str(id(fleet)),))

    # -- snapshots the HTTP endpoint serves -------------------------------

    def varz(self) -> dict:
        """The unified JSON snapshot: every registered source's stats
        under one schema (``Replica.stats()`` / ``Fleet.stats()`` / WAL
        stats are UNCHANGED — this surface is additive, MIGRATING.md)."""
        with self._lock:
            sources = dict(self._varz_sources)
        out: dict = {"sources": {}}
        for key, fn in sources.items():
            try:
                out["sources"][key] = fn()
            except Exception as e:  # a dying source must not 500 the page
                out["sources"][key] = {"error": repr(e)}
        return out

    def health(self) -> tuple[bool, dict]:
        """Aggregate health: ``(all_ok, {source: check})``."""
        with self._lock:
            checks = dict(self._health_checks)
        detail: dict = {}
        ok = True
        for key, fn in checks.items():
            try:
                res = fn()
            except Exception as e:
                res = {"ok": False, "error": repr(e)}
            detail[key] = res
            ok = ok and bool(res.get("ok"))
        return ok, detail

    # -- HTTP export -------------------------------------------------------

    def serve(self, host: str = "127.0.0.1", port: int = 0):
        """Start (idempotently) the per-process HTTP endpoint serving
        ``/metrics`` + ``/healthz`` + ``/varz`` for this plane; returns
        the :class:`~delta_crdt_ex_tpu.runtime.obs_server.ObsServer`."""
        from delta_crdt_ex_tpu.runtime.obs_server import ObsServer

        with self._lock:
            if self._server is None:
                self._server = ObsServer(self, host=host, port=port).start()
            return self._server

    def close(self) -> None:
        """Detach the bridge and stop the HTTP endpoint (tests; the
        telemetry handler table is process-global, so a discarded plane
        must not keep consuming events)."""
        self.bridge.detach()
        # same contract as unregister_replica/_fleet: a closed plane
        # must not keep running the compile-cache audit at scrape time
        self.registry.unregister_collector(self._jit_collector)
        self.remove_source("jitcache")
        self.registry.unregister_collector(self._transfer_collector)
        self.remove_source("transfers")
        with self._lock:
            server, self._server = self._server, None
        if server is not None:
            server.stop()


_default_obs: Observability | None = None
_default_lock = threading.Lock()


def default_observability() -> Observability:
    """The process-wide plane ``obs=True`` resolves to."""
    global _default_obs
    with _default_lock:
        if _default_obs is None:
            _default_obs = Observability()
        return _default_obs


def resolve_obs(obs) -> Observability | None:
    """``obs=`` knob semantics: ``None``/``False`` disabled, ``True``
    the process default, an :class:`Observability` used as-is."""
    if obs is None or obs is False:
        return None
    if obs is True:
        return default_observability()
    if isinstance(obs, Observability):
        return obs
    raise TypeError(
        f"obs= expects True/False/None or an Observability, got {obs!r}"
    )


__all__ = [
    "COUNT_BUCKETS",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "LATENCY_BUCKETS",
    "LagTracer",
    "MetricsBridge",
    "Observability",
    "Registry",
    "default_observability",
    "resolve_obs",
]
