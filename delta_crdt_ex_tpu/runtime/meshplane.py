"""Intra-mesh delivery plane (ISSUE 13): sync-tick messages whose
destination member lives on the same fleet mesh move as device-side
collectives instead of bouncing through the host transport.

A mesh-mode :class:`~delta_crdt_ex_tpu.runtime.fleet.Fleet` keeps its
members' stacked states replica-sharded over a 1-D device mesh; its
sync-tick egress, however, still produced per-member ``EntriesMsg``
sends that a co-located member would receive through the host mailbox —
on real hardware a device→host→device bounce for bytes that both live
and are consumed on the mesh. This module is the fleet-frame idea
(:class:`~delta_crdt_ex_tpu.runtime.sync.FleetFrameMsg`, PR 10) turned
inward: a tick's outbound messages bound for a co-mesh member are
buffered, their slice columns ride one ``lax.ppermute`` rotation per
(shard distance, buffer geometry) group along the ``replicas`` mesh
axis (:func:`delta_crdt_ex_tpu.runtime.transition.mesh_plane_rotate`),
and the per-entry host bookkeeping — message envelopes, payload dicts,
mailbox delivery, and through them WAL records, acks and telemetry at
the receiver — fans out exactly as the TCP path does. Only off-mesh
destinations fall back to the PR 10 frame collector / direct send.

Semantics are bit-for-bit the host path's:

- a rotation moves each entry's columns intact (integer lattice
  columns; a permute changes placement, never values), so the
  delivered ``EntriesMsg`` bodies are byte-identical to a pass-by-
  reference local send;
- ALL buffered messages (openers included — their digest blocks are
  host control metadata and ship as-is) deliver at :meth:`flush` in
  global send order, which is exactly the per-destination arrival
  order the un-planed tick produces (members emit in staged order,
  and nothing else touches the mailboxes mid-tick);
- ``send`` returning True commits the message to the tick's exchange;
  a drop after that (receiver died mid-tick) is the same lossy-
  transport case as a ``_SenderConn``/frame drop — cursors may run
  ahead by one tick and the periodic sync repair re-covers.

Compile discipline: rotation buffers pad their entry-slot axis to a
pow2 tier and group by exact column geometry, so distinct compiles of
the rotate kernel are bounded by (bucket slice geometry) × (mesh size
− 1 shard distances) — the compile-cache audit covers the kernel via
its ``named_jit`` registration, and ``bench.py --fleet --mesh`` gates
zero steady-state compiles in-run.

Transfer discipline (ISSUE 17, the device-resident campaign's first
retirement): the original exchange round-tripped the FULL padded
``[shards, depth, ...]`` buffers through the host twice per group —
``device_put`` on ship, ``device_get`` on deliver. The narrow path
(default) ships each group's entry rows as dense pow2-padded column
stacks plus int32 scatter vectors in ONE audited crossing per tick
(``meshplane.ship_dense``), scatters into the collective layout on
device (:func:`~delta_crdt_ex_tpu.runtime.transition
.mesh_plane_exchange`), and delivers device-resident slices of the
rotated buffers — no ``device_get``. The padded path survives behind
``MeshPlane(narrow=False)`` under its own audited sites
(``meshplane.ship_padded`` / ``meshplane.deliver_padded``) so
``bench.py --mesh`` can hold the before/after ledger delta as
evidence; every crossing either way goes through
:mod:`delta_crdt_ex_tpu.utils.transfers` (crdtlint TRANSFER001).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from delta_crdt_ex_tpu.models.binned import pow2_tier
from delta_crdt_ex_tpu.runtime import sync as sync_proto, transition
from delta_crdt_ex_tpu.utils import transfers

# -- audited device↔host transfer sites (crdtlint TRANSFER001) --
#: legacy padded path: whole [shards, depth, ...] buffers cross twice
#: per exchange group (ship + deliver)
_TR_SHIP_PADDED = transfers.register("meshplane.ship_padded")
_TR_DELIVER_PADDED = transfers.register("meshplane.deliver_padded")
#: narrow path: ONE crossing per tick — dense entry-row stacks plus
#: scatter index vectors; nothing comes back (delivery stays resident)
_TR_SHIP_DENSE = transfers.register("meshplane.ship_dense")

#: EntriesMsg columns that ride the device exchange; ``rows`` stays host
#: control metadata, mirroring ``Replica._slice_arrays`` where the row
#: index vector never leaves the host even on the device plane
_EXCHANGE_COLS = (
    "key", "valh", "ts", "node", "ctr", "alive",
    "ctx_rows", "ctx_lo", "ctx_gid",
)


class MeshPlane:
    """Per-fleet routing table + exchange factory. The fleet assigns
    its member addresses once (:meth:`assign`, re-run on membership
    change); each sync tick then opens one :class:`_TickExchange`
    whose ``send`` is handed to the members' emission tails in place
    of the frame collector's."""

    __slots__ = ("mesh", "shards", "sharding", "narrow", "_members")

    def __init__(self, mesh, *, narrow: bool = True) -> None:
        self.mesh = mesh
        self.shards = int(mesh.devices.size)
        self.sharding = transition.replica_sharding(mesh)
        #: narrow exchange (default): ship dense entry rows once per
        #: tick, scatter into the padded collective layout on device,
        #: and deliver device-resident slices — no device_get at all.
        #: ``narrow=False`` keeps the padded host round-trip path (the
        #: ledger prices both; bench.py --mesh compares them).
        self.narrow = bool(narrow)
        self._members: dict = {}  # addr -> (shard, transport)

    def assign(self, members: list) -> None:
        """Block-assign member ``(addr, transport)`` pairs to mesh
        shards — the same leading-axis block layout the resident
        stacked state shards with (lane tier padded to a shard
        multiple, so every shard owns a contiguous lane block)."""
        lanes = max(pow2_tier(len(members), floor=2), self.shards)
        per = lanes // self.shards
        self._members = {
            addr: (i // per, transport)
            for i, (addr, transport) in enumerate(members)
        }

    def shard_of(self, addr) -> "int | None":
        """The mesh shard hosting ``addr``'s lane, or None when the
        address is not a member of this mesh (the TCP-fallback set)."""
        ent = self._members.get(addr)
        return None if ent is None else ent[0]

    def members_per_shard(self) -> float:
        n = len(self._members)
        return round(n / self.shards, 3) if self.shards else 0.0

    def tree_group(self) -> tuple:
        """The tier-0 cluster key for hierarchical anti-entropy
        (ISSUE 15): every member of this mesh clusters as ONE
        bottom-tier subtree of the gossip spanning tree — an intra-mesh
        hop is a ``ppermute`` rotation, free relative to TCP, so the
        bottom tier of the tree IS the mesh. Deterministic in the
        assigned membership (any process knowing the member set derives
        the same key)."""
        from delta_crdt_ex_tpu.runtime.treesync import fleet_group_key

        return ("mesh",) + fleet_group_key(list(self._members))[1:]

    def begin_tick(self) -> "_TickExchange":
        return _TickExchange(self)


class _TickExchange:
    """One sync tick's buffered exchange: routes sends, runs the
    rotation collectives at :meth:`flush`, and returns the tick's
    delivery stats."""

    __slots__ = ("plane", "entries", "fallback_entries", "passthrough")

    def __init__(self, plane: MeshPlane) -> None:
        self.plane = plane
        self.entries: list = []  # (to, dst_shard, msg) in send order
        self.fallback_entries = 0
        #: co-mesh EntriesMsg the exchange could not carry (device-plane
        #: jax-array bodies from device-pinned members, or a non-member
        #: sender): delivered host-side in order, counted as FALLBACK so
        #: the crdt_mesh_* counters never read an idle plane while it
        #: moved all the traffic
        self.passthrough = 0

    def send_via(self, fallback, to, msg) -> bool:
        """Route one outbound message: co-mesh destinations buffer for
        the tick's exchange; everything else takes ``fallback`` (the
        member's frame-collector send — the unchanged PR 10 path)."""
        shard = self.plane.shard_of(to)
        if shard is None:
            if isinstance(msg, sync_proto.EntriesMsg):
                self.fallback_entries += 1
            return fallback(to, msg)
        self.entries.append((to, shard, msg))
        return True

    def _exchange_groups(self):
        """Collective-eligible entries grouped by (shard distance,
        column geometry); same-shard entries (distance 0) are already
        device-local and need no permute."""
        groups: dict = {}
        same_shard = 0
        for idx, (_to, dst, msg) in enumerate(self.entries):
            if not isinstance(msg, sync_proto.EntriesMsg):
                continue
            src = self.plane.shard_of(getattr(msg, "frm", None))
            if src is None:
                self.passthrough += 1
                continue
            a = msg.arrays
            if not all(
                isinstance(a.get(c), np.ndarray) for c in _EXCHANGE_COLS
            ):
                # device-plane or legacy body: host passthrough, counted
                # as fallback (the exchange did not carry it)
                self.passthrough += 1
                continue
            shift = (dst - src) % self.plane.shards
            if shift == 0:
                same_shard += 1
                continue
            geom = tuple(
                (c, a[c].shape, a[c].dtype.str) for c in _EXCHANGE_COLS
            )
            groups.setdefault((shift, geom), []).append((idx, src, dst, a))
        return groups, same_shard

    @staticmethod
    def _slot_layout(items):
        """Per-entry slot index within its source shard's buffer rows,
        plus the pow2 depth tier covering the busiest source."""
        slot_of: list = []
        per_src: dict = {}
        for _idx, src, _dst, _a in items:
            j = per_src.get(src, 0)
            per_src[src] = j + 1
            slot_of.append(j)
        return slot_of, pow2_tier(max(per_src.values()))

    def _exchange_padded(self, groups):
        """Legacy padded exchange: per group, materialise the full
        ``[shards, depth, ...]`` buffers on the host, ship them, rotate,
        and fetch the whole rotated stack back — two audited crossings
        per group, each moving ``shards × depth`` padded rows for the
        handful the tick actually delivers."""
        delivered_cols: dict = {}
        permuted_bytes = 0
        exchanges = 0
        shards = self.plane.shards
        for (shift, geom), items in groups.items():
            slot_of, depth = self._slot_layout(items)
            bufs = {
                c: np.zeros((shards, depth) + shape, np.dtype(dt))
                for c, shape, dt in geom
            }
            for (idx, src, _dst, a), j in zip(items, slot_of):
                for c in _EXCHANGE_COLS:
                    bufs[c][src, j] = a[c]
            shipped = _TR_SHIP_PADDED.put(bufs, self.plane.sharding)
            rotated = transition.jit_mesh_plane_rotate(
                self.plane.mesh, shift, shipped
            )
            host = _TR_DELIVER_PADDED.get(rotated)
            permuted_bytes += sum(b.nbytes for b in bufs.values())
            exchanges += 1
            for (idx, _src, dst, _a), j in zip(items, slot_of):
                delivered_cols[idx] = {
                    c: host[c][dst, j] for c in _EXCHANGE_COLS
                }
        return delivered_cols, permuted_bytes, exchanges

    def _exchange_narrow(self, groups):
        """Narrow exchange (the first ledger retirement): stage every
        group's entry rows as DENSE column stacks (pow2-padded on the
        entry axis) with int32 ``src``/``slot`` scatter vectors, ship
        the whole tick in ONE audited crossing, and let
        ``jit_mesh_plane_exchange`` scatter into the padded collective
        layout on device (pad rows carry ``src == shards`` and drop out
        of the scatter) before the same rotation. Nothing returns to
        the host: delivery hands out device-resident slices of the
        rotated buffers, which the receivers' device-plane body path
        already consumes. ``permuted_bytes`` keeps its meaning — the
        padded collective payload — computed analytically."""
        delivered_cols: dict = {}
        permuted_bytes = 0
        if not groups:
            return delivered_cols, 0, 0
        shards = self.plane.shards
        staged: list = []  # (items, slot_of, shift, depth) per group
        bundle: list = []  # matching {"cols", "src", "slot"} stacks
        for (shift, geom), items in groups.items():
            slot_of, depth = self._slot_layout(items)
            n_pad = pow2_tier(len(items))
            cols = {
                c: np.zeros((n_pad,) + shape, np.dtype(dt))
                for c, shape, dt in geom
            }
            # pad rows scatter out of range and are dropped on device
            src = np.full((n_pad,), shards, np.int32)
            slot = np.zeros((n_pad,), np.int32)
            for k, ((idx, s, _dst, a), j) in enumerate(
                zip(items, slot_of)
            ):
                for c in _EXCHANGE_COLS:
                    cols[c][k] = a[c]
                src[k] = s
                slot[k] = j
            bundle.append({"cols": cols, "src": src, "slot": slot})
            staged.append((items, slot_of, shift, depth))
            row_bytes = sum(
                np.dtype(dt).itemsize * int(np.prod(shape, dtype=np.int64))
                for _c, shape, dt in geom
            )
            permuted_bytes += shards * depth * row_bytes
        shipped = _TR_SHIP_DENSE.put(bundle)
        for g, (items, slot_of, shift, depth) in enumerate(staged):
            rotated = transition.jit_mesh_plane_exchange(
                self.plane.mesh,
                shift,
                depth,
                shipped[g]["cols"],
                shipped[g]["src"],
                shipped[g]["slot"],
            )
            for (idx, _src, dst, _a), j in zip(items, slot_of):
                delivered_cols[idx] = {
                    c: rotated[c][dst, j] for c in _EXCHANGE_COLS
                }
        return delivered_cols, permuted_bytes, len(staged)

    def flush(self) -> dict:
        """Run the rotation collectives, then deliver every buffered
        message in global send order. Returns the tick's stats."""
        groups, same_shard = self._exchange_groups()
        exchange = (
            self._exchange_narrow
            if self.plane.narrow
            else self._exchange_padded
        )
        delivered_cols, permuted_bytes, exchanges = exchange(groups)

        intra_entries = same_shard + len(delivered_cols)
        members = self.plane._members
        for idx, (to, _dst, msg) in enumerate(self.entries):
            cols = delivered_cols.get(idx)
            if cols is not None:
                cols["rows"] = msg.arrays["rows"]
                msg = dataclasses.replace(msg, arrays=cols)
            members[to][1].send(to, msg)
        self.entries.clear()
        return {
            "intra_entries": intra_entries,
            "fallback_entries": self.fallback_entries + self.passthrough,
            "permuted_bytes": permuted_bytes,
            "exchanges": exchanges,
        }
