"""Batched replica fleets — one process, thousands of replicas (ISSUE 6).

One :class:`~delta_crdt_ex_tpu.runtime.replica.Replica` today is one
Python event loop plus one device program per merge, so the ROADMAP's
"millions of users" north star priced out as millions of processes. A
:class:`Fleet` is the next altitude of PR 3's ingress coalescing:
where the replica loop groups messages *within* one mailbox into one
grouped dispatch, the fleet groups *across* replicas — it owns N
member replicas, drains all N mailboxes per :meth:`tick`, and joins
every member's coalesce groups with ONE vmapped kernel dispatch over a
leading replica axis (DrJAX-style map/reduce,
:func:`delta_crdt_ex_tpu.runtime.transition.fleet_merge_rows`).

Semantics are bit-for-bit the solo replica's (the acceptance gate of
``tests/test_fleet.py``):

- grouping reuses each member's own ``_coalesce_groups`` pass, so the
  per-replica combined slices are exactly what the solo grouped path
  would merge;
- ``vmap`` adds no arithmetic — lane k of the batched kernel IS the
  solo kernel on lane k's inputs;
- WAL records, acks, diff-subscriber work, seq numbering, and
  telemetry fan back out per replica through the SAME bookkeeping tail
  as the solo grouped path (``Replica._commit_entries_group``).

Scheduling is wave-ordered: each member's drained mailbox partitions
into units (a coalesce group, or a single non-entries message) and the
fleet processes wave w of every member before wave w+1 — per-member
arrival order is preserved exactly (a ``Down`` never passes entries
from the same peer), while cross-member units of one wave share a
dispatch.

Batch formation buckets staged groups by compatible shapes — state
geometry ``(L, B, R)`` and entry-lane tier S — and pads the ragged
axes per replica (row counts with ``-1`` rows, writer-table widths
with zero gids, the replica axis to a pow2 lane tier with all-padding
lanes) so unequal fan-in still batches by group
(:func:`delta_crdt_ex_tpu.models.binned_map.stack_entry_slices`).
Everything a batch cannot carry keeps the existing per-replica
fallback: bucket-of-one groups, device-plane slices, diff
subscribers, growth/gap escapes (per-lane ``ok`` flags), and
stale-version conflicts (staging is optimistic — a member mutated
between staging and commit replays solo).

The member states of a stable batch stay RESIDENT as the stacked
result of the previous dispatch (``Replica.state`` materialises a
lane only when something per-replica actually reads it), so the
steady-state hot path does no per-replica stacking or unstacking —
the host orchestrates, the device sees one launch per wave bucket.

The EGRESS half of the sync tick is batched too (ISSUE 10,
:meth:`Fleet.sync_tick`): due members' digest-tree builds, eager-delta
extractions, and own-counter cursor sources each run as ONE vmapped
dispatch per shape bucket, fanned back out through the replicas' own
plan/emit bookkeeping (``Replica._eager_jobs`` / ``_emit_push_job`` /
``_open_walks``) so wire bytes, opener streams, and cursor state are
bit-for-bit the per-member loop's. Outbound messages to a co-located
peer process that negotiated the fleet-frame capability aggregate into
one :class:`~delta_crdt_ex_tpu.runtime.sync.FleetFrameMsg` TCP frame
per endpoint per tick (see ``tcp_transport._FLEETF``).

MESH MODE (ISSUE 13, ``mesh=``): the same fleet lifted onto a 1-D
replica-sharded device mesh. Every batched dispatch swaps its ``vmap``
form for the ``shard_map`` twin
(:mod:`delta_crdt_ex_tpu.runtime.transition` ``mesh_fleet_*``) with
the replica-lane tier padded to a shard multiple (the existing
lane/row padding discipline, so SHAPE001 stays green), resident
stacked states stay device-sharded between ticks (a batched result is
already laid out for the next dispatch — no gather/rescatter), and a
sync tick's outbound messages bound for a co-mesh member ride the
interconnect as ``ppermute`` rotations through the intra-mesh delivery
plane (:mod:`delta_crdt_ex_tpu.runtime.meshplane`) — only off-mesh
destinations fall back to the frame collector / direct send. Lane k of
a sharded dispatch is bit-for-bit the vmapped kernel on lane k's
inputs, so mesh-vs-vmap fleets are identical on state bits, WAL bytes,
ack streams and wire bytes (``tests/test_mesh_fleet.py``, ``bench.py
--fleet --mesh`` assert it in-run).
"""

from __future__ import annotations

import threading
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from delta_crdt_ex_tpu.models.binned import pow2_tier
from delta_crdt_ex_tpu.models.binned_map import stack_entry_slices
from delta_crdt_ex_tpu.runtime import (
    metrics as metrics_mod,
    sync as sync_proto,
    telemetry,
    transition,
)
from delta_crdt_ex_tpu.runtime.meshplane import MeshPlane
from delta_crdt_ex_tpu.runtime.replica import (
    Replica,
    _LaneLevels,
    _StackedLevels,
)
from delta_crdt_ex_tpu.utils import transfers
from delta_crdt_ex_tpu.utils.faults import faultpoint

# -- audited device↔host transfer sites (crdtlint TRANSFER001) --------
_TR_MESH_PLACE = transfers.register("fleet.mesh_place")
_TR_DISPATCH_RESULT = transfers.register("fleet.dispatch_result")
_TR_DISPATCH_COUNTS = transfers.register("fleet.dispatch_counts")
_TR_OWN_CTR_COLUMNS = transfers.register("fleet.own_ctr_columns")
_TR_EGRESS_EXTRACT = transfers.register("fleet.egress_extract")


class _FrameCollector:
    """Per-transport aggregation of one egress tick's outbound sync
    messages into fleet-wide wire frames (ISSUE 10): sends whose
    destination endpoint negotiated the ``_FEAT_FLEET`` capability are
    buffered as ``(to, msg)`` entries and shipped at :meth:`flush` as
    ONE :class:`~delta_crdt_ex_tpu.runtime.sync.FleetFrameMsg` per
    endpoint — many members' eager-delta slices and openers in one TCP
    frame. Everything else (local peers, legacy peers, transports
    without ``fleet_sink``) passes straight through to the normal send.

    ``send`` returning True means the message is committed to a frame
    that a live negotiated connection will carry; a drop after that
    (sender queue filled mid-tick, peer died) is the same lossy-
    transport case as a ``_SenderConn`` drop — cursors may run ahead of
    delivery by one tick and the periodic sync / ``GetDiffMsg`` repair
    re-covers, exactly the per-member contract."""

    __slots__ = ("transport", "_sink_of", "_sinks", "frames", "senders")

    def __init__(self, transport) -> None:
        self.transport = transport
        self._sink_of = getattr(transport, "fleet_sink", None)
        self._sinks: dict = {}  # per-tick memo: destination -> sink|None
        self.frames: dict = {}  # endpoint -> [(to, msg), ...] send order
        self.senders: dict = {}  # endpoint -> distinct member addrs

    def send(self, to, msg) -> bool:
        if self._sink_of is not None:
            try:
                sink = self._sinks[to]
            except KeyError:
                # memoised per tick: fleet_sink probes the pooled
                # connection, and a dead endpoint would otherwise pay a
                # connect timeout per message instead of per tick
                sink = self._sinks[to] = self._sink_of(to)
            except TypeError:
                sink = self._sink_of(to)  # unhashable addr: no memo
            if sink is not None:
                self.frames.setdefault(sink, []).append((to, msg))
                self.senders.setdefault(sink, set()).add(
                    getattr(msg, "frm", None)
                )
                return True
        return self.transport.send(to, msg)

    def flush(self) -> "tuple[int, int]":
        """Ship the buffered envelopes; returns ``(frames, member
        slots)`` — member slots counts distinct contributing members
        per shipped frame (the members-per-frame numerator)."""
        frames = members = 0
        for sink, entries in self.frames.items():
            if self.transport.send_fleet_frame(sink, entries):
                frames += 1
                members += len(self.senders[sink])
        self.frames.clear()
        self.senders.clear()
        return frames, members


#: RowSlice entry columns carried at the dense lane tier (trimmed back
#: per member on content-sized backends; the ctx tables ride the state's
#: writer geometry instead and are never lane-tiered)
_ENTRY_LANE_COLS = ("key", "valh", "ts", "node", "ctr", "alive")


def _lane_slice(host, lane: int, rows: np.ndarray, tier: "int | None"):
    """Lane ``lane`` of a host-fetched stacked RowSlice as the member's
    solo-form slice: identical to what the member's own extraction
    would have produced — dense backends pack each row's entries as an
    arrival-ordered prefix with zeroed dead lanes, so trimming the
    entry-lane axis to the member's own pow2 tier is exact (bit-for-bit
    wire parity with the per-member loop)."""
    out = {}
    for c in host._fields:
        a = np.asarray(getattr(host, c))[lane]
        if tier is not None and c in _ENTRY_LANE_COLS:
            a = a[:, :tier]
        out[c] = a
    out["rows"] = rows  # the job's own planning array (identical values)
    return type(host)(**out)


class _EgressMember:
    """One member's snapshot through a batched sync tick: the (state,
    version) pair every batched dispatch reads, the planned push jobs,
    and the solo-fallback flag for members whose version moved."""

    __slots__ = (
        "rep", "state", "version", "need_ctr", "need_tree", "own_ctr",
        "jobs", "solo",
    )

    def __init__(self, rep, state, version, need_ctr, need_tree):
        self.rep = rep
        self.state = state
        self.version = version
        self.need_ctr = need_ctr
        self.need_tree = need_tree
        self.own_ctr = None
        self.jobs = None
        self.solo = False


class _Staged:
    """One member's staged coalesce group, awaiting a batched dispatch."""

    __slots__ = ("rep", "msgs", "sl", "offsets", "version", "key")

    def __init__(self, rep, msgs, sl, offsets, version, geometry):
        self.rep = rep
        self.msgs = msgs
        self.sl = sl
        self.offsets = offsets
        self.version = version
        # batch-compat bucket: identical state geometry and entry-lane
        # tier; row counts and writer-table widths may be ragged (the
        # stack pads them per replica)
        self.key = geometry + (sl.key.shape[1],)


class Fleet:
    """Scheduler owning N member replicas' event loops.

    Members must be UNTHREADED (``threaded=False`` /
    ``Replica.start()`` never called) — the fleet is their event loop.
    Deterministic drives call :meth:`tick` / :meth:`drain`; production
    use calls :meth:`start` for one background thread serving all N
    members' periodic duties (sync ticks, WAL group-commit cadence,
    interval checkpoints) plus the batched ingress drain.
    """

    def __init__(
        self,
        replicas: list,
        *,
        min_batch: int = 2,
        obs=None,
        mesh=None,
        mesh_narrow: bool = True,
    ):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        for r in replicas:
            if not isinstance(r, Replica):
                raise TypeError(f"not a Replica: {r!r}")
            if r._thread is not None:
                raise ValueError(
                    f"replica {r.name!r} runs its own event loop; fleet "
                    "members must be started with threaded=False"
                )
            if r._in_fleet:
                raise ValueError(
                    f"replica {r.name!r} already belongs to a fleet; two "
                    "fleets draining one mailbox would race (the same "
                    "hazard Replica.start() refuses for members)"
                )
        self.replicas = list(replicas)
        #: smallest batch worth stacking: below it the per-replica
        #: grouped path is strictly cheaper (nothing to amortise)
        self.min_batch = max(2, int(min_batch))
        #: mesh mode (ISSUE 13): a 1-D replica-sharded device mesh
        #: (pass a jax Mesh, an int shard count, or True for the
        #: detected-topology default), default off — hot dispatches
        #: then ride the shard_map twins, resident stacked states stay
        #: device-sharded, and intra-mesh sync-tick entries deliver as
        #: ppermute rotations (runtime/meshplane.py)
        self._mesh = None
        self._mesh_shards = 1
        self._mesh_sharding = None
        self._mesh_plane = None
        self._mesh_members_per_shard = 0.0
        if mesh is not None and mesh is not False:
            from delta_crdt_ex_tpu.utils import devices as _devices

            if mesh is True or isinstance(mesh, int):
                mesh = _devices.fleet_mesh(None if mesh is True else mesh)
            if tuple(mesh.axis_names) != (transition.MESH_AXIS,):
                raise ValueError(
                    f"fleet mesh must be 1-D over the "
                    f"{transition.MESH_AXIS!r} axis, got {mesh.axis_names}"
                )
            shards = int(mesh.devices.size)
            if shards & (shards - 1):
                raise ValueError(
                    f"fleet mesh size must be a power of two, got {shards}"
                )
            self._mesh = mesh
            self._mesh_shards = shards
            self._mesh_sharding = transition.replica_sharding(mesh)
            # mesh_narrow=False keeps the padded host round-trip
            # exchange — bench.py --mesh runs it as the ledger's
            # before-retirement leg (runtime/meshplane.py)
            self._mesh_plane = MeshPlane(mesh, narrow=mesh_narrow)
            self._mesh_plane.assign(
                [(r.addr, r.transport) for r in self.replicas]
            )
            # membership is fixed at construction: snapshot the ratio so
            # stats() never calls into the plane under the fleet lock
            self._mesh_members_per_shard = (
                self._mesh_plane.members_per_shard()
            )
        # snapshot once: the device topology is immutable after backend
        # init, and stats() must not re-enumerate devices under the
        # fleet lock on every scrape (same rationale as the
        # members-per-shard snapshot above)
        from delta_crdt_ex_tpu.utils import devices as _devices_mod

        self._mesh_topology = _devices_mod.detected_topology()
        #: intra-mesh delivery plane accounting (read by stats() under
        #: the fleet lock; the sync-tick thread writes them there too)
        self._mesh_intra_entries = 0
        self._mesh_fallback_entries = 0
        self._mesh_permuted_bytes = 0
        self._mesh_exchanges = 0
        self._lock = threading.Lock()
        #: resident stacked states per batch bucket: members tuple →
        #: (per-member state versions at stack time, stacked pytree,
        #: lane tier). Reused while no member's state moved outside the
        #: batched dispatch; conservatively dropped whenever any lane
        #: fell back mid-dispatch (its lane in the result is stale).
        self._stack_cache: dict = {}
        self._stack_cache_cap = 32
        # observability (ISSUE 6 satellite): occupancy, ragged fill,
        # ticks/sec — the production-visible counterpart of the bench
        self._ticks = 0
        self._tick_time = 0.0
        self._dispatches = 0
        self._batched_messages = 0
        self._occupancy_hist: dict[int, int] = {}
        self._real_rows = 0
        self._padded_rows = 0
        self._fallbacks = {"singleton": 0, "shape": 0, "escape": 0, "stale": 0}
        # batched egress observability (ISSUE 10): sync-tick passes,
        # members served, vmapped extraction/tree dispatches, per-bucket
        # occupancy, jobs that fell back to solo extraction, and the
        # FleetFrameMsg wire aggregation counters
        self._egress_ticks = 0
        self._egress_members = 0
        self._egress_time = 0.0
        self._egress_dispatches = 0
        self._egress_batched_jobs = 0
        self._egress_solo_jobs = 0
        self._egress_solo_members = 0
        self._egress_occupancy: dict[int, int] = {}
        self._egress_tree_batched = 0
        self._egress_frames = 0
        self._egress_frame_members = 0
        #: tick-freshness heartbeat for /healthz (a wedged fleet loop —
        #: stuck dispatch, dead thread — goes stale and flips unready)
        self._tick_ts = time.monotonic()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        for r in self.replicas:
            # member notify() wakes the FLEET loop, not a per-replica one
            r.notify = self._member_notify  # type: ignore[method-assign]
            r._in_fleet = True
        #: hierarchical anti-entropy tier 0 (ISSUE 15): tree-mode
        #: members share ONE tier-0 cluster key, so the whole fleet is
        #: a single bottom-tier subtree — intra-fleet hops are local
        #: mailbox (or, in mesh mode, ppermute) deliveries, and only
        #: the captain gossips outward. Mesh fleets key the cluster on
        #: the mesh plane so the bottom tier IS the mesh.
        if any(r.tree_gossip for r in self.replicas):
            from delta_crdt_ex_tpu.runtime import treesync

            if self._mesh_plane is not None:
                group = self._mesh_plane.tree_group()
            else:
                group = treesync.fleet_group_key(
                    [r.addr for r in self.replicas]
                )
            for r in self.replicas:
                if r.tree_group is None:
                    r.tree_group = group
        #: observability plane (ISSUE 9): the fleet registers its own
        #: varz/health sources + a scrape-time collector for occupancy /
        #: fill-ratio / tick gauges; members register themselves
        self._obs = metrics_mod.resolve_obs(obs)
        #: the fleet's cached FleetFrontdoor (ISSUE 14, ``frontdoor()``)
        self._frontdoor = None
        if self._obs is not None:
            self._obs.register_fleet(self)

    def _member_notify(self) -> None:
        if self._thread is not None:
            self._wake.set()

    # ------------------------------------------------------------------
    # ingress: drain all mailboxes, dispatch in waves

    def tick(self) -> int:
        """Drain one bounded batch from every member's mailbox and
        handle it — batched where compatible, per-replica everywhere
        else. Returns messages handled. Bounded per call exactly like
        ``Replica.process_pending``'s single drain: sustained ingress
        cannot starve the periodic duties between ticks."""
        t0 = time.perf_counter()
        with self._lock:
            # refreshed every tick (busy or idle): /healthz readiness is
            # "the loop is turning", not "traffic is flowing"
            self._tick_ts = time.monotonic()
        per_member: list = []
        n_msgs = 0
        for rep in self.replicas:
            drain = getattr(rep.transport, "drain_nowait", None)
            batch = (
                drain(rep.addr, rep.ingress_batch)
                if drain is not None
                else rep.transport.drain(rep.addr)
            )
            if batch:
                n_msgs += len(batch)
                per_member.append((rep, self._units(rep, batch)))
        wave = 0
        while True:
            pairs = []
            busy = False
            for rep, units in per_member:
                if wave >= len(units):
                    continue
                busy = True
                kind, payload = units[wave]
                if kind == "group":
                    pairs.append((rep, payload))
                else:
                    rep.handle(payload)
            if not busy:
                break
            if pairs:
                self._dispatch_wave(pairs)
            wave += 1
        # tree-mode relay epoch on the ingress side (ISSUE 15): what
        # this tick's waves merged into relay members re-emits NOW, so
        # multi-hop propagation cascades tick-by-tick through the fleet
        # instead of waiting for each member's next periodic sync
        for rep, _units in per_member:
            rep._relay_flush()
        if n_msgs:
            with self._lock:
                # tick/dispatch counters are read by stats() from any
                # caller thread while the fleet loop writes them
                # (crdtlint RACE001)
                self._ticks += 1
                self._tick_time += time.perf_counter() - t0
        return n_msgs

    def drain(self, max_rounds: int = 10_000) -> int:
        """Deterministic drive: tick until every mailbox is empty."""
        total = 0
        for _ in range(max_rounds):
            n = self.tick()
            if n == 0:
                return total
            total += n
        raise RuntimeError("fleet did not quiesce")

    def _units(self, rep, batch: list) -> list:
        """Partition one member's drained batch into ordered units —
        the fleet-side mirror of ``Replica._handle_batch``: consecutive
        ``EntriesMsg`` runs become coalesce groups (the member's OWN
        grouping pass, so fleet and solo group identically); every
        other message is a unit of its own, handled in place."""
        if not rep.ingress_coalesce or rep.on_diffs is not None:
            return [("msg", m) for m in batch]
        units: list = []
        run: list = []
        for m in batch:
            if isinstance(m, sync_proto.EntriesMsg):
                run.append(m)
                continue
            if run:
                units += [("group", g) for g in rep._coalesce_groups(run)]
                run = []
            units.append(("msg", m))
        if run:
            units += [("group", g) for g in rep._coalesce_groups(run)]
        return units

    def _solo(self, rep, msgs: list, reason: str) -> None:
        with self._lock:
            self._fallbacks[reason] += 1
        rep.fleet_handle_group(msgs)

    def _dispatch_wave(self, pairs: list) -> None:
        """Stage every (member, group) of one wave, bucket by shape
        compatibility, and launch one batched dispatch per bucket."""
        buckets: dict[tuple, list] = {}
        for rep, msgs in pairs:
            prep = rep.fleet_prepare(msgs)
            if prep is None:
                self._solo(rep, msgs, "shape")
                continue
            sl, offsets, version, geometry = prep
            staged = _Staged(rep, msgs, sl, offsets, version, geometry)
            buckets.setdefault(staged.key, []).append(staged)
        for members in buckets.values():
            if len(members) < self.min_batch:
                for st in members:
                    self._solo(st.rep, st.msgs, "singleton")
                continue
            self._dispatch_bucket(members)

    def _lane_tier(self, n: int) -> int:
        """Replica-axis compile tier for one batched dispatch: the pow2
        lane tier, padded up to the mesh shard count in mesh mode so
        the lane axis splits evenly across shards (padding lanes merge/
        extract nothing, exactly like the vmap form's). Shard counts
        are pow2, so one tier call covers both modes — and crdtlint's
        SHAPE001 sanitiser inference sees a pure tier function."""
        return pow2_tier(max(n, self._mesh_shards), floor=2)

    def _stacked_states(self, reps: list, lanes: int):
        """The stacked input states for one bucket — reused from the
        previous dispatch's RESULT when no member's state moved since
        (``_state_version`` match), else restacked from the members'
        per-replica states. Padding lanes replicate member 0 (their
        slices are all-padding: the merge is a no-op on them). In mesh
        mode a fresh stack is placed replica-sharded over the mesh; a
        cached result is ALREADY sharded (shard_map output), which is
        what keeps resident state device-sharded between ticks."""
        key = tuple(id(r) for r in reps) + (lanes,)
        versions = [r._state_version for r in reps]
        with self._lock:
            hit = self._stack_cache.get(key)
        if hit is not None and hit[0] == versions:
            return hit[1], key, versions
        states = [r.state for r in reps]
        states += [states[0]] * (lanes - len(states))
        stacked = transition.stack_states(states)
        if self._mesh_sharding is not None:
            stacked = _TR_MESH_PLACE.put(stacked, self._mesh_sharding)
        return stacked, key, versions

    def _dispatch_bucket(self, members: list) -> None:
        t0 = time.perf_counter()
        n = len(members)
        lanes = self._lane_tier(n)
        sl, real_rows = stack_entry_slices([st.sl for st in members], lanes=lanes)
        reps = [st.rep for st in members]
        stacked_in, cache_key, _versions = self._stacked_states(reps, lanes)
        # backend-owned batched dispatch (ISSUE 8): the bucket key is the
        # model's batch-compatibility key (backend tag included), so all
        # members of a bucket share one store backend and its vmapped
        # merge form — binned buckets split at lane-tier boundaries,
        # hash buckets only at a table rehash. Mesh mode (ISSUE 13)
        # swaps in the shard_map twin over the same stacked operands.
        if self._mesh is None:
            res = reps[0].model.fleet_merge_rows(stacked_in, sl)
        else:
            res = reps[0].model.mesh_fleet_merge_rows(
                self._mesh, stacked_in, sl
            )
        # hash backend: per-lane window pressure rides the same readback
        # so the growth advisory below costs no extra device sync
        wfill = getattr(res, "max_window_fill", None)
        if wfill is not None:
            ok, n_killed, wfill = _TR_DISPATCH_RESULT.get(
                (res.ok, res.n_killed, wfill)
            )
        else:
            ok, n_killed = _TR_DISPATCH_RESULT.get((res.ok, res.n_killed))
        probe_window = getattr(stacked_in, "probe_window", 0)
        dt = time.perf_counter() - t0
        # per-row count readback is lazy and shared: one device_get for
        # the whole stack, paid only if any SYNC_DONE handler exists.
        # Capture JUST the two stacked count arrays — a closure over
        # ``res`` would pin the whole stacked result (incl. the stacked
        # states) for as long as any member's deferral window parks the
        # fn, defeating XLA's input-buffer reuse on later dispatches
        counts_cell: list = []

        def counts_for(lane, ins_rows=res.n_ins_row, kill_rows=res.n_kill_row):
            def fn():
                if not counts_cell:
                    counts_cell.append(
                        _TR_DISPATCH_COUNTS.get((ins_rows, kill_rows))
                    )
                ins, kill = counts_cell[0]
                return ins[lane], kill[lane]

            return fn

        all_committed = True
        committed = 0
        committed_versions: list[int] = []
        for lane, st in enumerate(members):
            if not bool(ok[lane]):
                # growth/gap escape: the solo path owns retry tiers and
                # the CtxGapError partition/repair machinery
                all_committed = False
                self._solo(st.rep, st.msgs, "escape")
                continue
            new_version = st.rep.fleet_commit(
                st.msgs,
                st.offsets,
                res.state,
                lane,
                counts_for(lane),
                int(n_killed[lane]),
                dt / n,
                st.version,
            )
            if new_version is not None:
                committed += 1
                committed_versions.append(new_version)
                if wfill is not None and st.rep.model.load_high(
                    int(wfill[lane]), probe_window
                ):
                    # grow OFF the batch path (ISSUE 8): a lane whose
                    # hot probe window nears overflow would otherwise
                    # keep batching until it overflows and escapes
                    # mid-batch. The version bump drops it from the
                    # resident stack; it re-buckets at its new capacity
                    # next tick.
                    st.rep.grow_store_advised()
                    all_committed = False
            else:
                # the member mutated between staging and commit: the
                # batched merge read a stale state — replay solo
                all_committed = False
                self._solo(st.rep, st.msgs, "stale")
        with self._lock:
            if all_committed:
                # the result stack becomes the members' resident state:
                # the next tick with unchanged versions reuses it,
                # unstacked lanes are never materialised on the batch
                # hot path. The recorded versions are the
                # COMMIT-returned ones — a re-read here could race a
                # concurrent mutation and mask it.
                self._stack_cache[cache_key] = (committed_versions, res.state)
                while len(self._stack_cache) > self._stack_cache_cap:
                    self._stack_cache.pop(next(iter(self._stack_cache)))
            else:
                # a fallen-back lane's row in the result is stale —
                # never serve it as a materialisation source
                self._stack_cache.pop(cache_key, None)
            self._dispatches += 1
            self._batched_messages += sum(len(st.msgs) for st in members)
            self._occupancy_hist[committed] = (
                self._occupancy_hist.get(committed, 0) + 1
            )
            self._real_rows += real_rows
            self._padded_rows += lanes * int(sl.rows.shape[1])
        if telemetry.has_handlers(telemetry.FLEET_DISPATCH):
            telemetry.execute(
                telemetry.FLEET_DISPATCH,
                {
                    "replicas": n,
                    "lanes": lanes,
                    "messages": sum(len(st.msgs) for st in members),
                    "rows": real_rows,
                    "padded_rows": lanes * int(sl.rows.shape[1]),
                    "duration_s": dt,
                },
                {"fleet": id(self)},
            )

    # ------------------------------------------------------------------
    # batched sync-tick egress (ISSUE 10): one vmapped tree build + one
    # vmapped delta extraction per shape bucket, fanned back out through
    # the replicas' own plan/emit bookkeeping

    def sync_tick(self, members: "list | None" = None) -> int:
        """One sync tick for ``members`` (default: every member) with
        the egress half batched across the fleet: per-member planning
        (``Replica._eager_jobs``) and emission (``_emit_push_job`` /
        ``_open_walks``) run under each member's own lock exactly as
        ``sync_to_all`` would, but the device work between them — the
        own-counter cursor source, the eager-delta/full-row slice
        extractions, and the digest-tree builds — runs as ONE vmapped
        dispatch per shape bucket over a leading replica axis. Lane k
        of every batched result is bit-for-bit the solo dispatch on
        lane k's inputs, so wire bytes, opener streams and cursor state
        cannot drift from the per-member loop (``tests/
        test_fleet_egress.py``, ``bench.py --fleet`` assert it).

        Outbound messages whose destination endpoint negotiated the
        fleet-frame capability aggregate into one
        :class:`~delta_crdt_ex_tpu.runtime.sync.FleetFrameMsg` per
        endpoint per tick (flat gossip today; the frame hierarchical
        anti-entropy will ride). Returns the number of members synced."""
        reps = list(self.replicas if members is None else members)
        if not reps:
            return 0
        t0 = time.perf_counter()
        if len(reps) < self.min_batch:
            # nothing to amortise: the per-member path is strictly cheaper
            for rep in reps:
                rep.sync_to_all()
            with self._lock:
                self._egress_ticks += 1
                self._egress_members += len(reps)
                self._egress_solo_members += len(reps)
                self._egress_time += time.perf_counter() - t0
            return len(reps)

        # phase 0 — per member, under its lock: flush pending mutations,
        # refresh monitors, snapshot (state, version) as THE source every
        # batched dispatch below reads; a member whose version moves
        # before planning replays the whole tick solo
        staged: list = []
        for rep in reps:
            with rep._lock:
                rep._flush()
                rep._monitor_neighbours()
                staged.append(_EgressMember(
                    rep,
                    rep.state,
                    rep._state_version,
                    rep._own_ctr_cache is None,
                    rep._tree is None,
                ))

        # phase 0.5 — batched cursor-source refresh: one gather + one
        # transfer per ctx geometry group instead of N column reads
        ctr_groups: dict[tuple, list] = {}
        for ent in staged:
            if ent.need_ctr:
                ctr_groups.setdefault(
                    tuple(ent.state.ctx_max.shape), []
                ).append(ent)
        for items in ctr_groups.values():
            if len(items) < self.min_batch:
                continue  # _eager_jobs rebuilds those solo
            # pad to a pow2 lane tier (lane 0 replicated; mesh mode
            # rounds up to a shard multiple) — group membership varies
            # tick to tick with cache invalidation, and an exact-size
            # stack would recompile per distinct size
            lanes = self._lane_tier(len(items))
            tables = [e.state.ctx_max for e in items]
            tables += [tables[0]] * (lanes - len(items))
            slots = np.zeros(lanes, np.int32)
            slots[: len(items)] = [e.rep.self_slot for e in items]
            stacked_tables = transition.jit_stack_pytrees(*tables)
            if self._mesh is None:
                cols = _TR_OWN_CTR_COLUMNS.get(
                    transition.jit_fleet_own_ctr_columns(
                        stacked_tables, jnp.asarray(slots)
                    )
                )
            else:
                cols = _TR_OWN_CTR_COLUMNS.get(
                    transition.jit_mesh_fleet_own_ctr_columns(
                        self._mesh, stacked_tables, jnp.asarray(slots)
                    )
                )
            for lane, e in enumerate(items):
                e.own_ctr = cols[lane]

        # phase 1 — per member, under its lock: plan the tick's push
        # jobs against the snapshot (version-guarded: a moved member
        # would plan against newer state than the batch extracts from,
        # and its cursors could then overrun the shipped claims)
        for ent in staged:
            rep = ent.rep
            with rep._lock:
                if rep._state_version != ent.version:
                    ent.solo = True
                    continue
                if ent.own_ctr is not None and rep._own_ctr_cache is None:
                    rep._own_ctr_cache = ent.own_ctr
                ent.jobs = rep._eager_jobs()

        # phase 2a — bucket push jobs by (backend geometry, job kind,
        # row tier) and run ONE vmapped extraction + ONE whole-bucket
        # host transfer per bucket
        buckets: dict[tuple, list] = {}
        for ent in staged:
            if ent.solo or not ent.jobs:
                continue
            geo = ent.rep.model.geometry(ent.state)
            for job in ent.jobs:
                key = geo + (job.kind, job.rows.shape[0])
                buckets.setdefault(key, []).append((ent.rep, ent.state, job))
        extracted: dict[int, Any] = {}
        n_dispatch = n_batched_jobs = n_solo_jobs = 0
        occupancy: dict[int, int] = {}
        for items in buckets.values():
            if len(items) < self.min_batch:
                n_solo_jobs += len(items)
                continue
            self._extract_bucket(items, extracted)
            n_dispatch += 1
            n_batched_jobs += len(items)
            occupancy[len(items)] = occupancy.get(len(items), 0) + 1

        # phase 2b — batched digest-tree builds (leaf digests share one
        # geometry across backends); the opener's top levels prefetch in
        # one transfer per bucket, deep levels stay device-resident for
        # the receive-side walks to materialise lazily
        tree_groups: dict[int, list] = {}
        for ent in staged:
            if ent.need_tree and not ent.solo:
                tree_groups.setdefault(
                    int(ent.state.leaf.shape[-1]), []
                ).append(ent)
        lane_trees: dict[int, tuple] = {}
        n_tree_batched = 0
        for items in tree_groups.values():
            if len(items) < self.min_batch:
                continue  # _ensure_tree rebuilds those solo
            # pow2 lane tier like the ctr refresh above: a per-size
            # stack/build compile on the periodic path would stall a
            # steady-state fleet every time the due set's size moved
            lanes = self._lane_tier(len(items))
            leaves = [e.state.leaf for e in items]
            leaves += [leaves[0]] * (lanes - len(items))
            stacked_leaves = transition.jit_stack_pytrees(*leaves)
            if self._mesh is None:
                levels = transition.jit_fleet_tree_from_leaves(stacked_leaves)
            else:
                levels = transition.jit_mesh_fleet_tree_from_leaves(
                    self._mesh, stacked_leaves
                )
            stack = _StackedLevels(levels)
            stack.prefetch(max(e.rep.levels_per_round for e in items))
            n_tree_batched += len(items)
            for lane, e in enumerate(items):
                lane_trees[id(e.rep)] = (stack, lane, e.version)

        # phase 3 — per member, under its lock: adopt the batched tree
        # (version-guarded), emit every job through the shared
        # _emit_push_job tail (cursor advance, send accounting), open
        # the walk rounds (the _outstanding / _sync_open_seq bookkeeping
        # — unchanged), with sends aggregating into fleet frames. Mesh
        # mode interposes the intra-mesh delivery plane: co-mesh
        # destinations buffer for the tick's ppermute exchange (ordered
        # delivery at flush), everything else takes the collector path.
        exchange = (
            self._mesh_plane.begin_tick()
            if self._mesh_plane is not None
            else None
        )
        collectors: dict[int, _FrameCollector] = {}
        for ent in staged:
            rep = ent.rep
            coll = collectors.get(id(rep.transport))
            if coll is None:
                coll = collectors[id(rep.transport)] = _FrameCollector(
                    rep.transport
                )
            if exchange is None:
                send = coll.send
            else:
                # default-arg capture of JUST the collector send (the
                # lambda outlives this iteration's loop variables)
                send = lambda to, m, _f=coll.send: exchange.send_via(_f, to, m)  # noqa: E731
            with rep._lock:
                if ent.solo:
                    # stale member: the solo path end-to-end (its own
                    # plan, extraction, emission and walks)
                    rep._push_deltas(send)
                    rep._open_walks(send)
                    rep._relay_flush(send)
                    continue
                tv = lane_trees.get(id(rep))
                if (
                    tv is not None
                    and rep._tree is None
                    and rep._state_version == tv[2]
                ):
                    rep._tree = _LaneLevels(tv[0], tv[1])
                for job in ent.jobs:
                    sl = extracted.get(id(job))
                    if sl is None:
                        sl = rep._extract_push_job(job)
                    rep._emit_push_job(job, sl, send)
                rep._open_walks(send)
                # tree-mode relay epoch (ISSUE 15): coalesced
                # re-emissions ride the SAME tick send — fleet frames
                # aggregate them per endpoint, and in mesh mode co-mesh
                # links deliver through the ppermute exchange (tier 0)
                rep._relay_flush(send)

        # phase 3.5 — the intra-mesh exchange: rotate buffered co-mesh
        # entries along the replica axis and deliver every buffered
        # message in global send order (the host-path arrival order)
        mesh_stats = exchange.flush() if exchange is not None else None

        # phase 4 — ship the aggregated fleet frames, one per endpoint
        frames = frame_members = 0
        for coll in collectors.values():
            f, m = coll.flush()
            frames += f
            frame_members += m

        dt = time.perf_counter() - t0
        solo_members = sum(1 for ent in staged if ent.solo)
        with self._lock:
            self._egress_ticks += 1
            self._egress_members += len(reps)
            self._egress_time += dt
            self._egress_dispatches += n_dispatch
            self._egress_batched_jobs += n_batched_jobs
            self._egress_solo_jobs += n_solo_jobs
            self._egress_solo_members += solo_members
            for k, v in occupancy.items():
                self._egress_occupancy[k] = self._egress_occupancy.get(k, 0) + v
            self._egress_tree_batched += n_tree_batched
            self._egress_frames += frames
            self._egress_frame_members += frame_members
            if mesh_stats is not None:
                self._mesh_intra_entries += mesh_stats["intra_entries"]
                self._mesh_fallback_entries += mesh_stats["fallback_entries"]
                self._mesh_permuted_bytes += mesh_stats["permuted_bytes"]
                self._mesh_exchanges += mesh_stats["exchanges"]
        if mesh_stats is not None and telemetry.has_handlers(
            telemetry.MESH_EXCHANGE
        ):
            telemetry.execute(
                telemetry.MESH_EXCHANGE,
                {
                    "intra_entries": mesh_stats["intra_entries"],
                    "fallback_entries": mesh_stats["fallback_entries"],
                    "permuted_bytes": mesh_stats["permuted_bytes"],
                    "exchanges": mesh_stats["exchanges"],
                    "shards": self._mesh_shards,
                },
                {"fleet": id(self)},
            )
        if telemetry.has_handlers(telemetry.FLEET_EGRESS):
            telemetry.execute(
                telemetry.FLEET_EGRESS,
                {
                    "members": len(reps),
                    "jobs_batched": n_batched_jobs,
                    "jobs_solo": n_solo_jobs + solo_members,
                    "dispatches": n_dispatch,
                    "frames": frames,
                    "frame_members": frame_members,
                    "duration_s": dt,
                },
                {"fleet": id(self)},
            )
        return len(reps)

    def _extract_bucket(self, items: list, extracted: dict) -> None:
        """One vmapped extraction for a bucket of same-shape push jobs:
        stack the members' snapshot states and job inputs along a
        leading replica axis (pow2 lane tier; padding lanes replicate
        member 0 with all ``-1`` rows — they gather nothing), dispatch
        the backend's batched form, fetch the WHOLE stacked slice with
        one host transfer, and hand each job its lane — trimmed back to
        the member's own solo tier on dense backends — as the host-form
        slice ``_emit_push_job`` fans out."""
        model = items[0][0].model
        n = len(items)
        lanes = self._lane_tier(n)
        states = [st for _rep, st, _job in items]
        states += [states[0]] * (lanes - n)
        stacked = transition.jit_stack_pytrees(*states)
        u = items[0][2].rows.shape[0]
        rows = np.full((lanes, u), -1, np.int32)
        for k, (_rep, _st, job) in enumerate(items):
            rows[k] = job.rows
        if items[0][2].kind == "delta":
            slots = np.zeros(lanes, np.int32)
            gids = np.zeros(lanes, np.uint64)
            lo = np.zeros((lanes, u), np.uint32)
            for k, (rep, _st, job) in enumerate(items):
                slots[k] = rep.self_slot
                gids[k] = rep.node_id
                lo[k] = job.lo
            if self._mesh is None:
                sl, tiers = model.fleet_extract_own_delta(
                    stacked,
                    jnp.asarray(rows),
                    jnp.asarray(slots),
                    jnp.asarray(gids),
                    jnp.asarray(lo),
                )
            else:
                sl, tiers = model.mesh_fleet_extract_own_delta(
                    self._mesh,
                    stacked,
                    jnp.asarray(rows),
                    jnp.asarray(slots),
                    jnp.asarray(gids),
                    jnp.asarray(lo),
                )
        elif self._mesh is None:
            sl, tiers = model.fleet_extract_rows(stacked, jnp.asarray(rows))
        else:
            sl, tiers = model.mesh_fleet_extract_rows(
                self._mesh, stacked, jnp.asarray(rows)
            )
        host = _TR_EGRESS_EXTRACT.get(sl)  # one transfer for the whole bucket
        for k, (_rep, _st, job) in enumerate(items):
            extracted[id(job)] = _lane_slice(
                host, k, job.rows, None if tiers is None else tiers[k]
            )

    # ------------------------------------------------------------------
    # periodic duties + the one-thread event loop

    def run_duties(self, now: float | None = None) -> None:
        """One pass of every member's periodic duties — the per-replica
        loop body of ``Replica.start``, hoisted so N members share one
        thread."""
        now = time.monotonic() if now is None else now
        due: list = []
        for rep in self.replicas:
            with rep._lock:
                if rep._pending:
                    rep._flush()
            nxt = getattr(rep, "_fleet_next_sync", 0.0)
            if now >= nxt:
                due.append(rep)
                rep._fleet_next_sync = now + rep.sync_interval
            if rep.storage_mode == "interval" and rep.storage_module is not None:
                nxt = getattr(rep, "_fleet_next_ckpt", None)
                if nxt is None:
                    # first sight: schedule one interval out, matching
                    # the solo loop (an immediate checkpoint for every
                    # member would stall the shared thread at start)
                    rep._fleet_next_ckpt = now + rep.checkpoint_interval
                elif now >= nxt:
                    rep.checkpoint()
                    rep._fleet_next_ckpt = now + rep.checkpoint_interval
            with rep._lock:
                # deferred interval-mode fsync, same contract as the
                # solo event loop: the fd is replica-lock-serialised
                # state, and the None check sits under the lock too
                if rep._wal is not None:
                    rep._wal.maybe_sync()
        if due:
            # the batched egress: due members' tree builds + delta
            # extractions share one vmapped dispatch per shape bucket
            # (the solo loop body's sync_to_all, one altitude up)
            self.sync_tick(due)

    def start(self) -> "Fleet":
        """Run the fleet's event loop in ONE background thread serving
        every member — the served-users-per-host lever: thread count no
        longer scales with replica count."""
        if self._thread is not None:
            return self

        self._stop.clear()
        min_interval = min(r.sync_interval for r in self.replicas)

        def loop():
            while not self._stop.is_set():
                faultpoint("fleet.loop")
                self.tick()
                self.run_duties()
                self._wake.wait(timeout=min(min_interval, 0.05))
                self._wake.clear()

        self._thread = threading.Thread(
            target=loop, name=f"crdt-fleet-{id(self):x}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the loop and every member (best-effort final sync +
        WAL close, the solo ``Replica.stop`` contract per member). Each
        member's goodbye sync is DRAINED before the next member stops:
        in the solo topology surviving members' own loops merge a
        stopping peer's final push — here the fleet is that loop, so it
        must serve the push before the recipients close their WALs."""
        with self._lock:
            fd, self._frontdoor = self._frontdoor, None
        if fd is not None:
            # close the serving plane first: its admission workers must
            # not race member shutdown (outside the fleet lock — close
            # joins threads)
            fd.close()
        if self._thread is not None:
            self._stop.set()
            self._wake.set()
            self._thread.join(timeout=5)
            self._thread = None
        if self._obs is not None:
            self._obs.unregister_fleet(self)
        for rep in self.replicas:
            rep.stop()
            self.drain()  # surviving members process the goodbye sync

    # ------------------------------------------------------------------
    # serving plane (ISSUE 14)

    def frontdoor(self, **opts):
        """The fleet's serving front door, created on first use and
        cached: one :class:`~delta_crdt_ex_tpu.runtime.serve.Frontdoor`
        per member plus key-hash routing — see
        :class:`~delta_crdt_ex_tpu.runtime.serve.FleetFrontdoor`.
        Closed automatically by :meth:`stop`."""
        from delta_crdt_ex_tpu.runtime.serve import FleetFrontdoor

        with self._lock:
            if self._frontdoor is None:
                self._frontdoor = FleetFrontdoor(self, **opts)
            elif opts:
                raise ValueError(
                    "fleet front door already exists; options are fixed "
                    "at first creation"
                )
            return self._frontdoor

    # ------------------------------------------------------------------
    # observability (ISSUE 6 satellite)

    def stats(self) -> dict:
        """Fleet-level dispatch observability: batched-dispatch
        occupancy (replicas per launch), ragged-mask fill ratio, and
        tick throughput — the ``INGEST_COALESCE``-histogram pattern one
        altitude up. Served under the fleet lock: the loop thread
        updates every counter it reports (crdtlint RACE001/005)."""
        with self._lock:
            occ = dict(sorted(self._occupancy_hist.items()))
            total = sum(occ.values())
            return {
                "replicas": len(self.replicas),
                "ticks": self._ticks,
                "ticks_per_sec": (
                    round(self._ticks / self._tick_time, 3)
                    if self._tick_time
                    else 0.0
                ),
                "dispatches": self._dispatches,
                "batched_messages": self._batched_messages,
                # device↔host boundary ledger (ISSUE 17): PROCESS-WIDE
                # per-site totals (the registry is global; a fleet
                # shares it with its members and any co-resident fleet)
                "transfers": transfers.snapshot(),
                "occupancy_hist": occ,
                "avg_occupancy": (
                    round(sum(k * v for k, v in occ.items()) / total, 3)
                    if total
                    else 0.0
                ),
                "ragged_fill_ratio": (
                    round(self._real_rows / self._padded_rows, 4)
                    if self._padded_rows
                    else 0.0
                ),
                "fallbacks": dict(self._fallbacks),
                "egress": self._egress_stats_held(),
                "mesh": self._mesh_stats_held(),
            }

    def _mesh_stats_held(self) -> dict:
        """Mesh-mode observability (caller holds the fleet lock): shard
        layout, intra-mesh-vs-fallback delivery counters, permuted
        bytes, and the detected device/mesh topology (the
        ``test_multihost_spmd`` PROBE_SHAPE field vocabulary, snapshot
        at construction) so every stats consumer — /varz, bench
        artifacts — is self-describing about the hardware it ran on."""
        return {
            "enabled": self._mesh is not None,
            "shards": self._mesh_shards if self._mesh is not None else 0,
            "members_per_shard": self._mesh_members_per_shard,
            "intra_entries": self._mesh_intra_entries,
            "fallback_entries": self._mesh_fallback_entries,
            "permuted_bytes": self._mesh_permuted_bytes,
            "exchanges": self._mesh_exchanges,
            "topology": self._mesh_topology,
        }

    def _egress_stats_held(self) -> dict:
        """Batched-egress observability (caller holds the fleet lock):
        sync-tick throughput, vmapped extraction bucket occupancy, and
        the FleetFrameMsg wire-aggregation ratios the
        ``crdt_fleet_egress_*`` gauges export."""
        occ = dict(sorted(self._egress_occupancy.items()))
        occ_total = sum(occ.values())
        return {
            "ticks": self._egress_ticks,
            "members_synced": self._egress_members,
            "ticks_per_sec": (
                round(self._egress_ticks / self._egress_time, 3)
                if self._egress_time
                else 0.0
            ),
            "dispatches": self._egress_dispatches,
            "batched_jobs": self._egress_batched_jobs,
            "solo_jobs": self._egress_solo_jobs,
            "solo_members": self._egress_solo_members,
            "bucket_occupancy_hist": occ,
            "avg_bucket_occupancy": (
                round(
                    sum(k * v for k, v in occ.items()) / occ_total, 3
                )
                if occ_total
                else 0.0
            ),
            "trees_batched": self._egress_tree_batched,
            "frames": self._egress_frames,
            "frame_members": self._egress_frame_members,
            "members_per_frame": (
                round(self._egress_frame_members / self._egress_frames, 3)
                if self._egress_frames
                else 0.0
            ),
            "frames_per_tick": (
                round(self._egress_frames / self._egress_ticks, 3)
                if self._egress_ticks
                else 0.0
            ),
        }

    def obs_varz(self) -> dict:
        """The fleet's ``/varz`` stanza: the UNCHANGED :meth:`stats`
        dict under a typed envelope (additive surface, MIGRATING.md)."""
        return {"kind": "fleet", "stats": self.stats()}

    def health(self) -> dict:
        """Readiness for ``/healthz``: the shared event loop's tick is
        fresh (when threaded — deterministic drives pass trivially).
        Member-level WAL/neighbour checks ride each member's own
        :meth:`Replica.health` source."""
        with self._lock:
            tick_ts = self._tick_ts
        ok = True
        if self._thread is not None:
            fresh = time.monotonic() - tick_ts < max(
                5 * min(r.sync_interval for r in self.replicas), 2.0
            )
            ok = self._thread.is_alive() and fresh
        return {
            "ok": ok,
            "loop_responsive": ok,
            "replicas": len(self.replicas),
        }


def start_fleet(replicas: list, *, threaded: bool = True, **opts) -> Fleet:
    """Wrap unthreaded replicas in a :class:`Fleet`; ``threaded=True``
    starts the single shared event loop."""
    fleet = Fleet(replicas, **opts)
    if threaded:
        fleet.start()
    return fleet
