"""Batched replica fleets — one process, thousands of replicas (ISSUE 6).

One :class:`~delta_crdt_ex_tpu.runtime.replica.Replica` today is one
Python event loop plus one device program per merge, so the ROADMAP's
"millions of users" north star priced out as millions of processes. A
:class:`Fleet` is the next altitude of PR 3's ingress coalescing:
where the replica loop groups messages *within* one mailbox into one
grouped dispatch, the fleet groups *across* replicas — it owns N
member replicas, drains all N mailboxes per :meth:`tick`, and joins
every member's coalesce groups with ONE vmapped kernel dispatch over a
leading replica axis (DrJAX-style map/reduce,
:func:`delta_crdt_ex_tpu.runtime.transition.fleet_merge_rows`).

Semantics are bit-for-bit the solo replica's (the acceptance gate of
``tests/test_fleet.py``):

- grouping reuses each member's own ``_coalesce_groups`` pass, so the
  per-replica combined slices are exactly what the solo grouped path
  would merge;
- ``vmap`` adds no arithmetic — lane k of the batched kernel IS the
  solo kernel on lane k's inputs;
- WAL records, acks, diff-subscriber work, seq numbering, and
  telemetry fan back out per replica through the SAME bookkeeping tail
  as the solo grouped path (``Replica._commit_entries_group``).

Scheduling is wave-ordered: each member's drained mailbox partitions
into units (a coalesce group, or a single non-entries message) and the
fleet processes wave w of every member before wave w+1 — per-member
arrival order is preserved exactly (a ``Down`` never passes entries
from the same peer), while cross-member units of one wave share a
dispatch.

Batch formation buckets staged groups by compatible shapes — state
geometry ``(L, B, R)`` and entry-lane tier S — and pads the ragged
axes per replica (row counts with ``-1`` rows, writer-table widths
with zero gids, the replica axis to a pow2 lane tier with all-padding
lanes) so unequal fan-in still batches by group
(:func:`delta_crdt_ex_tpu.models.binned_map.stack_entry_slices`).
Everything a batch cannot carry keeps the existing per-replica
fallback: bucket-of-one groups, device-plane slices, diff
subscribers, growth/gap escapes (per-lane ``ok`` flags), and
stale-version conflicts (staging is optimistic — a member mutated
between staging and commit replays solo).

The member states of a stable batch stay RESIDENT as the stacked
result of the previous dispatch (``Replica.state`` materialises a
lane only when something per-replica actually reads it), so the
steady-state hot path does no per-replica stacking or unstacking —
the host orchestrates, the device sees one launch per wave bucket.
"""

from __future__ import annotations

import threading
import time
from typing import Any

import jax

from delta_crdt_ex_tpu.models.binned import pow2_tier
from delta_crdt_ex_tpu.models.binned_map import stack_entry_slices
from delta_crdt_ex_tpu.runtime import (
    metrics as metrics_mod,
    sync as sync_proto,
    telemetry,
    transition,
)
from delta_crdt_ex_tpu.runtime.replica import Replica


class _Staged:
    """One member's staged coalesce group, awaiting a batched dispatch."""

    __slots__ = ("rep", "msgs", "sl", "offsets", "version", "key")

    def __init__(self, rep, msgs, sl, offsets, version, geometry):
        self.rep = rep
        self.msgs = msgs
        self.sl = sl
        self.offsets = offsets
        self.version = version
        # batch-compat bucket: identical state geometry and entry-lane
        # tier; row counts and writer-table widths may be ragged (the
        # stack pads them per replica)
        self.key = geometry + (sl.key.shape[1],)


class Fleet:
    """Scheduler owning N member replicas' event loops.

    Members must be UNTHREADED (``threaded=False`` /
    ``Replica.start()`` never called) — the fleet is their event loop.
    Deterministic drives call :meth:`tick` / :meth:`drain`; production
    use calls :meth:`start` for one background thread serving all N
    members' periodic duties (sync ticks, WAL group-commit cadence,
    interval checkpoints) plus the batched ingress drain.
    """

    def __init__(self, replicas: list, *, min_batch: int = 2, obs=None):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        for r in replicas:
            if not isinstance(r, Replica):
                raise TypeError(f"not a Replica: {r!r}")
            if r._thread is not None:
                raise ValueError(
                    f"replica {r.name!r} runs its own event loop; fleet "
                    "members must be started with threaded=False"
                )
            if r._in_fleet:
                raise ValueError(
                    f"replica {r.name!r} already belongs to a fleet; two "
                    "fleets draining one mailbox would race (the same "
                    "hazard Replica.start() refuses for members)"
                )
        self.replicas = list(replicas)
        #: smallest batch worth stacking: below it the per-replica
        #: grouped path is strictly cheaper (nothing to amortise)
        self.min_batch = max(2, int(min_batch))
        self._lock = threading.Lock()
        #: resident stacked states per batch bucket: members tuple →
        #: (per-member state versions at stack time, stacked pytree,
        #: lane tier). Reused while no member's state moved outside the
        #: batched dispatch; conservatively dropped whenever any lane
        #: fell back mid-dispatch (its lane in the result is stale).
        self._stack_cache: dict = {}
        self._stack_cache_cap = 32
        # observability (ISSUE 6 satellite): occupancy, ragged fill,
        # ticks/sec — the production-visible counterpart of the bench
        self._ticks = 0
        self._tick_time = 0.0
        self._dispatches = 0
        self._batched_messages = 0
        self._occupancy_hist: dict[int, int] = {}
        self._real_rows = 0
        self._padded_rows = 0
        self._fallbacks = {"singleton": 0, "shape": 0, "escape": 0, "stale": 0}
        #: tick-freshness heartbeat for /healthz (a wedged fleet loop —
        #: stuck dispatch, dead thread — goes stale and flips unready)
        self._tick_ts = time.monotonic()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None
        for r in self.replicas:
            # member notify() wakes the FLEET loop, not a per-replica one
            r.notify = self._member_notify  # type: ignore[method-assign]
            r._in_fleet = True
        #: observability plane (ISSUE 9): the fleet registers its own
        #: varz/health sources + a scrape-time collector for occupancy /
        #: fill-ratio / tick gauges; members register themselves
        self._obs = metrics_mod.resolve_obs(obs)
        if self._obs is not None:
            self._obs.register_fleet(self)

    def _member_notify(self) -> None:
        if self._thread is not None:
            self._wake.set()

    # ------------------------------------------------------------------
    # ingress: drain all mailboxes, dispatch in waves

    def tick(self) -> int:
        """Drain one bounded batch from every member's mailbox and
        handle it — batched where compatible, per-replica everywhere
        else. Returns messages handled. Bounded per call exactly like
        ``Replica.process_pending``'s single drain: sustained ingress
        cannot starve the periodic duties between ticks."""
        t0 = time.perf_counter()
        with self._lock:
            # refreshed every tick (busy or idle): /healthz readiness is
            # "the loop is turning", not "traffic is flowing"
            self._tick_ts = time.monotonic()
        per_member: list = []
        n_msgs = 0
        for rep in self.replicas:
            drain = getattr(rep.transport, "drain_nowait", None)
            batch = (
                drain(rep.addr, rep.ingress_batch)
                if drain is not None
                else rep.transport.drain(rep.addr)
            )
            if batch:
                n_msgs += len(batch)
                per_member.append((rep, self._units(rep, batch)))
        wave = 0
        while True:
            pairs = []
            busy = False
            for rep, units in per_member:
                if wave >= len(units):
                    continue
                busy = True
                kind, payload = units[wave]
                if kind == "group":
                    pairs.append((rep, payload))
                else:
                    rep.handle(payload)
            if not busy:
                break
            if pairs:
                self._dispatch_wave(pairs)
            wave += 1
        if n_msgs:
            with self._lock:
                # tick/dispatch counters are read by stats() from any
                # caller thread while the fleet loop writes them
                # (crdtlint RACE001)
                self._ticks += 1
                self._tick_time += time.perf_counter() - t0
        return n_msgs

    def drain(self, max_rounds: int = 10_000) -> int:
        """Deterministic drive: tick until every mailbox is empty."""
        total = 0
        for _ in range(max_rounds):
            n = self.tick()
            if n == 0:
                return total
            total += n
        raise RuntimeError("fleet did not quiesce")

    def _units(self, rep, batch: list) -> list:
        """Partition one member's drained batch into ordered units —
        the fleet-side mirror of ``Replica._handle_batch``: consecutive
        ``EntriesMsg`` runs become coalesce groups (the member's OWN
        grouping pass, so fleet and solo group identically); every
        other message is a unit of its own, handled in place."""
        if not rep.ingress_coalesce or rep.on_diffs is not None:
            return [("msg", m) for m in batch]
        units: list = []
        run: list = []
        for m in batch:
            if isinstance(m, sync_proto.EntriesMsg):
                run.append(m)
                continue
            if run:
                units += [("group", g) for g in rep._coalesce_groups(run)]
                run = []
            units.append(("msg", m))
        if run:
            units += [("group", g) for g in rep._coalesce_groups(run)]
        return units

    def _solo(self, rep, msgs: list, reason: str) -> None:
        with self._lock:
            self._fallbacks[reason] += 1
        rep.fleet_handle_group(msgs)

    def _dispatch_wave(self, pairs: list) -> None:
        """Stage every (member, group) of one wave, bucket by shape
        compatibility, and launch one batched dispatch per bucket."""
        buckets: dict[tuple, list] = {}
        for rep, msgs in pairs:
            prep = rep.fleet_prepare(msgs)
            if prep is None:
                self._solo(rep, msgs, "shape")
                continue
            sl, offsets, version, geometry = prep
            staged = _Staged(rep, msgs, sl, offsets, version, geometry)
            buckets.setdefault(staged.key, []).append(staged)
        for members in buckets.values():
            if len(members) < self.min_batch:
                for st in members:
                    self._solo(st.rep, st.msgs, "singleton")
                continue
            self._dispatch_bucket(members)

    def _stacked_states(self, reps: list, lanes: int):
        """The stacked input states for one bucket — reused from the
        previous dispatch's RESULT when no member's state moved since
        (``_state_version`` match), else restacked from the members'
        per-replica states. Padding lanes replicate member 0 (their
        slices are all-padding: the merge is a no-op on them)."""
        key = tuple(id(r) for r in reps) + (lanes,)
        versions = [r._state_version for r in reps]
        with self._lock:
            hit = self._stack_cache.get(key)
        if hit is not None and hit[0] == versions:
            return hit[1], key, versions
        states = [r.state for r in reps]
        states += [states[0]] * (lanes - len(states))
        return transition.stack_states(states), key, versions

    def _dispatch_bucket(self, members: list) -> None:
        t0 = time.perf_counter()
        n = len(members)
        lanes = pow2_tier(n, floor=2)
        sl, real_rows = stack_entry_slices([st.sl for st in members], lanes=lanes)
        reps = [st.rep for st in members]
        stacked_in, cache_key, _versions = self._stacked_states(reps, lanes)
        # backend-owned batched dispatch (ISSUE 8): the bucket key is the
        # model's batch-compatibility key (backend tag included), so all
        # members of a bucket share one store backend and its vmapped
        # merge form — binned buckets split at lane-tier boundaries,
        # hash buckets only at a table rehash
        res = reps[0].model.fleet_merge_rows(stacked_in, sl)
        # hash backend: per-lane window pressure rides the same readback
        # so the growth advisory below costs no extra device sync
        wfill = getattr(res, "max_window_fill", None)
        if wfill is not None:
            ok, n_killed, wfill = jax.device_get((res.ok, res.n_killed, wfill))
        else:
            ok, n_killed = jax.device_get((res.ok, res.n_killed))
        probe_window = getattr(stacked_in, "probe_window", 0)
        dt = time.perf_counter() - t0
        # per-row count readback is lazy and shared: one device_get for
        # the whole stack, paid only if any SYNC_DONE handler exists.
        # Capture JUST the two stacked count arrays — a closure over
        # ``res`` would pin the whole stacked result (incl. the stacked
        # states) for as long as any member's deferral window parks the
        # fn, defeating XLA's input-buffer reuse on later dispatches
        counts_cell: list = []

        def counts_for(lane, ins_rows=res.n_ins_row, kill_rows=res.n_kill_row):
            def fn():
                if not counts_cell:
                    counts_cell.append(jax.device_get((ins_rows, kill_rows)))
                ins, kill = counts_cell[0]
                return ins[lane], kill[lane]

            return fn

        all_committed = True
        committed = 0
        committed_versions: list[int] = []
        for lane, st in enumerate(members):
            if not bool(ok[lane]):
                # growth/gap escape: the solo path owns retry tiers and
                # the CtxGapError partition/repair machinery
                all_committed = False
                self._solo(st.rep, st.msgs, "escape")
                continue
            new_version = st.rep.fleet_commit(
                st.msgs,
                st.offsets,
                res.state,
                lane,
                counts_for(lane),
                int(n_killed[lane]),
                dt / n,
                st.version,
            )
            if new_version is not None:
                committed += 1
                committed_versions.append(new_version)
                if wfill is not None and st.rep.model.load_high(
                    int(wfill[lane]), probe_window
                ):
                    # grow OFF the batch path (ISSUE 8): a lane whose
                    # hot probe window nears overflow would otherwise
                    # keep batching until it overflows and escapes
                    # mid-batch. The version bump drops it from the
                    # resident stack; it re-buckets at its new capacity
                    # next tick.
                    st.rep.grow_store_advised()
                    all_committed = False
            else:
                # the member mutated between staging and commit: the
                # batched merge read a stale state — replay solo
                all_committed = False
                self._solo(st.rep, st.msgs, "stale")
        with self._lock:
            if all_committed:
                # the result stack becomes the members' resident state:
                # the next tick with unchanged versions reuses it,
                # unstacked lanes are never materialised on the batch
                # hot path. The recorded versions are the
                # COMMIT-returned ones — a re-read here could race a
                # concurrent mutation and mask it.
                self._stack_cache[cache_key] = (committed_versions, res.state)
                while len(self._stack_cache) > self._stack_cache_cap:
                    self._stack_cache.pop(next(iter(self._stack_cache)))
            else:
                # a fallen-back lane's row in the result is stale —
                # never serve it as a materialisation source
                self._stack_cache.pop(cache_key, None)
            self._dispatches += 1
            self._batched_messages += sum(len(st.msgs) for st in members)
            self._occupancy_hist[committed] = (
                self._occupancy_hist.get(committed, 0) + 1
            )
            self._real_rows += real_rows
            self._padded_rows += lanes * int(sl.rows.shape[1])
        if telemetry.has_handlers(telemetry.FLEET_DISPATCH):
            telemetry.execute(
                telemetry.FLEET_DISPATCH,
                {
                    "replicas": n,
                    "lanes": lanes,
                    "messages": sum(len(st.msgs) for st in members),
                    "rows": real_rows,
                    "padded_rows": lanes * int(sl.rows.shape[1]),
                    "duration_s": dt,
                },
                {"fleet": id(self)},
            )

    # ------------------------------------------------------------------
    # periodic duties + the one-thread event loop

    def run_duties(self, now: float | None = None) -> None:
        """One pass of every member's periodic duties — the per-replica
        loop body of ``Replica.start``, hoisted so N members share one
        thread."""
        now = time.monotonic() if now is None else now
        for rep in self.replicas:
            with rep._lock:
                if rep._pending:
                    rep._flush()
            nxt = getattr(rep, "_fleet_next_sync", 0.0)
            if now >= nxt:
                rep.sync_to_all()
                rep._fleet_next_sync = now + rep.sync_interval
            if rep.storage_mode == "interval" and rep.storage_module is not None:
                nxt = getattr(rep, "_fleet_next_ckpt", None)
                if nxt is None:
                    # first sight: schedule one interval out, matching
                    # the solo loop (an immediate checkpoint for every
                    # member would stall the shared thread at start)
                    rep._fleet_next_ckpt = now + rep.checkpoint_interval
                elif now >= nxt:
                    rep.checkpoint()
                    rep._fleet_next_ckpt = now + rep.checkpoint_interval
            with rep._lock:
                # deferred interval-mode fsync, same contract as the
                # solo event loop: the fd is replica-lock-serialised
                # state, and the None check sits under the lock too
                if rep._wal is not None:
                    rep._wal.maybe_sync()

    def start(self) -> "Fleet":
        """Run the fleet's event loop in ONE background thread serving
        every member — the served-users-per-host lever: thread count no
        longer scales with replica count."""
        if self._thread is not None:
            return self

        self._stop.clear()
        min_interval = min(r.sync_interval for r in self.replicas)

        def loop():
            while not self._stop.is_set():
                self.tick()
                self.run_duties()
                self._wake.wait(timeout=min(min_interval, 0.05))
                self._wake.clear()

        self._thread = threading.Thread(
            target=loop, name=f"crdt-fleet-{id(self):x}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the loop and every member (best-effort final sync +
        WAL close, the solo ``Replica.stop`` contract per member). Each
        member's goodbye sync is DRAINED before the next member stops:
        in the solo topology surviving members' own loops merge a
        stopping peer's final push — here the fleet is that loop, so it
        must serve the push before the recipients close their WALs."""
        if self._thread is not None:
            self._stop.set()
            self._wake.set()
            self._thread.join(timeout=5)
            self._thread = None
        if self._obs is not None:
            self._obs.unregister_fleet(self)
        for rep in self.replicas:
            rep.stop()
            self.drain()  # surviving members process the goodbye sync

    # ------------------------------------------------------------------
    # observability (ISSUE 6 satellite)

    def stats(self) -> dict:
        """Fleet-level dispatch observability: batched-dispatch
        occupancy (replicas per launch), ragged-mask fill ratio, and
        tick throughput — the ``INGEST_COALESCE``-histogram pattern one
        altitude up. Served under the fleet lock: the loop thread
        updates every counter it reports (crdtlint RACE001/005)."""
        with self._lock:
            occ = dict(sorted(self._occupancy_hist.items()))
            total = sum(occ.values())
            return {
                "replicas": len(self.replicas),
                "ticks": self._ticks,
                "ticks_per_sec": (
                    round(self._ticks / self._tick_time, 3)
                    if self._tick_time
                    else 0.0
                ),
                "dispatches": self._dispatches,
                "batched_messages": self._batched_messages,
                "occupancy_hist": occ,
                "avg_occupancy": (
                    round(sum(k * v for k, v in occ.items()) / total, 3)
                    if total
                    else 0.0
                ),
                "ragged_fill_ratio": (
                    round(self._real_rows / self._padded_rows, 4)
                    if self._padded_rows
                    else 0.0
                ),
                "fallbacks": dict(self._fallbacks),
            }

    def obs_varz(self) -> dict:
        """The fleet's ``/varz`` stanza: the UNCHANGED :meth:`stats`
        dict under a typed envelope (additive surface, MIGRATING.md)."""
        return {"kind": "fleet", "stats": self.stats()}

    def health(self) -> dict:
        """Readiness for ``/healthz``: the shared event loop's tick is
        fresh (when threaded — deterministic drives pass trivially).
        Member-level WAL/neighbour checks ride each member's own
        :meth:`Replica.health` source."""
        with self._lock:
            tick_ts = self._tick_ts
        ok = True
        if self._thread is not None:
            fresh = time.monotonic() - tick_ts < max(
                5 * min(r.sync_interval for r in self.replicas), 2.0
            )
            ok = self._thread.is_alive() and fresh
        return {
            "ok": ok,
            "loop_responsive": ok,
            "replicas": len(self.replicas),
        }


def start_fleet(replicas: list, *, threaded: bool = True, **opts) -> Fleet:
    """Wrap unthreaded replicas in a :class:`Fleet`; ``threaded=True``
    starts the single shared event loop."""
    fleet = Fleet(replicas, **opts)
    if threaded:
        fleet.start()
    return fleet
