"""Cross-host control-plane transport (reference: distributed Erlang).

The reference gets cross-node distribution for free from the BEAM:
location-transparent ``send/2`` to ``{name, node}`` over the full-mesh
TCP of distributed Erlang (``causal_crdt_test.exs:68-78``; SURVEY §5.8).
This module provides the equivalent for the TPU runtime: one
:class:`TcpTransport` per host process, length-prefixed pickled frames
over persistent TCP connections, with remote addresses written
``(name, (host, port))`` — the ``{name, node}`` analog. Local names
behave exactly like :class:`~delta_crdt_ex_tpu.runtime.transport.
LocalTransport` addresses, so a replica's protocol code is transport-
agnostic.

Monitors over TCP are heartbeat-based: a background thread pings each
monitored remote every ``heartbeat_interval``; a failed ping delivers
:class:`~delta_crdt_ex_tpu.runtime.transport.Down` to the watcher — the
``:DOWN`` analog (``causal_crdt.ex:127-145``). Like distributed Erlang
inside a trusted cluster, frames are pickled Python objects: this
transport assumes a trusted network (run it inside your pod/VPC), which
is the same trust model the reference inherits from Erlang cookies.

The *data plane* rides the same frames here (entry slices are numpy
arrays, pickle handles them); moving slices device-to-device over
ICI/DCN without the host hop is the :mod:`delta_crdt_ex_tpu.parallel`
mesh path.
"""

from __future__ import annotations

import logging
import pickle
import zlib
import queue
import socket
import struct
import threading
import time
from typing import Any, Hashable

from delta_crdt_ex_tpu.runtime import sync as sync_proto
from delta_crdt_ex_tpu.runtime.transport import Down, forward_fleet_entries
from delta_crdt_ex_tpu.utils.faults import FaultInjected, faultpoint

logger = logging.getLogger("delta_crdt_ex_tpu")

_LEN = struct.Struct(">I")

# frame kinds
_MSG = 0
_PING = 1
_PONG = 2
_MSGZ = 3  # zlib-compressed _MSG — only sent to peers that advertised
# _FEAT_MSGZ in the HELLO exchange (legacy peers get plain _MSG frames,
# so mixed-version clusters keep converging; see MIGRATING.md)
_HELLO = 4  # capability negotiation: payload = [wire_version, features]
_MSGB = 5  # arrays side-channel: pickle-5 head + out-of-band buffers,
# each buffer raw or zlib'd by a sampled compressibility probe. The DCN
# data plane for sync slices (SURVEY §5.8): dense walk slices compress
# only ~4.5x while zlib-1 costs ~16 ms/slice CPU (measured, BASELINE.md
# "cross-host wire"), so whole-frame pickle4+zlib loses to raw framing
# at any link >= 1 Gb/s; sparse delta slices still compress 25x+ and the
# per-buffer probe keeps that win.
_FLEETF = 6  # fleet egress envelope (ISSUE 10): one frame carrying a
# FleetFrameMsg — many fleet members' per-peer sync messages to one
# co-located peer process, decoded back to per-member mailbox
# deliveries here. _MSGB buffer framing inside; only sent to peers that
# advertised _FEAT_FLEET (legacy peers get per-member frames instead,
# so mixed-version clusters keep converging; see MIGRATING.md).

_WIRE_VERSION = 1
_FEAT_MSGZ = 1  # feature bit: peer accepts zlib-compressed _MSG frames
_FEAT_MSGB = 2  # feature bit: peer accepts _MSGB array-buffer frames
_FEAT_FLEET = 4  # feature bit: peer accepts _FLEETF fleet-frame envelopes
_OUR_FEATURES = _FEAT_MSGZ | _FEAT_MSGB | _FEAT_FLEET

#: how long the HELLO waiter keeps reading for a late reply before giving
#: up (several socket timeouts — a loaded peer may accept late; a legacy
#: peer never replies and just costs one daemon thread for this window)
_HELLO_WAIT_S = 30.0

#: compress frames at least this large. Sync payloads are padded
#: static-shape arrays (mostly zeros), so cheap level-1 zlib typically
#: shrinks them 10-50x — real bandwidth on the DCN leg; tiny control
#: frames skip the round trip.
_COMPRESS_MIN = 4096


def _send_frame(sock: socket.socket, kind: int, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload) + 1) + bytes([kind]) + payload)


_BUF_HDR = struct.Struct(">BI")  # per-buffer: flags (1 = zlib), wire length
_Z_SAMPLE = 1 << 12  # probe the first 4 KiB for compressibility


def _maybe_z_buffer(mv: memoryview) -> "tuple[int, bytes | memoryview]":
    """Per-buffer compression decision: compress only when a cheap
    sample probe predicts a real win — padded (sparse) slice columns
    shrink 25x+ and take the zlib path; dense columns ship raw instead
    of paying ~8 ms/MiB for ~4.5x (measured; see _MSGB note)."""
    n = mv.nbytes
    if n >= _COMPRESS_MIN:
        # probe head AND tail separately: wire tiers pad slices with
        # TRAILING zero rows (pow4 rounding), so a head-only probe would
        # read a dense prefix and miss exactly the padding it exists
        # for, while a combined sample would dilute a padded tail's
        # signal below the bar. Either a compressible-overall sample or
        # a nearly-empty tail (the padded-slice signature) triggers the
        # real attempt; the attempt keeps only a >=2x shrink.
        mvb = mv.cast("B")
        half = _Z_SAMPLE // 2
        head = bytes(mvb[:half]) if n > half else bytes(mvb)
        tail = bytes(mvb[-half:]) if n > 2 * half else b""
        zh, zt = len(zlib.compress(head, 1)), len(zlib.compress(tail, 1))
        if (zh + zt) * 3 <= len(head) + len(tail) or (
            tail and zt * 8 <= len(tail)
        ):
            z = zlib.compress(mv, 1)
            if len(z) * 2 <= n:
                return 1, z
    return 0, mv


#: below this much raw buffer data the side-channel's per-buffer probe
#: and framing overheads beat its copy savings — small (eager-delta)
#: messages stay on the legacy whole-frame path, which also compresses
#: cross-buffer redundancy better on mostly-padding slices (measured:
#: 16-row push 0.49 ms legacy vs 0.89 ms framed; 512-row walk 6.1 ms
#: framed vs 21.4 ms legacy — BASELINE.md "cross-host wire")
_MSGB_MIN = 256 << 10


def _encode_msgb(obj, min_bytes: int = 0) -> bytes | None:
    """(head, buffers) wire form: pickle protocol 5 with out-of-band
    buffers — the big numpy slice columns are framed as raw (or
    probe-compressed) bytes instead of being copied through the pickle
    stream and zlib'd wholesale. Returns None when the buffers hold
    fewer than ``min_bytes`` (caller should use the legacy frame)."""
    bufs: list[pickle.PickleBuffer] = []

    def keep_oob(pb: pickle.PickleBuffer):
        # pickle semantics: a FALSY return serializes out-of-band, a
        # truthy one falls back to in-band (used for non-contiguous
        # buffers, which can't be framed raw)
        try:
            pb.raw()
        except BufferError:
            return True
        bufs.append(pb)
        return False

    head = pickle.dumps(obj, protocol=5, buffer_callback=keep_oob)
    if sum(pb.raw().nbytes for pb in bufs) < min_bytes:
        return None  # head dump is cheap; the legacy path re-pickles
    parts = [struct.pack(">II", len(bufs), len(head)), head]
    for pb in bufs:
        flags, data = _maybe_z_buffer(pb.raw())
        # raw buffers stay memoryviews here — join copies them exactly
        # once (the source arrays outlive the call)
        parts.append(_BUF_HDR.pack(flags, len(data)))
        parts.append(data)
    return b"".join(parts)


def _decode_msgb(payload: bytes):
    n_bufs, head_len = struct.unpack_from(">II", payload, 0)
    off = 8
    head = payload[off : off + head_len]
    off += head_len
    bufs = []
    for _ in range(n_bufs):
        flags, wire_len = _BUF_HDR.unpack_from(payload, off)
        off += _BUF_HDR.size
        data = payload[off : off + wire_len]
        off += wire_len
        # bytearray: reconstructed arrays must be WRITABLE like the
        # legacy pickle4 paths' — handler behaviour must not depend on
        # which negotiated wire path a message happened to take
        bufs.append(bytearray(zlib.decompress(data)) if flags & 1 else bytearray(data))
    return pickle.loads(head, buffers=bufs)


def _recv_frame(sock: socket.socket) -> tuple[int, bytes] | None:
    """Read one length-prefixed frame; ``(kind, payload)`` or None on a
    short read. The serve loop and ping round-trip parse through here;
    the HELLO waiter keeps its own cross-timeout byte buffer (it must not
    drop partial reads), so a wire-format change must update both."""
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    body = _recv_exact(sock, _LEN.unpack(hdr)[0])
    if not body:
        return None
    return body[0], body[1:]


def _start_hello_negotiation(conn: "_SenderConn") -> None:
    """Negotiate wire capabilities on a fresh outbound connection,
    without ever blocking the send path.

    Sends our HELLO, then a short-lived daemon thread waits for the
    peer's reply and flips the connection's feature flags when it lands.
    Until then (and forever, for an older peer that drops unknown frame
    kinds and never replies) the connection advertises no optional
    features — compression is never sent to a peer that did not claim
    it, so a rolling upgrade cannot silently stop convergence, and a
    stalled peer costs the caller nothing (frames just stay uncompressed).
    """
    try:
        _send_frame(conn.sock, _HELLO, bytes([_WIRE_VERSION, _OUR_FEATURES]))
    except OSError:
        return  # the sender thread will discover the dead socket itself

    def wait_reply() -> None:
        # Keep reading until the HELLO lands or a deadline passes: one
        # socket-timeout's grace is not enough for a loaded peer whose
        # accept loop replies late, and leaving the reply unread would
        # pin compression off for the connection's whole lifetime.
        # Bytes are accumulated locally (not via _recv_exact, which drops
        # its partial buffer when a timeout fires mid-frame), so a reply
        # that trickles in across several read timeouts still parses at
        # the right frame boundary. Only timeouts keep the loop going —
        # any other socket error means the connection is gone.
        deadline = time.monotonic() + _HELLO_WAIT_S
        buf = b""
        while time.monotonic() < deadline:
            try:
                chunk = conn.sock.recv(65536)
            except TimeoutError:
                continue  # per-read timeout paces the wait; buf is kept
            except OSError:
                return  # reset/closed: stay feature-less
            if not chunk:
                return  # peer closed
            buf += chunk
            while len(buf) >= 4:
                ln = _LEN.unpack(buf[:4])[0]
                if len(buf) < 4 + ln:
                    break
                body, buf = buf[4 : 4 + ln], buf[4 + ln :]
                # body = [kind, payload...]; HELLO payload = [ver, features]
                if ln >= 1 and body[0] == _HELLO:
                    if ln >= 3:
                        conn.accepts_z = bool(body[2] & _FEAT_MSGZ)
                        conn.accepts_b = bool(body[2] & _FEAT_MSGB)
                        conn.accepts_f = bool(body[2] & _FEAT_FLEET)
                    return  # a short/malformed HELLO concludes feature-less
                # other frame kinds on an outbound conn are unexpected —
                # skip and keep waiting for the HELLO

    threading.Thread(target=wait_reply, daemon=True,
                     name="tcp-hello-wait").start()


class _SenderConn:
    """One pooled outbound connection with its own send queue + thread.

    ``sendall`` to a stalled peer can block for the full socket timeout;
    pushing it onto a per-connection thread means one slow peer delays
    only its own queue — every other edge keeps flowing (the failure-
    isolation the reference gets from per-process mailboxes). A full
    queue drops the frame (anti-entropy is idempotent and retried, so
    backpressure loss only delays convergence)."""

    QUEUE_MAX = 256
    #: byte bound alongside the frame-count bound: raw-framed _MSGB
    #: slices are ~2x their zlib'd size, so a stalled peer must cap on
    #: BYTES queued, not just frames (drops are safe — anti-entropy is
    #: idempotent and the periodic sync re-covers)
    QUEUE_MAX_BYTES = 64 << 20

    def __init__(
        self, sock: socket.socket, on_dead, accepts_z: bool = False,
        on_sent=None,
    ) -> None:
        self.sock = sock
        #: negotiated via HELLO: whether this peer accepts _MSGZ frames
        self.accepts_z = accepts_z
        #: negotiated via HELLO: whether this peer accepts _MSGB frames
        self.accepts_b = False
        #: negotiated via HELLO: whether this peer accepts _FLEETF frames
        self.accepts_f = False
        self._q_bytes = 0  # approximate: adjusted under _dead_lock only
        self._q: queue.Queue = queue.Queue(maxsize=self.QUEUE_MAX)
        self._on_dead = on_dead
        #: wire-byte accounting callback (observability plane): called
        #: with each frame's on-wire size AFTER a successful send
        self._on_sent = on_sent
        self._dead = False
        self._dead_lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def enqueue(self, kind: int, payload: bytes, attempt: int = 0) -> bool:
        # the dead flag is flipped (under this lock) BEFORE the dying
        # sender thread drains the queue, so a late enqueue can never
        # slip in after the drain and be claimed sent but never salvaged
        with self._dead_lock:
            if self._dead:
                return False
            if self._q_bytes + len(payload) > self.QUEUE_MAX_BYTES:
                return False  # byte cap: dropped; periodic sync will retry
            try:
                self._q.put_nowait((kind, payload, attempt))
                self._q_bytes += len(payload)
                return True
            except queue.Full:
                return False  # dropped; periodic sync will retry

    def queued_bytes(self) -> int:
        """Bytes currently queued on this connection (scrape-time)."""
        with self._dead_lock:
            return self._q_bytes

    def close(self) -> None:
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        # close() can run ON the sender thread (the _on_dead path fires
        # from _loop's error handler) — joining ourselves would deadlock
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            with self._dead_lock:
                self._q_bytes -= len(item[1])
            try:
                faultpoint("transport.send")
            except FaultInjected:
                # injected send-side loss: this frame is gone, exactly
                # like a dropped packet — periodic anti-entropy heals it
                continue
            try:
                _send_frame(self.sock, item[0], item[1])
                if self._on_sent is not None:
                    self._on_sent(len(item[1]) + 5)  # + length word + kind
            except OSError:
                # hand the failed frame and the rest of the queue back to
                # the transport: a stale pooled conn (peer restarted) must
                # not silently eat frames the caller was told were sent
                with self._dead_lock:
                    self._dead = True  # late enqueues now refuse
                pending = [item]
                while True:
                    try:
                        nxt = self._q.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is not None:
                        pending.append(nxt)
                try:
                    self.sock.close()
                except OSError:
                    pass
                self._on_dead(self, pending)
                return


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class TcpTransport:
    """Transport with the LocalTransport interface plus TCP remote sends.

    Remote addresses: ``(name, (host, port))``. Everything else (bare
    names) is local to this process.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, heartbeat_interval: float = 0.5):
        self._lock = threading.Lock()
        self._mailboxes: dict[Hashable, queue.Queue] = {}
        self._owners: dict[Hashable, Any] = {}
        self._monitors: dict[Hashable, set[Hashable]] = {}
        self._conns: dict[tuple, _SenderConn] = {}
        self._hb_conns: dict[tuple, socket.socket] = {}  # persistent ping conns
        self.heartbeat_interval = heartbeat_interval
        #: wire-byte accounting (observability plane): written by the
        #: per-connection sender threads / per-connection serve threads,
        #: read by :meth:`transport_stats` at scrape time. A dedicated
        #: lock (crdtlint RACE001) — bumping an int per frame on the
        #: transport-wide lock would contend with register/send/drain
        #: on every frame of every connection
        self._bytes_lock = threading.Lock()
        self._tx_bytes = 0
        self._rx_bytes = 0
        self._stop = threading.Event()

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self.host, self.port = self._listener.getsockname()

        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"tcp-accept-{self.port}", daemon=True
        )
        self._accept_thread.start()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name=f"tcp-hb-{self.port}", daemon=True
        )
        self._hb_thread.start()

    @property
    def endpoint(self) -> tuple[str, int]:
        return (self.host, self.port)

    def remote_addr(self, name: Hashable) -> tuple:
        """The ``{name, node}``-style address of a local name, for peers."""
        return (name, self.endpoint)

    canonical_addr = remote_addr  # replicas self-identify cross-node

    # -- local registry (same contract as LocalTransport) -----------------

    def register(self, addr: Hashable, owner: Any) -> None:
        with self._lock:
            if addr in self._owners:
                raise ValueError(f"address already registered: {addr!r}")
            self._mailboxes[addr] = queue.Queue()
            self._owners[addr] = owner

    def unregister(self, addr: Hashable) -> None:
        addr = self._local_name(addr)
        with self._lock:
            self._mailboxes.pop(addr, None)
            self._owners.pop(addr, None)
            watchers = self._monitors.pop(addr, set())
        for w in watchers:
            self.send(w, Down(addr))

    def _is_remote(self, addr) -> bool:
        return (
            isinstance(addr, tuple)
            and len(addr) == 2
            and isinstance(addr[1], tuple)
            and len(addr[1]) == 2
            and addr[1] != self.endpoint
        )

    def alive(self, addr: Hashable) -> bool:
        if self._is_remote(addr):
            return self._ping(addr)
        addr = self._local_name(addr)
        with self._lock:
            return addr in self._owners

    def device_of(self, addr: Hashable):
        """Device data plane applies only to same-process peers: remote
        frames must serialise, so cross-host slices stay on the host
        plane (pickled + compressed) and the mesh SPMD path covers
        device↔device movement across hosts."""
        if self._is_remote(addr):
            return None
        with self._lock:
            return getattr(self._owners.get(self._local_name(addr)), "device", None)

    def _local_name(self, addr):
        # a remote-style address pointing at ourselves resolves locally
        if isinstance(addr, tuple) and len(addr) == 2 and addr[1] == self.endpoint:
            return addr[0]
        return addr

    # -- sending -----------------------------------------------------------

    def send(self, addr: Hashable, msg: Any) -> bool:
        if self._is_remote(addr):
            return self._send_remote(addr, (_MSG, addr[0], msg))
        name = self._local_name(addr)
        with self._lock:
            mb = self._mailboxes.get(name)
            owner = self._owners.get(name)
        if mb is None:
            return False
        mb.put(msg)
        notify = getattr(owner, "notify", None)
        if notify is not None:
            notify()
        return True

    def _connect(self, endpoint: tuple) -> "_SenderConn | None":
        with self._lock:
            conn = self._conns.get(endpoint)
        if conn is not None:
            return conn
        if self._stop.is_set():
            return None
        try:
            sock = socket.create_connection(endpoint, timeout=2.0)
            sock.settimeout(5.0)
        except OSError:
            return None

        def on_dead(dead_conn, pending):
            with self._lock:
                if self._conns.get(endpoint) is dead_conn:
                    del self._conns[endpoint]
            # salvage frames that died on a stale pooled connection: one
            # reconnect attempt per frame (attempt tag prevents a retry
            # loop against a flapping peer; a dead listener fails the
            # connect and the frames drop — the periodic sync re-covers)
            retry = [(k, p) for k, p, attempt in pending if attempt == 0]
            if retry and not self._stop.is_set():
                fresh = self._connect(endpoint)
                if fresh is not None:
                    for k, p in retry:
                        # renegotiated down (peer restarted on an older
                        # build, or the fresh HELLO hasn't landed yet):
                        # re-frame for the lowest common denominator
                        if k == _MSGZ and not fresh.accepts_z:
                            k, p = _MSG, zlib.decompress(p)
                        elif k == _MSGB and not fresh.accepts_b:
                            k, p = _MSG, pickle.dumps(_decode_msgb(p), protocol=4)
                        elif k == _FLEETF and not fresh.accepts_f:
                            # unbundle the envelope for the downgraded
                            # peer: one per-member frame per entry
                            fm = _decode_msgb(p)
                            for to, m in fm.entries:
                                self._send_remote(to, (_MSG, to[0], m))
                            continue
                        fresh.enqueue(k, p, attempt=1)

        conn = _SenderConn(sock, on_dead, on_sent=self._count_tx)
        _start_hello_negotiation(conn)
        with self._lock:
            if self._stop.is_set():
                # close() already ran (or is running): never insert a
                # fresh conn it would miss — refuse under the same lock
                conn.close()
                return None
            existing = self._conns.get(endpoint)
            if existing is not None:
                conn.close()
                return existing
            self._conns[endpoint] = conn
        return conn

    def _send_remote(self, addr: tuple, frame: tuple) -> bool:
        """Fast-fail if no connection can be established (the dead-
        neighbour signal, ``causal_crdt.ex:269-282``); otherwise enqueue
        on the connection's sender thread and return immediately."""
        _name, endpoint = addr
        conn = self._connect(endpoint)
        if conn is None:
            return False
        kind = frame[0]
        # both wire upgrades are negotiated capabilities (HELLO), never
        # assumed: a legacy peer gets plain pickle4 frames
        if kind == _MSG and conn.accepts_b:
            # arrays side-channel: zero-copy buffer framing + per-buffer
            # probe-gated compression (the DCN data plane for big
            # slices); small messages fall through to the legacy frame
            payload_b = _encode_msgb(frame[1:], min_bytes=_MSGB_MIN)
            if payload_b is not None:
                return conn.enqueue(_MSGB, payload_b)
        payload = pickle.dumps(frame[1:], protocol=4)
        if kind == _MSG and conn.accepts_z and len(payload) >= _COMPRESS_MIN:
            z = zlib.compress(payload, 1)
            if len(z) < 0.9 * len(payload):  # keep incompressible frames raw
                payload, kind = z, _MSGZ
        return conn.enqueue(kind, payload)

    def _count_tx(self, n: int) -> None:
        with self._bytes_lock:
            self._tx_bytes += n

    # -- fleet egress frames (ISSUE 10) ------------------------------------

    def fleet_sink(self, addr: Hashable) -> "tuple | None":
        """The fleet-frame aggregation key for ``addr``: its remote
        endpoint when the pooled connection there negotiated
        ``_FEAT_FLEET``, else ``None`` (per-member frames — a local
        peer, a dead endpoint, a legacy peer, or a HELLO still in
        flight). The fleet egress groups one tick's outbound sync
        messages by this key and ships one :class:`~delta_crdt_ex_tpu.
        runtime.sync.FleetFrameMsg` per endpoint."""
        if not self._is_remote(addr):
            return None
        endpoint = addr[1]
        conn = self._connect(endpoint)
        if conn is None or not conn.accepts_f:
            return None
        return endpoint

    def send_fleet_frame(self, endpoint: tuple, entries: list) -> bool:
        """Ship one fleet egress envelope — many members' per-peer sync
        messages in ONE ``_FLEETF`` frame — to a peer process. Falls
        back to per-member sends when the connection renegotiated down
        between :meth:`fleet_sink` and here (peer restarted on an older
        build); the messages still flow, but the return is ``False`` so
        callers' frame-aggregation accounting never reports an envelope
        that did not actually ride the wire. ``True`` means the frame is
        queued on the sender connection; a later drop (peer died
        mid-flight) is healed by the periodic sync like any lost frame."""
        conn = self._connect(endpoint)
        if conn is None:
            return False
        if not conn.accepts_f:
            for to, m in entries:
                self.send(to, m)
            return False
        fm = sync_proto.FleetFrameMsg(frm=self.endpoint, entries=list(entries))
        payload = _encode_msgb(fm, min_bytes=0)
        return conn.enqueue(_FLEETF, payload)

    def _deliver_fleet_frame(self, fm) -> None:
        """Fan one received fleet envelope out: local entries deliver to
        mailboxes in send order; entries addressed to members of ANOTHER
        process — a relay hop in hierarchical anti-entropy (ISSUE 15) —
        regroup by next-hop endpoint and re-emit as ONE rewritten
        ``_FLEETF`` frame each (the envelope's ``entries`` rewritten in
        place, inner messages untouched) instead of N per-member remote
        frames. Per-destination order is preserved (grouping never
        reorders entries sharing a destination); a legacy/dead next hop
        falls back to per-member sends exactly like the sender-side
        renegotiated-down path. One shared policy with the replica's
        whole-envelope fallback (``transport.forward_fleet_entries``)."""
        forward_fleet_entries(self, fm.entries)

    def queue_depth(self, addr: Hashable) -> int:
        """Queued messages in one LOCAL mailbox (the observability
        plane's mailbox-depth gauge; same contract as LocalTransport)."""
        with self._lock:
            mb = self._mailboxes.get(self._local_name(addr))
        return mb.qsize() if mb is not None else 0

    def transport_stats(self) -> dict:
        """Scrape-time wire accounting: bytes sent/received over TCP and
        bytes queued on sender connections (the backpressure signal —
        ``QUEUE_MAX_BYTES`` drops begin when a peer's queue fills)."""
        with self._bytes_lock:
            tx, rx = self._tx_bytes, self._rx_bytes
        with self._lock:
            conns = list(self._conns.values())
        return {
            "endpoint": f"{self.host}:{self.port}",
            "tx_bytes": tx,
            "rx_bytes": rx,
            "queue_bytes": sum(c.queued_bytes() for c in conns),
        }

    @staticmethod
    def _ping_roundtrip(sock: socket.socket) -> bool:
        """One PING → PONG exchange on an open socket (the single wire
        handshake shared by ``alive()`` probes and heartbeats)."""
        _send_frame(sock, _PING, b"")
        frame = _recv_frame(sock)
        return frame is not None and frame[0] == _PONG

    def _ping(self, addr: tuple) -> bool:
        # connection-level liveness: a fresh short-lived connection probes
        # the remote listener (the monitored name is checked by heartbeat
        # MSG delivery failures instead; a dead listener is the BEAM
        # "node down" analog)
        try:
            with socket.create_connection(addr[1], timeout=1.0) as s:
                s.settimeout(2.0)
                return self._ping_roundtrip(s)
        except OSError:
            return False

    # -- monitors ----------------------------------------------------------

    def monitor(self, watcher: Hashable, target: Hashable) -> bool:
        if not self.alive(target):
            return False
        key = target if not isinstance(target, list) else tuple(target)
        with self._lock:
            self._monitors.setdefault(key, set()).add(watcher)
        return True

    def demonitor(self, watcher: Hashable, target: Hashable) -> None:
        with self._lock:
            self._monitors.get(target, set()).discard(watcher)

    def _hb_drop(self, endpoint: tuple) -> None:
        sock = self._hb_conns.pop(endpoint, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _ping_once(self, endpoint: tuple) -> bool:
        """One ping round-trip over the cached per-endpoint connection
        (opened on first use) — no connection churn per tick."""
        sock = self._hb_conns.get(endpoint)
        try:
            if sock is None:
                sock = socket.create_connection(endpoint, timeout=1.0)
                sock.settimeout(2.0)
                self._hb_conns[endpoint] = sock
            if not self._ping_roundtrip(sock):
                raise OSError("bad pong")
            return True
        except OSError:
            self._hb_drop(endpoint)
            return False

    def _ping_persistent(self, endpoint: tuple) -> bool:
        """Heartbeat with a PERSISTENT connection, but never declare a
        peer dead on a stale cached socket alone: a failed cached ping
        retries once on a fresh connection (a transport restart on the
        same port, or an idle conn reset, must not deliver a false Down).
        Only the heartbeat thread touches ``_hb_conns``."""
        had_conn = self._hb_conns.get(endpoint) is not None
        if self._ping_once(endpoint):
            return True
        return had_conn and self._ping_once(endpoint)

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            with self._lock:
                remote_targets = [t for t in self._monitors if self._is_remote(t)]
            # close heartbeat conns for endpoints no longer monitored
            # (demonitored peers must not leak sockets)
            live = {t[1] for t in remote_targets}
            for ep in [e for e in self._hb_conns if e not in live]:
                self._hb_drop(ep)
            for t in remote_targets:
                if not self._ping_persistent(t[1]):
                    with self._lock:
                        watchers = self._monitors.pop(t, set())
                    for w in watchers:
                        self.send(w, Down(t))
        # _stop is set: release remaining heartbeat conns on this thread
        # (the only writer of _hb_conns — close() joins us, no race)
        for ep in list(self._hb_conns):
            self._hb_drop(ep)

    # -- receiving ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _peer = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        warned_unknown = False
        with conn:
            while not self._stop.is_set():
                frame = _recv_frame(conn)
                if frame is None:
                    return
                kind, payload = frame
                with self._bytes_lock:
                    self._rx_bytes += len(payload) + 5
                if kind == _PING:
                    try:
                        _send_frame(conn, _PONG, b"")
                    except OSError:
                        return
                elif kind == _HELLO:
                    try:
                        _send_frame(conn, _HELLO,
                                    bytes([_WIRE_VERSION, _OUR_FEATURES]))
                    except OSError:
                        return
                elif kind == _MSG:
                    try:
                        faultpoint("transport.recv")
                    except FaultInjected:
                        continue  # injected receive-side loss (see send)
                    name, msg = pickle.loads(payload)
                    self.send(name, msg)
                elif kind == _MSGZ:
                    name, msg = pickle.loads(zlib.decompress(payload))
                    self.send(name, msg)
                elif kind == _MSGB:
                    name, msg = _decode_msgb(payload)
                    self.send(name, msg)
                elif kind == _FLEETF:
                    # fleet egress envelope: decode back to per-member
                    # mailbox deliveries, in send order (per-(sender,
                    # receiver) ordering is exactly the per-member path's)
                    fm = _decode_msgb(payload)
                    self._deliver_fleet_frame(fm)
                elif not warned_unknown:
                    # once per connection: a misbehaving/newer peer
                    # streaming frames must not flood the log
                    warned_unknown = True
                    logger.warning(
                        "dropping unknown frame kind %d (peer on a newer "
                        "wire format?) — further unknown frames on this "
                        "connection are dropped silently", kind)

    # -- deterministic driving (parity with LocalTransport) ----------------

    def drain_nowait(self, addr: Hashable, max_n: int | None = None) -> list:
        """Pop up to ``max_n`` queued messages (all when ``None``) without
        blocking — same contract as ``LocalTransport.drain_nowait``:
        per-mailbox FIFO order is preserved across message types, so a
        ``Down`` never passes entries from the same peer and log-shipping
        catch-up frames (``GetLogMsg``/``LogChunkMsg``, whose slice
        arrays ride the ``_MSGB`` buffer side-channel like any other
        big-array frame) stay ordered against walk and entries traffic."""
        with self._lock:
            mb = self._mailboxes.get(self._local_name(addr))
        out: list = []
        if mb is None:
            return out
        while max_n is None or len(out) < max_n:
            try:
                out.append(mb.get_nowait())
            except queue.Empty:
                break
        return out

    def drain(self, addr: Hashable) -> list:
        return self.drain_nowait(addr, None)

    def pump(self, max_rounds: int = 10_000) -> int:
        delivered = 0
        for _ in range(max_rounds):
            progressed = False
            with self._lock:
                addrs = list(self._owners)
            for addr in addrs:
                with self._lock:
                    owner = self._owners.get(addr)
                if owner is None:
                    continue
                for msg in self.drain(addr):
                    owner.handle(msg)
                    delivered += 1
                    progressed = True
            if not progressed:
                return delivered
        raise RuntimeError("transport did not quiesce")

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        # heartbeat conns are owned by the hb thread; joining it (it exits
        # promptly on _stop) lets it close them without a cross-thread race
        self._hb_thread.join(timeout=5)
        # the accept loop unblocks when the listener above is closed
        self._accept_thread.join(timeout=5)
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.close()
