"""Persistence behaviour (reference ``DeltaCrdt.Storage``, ``storage.ex:15-16``).

Contract: ``write(name, snapshot)`` / ``read(name) -> snapshot | None``.
The snapshot carries everything needed for dot-counter continuity across
a crash-restart (reference stores ``{node_id, sequence_number, crdt_state,
merkle_map}``, ``causal_crdt.ex:242-250`` — note its typespec says 3-tuple,
a doc/impl mismatch we fix rather than copy, SURVEY §2.1 #5).

The reference writes through on **every** state change
(``causal_crdt.ex:402-403``), which would serialise the device pipeline
here; the replica driver therefore supports ``storage_mode="every_op"``
(parity default: the crash-rehydrate tests of the reference hold exactly)
and ``storage_mode="interval"`` (async snapshot cadence — the TPU-sane
choice, SURVEY §5.4).
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import threading
from typing import Any, Protocol

import numpy as np


#: engine layout written by the current replica driver; bumped whenever
#: the column set / array shapes change incompatibly
CURRENT_LAYOUT = "binned-v2"  # v2: payload dots keyed (gid, bucket, ctr); per-bucket counters


@dataclasses.dataclass
class Snapshot:
    """Host-side image of a replica: device arrays + host dictionaries."""

    node_id: int  # dot-namespace continuity across restarts
    sequence_number: int  # number of applied mutation batches
    arrays: dict[str, np.ndarray]  # DotStore columns + ctx tables
    payloads: dict[tuple[int, int, int], tuple[Any, Any]]  # (gid, bucket, ctr) -> (key_term, value)
    key_terms: dict[int, Any]  # key hash -> key term
    last_ts: int  # clock continuity (LWW monotonicity)
    layout: str = CURRENT_LAYOUT  # engine layout tag (rehydrate checks it)
    #: per-peer applied watermarks (addr -> that peer's seq this replica
    #: fully covered when the snapshot was cut) — lets a restarted
    #: replica resume log-shipping catch-up instead of paying a full
    #: digest walk. Sound to restore because recovery replays state AT
    #: LEAST as far as the snapshot: the restored state still covers
    #: everything the watermark claims. Legacy pickles lack the field
    #: (read via ``__dict__.get``): catch-up then starts from 0, which
    #: only over-serves (merges are idempotent).
    peer_seqs: dict | None = None
    #: dot-store backend that wrote ``arrays`` (ISSUE 8: "binned" — the
    #: legacy default for untagged pickles — or "hash"). Rehydrate
    #: rejects a mismatch: the layouts share no array shapes, so
    #: cross-backend restore goes through extraction (MIGRATING.md).
    store: str = "binned"


def require_layout(tag, what: str) -> None:
    """Reject snapshots written by a different engine layout with one
    shared, descriptive error (used by replica rehydrate AND mesh
    restore, so the two paths cannot drift). ``tag`` must come from the
    snapshot's instance data — beware dataclass defaults masking legacy
    untagged pickles (read ``__dict__``, not ``getattr``)."""
    if tag != CURRENT_LAYOUT:
        raise ValueError(
            f"{what} was written by engine layout {tag!r}; this build "
            f"reads {CURRENT_LAYOUT!r} — migrate or delete the stored "
            "snapshot to start fresh"
        )


def fsync_dir(directory: str) -> None:
    """fsync a directory so entry creations/renames/unlinks inside it
    survive power loss (fsyncing a file does NOT persist its dirent)."""
    fd = os.open(directory, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def name_key(name: Any) -> str:
    """Stable filesystem-safe key for a replica name (shared by
    :class:`FileStorage` snapshot paths and the WAL's per-replica
    segment directories, so the two stay colocatable)."""
    import hashlib

    return hashlib.blake2b(repr(name).encode(), digest_size=8).hexdigest()


class Storage(Protocol):
    def write(self, name: Any, snapshot: Snapshot) -> None: ...

    def read(self, name: Any) -> Snapshot | None: ...


class MemoryStorage:
    """In-memory store (reference test fixture ``memory_storage.ex``) —
    process-global so a restarted replica with the same name rehydrates."""

    _store: dict[Any, bytes] = {}
    _lock = threading.Lock()

    def write(self, name, snapshot: Snapshot) -> None:
        with self._lock:
            MemoryStorage._store[name] = pickle.dumps(snapshot)

    def read(self, name) -> Snapshot | None:
        with self._lock:
            blob = MemoryStorage._store.get(name)
        return pickle.loads(blob) if blob is not None else None

    @classmethod
    def clear(cls) -> None:
        with cls._lock:
            cls._store.clear()


class FileStorage:
    """Directory-backed store: one pickle per replica name.

    ``fsync=True`` makes each write power-loss durable (file contents
    fsynced before the rename, directory entry fsynced after) — required
    when the snapshot is a WAL compaction checkpoint, because compaction
    DELETES the fsynced log records the snapshot supersedes; an
    unflushed snapshot there would trade durable records for page
    cache. The default stays False: the plain ``every_op`` snapshot
    path never promised machine-crash durability."""

    def __init__(self, directory: str, *, fsync: bool = False):
        self.directory = directory
        self.fsync = fsync
        self._dir_synced = False  # own dirent persisted in the parent
        os.makedirs(directory, exist_ok=True)

    def _path(self, name) -> str:
        return os.path.join(self.directory, f"crdt_{name_key(name)}.pkl")

    def write(self, name, snapshot: Snapshot) -> None:
        tmp = self._path(name) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(snapshot, f)
            if self.fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, self._path(name))
        if self.fsync:
            fsync_dir(self.directory)
            if not self._dir_synced:
                # a freshly created snapshot dir's own dirent must reach
                # disk too, or power loss vanishes the directory whole
                fsync_dir(os.path.dirname(self.directory) or ".")
                self._dir_synced = True

    def read(self, name) -> Snapshot | None:
        try:
            with open(self._path(name), "rb") as f:
                return pickle.load(f)
        except FileNotFoundError:
            return None
