"""Durable delta write-ahead log — O(delta) durability for O(delta) work.

The reference persists by writing the WHOLE replica image through
storage on every state change (``causal_crdt.ex:402-403``) — O(state)
serialisation cost per mutation, the write-through bottleneck SURVEY
§5.4 flags. Deltas are already irredundant join decompositions (Enes et
al. 2018), so the natural durability unit is the delta itself: this
module logs mutation batches and accepted remote delta slices as
CRC-checked, length-prefixed records in rolling segment files, and
recovery becomes *snapshot + replay* through the replica's normal
idempotent merge path (double-apply is harmless by lattice idempotence).

Wire format (all little-endian):

- segment file ``seg-<start_seq:020d>.wal``:
  ``MAGIC(8)`` then a header record, then data records;
- every record is ``[u32 length][u32 crc32(payload)][payload]`` where
  the payload is a pickled dict. The header record's payload carries
  ``{"layout", "node_id", "start_seq"}`` — layout-tagged like snapshots
  (:data:`~delta_crdt_ex_tpu.runtime.storage.CURRENT_LAYOUT`), so a
  build with an incompatible engine layout refuses the log instead of
  replaying garbage; ``node_id`` preserves dot-namespace continuity
  even when the crash landed before the first snapshot.

Data records (``seq`` is the replica's applied-batch sequence number
AFTER the apply, contiguous across records):

- ``{"kind": "batch", "seq", "ops": [(f, key_term, value)...], "ts":
  [int...]}`` — one local mutation batch with the exact LWW timestamps
  it minted (replay re-applies through ``_flush_batch`` under a replay
  clock that re-issues those stamps, so dot counters and LWW outcomes
  reproduce bit-for-bit);
- ``{"kind": "entries", "seq", "arrays": {col: np.ndarray}, "payloads",
  "buckets"}`` — one accepted remote delta slice (host-plane numpy
  image of the ``EntriesMsg``), replayed through the normal merge
  kernel.

Group commit: ``append`` encodes into an in-process buffer (raw
``os.write``/``os.fsync`` file I/O — no Python buffering, so a crashed
replica loses exactly the uncommitted suffix, nothing less). ``commit``
marks a durability point; the ``fsync_mode`` knob picks the cadence:

- ``"record"`` — write + fsync on every append (safest, slowest);
- ``"batch"``  — write + fsync once per commit (one mutation batch or
  one accepted slice; the group-commit default);
- ``"interval"`` — write per commit, fsync at most every
  ``fsync_interval`` seconds (the replica's event loop also calls
  ``maybe_sync`` so an idle replica still reaches disk);
- ``"none"`` — write per commit, never fsync (tests / ephemera).

A torn tail record (short read or CRC mismatch in the LAST segment) is
truncated away on recovery, not crashed on; corruption in a non-final
segment raises :class:`WalCorruption` — that is real data loss, not an
interrupted append. Compaction deletes segments fully covered by a
snapshot's ``sequence_number``; the active segment is rotated first so
every segment can eventually be reclaimed.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import pickle
import struct
import time
import zlib
from typing import Any, Iterator

from delta_crdt_ex_tpu.runtime.storage import (
    CURRENT_LAYOUT,
    fsync_dir,
    require_layout,
)
from delta_crdt_ex_tpu.utils.faults import CrashInjected, faultpoint

logger = logging.getLogger("delta_crdt_ex_tpu")

MAGIC = b"DCWAL001"
_HEADER = struct.Struct("<II")  # record length, crc32(payload)

FSYNC_MODES = ("record", "batch", "interval", "none")


class WalCorruption(Exception):
    """Unrecoverable log damage (corruption NOT at the tail)."""


@dataclasses.dataclass
class SegmentInfo:
    """In-memory index entry for one segment file."""

    path: str
    start_seq: int  # first data-record seq this segment may hold


def _encode(payload: dict) -> bytes:
    blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    return _HEADER.pack(len(blob), zlib.crc32(blob)) + blob


def _iter_records(blob: bytes, offset: int) -> Iterator[tuple[int, dict]]:
    """Yield ``(end_offset, payload)`` per complete CRC-valid record;
    stop (without raising) at the first torn/short/corrupt record — the
    caller decides whether stopping early is truncation or corruption."""
    n = len(blob)
    while offset + _HEADER.size <= n:
        length, crc = _HEADER.unpack_from(blob, offset)
        start = offset + _HEADER.size
        end = start + length
        if end > n:
            return  # short record: torn mid-payload
        payload = blob[start:end]
        if zlib.crc32(payload) != crc:
            return  # torn/corrupt record
        yield end, pickle.loads(payload)
        offset = end


def _stream_records(path: str, chunk: int = 256 << 10) -> Iterator[tuple[int, dict]]:
    """Stream ``(record_bytes, payload)`` from one segment file WITHOUT
    loading the whole segment: the file is read in ``chunk``-sized
    blocks and :func:`_iter_records` parses each block's buffer, with
    the unconsumed tail carried into the next block so records spanning
    block boundaries (or larger than a block) still parse whole. Stops
    at the first torn/corrupt record exactly like ``_iter_records`` —
    range reads over the ACTIVE segment race an in-flight append
    harmlessly (the half-written tail reads as torn and simply ends the
    stream; the caller's cursor picks it up next round). The first
    yielded payload is the segment header."""
    with open(path, "rb") as f:
        buf = f.read(max(chunk, len(MAGIC)))
        if buf[: len(MAGIC)] != MAGIC:
            return  # torn at birth / foreign file: nothing servable
        off = len(MAGIC)
        while True:
            for end, payload in _iter_records(buf, off):
                yield end - off, payload
                off = end
            # truncation vs corruption: when the buffer already holds
            # the stuck record WHOLE (length prefix satisfied) and it
            # still failed to parse, more bytes cannot help — stop, or
            # a mid-segment CRC tear would rebuffer the rest of the
            # file quadratically chunk by chunk. When the prefix says
            # the record is BIGGER than a chunk, read the exact
            # remainder in one call — chunk-sized nibbling would
            # re-copy the accumulated buffer once per chunk (quadratic
            # in the record size).
            want = chunk
            if len(buf) - off >= _HEADER.size:
                length, _crc = _HEADER.unpack_from(buf, off)
                need = _HEADER.size + length - (len(buf) - off)
                if need <= 0:
                    return  # complete but unparseable: corruption
                want = max(need, chunk)
            nxt = f.read(want)
            if not nxt:
                return
            buf = buf[off:] + nxt
            off = 0


class WalLog:
    """One replica's write-ahead delta log in ``directory``.

    Not thread-safe by itself — the replica serialises all calls under
    its own lock, like every other piece of host state.
    """

    def __init__(
        self,
        directory: str,
        *,
        fsync_mode: str = "batch",
        segment_bytes: int = 4 << 20,
        fsync_interval: float = 0.05,
    ):
        if fsync_mode not in FSYNC_MODES:
            raise ValueError(
                f"{fsync_mode!r} is not a valid fsync_mode; pick one of {FSYNC_MODES}"
            )
        self.directory = directory
        self.fsync_mode = fsync_mode
        self.segment_bytes = int(segment_bytes)
        self.fsync_interval = float(fsync_interval)
        os.makedirs(directory, exist_ok=True)
        self.node_id: int | None = None  # bound after recovery/first init
        self.recovered_bytes = 0  # data bytes scanned by the last recover()
        self._dir_synced = False  # parent dirent of the log dir persisted
        self._segments: list[SegmentInfo] = self._scan_segments()
        self._fd: int | None = None
        self._buf = bytearray()
        self._size = 0  # bytes in the active segment (including header)
        self._last_seq = 0  # highest data-record seq ever appended/seen
        self._dirty = False  # bytes written since the last fsync
        self._last_sync = time.monotonic()
        #: memoised compaction horizon (None = recompute): appends never
        #: move it (new records extend the servable suffix), only
        #: compaction and recovery do — so sync openers can stamp it
        #: every tick without re-reading segment heads
        self._horizon: int | None = None

    # ------------------------------------------------------------------
    # segment bookkeeping

    def _scan_segments(self) -> list[SegmentInfo]:
        segs = []
        for fn in os.listdir(self.directory):
            if fn.startswith("seg-") and fn.endswith(".wal"):
                try:
                    start = int(fn[4:-4])
                except ValueError:
                    continue
                segs.append(SegmentInfo(os.path.join(self.directory, fn), start))
        segs.sort(key=lambda s: s.start_seq)
        return segs

    def _open_segment(self, start_seq: int) -> None:
        assert self.node_id is not None, "bind(node_id) before appending"
        path = os.path.join(self.directory, f"seg-{start_seq:020d}.wal")
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
        header = _encode(
            {"layout": CURRENT_LAYOUT, "node_id": self.node_id, "start_seq": start_seq}
        )
        os.write(fd, MAGIC + header)
        if self.fsync_mode != "none":
            # persist the DIRENT too: fsyncing record bytes into a file
            # whose directory entry is still cache-only would let power
            # loss vanish the whole segment, records and all — and the
            # same applies one level up for a freshly created log dir
            fsync_dir(self.directory)
            if not self._dir_synced:
                fsync_dir(os.path.dirname(self.directory) or ".")
                self._dir_synced = True
        self._fd = fd
        self._size = len(MAGIC) + len(header)
        # a start_seq can be REUSED when recovery truncated a segment's
        # first record and its re-mint reopens the same filename — the
        # stale index entry must not survive as a duplicate
        self._segments = [s for s in self._segments if s.path != path]
        self._segments.append(SegmentInfo(path, start_seq))

    def bind(self, node_id: int) -> None:
        """Set the dot-namespace id stamped into segment headers (called
        once the replica knows its identity — post-recovery)."""
        self.node_id = int(node_id)

    # ------------------------------------------------------------------
    # append / group commit

    def append(self, record: dict) -> int:
        """Stage one data record; returns its encoded size in bytes.
        Durability follows ``fsync_mode`` — ``"record"`` reaches disk
        here, everything else at :meth:`commit`."""
        faultpoint("wal.append")
        seq = int(record["seq"])
        blob = _encode(record)
        if self._fd is None:
            self._open_segment(seq)
        self._buf += blob
        self._last_seq = seq
        if self.fsync_mode == "record":
            self._write_out(fsync=True)
        return len(blob)

    def abort(self) -> None:
        """Drop staged-but-unwritten bytes after a failed append/commit.
        The caller rolls its seq back and re-stages on retry — an
        aborted record left in the buffer would ride the NEXT group
        commit alongside the re-minted seq, and recovery (correctly)
        rejects a duplicate-seq log as corrupt. Written-but-unfsynced
        bytes are :meth:`_scrub`-ed inside :meth:`_write_out` itself;
        this handles the stage-only window before them."""
        self._buf.clear()

    def commit(self) -> None:
        """Group-commit boundary: flush staged records to the OS, fsync
        per ``fsync_mode``, and rotate the segment if it outgrew
        ``segment_bytes``."""
        self._write_out(fsync=self.fsync_mode == "batch")
        if self.fsync_mode == "interval":
            self.maybe_sync()
        if self._size >= self.segment_bytes:
            self.rotate()

    def maybe_sync(self) -> None:
        """Interval-mode deferred fsync (also called from the replica's
        event loop so an idle replica still reaches disk). A no-op in
        every other mode — ``"none"`` means NEVER fsync, and
        record/batch modes are clean at commit boundaries."""
        if self.fsync_mode != "interval":
            return
        if (
            self._dirty
            and self._fd is not None
            and time.monotonic() - self._last_sync >= self.fsync_interval
        ):
            os.fsync(self._fd)
            self._dirty = False
            self._last_sync = time.monotonic()

    def _write_out(self, fsync: bool) -> None:
        staged = b""
        base = None  # file length before THIS batch's bytes landed
        if self._buf:
            if self._fd is None:
                raise WalCorruption("append buffer with no open segment")
            frac = faultpoint("wal.write")
            if frac is not None:
                # cooperating partial-write injection: persist only a
                # prefix of the staged bytes (fsynced, so the torn tail
                # is deterministically on disk), then die — the
                # recovery legs' reproducible truncate-the-tail input
                buf = bytes(self._buf)
                n = max(1, min(len(buf) - 1, int(len(buf) * frac)))
                os.write(self._fd, buf[:n])
                os.fsync(self._fd)
                self._buf.clear()
                raise CrashInjected(
                    f"partial WAL write injected: {n}/{len(buf)} bytes"
                )
            staged = bytes(self._buf)
            base = os.fstat(self._fd).st_size
            try:
                os.write(self._fd, staged)
            except BaseException:
                # the batch may be partially written; the caller rolls
                # its seq back and will RE-stage these records, so both
                # the file tail and the buffer must forget them
                self._buf.clear()
                self._scrub(base)
                raise
            self._size += len(staged)
            self._buf.clear()
            self._dirty = True
        if fsync and self._dirty and self._fd is not None:
            try:
                faultpoint("wal.fsync")
                os.fsync(self._fd)
            except BaseException:
                if base is not None:
                    # failure atomicity for the group commit: this
                    # batch's bytes are written but not fsynced, while
                    # the caller's seq rollback + retry will re-append
                    # the same seqs — scrub the batch or recovery later
                    # reads a duplicate-seq log and (correctly) calls
                    # it corrupt ("sequence regressed")
                    self._scrub(base)
                    self._size -= len(staged)
                raise
            self._dirty = False
            self._last_sync = time.monotonic()

    def _scrub(self, base: int) -> None:
        """Roll the active segment back to ``base`` bytes after a failed
        group commit. The segment fd is NOT ``O_APPEND`` (writes track
        the seek cursor), so the cursor must follow the truncate or the
        next batch would land past EOF and leave a hole. The truncate is
        itself fsynced: a scrub that only lives in page cache could
        resurrect the aborted batch on power loss."""
        try:
            os.ftruncate(self._fd, base)
            os.lseek(self._fd, base, os.SEEK_SET)
            os.fsync(self._fd)
        except OSError:
            # the device itself is failing; recovery's tail scan (and
            # its seq-regression check) is the remaining line of defence
            pass

    def rotate(self) -> None:
        """Close the active segment; the next append opens a fresh one.
        Rotation is what makes the once-active segment eligible for
        compaction. Interval mode fsyncs the tail here regardless of
        cadence: ``maybe_sync`` can never reach a closed fd, so an
        unflushed tail would otherwise stay cache-only forever."""
        faultpoint("wal.rotate")
        self._write_out(fsync=self.fsync_mode != "none")
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None
            self._size = 0

    # ------------------------------------------------------------------
    # recovery

    def recover(self) -> tuple[dict | None, list[dict]]:
        """Scan all segments in order; returns ``(header, records)``
        where ``header`` is the newest segment header (None when the log
        is empty) and ``records`` every CRC-valid data record in seq
        order. A torn tail in the FINAL segment is truncated in place;
        damage anywhere else raises :class:`WalCorruption`."""
        header: dict | None = None
        records: list[dict] = []
        self.recovered_bytes = 0
        self._horizon = None  # truncation/discard may move the horizon
        self._segments = self._scan_segments()
        for i, seg in enumerate(self._segments):
            is_last = i == len(self._segments) - 1
            with open(seg.path, "rb") as f:
                blob = f.read()
            if blob[: len(MAGIC)] != MAGIC:
                # power loss between the dirent fsync and the first
                # content fsync leaves a durable empty/short segment —
                # torn at birth, nothing committed was in it
                if is_last:
                    logger.warning(
                        "WAL %s: missing/torn magic; discarding segment", seg.path
                    )
                    os.unlink(seg.path)
                    self._segments.pop(i)
                    break
                raise WalCorruption(f"{seg.path}: bad magic")
            good_end = len(MAGIC)
            seg_header = None
            for end, payload in _iter_records(blob, len(MAGIC)):
                if seg_header is None:
                    seg_header = payload
                    require_layout(
                        payload.get("layout", "<untagged>"), f"WAL segment {seg.path}"
                    )
                else:
                    if records and int(payload["seq"]) <= int(records[-1]["seq"]):
                        raise WalCorruption(
                            f"{seg.path}: sequence regressed "
                            f"({payload['seq']} after {records[-1]['seq']})"
                        )
                    records.append(payload)
                    self.recovered_bytes += end - good_end
                good_end = end
            if seg_header is None:
                # not even the header survived — an append torn at birth
                if is_last:
                    logger.warning("WAL %s: torn header; discarding segment", seg.path)
                    os.unlink(seg.path)
                    self._segments.pop(i)
                    break
                raise WalCorruption(f"{seg.path}: unreadable header")
            header = seg_header
            if good_end < len(blob):
                if not is_last:
                    raise WalCorruption(
                        f"{seg.path}: corrupt record mid-log at byte {good_end}"
                    )
                logger.warning(
                    "WAL %s: torn tail at byte %d of %d — truncating",
                    seg.path, good_end, len(blob),
                )
                with open(seg.path, "r+b") as f:
                    f.truncate(good_end)
        if records:
            self._last_seq = int(records[-1]["seq"])
        if header is not None and self.node_id is None:
            self.node_id = int(header["node_id"])
        # appends continue in a FRESH segment: reopening the truncated
        # tail for append would need seek bookkeeping for zero benefit
        return header, records

    # ------------------------------------------------------------------
    # range reads (log-shipping catch-up, ISSUE 4)

    @property
    def last_seq(self) -> int:
        """Highest data-record seq ever appended or recovered — the
        upper bound of what a range read can serve."""
        return self._last_seq

    def horizon(self) -> int:
        """The compaction horizon: the lowest ``lo`` such that
        :meth:`read_range` can serve EVERY record in ``(lo, last_seq]``.
        A catch-up request below it must fall back to the digest walk
        for the pre-horizon prefix. Computed from the head of the oldest
        retained segment (one small read — no index to maintain) and
        memoised: appends only extend the servable suffix, so the value
        is invalidated by compaction and recovery alone. An empty log's
        horizon is ``last_seq`` (nothing servable) — memoising that is
        still sound because the next append's record carries
        ``last_seq + k`` and serving starts right above the memo."""
        if self._horizon is None:
            h: int | None = None
            for seg in self._scan_segments():
                header_seen = False
                for _n, payload in _stream_records(seg.path):
                    if not header_seen:
                        header_seen = True
                        continue
                    h = int(payload["seq"]) - 1
                    break
                if h is not None:
                    break
            self._horizon = self._last_seq if h is None else h
        return self._horizon

    def read_range(
        self,
        lo: int,
        hi: int,
        *,
        max_records: int = 4096,
        max_bytes: int = 4 << 20,
    ) -> tuple[list[dict], int, bool]:
        """Serve data records with ``lo < seq ≤ hi``, oldest first,
        bounded by ``max_records`` / ``max_bytes`` of scanned record
        payload. Returns ``(records, next_seq, exhausted)`` where
        ``next_seq`` is the seq of the last record returned (== ``lo``
        when none) — the cursor a chunked reader resumes from — and
        ``exhausted`` is True when every record ≤ ``hi`` currently on
        disk was returned. The scan streams segments record by record
        (:func:`_stream_records`) and skips segments wholly below the
        cursor, so a chunked catch-up re-reads at most one segment's
        prefix per call, never the whole log. Callers serve from the
        window membership-gated compaction retains (``_ack_floor``);
        records compacted away are simply absent — detect that with
        :meth:`horizon`, not here."""
        records: list[dict] = []
        seen_bytes = 0
        cursor = lo
        segs = self._scan_segments()
        for i, seg in enumerate(segs):
            # a segment's records end where the next segment starts;
            # the final segment runs to the last appended seq
            end_seq = segs[i + 1].start_seq - 1 if i + 1 < len(segs) else self._last_seq
            if end_seq <= lo:
                continue  # wholly below the cursor: skip without reading
            if seg.start_seq > hi + 1:
                break
            header_seen = False
            for n_bytes, payload in _stream_records(seg.path):
                if not header_seen:
                    header_seen = True
                    continue
                seq = int(payload["seq"])
                if seq <= lo:
                    continue
                if seq > hi:
                    return records, cursor, True
                records.append(payload)
                cursor = seq
                seen_bytes += n_bytes
                if len(records) >= max_records or seen_bytes >= max_bytes:
                    return records, cursor, cursor >= min(hi, self._last_seq)
        return records, cursor, True

    # ------------------------------------------------------------------
    # compaction

    def compact(self, covered_seq: int) -> tuple[int, int]:
        """Delete segments whose every record has seq ≤ ``covered_seq``
        (i.e. fully captured by a snapshot). The active segment is
        rotated first so it, too, becomes reclaimable. Returns
        ``(segments_deleted, bytes_reclaimed)``."""
        self.rotate()
        deleted = 0
        freed = 0
        keep: list[SegmentInfo] = []
        segs = self._segments
        for i, seg in enumerate(segs):
            # a segment's records end where the next segment starts; the
            # final segment's end is the last appended seq
            end_seq = segs[i + 1].start_seq - 1 if i + 1 < len(segs) else self._last_seq
            if end_seq <= covered_seq and seg.start_seq <= covered_seq + 1:
                try:
                    freed += os.path.getsize(seg.path)
                    os.unlink(seg.path)
                    deleted += 1
                except FileNotFoundError:
                    pass  # already gone (e.g. a deduped reopen): drop it
                except OSError:
                    keep.append(seg)
            else:
                keep.append(seg)
        self._segments = keep
        self._horizon = None  # reclaimed segments raise the horizon
        if deleted and self.fsync_mode != "none":
            fsync_dir(self.directory)
        return deleted, freed

    # ------------------------------------------------------------------

    def close(self, *, flush: bool = True) -> None:
        """Close the log. ``flush=False`` models a crash: staged bytes
        in the append buffer are DROPPED (exactly what a process death
        loses under the chosen fsync cadence)."""
        if not flush:
            self._buf.clear()
        else:
            self._write_out(fsync=self.fsync_mode != "none")
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None
            self._size = 0

    def segment_paths(self) -> list[str]:
        """Current on-disk segment files, oldest first (observability +
        tests)."""
        return [s.path for s in self._scan_segments()]

    def size_bytes(self) -> int:
        """On-disk footprint of every retained segment plus the staged
        (uncommitted) append buffer — the ``crdt_wal_bytes`` gauge the
        observability plane polls at scrape time (ISSUE 9)."""
        total = len(self._buf)
        for seg in self._scan_segments():
            try:
                total += os.path.getsize(seg.path)
            except OSError:
                pass  # compaction may race a scrape; a gone segment is 0
        return total


class ReplayClock:
    """Re-issues the exact LWW timestamps a logged batch minted, so
    replay through ``_flush_batch`` reproduces dots and LWW outcomes
    bit-for-bit. Quacks like :class:`~delta_crdt_ex_tpu.runtime.clock.
    Clock` for the two minting calls the flush paths make."""

    def __init__(self, ts: list[int]):
        import numpy as np

        self._ts = np.asarray(ts, np.int64)
        self._i = 0
        self._np = np

    def next(self) -> int:
        v = int(self._ts[self._i])
        self._i += 1
        return v

    def next_n(self, n: int):
        out = self._ts[self._i : self._i + n]
        assert len(out) == n, "replay batch shorter than its ts record"
        self._i += n
        return out

    def observe(self, ts: int) -> None:  # pragma: no cover - parity stub
        pass


def wal_record_bytes(record: dict) -> int:
    """Encoded size of a record without staging it (benchmark/telemetry
    helper)."""
    return len(_encode(record))


__all__ = [
    "FSYNC_MODES",
    "ReplayClock",
    "SegmentInfo",
    "WalCorruption",
    "WalLog",
    "wal_record_bytes",
]
