"""Heavy-traffic serving plane (ISSUE 14 tentpole) — the client front door.

The reference exists to serve ``read``/``mutate``/``mutate_async`` under
load (Horde.Supervisor/Registry sit directly on top of ``DeltaCrdt``),
yet until this module every read competed with the replica event loop
for the one serialisation lock and every client mutate paid its own
lock/queue/notify round-trip. The front door restructures the client
hot path around three mechanisms:

- **Lock-free versioned read snapshots** (:class:`ReadSnapshot`): the
  replica's commit paths publish an immutable ``(version, store,
  payloads)`` triple (``Replica._serve_pub`` — a single atomic
  attribute swap under the replica lock, read here WITHOUT it). JAX
  store pytrees are immutable and the published payload dict is
  append-only for the lifetime of its generation (``Replica.gc``
  REPLACES the dict instead of pruning it in place — exactly so a
  pinned snapshot keeps resolving), so point reads, bulk
  :meth:`ReadSnapshot.read_keys`, full reads and prefix scans run off a
  pinned store generation while the event loop keeps merging. A read
  that races a commit window it cannot resolve raises
  :class:`StaleSnapshot` internally and the front door retries against
  the newer published generation (bounded, then falls back to the
  classic strong read). ``Replica.read(timeout)`` keeps its
  flush-then-read semantics untouched — it IS the strong-read mode.
- **Write admission with request coalescing** (:meth:`Frontdoor.mutate`
  / :meth:`Frontdoor.mutate_async`): client ops enqueue on an admission
  queue and a single admission worker folds everything queued into ONE
  grouped commit per window through :meth:`Replica.apply_ops` — the
  same grouped-commit entrance ``mutate_batch`` uses (one shared
  implementation, so the two batched write entrances cannot drift:
  bit-for-bit WAL/state parity is pinned in ``tests/test_serve.py``).
  N concurrent clients then cost one vectorised kernel dispatch + one
  WAL group commit instead of N lock/notify round-trips — the PR 3
  ingress-coalescing amortisation turned outward. Write tickets
  resolve when their group's kernel accounting lands (the commit
  returned), exactly like ingress acks resolve after the grouped merge.
- **Backpressure and shedding**: admission is gated on the admission
  queue depth, the transport mailbox depth, the TCP sender
  ``queue_bytes``, and the WAL compaction backlog; past a limit the op
  is REJECTED with :class:`Overloaded` (explicit shed — queueing it
  anyway would just move the collapse into the tail latency).
  Shedding state surfaces on ``/healthz`` (a registered health check
  flips the page to 503 while overloaded and recovers with the queue)
  and on the ``crdt_serve_*`` metrics family (admitted / shed /
  coalesce-depth / commit+read latency histograms via the PR 9
  registry).

Lock order: the front door's one lock is a leaf below the replica lock
(``Frontdoor`` never calls into the replica while holding it; the
admission worker pops its batch, releases, then takes the replica lock
inside ``apply_ops``). Snapshot reads take NO runtime lock at all.

``bench.py --serve`` is the open-loop load harness gating this plane:
fixed arrival rates (not closed-loop, so coordinated omission cannot
flatter the tail), p50/p99 per op class, grouped-vs-per-op write
throughput, shed/healthz flip/recovery, and bit-for-bit state/WAL
parity against an unloaded twin replaying the admission journal.
"""

from __future__ import annotations

import threading
import time
from typing import Any

import jax
import numpy as np

from delta_crdt_ex_tpu.models.binned import pow4_tier
from delta_crdt_ex_tpu.runtime import telemetry, transition
from delta_crdt_ex_tpu.utils import transfers
from delta_crdt_ex_tpu.utils.hashing import key_hash64

# -- audited device↔host transfer sites (crdtlint TRANSFER001) --------
_TR_WINNER_COLUMNS = transfers.register("serve.winner_columns")
_TR_READ_KEYS = transfers.register("serve.read_keys")


class Overloaded(RuntimeError):
    """The serving plane shed this op (explicit admission rejection).

    ``reason`` names the tripped signal: ``"admission_queue"`` (the
    front door's own pending window), ``"mailbox"`` (the replica's
    transport mailbox depth), ``"queue_bytes"`` (TCP sender queues), or
    ``"wal"`` (WAL compaction backlog). Clients should back off and
    retry; the op was NOT enqueued."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(
            f"serving plane overloaded ({reason})" + (f": {detail}" if detail else "")
        )
        self.reason = reason


class StaleSnapshot(RuntimeError):
    """A snapshot read raced a commit window it cannot resolve (a
    winner dot's payload is not in the pinned payload view). Internal:
    the front door retries against the newer published generation."""


def _winner_columns(model, store) -> tuple:
    """Flat LWW-winner columns ``(key, gid, ctr, ts)`` of a pinned
    store generation — the snapshot-side twin of the full-map pass in
    ``Replica._winner_arrays_rows(None)``: one full-table device pass,
    one batched transfer, one nonzero + flat gathers."""
    w = model.winner_all(store)
    win, key, gid, ctr, _valh, ts = _TR_WINNER_COLUMNS.get(w)
    u_idx, b_idx = np.nonzero(win)
    return tuple(a[u_idx, b_idx] for a in (key, gid, ctr, ts))


class ReadSnapshot:
    """One pinned, immutable read generation: the published store
    pytree, its payload view, and the version it committed at.

    Every read method is a pure function of the pinned triple — no
    replica lock, no event-loop interaction. Missing payloads (a
    defensive impossibility by the publication invariant: payloads are
    registered before the commit that publishes them) raise
    :class:`StaleSnapshot` so the front door can retry on a fresher
    generation instead of serving a torn view."""

    __slots__ = ("version", "store", "model", "num_buckets", "_payloads")

    def __init__(self, version: int, store, model, num_buckets: int, payloads: dict):
        self.version = version
        self.store = store
        self.model = model
        self.num_buckets = num_buckets
        self._payloads = payloads

    # -- point / bulk reads ---------------------------------------------

    def read_keys(self, key_terms: list) -> "dict | set":
        """Consistent point reads off the pinned generation (the
        lock-free counterpart of ``Replica.read_keys``)."""
        hashes = [key_hash64(k) for k in key_terms]
        k = pow4_tier(max(len(hashes), 1), 8)
        arr = np.zeros(k, np.uint64)
        arr[: len(hashes)] = hashes
        w = self.model.winners_for_keys(self.store, arr)
        found, gid, ctr = _TR_READ_KEYS.get((w.found, w.gid, w.ctr))
        out = {}
        mask = self.num_buckets - 1
        pay = self._payloads
        for i, term in enumerate(key_terms):
            if found[i]:
                dot = (int(gid[i]), int(hashes[i]) & mask, int(ctr[i]))
                rec = pay.get(dot)
                if rec is None:
                    raise StaleSnapshot(f"winner dot {dot} has no payload")
                out[term] = rec[1]
        return self.model.read_view(out)

    # -- full / scan reads ----------------------------------------------

    def _pairs(self) -> dict:
        """Full resolved map of the pinned generation (the snapshot
        twin of ``Replica._read_pairs``, including the deterministic
        LWW-ascending reinsert when ``==``-equal terms collapse)."""
        key, gid, ctr, ts = _winner_columns(self.model, self.store)
        pay = self._payloads

        def build(k, g, c):
            bucket = (k & np.uint64(self.num_buckets - 1)).astype(np.int64)
            dots = zip(g.tolist(), bucket.tolist(), c.tolist())
            try:
                return dict(map(pay.__getitem__, dots))
            except KeyError as e:
                raise StaleSnapshot(f"winner dot {e} has no payload") from None

        out = build(key, gid, ctr)
        if len(out) == len(key):
            return out
        # ==-equal terms with distinct canonical keys (1 vs True):
        # reinsert in ascending LWW order so the collapse keeps the
        # LWW-greatest write deterministically (Replica._read_pairs)
        order = np.lexsort((ctr, gid, ts))
        return build(key[order], gid[order], ctr[order])

    def read(self) -> "dict | set":
        """Full resolved read off the pinned generation."""
        return self.model.read_view(self._pairs())

    def items(self) -> list:
        """Full read as (key, value) pairs (supports unhashable-key
        maps the way ``Replica.read_items`` does)."""
        key, gid, ctr, _ts = _winner_columns(self.model, self.store)
        bucket = (key & np.uint64(self.num_buckets - 1)).astype(np.int64)
        dots = zip(gid.tolist(), bucket.tolist(), ctr.tolist())
        try:
            return list(map(self._payloads.__getitem__, dots))
        except KeyError as e:
            raise StaleSnapshot(f"winner dot {e} has no payload") from None

    def scan(self, prefix: str) -> "dict | set":
        """Prefix scan over string key terms of the pinned generation
        (non-string keys never match a string prefix)."""
        pairs = self._pairs()
        return self.model.read_view({
            k: v
            for k, v in pairs.items()
            if isinstance(k, str) and k.startswith(prefix)
        })


class WriteTicket:
    """One admitted async write: resolves when its admission group's
    commit lands (the grouped kernel/WAL accounting returned) — the
    client-facing analog of an ingress ack. ``Event``-backed, so
    waiting is a plain happens-before edge."""

    __slots__ = ("_done", "error", "t_done")

    def __init__(self) -> None:
        self._done = threading.Event()
        self.error: "BaseException | None" = None
        self.t_done = 0.0

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: "float | None" = None) -> None:
        """Block until the group committed; re-raises a commit failure."""
        if not self._done.wait(timeout):
            raise TimeoutError("write not committed within timeout")
        if self.error is not None:
            raise self.error


class _Op:
    __slots__ = ("f", "args", "ticket")

    def __init__(self, f: str, args: list) -> None:
        self.f = f
        self.args = args
        self.ticket = WriteTicket()


class Frontdoor:
    """The serving front door of ONE replica: lock-free snapshot reads,
    coalesced write admission, backpressure/shedding.

    Construction wires the plane into the replica's observability plane
    when one is attached (``serve:{name}`` health check + varz source +
    polled ``crdt_serve_pending_ops``/``crdt_serve_overloaded`` gauges,
    removed again on :meth:`close` / ``unregister_replica``). The
    admission worker is one daemon thread per front door; reads execute
    on the calling thread and never block on the replica lock.
    """

    def __init__(
        self,
        replica,
        *,
        max_commit_ops: int = 256,
        max_pending_ops: int = 4096,
        max_mailbox_depth: int = 8192,
        max_queue_bytes: int = 32 << 20,
        max_wal_backlog: "int | None" = None,
        shed_health_hold: float = 1.0,
        read_retries: int = 4,
        journal: bool = False,
    ):
        if not (1 <= max_commit_ops <= replica.MAX_BATCH):
            # one admission group == one _flush_batch == one WAL group
            # commit; past MAX_BATCH the flush would split the group and
            # the journal's group boundaries would no longer be the WAL's
            raise ValueError(
                f"max_commit_ops must be in [1, {replica.MAX_BATCH}]"
            )
        self._rep = replica
        self.name = replica.name
        self.max_commit_ops = int(max_commit_ops)
        self.max_pending_ops = int(max_pending_ops)
        self.max_mailbox_depth = int(max_mailbox_depth)
        self.max_queue_bytes = int(max_queue_bytes)
        self.max_wal_backlog = (
            int(max_wal_backlog) if max_wal_backlog is not None else None
        )
        self.shed_health_hold = float(shed_health_hold)
        self.read_retries = int(read_retries)
        #: one lock for admission queue + counters + the snapshot cache;
        #: a LEAF lock — nothing here calls into the replica (or any
        #: other runtime object) while holding it
        self._lock = threading.Lock()
        self._queue: list[_Op] = []
        self._pending_ops = 0  # queued + in-flight (the admission window)
        self._admitted_ops = 0
        self._commits = 0
        self._commit_time = 0.0
        self._commit_depth_hist: dict[int, int] = {}
        self._shed_ops = 0
        self._shed_by_reason: dict[str, int] = {}
        self._last_shed_ts = 0.0
        self._reads = 0
        self._read_retries = 0
        self._strong_fallbacks = 0
        self._snap: "ReadSnapshot | None" = None
        self._journal: "list | None" = [] if journal else None
        self._closing = False
        self._stop = threading.Event()
        self._have_ops = threading.Event()
        # prime the published snapshot so the first read never takes the
        # replica lock on the hot path (RLock-reentrant if called under it)
        replica.publish_read_snapshot()
        self._obs = replica._obs
        if self._obs is not None:
            self._obs.register_serve(self)
        # started LAST: every attribute the worker reads is published
        # before start() (the RACE004 publication idiom)
        self._worker = threading.Thread(
            target=self._admission_loop,
            name=f"crdt-serve-{replica.name}",
            daemon=True,
        )
        self._worker.start()

    # ------------------------------------------------------------------
    # reads: lock-free snapshots

    def snapshot(self) -> ReadSnapshot:
        """The newest published read generation, monotone per front
        door (a reader never observes the version go backwards). The
        replica-lock-free hot path: one atomic attribute read of the
        published triple, a cached materialisation per version."""
        pub = self._rep._serve_pub
        if pub is None:
            pub = self._rep.publish_read_snapshot()
        version = pub[0]
        with self._lock:
            snap = self._snap
            # cache hit requires the same PUBLICATION, not just the
            # same version: gc() republishes an identical version with
            # a freshly-pruned payload dict, and serving the cached
            # pre-gc view would pin the garbage gc just reclaimed
            if snap is not None and snap.version >= version and (
                snap.version > version or snap._payloads is pub[3]
            ):
                return snap
        # materialise OUTSIDE the lock: index_state is a pure function
        # of the immutable stacked pytree (a racing duplicate is benign)
        _version, state, fleet_src, payloads = pub
        if state is None:
            stacked, lane = fleet_src
            state = transition.index_state(stacked, lane)
        fresh = ReadSnapshot(
            version, state, self._rep.model, self._rep.num_buckets, payloads
        )
        with self._lock:
            cur = self._snap
            if (
                cur is None
                or cur.version < fresh.version
                # same version, newer publication (gc's pruned dict):
                # adopt it so the pre-gc dict is released
                or (
                    cur.version == fresh.version
                    and cur._payloads is not fresh._payloads
                )
            ):
                self._snap = fresh
            return self._snap

    def _read(self, mode: str, fn, strong_fn) -> Any:
        """Shared retry shell of every snapshot read: bounded
        :class:`StaleSnapshot` retries against fresher generations,
        then ``strong_fn`` — the classic locked read — as the last
        resort (defensive: the publication invariant makes the stale
        path unreachable in practice)."""
        t0 = time.perf_counter()
        retries = 0
        strong = False
        try:
            for _attempt in range(self.read_retries):
                snap = self.snapshot()
                try:
                    return fn(snap)
                except StaleSnapshot:
                    retries += 1
                    # drop the raced snapshot from the cache (the next
                    # attempt must rebuild, not re-serve it) and force
                    # a fresh publication past the raced window
                    with self._lock:
                        if self._snap is snap:
                            self._snap = None
                    self._rep.publish_read_snapshot()
            strong = True
            return strong_fn()
        finally:
            with self._lock:
                self._reads += 1
                self._read_retries += retries
                if strong:
                    self._strong_fallbacks += 1
            if telemetry.has_handlers(telemetry.SERVE_READ):
                telemetry.execute(
                    telemetry.SERVE_READ,
                    {
                        "reads": 1,
                        "retries": retries,
                        "duration_s": time.perf_counter() - t0,
                    },
                    {"name": self.name, "mode": mode},
                )

    def read_keys(self, key_terms: list) -> "dict | set":
        """Lock-free consistent point reads (pinned generation)."""
        return self._read(
            "keys",
            lambda snap: snap.read_keys(key_terms),
            lambda: self._rep.read_keys(key_terms),
        )

    def read(self) -> "dict | set":
        """Lock-free full read off the pinned generation. For the
        strong flush-then-read mode call ``Replica.read(timeout)``."""
        return self._read("full", lambda snap: snap.read(), self._rep.read)

    def scan(self, prefix: str) -> "dict | set":
        """Lock-free prefix scan over string keys (pinned generation)."""
        def strong():
            view = self._rep.read()
            d = view if isinstance(view, dict) else {k: True for k in view}
            return self._rep.model.read_view({
                k: v
                for k, v in d.items()
                if isinstance(k, str) and k.startswith(prefix)
            })

        return self._read("scan", lambda snap: snap.scan(prefix), strong)

    # ------------------------------------------------------------------
    # writes: admission with request coalescing

    def _validate(self, f: str, args: list) -> None:
        # per-client validation BEFORE grouping: a malformed op must
        # reject its own client, never poison co-admitted ops' commit
        ops = self._rep.model.OPS
        if f not in ops:
            raise ValueError(f"unknown operation {f!r}; available: {sorted(ops)}")
        _, arity = ops[f]
        if len(args) != arity:
            raise ValueError(f"{f} expects {arity} argument(s), got {len(args)}")

    def _overload_reason_locked(self) -> "str | None":
        """The first tripped backpressure signal (caller holds the
        front-door lock). Transport probes are dict-length / counter
        reads — cheap enough for the admission path — and the WAL
        backlog reads the replica's uncompacted-record counter without
        its lock (advisory: a torn read here sheds one op early or
        late, never corrupts anything)."""
        if self._pending_ops >= self.max_pending_ops:
            return "admission_queue"
        rep = self._rep
        depth_fn = getattr(rep.transport, "queue_depth", None)
        if depth_fn is not None and depth_fn(rep.addr) > self.max_mailbox_depth:
            return "mailbox"
        tstats_fn = getattr(rep.transport, "transport_stats", None)
        if (
            tstats_fn is not None
            and tstats_fn()["queue_bytes"] > self.max_queue_bytes
        ):
            return "queue_bytes"
        if (
            self.max_wal_backlog is not None
            and rep._wal is not None
            and rep._wal_unc > self.max_wal_backlog
        ):
            return "wal"
        return None

    def _submit(self, f: str, args: list) -> _Op:
        self._validate(f, args)
        with self._lock:
            if self._closing:
                raise RuntimeError(f"front door for {self.name!r} is closed")
            reason = self._overload_reason_locked()
            if reason is None:
                op = _Op(f, list(args))
                self._queue.append(op)
                self._pending_ops += 1
            else:
                self._shed_ops += 1
                self._shed_by_reason[reason] = (
                    self._shed_by_reason.get(reason, 0) + 1
                )
                self._last_shed_ts = time.monotonic()
        if reason is not None:
            if telemetry.has_handlers(telemetry.SERVE_SHED):
                telemetry.execute(
                    telemetry.SERVE_SHED,
                    {"ops": 1},
                    {"name": self.name, "reason": reason},
                )
            raise Overloaded(reason)
        self._have_ops.set()
        return op

    def mutate(self, f: str, args: list, timeout: "float | None" = None) -> None:
        """Admitted synchronous mutation: returns once the op's
        admission group committed (kernel + WAL accounting landed).
        Raises :class:`Overloaded` when shed — the op was NOT applied."""
        self._submit(f, args).ticket.result(timeout)

    def mutate_async(self, f: str, args: list) -> WriteTicket:
        """Admitted asynchronous mutation: returns the
        :class:`WriteTicket` that resolves at group commit."""
        return self._submit(f, args).ticket

    # ------------------------------------------------------------------
    # the admission worker

    def _admission_loop(self) -> None:
        while not self._stop.is_set():
            self._have_ops.wait(timeout=0.05)
            self._have_ops.clear()
            self._drain_admission()
        self._drain_admission()  # commit everything admitted before close

    def _drain_admission(self) -> int:
        """Fold everything queued into grouped commits (one
        ``apply_ops`` per window of up to ``max_commit_ops``). The
        admission window is emergent: ops arriving while a commit is in
        flight form the next group."""
        total = 0
        while True:
            with self._lock:
                batch = self._queue[: self.max_commit_ops]
                del self._queue[: len(batch)]
            if not batch:
                return total
            t0 = time.perf_counter()
            err: "BaseException | None" = None
            try:
                # THE shared grouped-commit entrance (same as
                # mutate_batch): one lock acquisition, one vectorised
                # flush, one WAL group commit for the whole window
                self._rep.apply_ops([(op.f, op.args) for op in batch])
            except BaseException as e:  # noqa: BLE001 — fanned to tickets
                err = e
            dt = time.perf_counter() - t0
            t_done = time.perf_counter()
            for op in batch:
                op.ticket.error = err
                op.ticket.t_done = t_done
                op.ticket._done.set()
            depth = len(batch)
            with self._lock:
                self._pending_ops -= depth
                self._commits += 1
                self._commit_time += dt
                self._commit_depth_hist[depth] = (
                    self._commit_depth_hist.get(depth, 0) + 1
                )
                if err is None:
                    self._admitted_ops += depth
                if self._journal is not None and err is None:
                    self._journal.append([(op.f, list(op.args)) for op in batch])
            if err is None and telemetry.has_handlers(telemetry.SERVE_ADMIT):
                # failed groups are fanned to their tickets, not counted
                # as admitted-and-committed (the internal stats already
                # exclude them — the two surfaces must agree)
                telemetry.execute(
                    telemetry.SERVE_ADMIT,
                    {"ops": depth, "duration_s": dt},
                    {"name": self.name},
                )
            total += depth

    # ------------------------------------------------------------------
    # observability

    def journal(self) -> list:
        """Committed op groups in commit order (``journal=True`` only)
        — the parity-replay record ``bench.py --serve`` feeds to the
        unloaded twin through the same ``apply_ops`` entrance."""
        with self._lock:
            if self._journal is None:
                raise ValueError("front door was created with journal=False")
            return [list(g) for g in self._journal]

    def stats(self) -> dict:
        with self._lock:
            commits = self._commits
            hist = dict(sorted(self._commit_depth_hist.items()))
            overload = self._overload_reason_locked()
            shedding = (
                overload is not None
                or time.monotonic() - self._last_shed_ts < self.shed_health_hold
            )
            return {
                "name": self.name,
                "pending_ops": self._pending_ops,
                "admitted_ops": self._admitted_ops,
                "commits": commits,
                "ops_per_commit": (
                    round(self._admitted_ops / commits, 3) if commits else 0.0
                ),
                "commit_depth_hist": hist,
                "shed_ops": self._shed_ops,
                "shed_by_reason": dict(self._shed_by_reason),
                "overloaded": shedding,
                "overload_reason": overload,
                "reads": self._reads,
                "read_retries": self._read_retries,
                "strong_read_fallbacks": self._strong_fallbacks,
                "snapshot_version": (
                    self._snap.version if self._snap is not None else 0
                ),
            }

    def obs_varz(self) -> dict:
        """``/varz`` stanza: the UNCHANGED :meth:`stats` dict under the
        typed envelope (the additive-surface contract, MIGRATING.md)."""
        return {"kind": "serve", "stats": self.stats()}

    def health(self) -> dict:
        """Readiness for ``/healthz``: unready while the plane is
        overloaded (a signal currently tripped, or sheds within the
        last ``shed_health_hold`` seconds — the sticky window that
        makes a shed spike observable) or the admission worker died.
        Recovers as soon as the pressure drains."""
        st = self.stats()
        worker_ok = self._worker.is_alive()
        return {
            "ok": worker_ok and not st["overloaded"],
            "admission_worker_alive": worker_ok,
            "overloaded": st["overloaded"],
            "overload_reason": st["overload_reason"],
            "pending_ops": st["pending_ops"],
            "shed_ops": st["shed_ops"],
        }

    # ------------------------------------------------------------------

    def close(self) -> None:
        """Stop admitting, commit everything already admitted, stop the
        worker, and unwire the observability sources (idempotent)."""
        with self._lock:
            if self._closing:
                return
            self._closing = True
        self._stop.set()
        self._have_ops.set()
        self._worker.join(timeout=10)
        if self._obs is not None:
            self._obs.unregister_serve(self)


class FleetFrontdoor:
    """One front door per fleet member plus key-hash routing: the
    many-reader/many-writer front of a whole fleet. Writes route to the
    key's owner member (``clear`` broadcasts — observed-remove
    semantics make the union of per-member clears the fleet-wide
    clear); ``read_keys`` routes each key to its owner, so clients get
    read-your-writes per key without waiting for gossip."""

    def __init__(self, fleet, **opts):
        self.fleet = fleet
        # through the replica-level accessor, NOT a bare Frontdoor():
        # each member's door registers on rep._frontdoor, so an
        # individually crashed/stopped member closes its own admission
        # worker and unwires its serve gauges exactly like a solo
        # replica's would
        self.members = [rep.frontdoor(**opts) for rep in fleet.replicas]

    def member_for(self, key_term) -> Frontdoor:
        return self.members[key_hash64(key_term) % len(self.members)]

    def mutate(self, f: str, args: list, timeout: "float | None" = None) -> None:
        for t in self._route(f, args):
            t.result(timeout)

    def mutate_async(self, f: str, args: list) -> "list[WriteTicket]":
        return self._route(f, args)

    def _route(self, f: str, args: list) -> "list[WriteTicket]":
        # validate BEFORE routing: a malformed op must raise the same
        # friendly ValueError the solo door gives, not an IndexError
        # from reading args[0] of an empty list
        self.members[0]._validate(f, args)
        if f == "clear":
            return [fd.mutate_async(f, args) for fd in self.members]
        return [self.member_for(args[0]).mutate_async(f, args)]

    def read_keys(self, key_terms: list) -> "dict | set":
        by_member: dict[int, list] = {}
        for term in key_terms:
            by_member.setdefault(
                key_hash64(term) % len(self.members), []
            ).append(term)
        out: dict = {}
        for idx, terms in by_member.items():
            view = self.members[idx].read_keys(terms)
            if isinstance(view, dict):
                out.update(view)
            else:  # AWSet member subset
                out.update({t: True for t in view})
        return self.members[0]._rep.model.read_view(out)

    def read(self, member: int = 0) -> "dict | set":
        """One member's lock-free full view (eventually consistent —
        routed writes reach other members through gossip)."""
        return self.members[member].read()

    def stats(self) -> dict:
        per = [fd.stats() for fd in self.members]
        return {
            "members": len(per),
            "pending_ops": sum(s["pending_ops"] for s in per),
            "admitted_ops": sum(s["admitted_ops"] for s in per),
            "shed_ops": sum(s["shed_ops"] for s in per),
            "commits": sum(s["commits"] for s in per),
            "overloaded": any(s["overloaded"] for s in per),
            "per_member": per,
        }

    def health(self) -> dict:
        per = [fd.health() for fd in self.members]
        return {
            "ok": all(h["ok"] for h in per),
            "members": len(per),
            "overloaded": [
                str(fd.name) for fd, h in zip(self.members, per) if h["overloaded"]
            ],
        }

    def close(self) -> None:
        for fd in self.members:
            fd.close()


__all__ = [
    "FleetFrontdoor",
    "Frontdoor",
    "Overloaded",
    "ReadSnapshot",
    "StaleSnapshot",
    "WriteTicket",
]
