"""Telemetry — the observability surface (reference: one ``:telemetry`` event).

The reference fires ``[:delta_crdt, :sync, :done]`` with
``%{keys_updated_count: n}`` and ``%{name: name}`` on **every** merge —
local ops and remote deltas alike (``causal_crdt.ex:396-398``). Same
contract here, plus a few TPU-runtime events (kernel timings, capacity
growth) under the same attach/execute API.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Callable

SYNC_DONE = ("delta_crdt", "sync", "done")  # measurements: keys_updated_count
CAPACITY_GROWN = ("delta_crdt", "capacity", "grown")  # measurements: capacity, replica_capacity
SYNC_ROUND = ("delta_crdt", "sync", "round")  # measurements: duration_s, buckets, entries; metadata: name, plane
INGEST_COALESCE = ("delta_crdt", "ingest", "coalesce")  # measurements: depth, rows, entries, duration_s; metadata: name
WAL_APPEND = ("delta_crdt", "wal", "append")  # measurements: bytes, records, duration_s
WAL_COMPACT = ("delta_crdt", "wal", "compact")  # measurements: segments_deleted, bytes_reclaimed, duration_s
WAL_RECOVER = ("delta_crdt", "wal", "recover")  # measurements: records, bytes, duration_s
CATCHUP_CHUNK = ("delta_crdt", "catchup", "chunk")  # measurements: records, rows, entries, bytes, duration_s; metadata: name, role ("server"|"client"), peer
CATCHUP_DONE = ("delta_crdt", "catchup", "done")  # measurements: chunks, duration_s, horizon_fallback; metadata: name, peer
FLEET_DISPATCH = ("delta_crdt", "fleet", "dispatch")  # measurements: replicas, lanes, messages, rows, padded_rows, duration_s; metadata: fleet

_lock = threading.Lock()
_handlers: dict[tuple, list[Callable]] = defaultdict(list)


def attach(event: tuple, handler: Callable[[tuple, dict, dict], None]) -> None:
    with _lock:
        _handlers[event].append(handler)


def detach(event: tuple, handler: Callable) -> None:
    with _lock:
        if handler in _handlers.get(event, []):
            _handlers[event].remove(handler)


def has_handlers(event: tuple) -> bool:
    with _lock:
        return bool(_handlers.get(event))


def execute(event: tuple, measurements: dict, metadata: dict) -> None:
    with _lock:
        handlers = list(_handlers.get(event, []))
    for h in handlers:
        h(event, measurements, metadata)
