"""Telemetry — the observability surface (reference: one ``:telemetry`` event).

The reference fires ``[:delta_crdt, :sync, :done]`` with
``%{keys_updated_count: n}`` and ``%{name: name}`` on **every** merge —
local ops and remote deltas alike (``causal_crdt.ex:396-398``). Same
contract here, plus a few TPU-runtime events (kernel timings, capacity
growth) under the same attach/execute API.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Callable

SYNC_DONE = ("delta_crdt", "sync", "done")  # measurements: keys_updated_count
CAPACITY_GROWN = ("delta_crdt", "capacity", "grown")  # measurements: capacity, replica_capacity
SYNC_ROUND = ("delta_crdt", "sync", "round")  # measurements: duration_s, buckets, entries; metadata: name, plane
INGEST_COALESCE = ("delta_crdt", "ingest", "coalesce")  # measurements: depth, rows, entries, duration_s; metadata: name
WAL_APPEND = ("delta_crdt", "wal", "append")  # measurements: bytes, records, duration_s
WAL_COMPACT = ("delta_crdt", "wal", "compact")  # measurements: segments_deleted, bytes_reclaimed, duration_s
WAL_RECOVER = ("delta_crdt", "wal", "recover")  # measurements: records, bytes, duration_s
CATCHUP_CHUNK = ("delta_crdt", "catchup", "chunk")  # measurements: records, rows, entries, bytes, duration_s; metadata: name, role ("server"|"client"), peer
CATCHUP_DONE = ("delta_crdt", "catchup", "done")  # measurements: chunks, duration_s, horizon_fallback; metadata: name, peer
FLEET_DISPATCH = ("delta_crdt", "fleet", "dispatch")  # measurements: replicas, lanes, messages, rows, padded_rows, duration_s; metadata: fleet
FLEET_EGRESS = ("delta_crdt", "fleet", "egress")  # measurements: members, jobs_batched, jobs_solo, dispatches, frames, frame_members, duration_s; metadata: fleet
MESH_EXCHANGE = ("delta_crdt", "mesh", "exchange")  # measurements: intra_entries, fallback_entries, permuted_bytes, exchanges, shards; metadata: fleet
JIT_COMPILE = ("delta_crdt", "jit", "compile")  # measurements: compiles (absolute tracing-cache size); metadata: name (jit entry root)
SERVE_ADMIT = ("delta_crdt", "serve", "admit")  # measurements: ops, duration_s; metadata: name
SERVE_SHED = ("delta_crdt", "serve", "shed")  # measurements: ops; metadata: name, reason
SERVE_READ = ("delta_crdt", "serve", "read")  # measurements: reads, retries, duration_s; metadata: name, mode ("keys"|"full"|"scan")
TREE_RELAY = ("delta_crdt", "tree", "relay")  # measurements: depth, entries, buckets, links, tx_bytes, rx_bytes, duration_s; metadata: name, tier
TREE_TOPOLOGY = ("delta_crdt", "tree", "topology")  # measurements: depth, fanout, tier, role (0 leaf/1 relay/2 root), members, down, degraded; metadata: name
TRANSFER = ("delta_crdt", "transfer", "crossing")  # measurements: crossings, bytes (absolute per-site ledger totals); metadata: site
FAULT_TRIP = ("delta_crdt", "fault", "trip")  # measurements: trips (per trip); metadata: site

def declared_events() -> tuple[tuple, ...]:
    """Every event tuple this module declares (the OBS001 contract:
    each must have ≥1 emission site and a metrics-bridge subscription
    row — crdtlint OBS001 enforces both statically, the bridge warns
    about missing rows at attach time, and ``tests/test_metrics.py``
    pins full table coverage)."""
    return tuple(
        v
        for k, v in sorted(globals().items())
        if k.isupper()
        and isinstance(v, tuple)
        and v
        and all(isinstance(p, str) for p in v)
    )


_lock = threading.Lock()
#: event -> handler tuple. Handler tables are REPLACED, never mutated
#: in place (copy-on-write under ``_lock``), so ``execute`` can iterate
#: the tuple it read without copying it first — one hot-path
#: allocation per event gone, and a concurrent attach/detach never
#: mutates a tuple an ``execute`` is mid-iteration over.
_handlers: dict[tuple, tuple[Callable, ...]] = defaultdict(tuple)


def attach(event: tuple, handler: Callable[[tuple, dict, dict], None]) -> None:
    with _lock:
        _handlers[event] = _handlers[event] + (handler,)


def detach(event: tuple, handler: Callable) -> None:
    with _lock:
        table = _handlers.get(event, ())
        if handler in table:
            i = table.index(handler)  # first occurrence, like list.remove
            _handlers[event] = table[:i] + table[i + 1:]


def has_handlers(event: tuple) -> bool:
    with _lock:
        return bool(_handlers.get(event))


def execute(event: tuple, measurements: dict, metadata: dict) -> None:
    with _lock:
        handlers = _handlers.get(event, ())
    for h in handlers:
        h(event, measurements, metadata)


def execute_many(event: tuple, measurements_list: list, metadata: dict) -> None:
    """Emit one event per element of ``measurements_list`` (shared
    ``metadata``), preserving order — the batch form the grouped ingest
    path uses where it already holds a natural batch.

    A plain handler observes the EXACT per-message stream ``execute``
    in a loop would deliver (the parity contracts over SYNC_DONE /
    SYNC_ROUND streams hold verbatim). A handler carrying a ``batch``
    attribute (the metrics bridge: one registry-lock acquire and one
    label resolve for the whole batch, instead of per message —
    per-message handler dispatch is the dominant enabled-telemetry cost
    at coalesce depth 16) consumes the whole list in one call."""
    with _lock:
        handlers = _handlers.get(event, ())
    for h in handlers:
        batch = getattr(h, "batch", None)
        if batch is not None:
            batch(event, measurements_list, metadata)
        else:
            for meas in measurements_list:
                h(event, meas, metadata)
