"""Anti-entropy protocol: messages + the digest-tree level walk.

This is the TPU-native redesign of the reference's two-phase Merkle
anti-entropy (Almeida et al. Algorithm 2 shell, ``causal_crdt.ex:252-289``
+ ``:86-123``):

- the originator A opens a sync with its tree root block; the peers then
  **ping-pong bounded frontier blocks** — each message carries the
  sender's digests for up to ``levels_per_round`` (default 8, exactly the
  reference's ``prepare_partial_diff(mm, 8)`` fan) tree levels beneath the
  currently-differing frontier, truncated to ``max_sync_size`` nodes
  (reference ``truncate``, ``causal_crdt.ex:206-214``);
- the receiver walks the block against its own tree (host numpy over
  device-computed digests — control on host, bulk math on device), either
  continuing the ping-pong, acking on equality (``{:ok, []}`` path,
  ``causal_crdt.ex:101-102``), or arriving at differing leaf buckets;
- differing buckets resolve to an entries transfer from the originator to
  the peer (``get_diff`` / direct-slice paths, ``causal_crdt.ex:324-335``),
  joined on device.

Every message is bounded; truncated divergence heals over subsequent
rounds (sync is idempotent). Data flows originator → peer only, matching
the reference's unidirectional edges (``delta_crdt.ex:89-94``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Hashable

import numpy as np


@dataclasses.dataclass
class DiffMsg:
    """Frontier block (the reference's ``%Diff{continuation: …}``)."""

    originator: Hashable
    frm: Hashable
    to: Hashable
    level: int  # tree level of the frontier (0 = root)
    idx: np.ndarray  # int64[f] frontier node indices at `level`
    blocks: list[np.ndarray]  # sender digests for levels level..level+j under idx


@dataclasses.dataclass
class GetDiffMsg:
    """Peer asks the originator for its entries in differing buckets
    (reference ``{:get_diff, diff, keys}``, ``causal_crdt.ex:112-123``)."""

    originator: Hashable
    frm: Hashable
    to: Hashable
    buckets: np.ndarray  # int64[b] differing leaf-bucket indices


@dataclasses.dataclass
class EntriesMsg:
    """Entry slice transfer (reference ``{:diff, crdt_slice, keys}``)."""

    originator: Hashable
    frm: Hashable
    to: Hashable
    buckets: np.ndarray
    arrays: dict[str, np.ndarray]  # DotStore slice columns + ctx tables
    payloads: dict[tuple[int, int, int], tuple[Any, Any]]  # (gid, bucket, ctr) -> (key_term, value)


@dataclasses.dataclass
class AckMsg:
    """Clears the originator's in-flight slot for `clear_addr`
    (reference ``{:ack_diff, to}``, ``causal_crdt.ex:82-84,406-412``)."""

    clear_addr: Hashable


def make_blocks(
    tree: list[np.ndarray], level: int, idx: np.ndarray, levels_per_round: int
) -> list[np.ndarray]:
    """Digest blocks for `levels_per_round` levels beneath frontier `idx`.

    ``blocks[j]`` holds digests at ``level+j`` for all descendants of the
    frontier, ordered (frontier position, subtree offset) — positions are
    derivable, so only digest values travel.
    """
    depth = len(tree) - 1
    end = min(level + levels_per_round, depth)
    blocks = [tree[level][idx]]
    for j in range(1, end - level + 1):
        child_idx = (idx[:, None] * (1 << j) + np.arange(1 << j)[None, :]).reshape(-1)
        blocks.append(tree[level + j][child_idx])
    return blocks


def walk(
    tree: list[np.ndarray],
    level: int,
    idx: np.ndarray,
    blocks: list[np.ndarray],
    max_frontier: float,
) -> tuple[int, np.ndarray]:
    """Compare a received block against the local tree.

    Returns ``(end_level, differing_idx)``: the deepest level the block
    reaches and the still-differing node indices there (truncated per
    level to ``max_frontier``, reference ``causal_crdt.ex:98,105``).
    """
    depth = len(tree) - 1
    cur = np.asarray(idx, dtype=np.int64)
    pos = np.arange(len(cur), dtype=np.int64)
    diff = tree[level][cur] != blocks[0][pos]
    cur, pos = cur[diff], pos[diff]
    j = 0
    while j + 1 < len(blocks) and len(cur):
        j += 1
        cur = np.stack([cur * 2, cur * 2 + 1], 1).reshape(-1)
        pos = np.stack([pos * 2, pos * 2 + 1], 1).reshape(-1)
        diff = tree[level + j][cur] != blocks[j][pos]
        cur, pos = cur[diff], pos[diff]
        if len(cur) > max_frontier:
            cur, pos = cur[: int(max_frontier)], pos[: int(max_frontier)]
    assert level + j <= depth
    return level + j, cur
