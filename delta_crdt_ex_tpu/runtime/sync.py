"""Anti-entropy protocol: messages + the digest-tree level walk.

This is the TPU-native redesign of the reference's two-phase Merkle
anti-entropy (Almeida et al. Algorithm 2 shell, ``causal_crdt.ex:252-289``
+ ``:86-123``):

- the originator A opens a sync with its tree root block; the peers then
  **ping-pong bounded frontier blocks** — each message carries the
  sender's digests for up to ``levels_per_round`` (default 8, exactly the
  reference's ``prepare_partial_diff(mm, 8)`` fan) tree levels beneath the
  currently-differing frontier, truncated to ``max_sync_size`` nodes
  (reference ``truncate``, ``causal_crdt.ex:206-214``);
- the receiver walks the block against its own tree (host numpy over
  device-computed digests — control on host, bulk math on device), either
  continuing the ping-pong, acking on equality (``{:ok, []}`` path,
  ``causal_crdt.ex:101-102``), or arriving at differing leaf buckets;
- differing buckets resolve to an entries transfer from the originator to
  the peer (``get_diff`` / direct-slice paths, ``causal_crdt.ex:324-335``),
  joined on device.

Every message is bounded; truncated divergence heals over subsequent
rounds (sync is idempotent). Data flows originator → peer only, matching
the reference's unidirectional edges (``delta_crdt.ex:89-94``).

Log-shipping catch-up (ISSUE 4) rides the same transport: a rejoining
or lagging peer's divergence has a *known shape* — the suffix of the
server's per-replica delta log (the WAL) past the peer's last fully
observed sequence number — so instead of walking the digest tree it
sends :class:`GetLogMsg` with that watermark and the server answers
:class:`LogChunkMsg` runs. Watermarks are learned from the walk itself:
every :class:`DiffMsg` stamps the sender's applied ``seq``, and a walk
that ends in equality proves the receiver covers the sender's state at
that seq (digest equality ⇒ content equality). The chunk payload is NOT
a literal replay of the server's ``batch`` records — replaying another
writer's local mutation ops at the receiver would re-mint dots under
the wrong writer/counters and break add-wins once deltas also arrive
transitively — instead the WAL range is used as a *changed-bucket
index*: the server ships current full-row slices (``ctx_lo = 0``,
exactly the walk's entries transfer shape) for every bucket the range
touched, deduplicated across the range. Chunks therefore merge through
the normal idempotent entries path, coalesce on the grouped-ingest fast
path, and are bit-comparable against a digest-walk catch-up. A request
below the log's compaction horizon is answered with the explicit
``horizon`` so only the pre-horizon prefix falls back to the tree walk.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Hashable

import numpy as np


@dataclasses.dataclass
class DiffMsg:
    """Frontier block (the reference's ``%Diff{continuation: …}``)."""

    originator: Hashable
    frm: Hashable
    to: Hashable
    level: int  # tree level of the frontier (0 = root)
    idx: np.ndarray  # int64[f] frontier node indices at `level`
    blocks: list[np.ndarray]  # sender digests for levels level..level+j under idx
    #: the SENDER's applied sequence number when this block was built.
    #: A walk ending in equality proves the receiver covers the sender's
    #: state at this seq — the watermark log-shipping catch-up resumes
    #: from (0 on frames from builds predating log shipping: the
    #: watermark then stays conservative and catch-up over-serves, which
    #: is safe — merges are idempotent).
    seq: int = 0
    #: the sender's WAL compaction horizon when this is a round OPENER
    #: from a log-shipping-capable originator (None otherwise). The peer
    #: compares its applied watermark against it to decide the round's
    #: mode: watermark within the horizon → answer ``GetLogMsg`` (the
    #: log suffix IS the divergence, one streamed replay instead of the
    #: level walk); below it the peer weighs the servable suffix
    #: ``seq − log_horizon`` against the walk-bound prefix
    #: ``log_horizon − watermark`` — a dominant suffix (≥ the replica's
    #: ``catchup_suffix_ratio``) still streams as a horizon-clamped
    #: chunk run with only the prefix walking, anything less takes the
    #: classic ping-pong outright (the walk heals everything it finds,
    #: so chunks on top of a comparable walk are pure extra rounds).
    #: The decision rides the opener so data keeps flowing originator →
    #: peer only, exactly like the ``GetDiffMsg`` leaf fetch.
    log_horizon: int | None = None


@dataclasses.dataclass
class GetDiffMsg:
    """Peer asks the originator for its entries in differing buckets
    (reference ``{:get_diff, diff, keys}``, ``causal_crdt.ex:112-123``)."""

    originator: Hashable
    frm: Hashable
    to: Hashable
    buckets: np.ndarray  # int64[b] differing leaf-bucket indices


@dataclasses.dataclass
class EntriesMsg:
    """Entry slice transfer (reference ``{:diff, crdt_slice, keys}``)."""

    originator: Hashable
    frm: Hashable
    to: Hashable
    buckets: np.ndarray
    arrays: dict[str, np.ndarray]  # DotStore slice columns + ctx tables
    payloads: dict[tuple[int, int, int], tuple[Any, Any]]  # (gid, bucket, ctr) -> (key_term, value)


@dataclasses.dataclass
class GetLogMsg:
    """Log-shipping catch-up request: "ship me everything you applied
    past ``last_seq``". The server answers with one
    :class:`LogChunkMsg`; the requester paces the stream by
    re-requesting from each chunk's resume point while ``more`` is set,
    so the server stays stateless and a dead requester leaks nothing.

    ``last_seq`` is the RESUME CURSOR — after a horizon/barrier-clamped
    chunk it sits past spans the requester never received.
    ``applied_seq`` is the requester's honest COVERAGE CLAIM (its
    applied watermark), the only field the server may advance its
    membership-compaction ack floor from; conflating the two would let
    a resume past a barrier reclaim records the peer still needs. 0
    (the pre-field default on old builds) claims nothing."""

    frm: Hashable
    to: Hashable
    last_seq: int
    applied_seq: int = 0


@dataclasses.dataclass
class LogChunkMsg:
    """One bounded run of log-shipped catch-up state covering the
    server's applied range ``(seq_lo, seq_hi]``.

    ``slices`` is a list of full-row entry slices (``{"buckets",
    "arrays", "payloads"}`` — the exact :class:`EntriesMsg` body shape)
    for every bucket the server's WAL records in the range touched,
    deduplicated; the receiver feeds them through the normal idempotent
    entries-merge path. ``horizon`` is set when part of the requested
    range is unservable by log — the request's ``last_seq`` fell below
    the compaction horizon, or the next record is a serving BARRIER (an
    unknown kind, or a ``clear`` touching more buckets than the hard
    row cap): the chunk then covers only ``(horizon, seq_hi]`` (or
    nothing, for a barrier) and the span through ``horizon`` must heal
    by the classic digest walk, which the server opens alongside.
    Receivers must not advance their applied watermark across an
    unshipped span (the chunk connects only when their watermark ≥
    ``seq_lo``). ``more`` means records past the chunk remain —
    re-request from ``max(seq_hi, horizon)``."""

    frm: Hashable
    to: Hashable
    seq_lo: int  # exclusive lower bound actually served
    seq_hi: int  # inclusive upper bound actually served
    more: bool  # records past seq_hi remain: re-request from seq_hi
    horizon: int | None  # set when last_seq was compacted past (see above)
    slices: list  # [{"buckets": int64[b], "arrays": {...}, "payloads": {...}}]


@dataclasses.dataclass
class AckMsg:
    """Clears the originator's in-flight slot for `clear_addr`
    (reference ``{:ack_diff, to}``, ``causal_crdt.ex:82-84,406-412``)."""

    clear_addr: Hashable


@dataclasses.dataclass
class FleetFrameMsg:
    """Fleet-wide egress envelope (ISSUE 10): one wire frame carrying
    many fleet members' per-peer sync messages — eager-delta
    ``EntriesMsg`` slices and ``DiffMsg`` openers — to a co-located
    peer process, where the transport decodes it back into per-member
    mailbox deliveries. ``entries`` is an ordered list of
    ``(to_addr, message)`` pairs; per-(sender, receiver) message order
    is the list order, exactly what per-member sends would produce.

    This is a negotiated capability (the TCP transport's ``_FLEETF``
    frame kind behind the ``_FEAT_FLEET`` HELLO bit): a peer that never
    advertised it receives plain per-member frames instead, so
    mixed-version clusters keep converging message-for-message. Flat
    gossip rides it today; it is the frame hierarchical anti-entropy
    (ROADMAP) will coalesce on — an intermediate hop can rewrite
    ``entries`` without touching the inner messages.

    A replica handed the whole envelope (a transport without
    frame-level decode) fans it out itself: entries addressed to the
    replica dispatch locally, everything else forwards."""

    frm: Hashable  # sending process identity (diagnostics/tracing)
    entries: list  # [(to_addr, message), ...] in send order


def make_blocks(
    tree: list[np.ndarray], level: int, idx: np.ndarray, levels_per_round: int
) -> list[np.ndarray]:
    """Digest blocks for `levels_per_round` levels beneath frontier `idx`.

    ``blocks[j]`` holds digests at ``level+j`` for all descendants of the
    frontier, ordered (frontier position, subtree offset) — positions are
    derivable, so only digest values travel.
    """
    depth = len(tree) - 1
    end = min(level + levels_per_round, depth)
    blocks = [tree[level][idx]]
    for j in range(1, end - level + 1):
        child_idx = (idx[:, None] * (1 << j) + np.arange(1 << j)[None, :]).reshape(-1)
        blocks.append(tree[level + j][child_idx])
    return blocks


def walk(
    tree: list[np.ndarray],
    level: int,
    idx: np.ndarray,
    blocks: list[np.ndarray],
    max_frontier: float,
) -> tuple[int, np.ndarray]:
    """Compare a received block against the local tree.

    Returns ``(end_level, differing_idx)``: the deepest level the block
    reaches and the still-differing node indices there (truncated per
    level to ``max_frontier``, reference ``causal_crdt.ex:98,105``).
    """
    depth = len(tree) - 1
    cur = np.asarray(idx, dtype=np.int64)
    pos = np.arange(len(cur), dtype=np.int64)
    diff = tree[level][cur] != blocks[0][pos]
    cur, pos = cur[diff], pos[diff]
    j = 0
    while j + 1 < len(blocks) and len(cur):
        j += 1
        cur = np.stack([cur * 2, cur * 2 + 1], 1).reshape(-1)
        pos = np.stack([pos * 2, pos * 2 + 1], 1).reshape(-1)
        diff = tree[level + j][cur] != blocks[j][pos]
        cur, pos = cur[diff], pos[diff]
        if len(cur) > max_frontier:
            cur, pos = cur[: int(max_frontier)], pos[: int(max_frontier)]
    assert level + j <= depth
    return level + j, cur
