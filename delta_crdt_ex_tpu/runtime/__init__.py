from delta_crdt_ex_tpu.runtime.replica import Replica
from delta_crdt_ex_tpu.runtime.storage import MemoryStorage, Storage
from delta_crdt_ex_tpu.runtime.transport import LocalTransport

__all__ = ["LocalTransport", "MemoryStorage", "Replica", "Storage"]
