"""In-process transport + failure detection.

The reference's transport is raw BEAM message passing — location-
transparent ``send/2`` to pid / name / ``{name, node}`` with
``Process.monitor`` for neighbour liveness (``causal_crdt.ex:270,291-314``).
The TPU-native control plane mirrors that contract behind a small
interface so the same replica/protocol code runs over:

- :class:`LocalTransport` — same-process registry + mailboxes (covers the
  reference's single-VM test topology, SURVEY §4, and the batched
  many-replicas-per-chip bench path);
- :class:`delta_crdt_ex_tpu.runtime.tcp_transport.TcpTransport` — a
  socket transport for cross-host control, with the data plane still
  moving tensor slices.

Send to a dead address returns ``False`` (the reference rescues
``ArgumentError`` and moves on — sync is idempotent, ``causal_crdt.ex:
269-282``); monitors deliver a :class:`Down` message on unregister, the
``:DOWN`` analog.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Any, Hashable


@dataclasses.dataclass
class Down:
    """Neighbour-death notification (reference ``:DOWN``, ``causal_crdt.ex:127``)."""

    addr: Hashable


class LocalTransport:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._mailboxes: dict[Hashable, queue.Queue] = {}
        self._owners: dict[Hashable, Any] = {}
        # target addr -> set of watcher addrs
        self._monitors: dict[Hashable, set[Hashable]] = {}

    def register(self, addr: Hashable, owner: Any) -> None:
        with self._lock:
            if addr in self._owners:
                raise ValueError(f"address already registered: {addr!r}")
            self._mailboxes[addr] = queue.Queue()
            self._owners[addr] = owner

    def canonical_addr(self, name: Hashable) -> Hashable:
        """The address peers should use to reach ``name`` (in-process:
        the name itself; TCP: ``(name, endpoint)``)."""
        return name

    def unregister(self, addr: Hashable) -> None:
        with self._lock:
            self._mailboxes.pop(addr, None)
            self._owners.pop(addr, None)
            watchers = self._monitors.pop(addr, set())
        for w in watchers:
            self.send(w, Down(addr))

    def alive(self, addr: Hashable) -> bool:
        with self._lock:
            return addr in self._owners

    def device_of(self, addr: Hashable):
        """The jax device the replica behind ``addr`` pinned its state to
        (None when unpinned or unknown). Senders use this to place sync
        slices directly on the receiver's device — the device data plane;
        in-process messages pass by reference, so a device-resident array
        in an EntriesMsg never takes a host round trip."""
        with self._lock:
            return getattr(self._owners.get(addr), "device", None)

    def send(self, addr: Hashable, msg: Any) -> bool:
        with self._lock:
            mb = self._mailboxes.get(addr)
            owner = self._owners.get(addr)
        if mb is None:
            return False
        mb.put(msg)
        notify = getattr(owner, "notify", None)
        if notify is not None:
            notify()  # wake a threaded replica's event loop
        return True

    def monitor(self, watcher: Hashable, target: Hashable) -> bool:
        """Watch ``target``; ``False`` if it is already dead (the reference
        rescues monitoring a dead process, ``causal_crdt.ex:295-308``)."""
        with self._lock:
            if target not in self._owners:
                return False
            self._monitors.setdefault(target, set()).add(watcher)
            return True

    def demonitor(self, watcher: Hashable, target: Hashable) -> None:
        with self._lock:
            self._monitors.get(target, set()).discard(watcher)

    def queue_depth(self, addr: Hashable) -> int:
        """Queued messages in one mailbox — the mailbox-depth gauge the
        observability plane polls at scrape time (``qsize`` is approximate
        under concurrency, which is exactly what a gauge is for)."""
        with self._lock:
            mb = self._mailboxes.get(addr)
        return mb.qsize() if mb is not None else 0

    # -- driving (deterministic mode) ------------------------------------

    def drain_nowait(self, addr: Hashable, max_n: int | None = None) -> list:
        """Pop up to ``max_n`` queued messages for one address (all of
        them when ``None``), never blocking. Arrival (FIFO) order is
        preserved across message types — a ``Down`` is never reordered
        past entries queued before it from the same peer, which is what
        lets the replica's ingress coalescing batch-receive without
        changing protocol semantics, and likewise keeps log-shipping
        catch-up frames (``GetLogMsg``/``LogChunkMsg``) ordered against
        the walk and entries traffic they interleave with (a chunk
        never passes the ``Down`` of the server that sent it)."""
        with self._lock:
            mb = self._mailboxes.get(addr)
        out: list = []
        if mb is None:
            return out
        while max_n is None or len(out) < max_n:
            try:
                out.append(mb.get_nowait())
            except queue.Empty:
                break
        return out

    def drain(self, addr: Hashable) -> list:
        """Pop all queued messages for one address."""
        return self.drain_nowait(addr, None)

    def pump(self, max_rounds: int = 10_000) -> int:
        """Deterministically deliver messages until quiescent.

        The reference's tests await convergence with ``Process.sleep``
        (flaky-prone, SURVEY §4); this is the deterministic "deliver
        everything now" alternative. Returns messages delivered.
        """
        delivered = 0
        for _ in range(max_rounds):
            progressed = False
            with self._lock:
                addrs = list(self._owners)
            for addr in addrs:
                with self._lock:
                    owner = self._owners.get(addr)
                if owner is None:
                    continue
                for msg in self.drain(addr):
                    owner.handle(msg)
                    delivered += 1
                    progressed = True
            if not progressed:
                return delivered
        raise RuntimeError("transport did not quiesce")


def forward_fleet_entries(transport, entries, local=None) -> None:
    """THE fleet-envelope relay policy (ISSUE 15), shared by the TCP
    receive path and the replica's whole-envelope fallback so the two
    cannot drift: entries ``local`` claims (returns True) are done;
    the rest regroup per next-hop endpoint (``transport.fleet_sink``)
    and re-emit as ONE rewritten frame each — per-destination order
    preserved, inner messages untouched — with per-member
    ``transport.send`` for sink-less destinations and the
    renegotiated-down unbundle inside ``send_fleet_frame`` itself."""
    sink_of = getattr(transport, "fleet_sink", None)
    forwards: dict = {}
    for to, m in entries:
        if local is not None and local(to, m):
            continue
        sink = sink_of(to) if sink_of is not None else None
        if sink is None:
            transport.send(to, m)
        else:
            forwards.setdefault(sink, []).append((to, m))
    for endpoint, group in forwards.items():
        transport.send_fleet_frame(endpoint, group)


_default: LocalTransport | None = None
_default_lock = threading.Lock()


def default_transport() -> LocalTransport:
    global _default
    with _default_lock:
        if _default is None:
            _default = LocalTransport()
        return _default
