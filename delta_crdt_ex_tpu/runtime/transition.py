"""Pure, jit-able replica state transitions — the device half of the
replica split (ISSUE 6).

:mod:`delta_crdt_ex_tpu.runtime.replica` is two layers fused: a pure
state transition (merge/join/compact/read over the binned store) and an
I/O shell (locks, WAL, transport, payload dicts, telemetry). This module
is the *pure* layer factored out, generalised over a leading replica
axis: every function here is a deterministic function of its array
inputs — no locks, no WAL, no transport, no host syncs — and is safe to
``jax.jit``/``jax.vmap``/``shard_map``. crdtlint enforces the contract
structurally: every function in this module is host-sync-checked as a
jit entry point (SYNC001) and the lattice ops are purity-checked
(PURE001–003).

The fleet forms (``fleet_*``) are the DrJAX-style map/reduce shape over
a leading replica axis (PAPERS.md): N replica states stacked along axis
0 ride ONE batched dispatch instead of N host round-trips — the
single-process thousands-of-replicas scheduler
(:mod:`delta_crdt_ex_tpu.runtime.fleet`) drains N mailboxes per tick
into :func:`fleet_merge_rows`. ``vmap`` adds no arithmetic of its own
(integer/bool lattice math batches element-exact), so a vmapped lane is
bit-for-bit the solo kernel on that lane's inputs — the property
``tests/test_fleet.py`` pins.

The mesh forms (``mesh_fleet_*``, ISSUE 13) are the same fleet kernels
lifted one axis further: ``shard_map`` over a 1-D replica-sharded
``Mesh`` with the per-shard ``vmap`` form inside, so N stacked lanes
split into ``mesh.devices.size`` device-resident blocks and each block
runs the UNCHANGED vmapped kernel — lane k's math is identical whether
its block holds 2 lanes or 256, which is why mesh-vs-vmap fleet runs
are bit-for-bit (the SPMD001 lint family proves the kernels carry no
host callbacks, replica-axis Python branches, or implicit axis
reductions that would break under the lift). ``mesh_plane_rotate`` is
the intra-mesh delivery plane's collective: one ``lax.ppermute``
rotation of padded slice buffers along the replica axis
(:mod:`delta_crdt_ex_tpu.runtime.meshplane`).

Stacking helpers (:func:`stack_states`, :func:`index_state`) are pure
pytree shuffles and live here so the shell never touches array layout.
"""

from __future__ import annotations

import jax

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _new_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs):
        return _new_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
except ImportError:  # jax < 0.6 ships shard_map under experimental,
    # with the replication check spelled check_rep instead of check_vma
    from jax.experimental.shard_map import shard_map as _old_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs):
        return _old_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )

from delta_crdt_ex_tpu.models.binned import BinnedStore
from delta_crdt_ex_tpu.models.hash_store import HashStore
from delta_crdt_ex_tpu.ops import binned as binned_ops
from delta_crdt_ex_tpu.ops import hash_map as hash_ops
from delta_crdt_ex_tpu.utils.jitcache import named_jit

# ---------------------------------------------------------------------------
# single-replica transitions (the replica loop's device calls, re-exported
# here as THE pure seam — the shell may only reach the store through these
# or through models/binned_map.py's tiered wrappers)

merge_rows = binned_ops.merge_rows
row_apply = binned_ops.row_apply
extract_rows = binned_ops.extract_rows
compact_rows = binned_ops.compact_rows
winner_all = binned_ops.winner_all


# ---------------------------------------------------------------------------
# fleet transitions: leading replica axis, one dispatch for N replicas


def fleet_merge_rows(states: BinnedStore, slices) -> binned_ops.MergeRowsResult:
    """Batched anti-entropy merge: lane k joins ``slices`` lane k into
    ``states`` lane k (:func:`~delta_crdt_ex_tpu.ops.binned.merge_rows`
    over a leading replica axis). Every result field gains the leading
    axis — per-lane ``ok``/``need_*`` flags let the host shell retry
    only the overflowing lanes through the solo growth path, and
    per-lane ``gap_row`` masks keep the ``CtxGapError`` repair
    per-sender. Padding lanes (rows all ``-1``) merge nothing and
    report ``ok``."""
    return jax.vmap(binned_ops.merge_rows)(states, slices)


def fleet_row_apply(states, self_slots, rows, op, key, valh, ts):
    """Batched local mutation: lane k applies its bucket-grouped batch
    to ``states`` lane k (:func:`~delta_crdt_ex_tpu.ops.binned.row_apply`
    over a leading replica axis)."""
    return jax.vmap(binned_ops.row_apply)(
        states, self_slots, rows, op, key, valh, ts
    )


def fleet_extract_rows(states, rows) -> binned_ops.RowSlice:
    """Batched sync-slice extraction: lane k gathers its own ``rows``
    lane (``-1`` pads) — the fleet-wide eager-push gather."""
    return jax.vmap(binned_ops.extract_rows)(states, rows)


def fleet_interval_slices(states, rows, self_slots, gid_selfs, lo) -> binned_ops.RowSlice:
    """Batched own-writer delta-interval extraction (ISSUE 10): lane k
    gathers its own alive entries with counter in ``(lo, ctx_max]`` per
    bucket row — the fleet-wide eager-push (Almeida et al.'s delta
    mode) in ONE dispatch instead of one ``extract_own_delta`` per
    member. ``self_slots``/``gid_selfs`` are per-lane scalars; padding
    lanes (rows all ``-1``) extract nothing."""
    return jax.vmap(binned_ops.extract_own_delta)(
        states, rows, self_slots, gid_selfs, lo
    )


def fleet_tree_from_leaves(leaves) -> list:
    """Batched digest-tree build over stacked leaf digests ``[N, L]``:
    one dispatch yields every member's levels (each ``[N, 2^j]``) —
    the sync-tick tree rebuild without N per-member launches. Leaf
    digests are backend-agnostic (the shared sync-index geometry), so
    one form serves both stores."""
    return jax.vmap(binned_ops.tree_from_leaves)(leaves)


def fleet_own_ctr_columns(ctx_max, self_slots):
    """uint32[N, L]: each lane's own-writer ``ctx_max`` column — the
    eager-push cursor source (``Replica._own_ctr_cache``), refreshed
    for a whole fleet in one gather + one host transfer instead of N
    per-member column reads. Backend-agnostic: ``ctx_max`` is the
    shared sync-index geometry."""
    return jax.vmap(lambda cm, s: cm[:, s])(ctx_max, self_slots)


def fleet_compact_rows(states: BinnedStore) -> BinnedStore:
    """Batched full repack + invariant rebuild, one dispatch for the
    whole stack."""
    return jax.vmap(binned_ops.compact_rows)(states)


def fleet_winner_all(states: BinnedStore) -> binned_ops.RowWinners:
    """Batched whole-table LWW winner resolution (the fleet read path)."""
    return jax.vmap(binned_ops.winner_all)(states)


# named_jit = jax.jit + a compile-cache audit registration (the SHAPE
# family's runtime cross-check: ``crdt_jit_compiles_total{name=...}``)
jit_fleet_merge_rows = named_jit(fleet_merge_rows)
jit_fleet_row_apply = named_jit(fleet_row_apply)
jit_fleet_extract_rows = named_jit(fleet_extract_rows)
jit_fleet_interval_slices = named_jit(fleet_interval_slices)
jit_fleet_tree_from_leaves = named_jit(fleet_tree_from_leaves)
jit_fleet_own_ctr_columns = named_jit(fleet_own_ctr_columns)
jit_fleet_compact_rows = named_jit(fleet_compact_rows)
jit_fleet_winner_all = named_jit(fleet_winner_all)


# ---------------------------------------------------------------------------
# hash-store fleet transitions (ISSUE 8): the same leading-replica-axis
# shape over the open-addressing backend — hash-store fleet members
# bucket by TABLE CAPACITY (which moves only on a rehash) instead of
# the binned per-bucket lane tier, so batches survive growth


def fleet_hash_merge_rows(states: HashStore, slices) -> hash_ops.HashMergeResult:
    """Batched anti-entropy merge over stacked hash-store states: lane k
    joins ``slices`` lane k via
    :func:`~delta_crdt_ex_tpu.ops.hash_map.merge_rows`. Per-lane
    ``ok``/``need_*``/``gap_row`` escapes route through the solo
    growth/repair paths exactly like the binned fleet form."""
    return jax.vmap(hash_ops.merge_rows)(states, slices)


def fleet_hash_row_apply(states, self_slots, rows, op, key, valh, ts):
    """Batched local mutation over stacked hash-store states."""
    return jax.vmap(hash_ops.row_apply)(states, self_slots, rows, op, key, valh, ts)


def fleet_hash_winner_all(states: HashStore):
    """Batched whole-table LWW winner resolution, hash backend."""
    return jax.vmap(hash_ops.winner_all)(states)


def fleet_hash_row_counts(states: HashStore, rows):
    """Batched dense-extraction sizing pass: alive entries per requested
    sync row, every lane in one dispatch (``int32[N, U]``) — the host
    reads it back ONCE and tiers each member's dense lane width pow2 so
    ragged members still share one packed-extraction compile."""
    return jax.vmap(hash_ops.row_counts)(states, rows)


def fleet_hash_own_delta_counts(states: HashStore, rows, self_slots, lo):
    """Batched sizing pass for the own-writer delta-interval extraction
    (``int32[N, U]`` entries per row in ``(lo, ∞)``)."""
    return jax.vmap(hash_ops.own_delta_counts)(states, rows, self_slots, lo)


def fleet_hash_extract_rows(states: HashStore, rows, lanes: int) -> binned_ops.RowSlice:
    """Batched dense full-row extraction over stacked hash stores:
    ``lanes`` is the bucket-wide pow2 tier (the max of the members'
    own dense tiers); each member's solo-tier slice is the leading
    ``[:, :member_lanes]`` columns of its lane, bit-for-bit."""
    return jax.vmap(lambda st, r: hash_ops.extract_rows_packed(st, r, lanes))(
        states, rows
    )


def fleet_hash_interval_slices(
    states: HashStore, rows, self_slots, gid_selfs, lo, lanes: int
) -> binned_ops.RowSlice:
    """Batched dense own-writer delta-interval extraction, hash
    backend (the ``fleet_interval_slices`` shape with a bucket-wide
    static dense lane tier)."""
    return jax.vmap(
        lambda st, r, ss, gs, lo_: hash_ops.extract_own_delta_packed(
            st, r, ss, gs, lo_, lanes
        )
    )(states, rows, self_slots, gid_selfs, lo)


jit_fleet_hash_merge_rows = named_jit(fleet_hash_merge_rows)
jit_fleet_hash_row_apply = named_jit(fleet_hash_row_apply)
jit_fleet_hash_winner_all = named_jit(fleet_hash_winner_all)
jit_fleet_hash_row_counts = named_jit(fleet_hash_row_counts)
jit_fleet_hash_own_delta_counts = named_jit(fleet_hash_own_delta_counts)
jit_fleet_hash_extract_rows = named_jit(
    fleet_hash_extract_rows, static_argnames=("lanes",)
)
jit_fleet_hash_interval_slices = named_jit(
    fleet_hash_interval_slices, static_argnames=("lanes",)
)


# ---------------------------------------------------------------------------
# mesh-lifted fleet transitions (ISSUE 13): shard_map over a 1-D
# replica-sharded mesh with the per-shard vmap form inside. The lanes
# axis must be a multiple of the mesh size (the fleet pads lane tiers
# to max(pow2, shards) — the same discipline as the lane/row padding,
# so SHAPE001 stays green); every twin is registered in utils/jitcache
# so the compile-cache audit covers the mesh seam too. The mesh rides
# as a static argument: one tracing-cache entry per (mesh, geometry),
# bounded exactly like the vmap forms' per-geometry entries.

#: the 1-D fleet mesh axis (matches parallel/mesh_gossip.AXIS)
MESH_AXIS = "replicas"


def replica_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis replica sharding over the fleet mesh — the placement
    of every resident stacked state and stacked slice in mesh mode."""
    return NamedSharding(mesh, P(MESH_AXIS))


def _lift(mesh: Mesh, fn):
    """The mesh lift: ``fn`` (a vmapped ``fleet_*`` form) over per-shard
    lane blocks. A single spec broadcasts over the argument and result
    pytrees — every leaf carries the leading replica axis."""
    spec = P(MESH_AXIS)
    return shard_map(fn, mesh=mesh, in_specs=spec, out_specs=spec)


def mesh_fleet_merge_rows(mesh, states, slices):
    """:func:`fleet_merge_rows` sharded over the replica mesh: each
    device merges its resident lane block, no cross-shard traffic (the
    merge is lane-local; only the delivery plane permutes)."""
    return _lift(mesh, fleet_merge_rows)(states, slices)


def mesh_fleet_row_apply(mesh, states, self_slots, rows, op, key, valh, ts):
    """:func:`fleet_row_apply` sharded over the replica mesh."""
    return _lift(mesh, fleet_row_apply)(
        states, self_slots, rows, op, key, valh, ts
    )


def mesh_fleet_extract_rows(mesh, states, rows):
    """:func:`fleet_extract_rows` sharded over the replica mesh."""
    return _lift(mesh, fleet_extract_rows)(states, rows)


def mesh_fleet_interval_slices(mesh, states, rows, self_slots, gid_selfs, lo):
    """:func:`fleet_interval_slices` sharded over the replica mesh."""
    return _lift(mesh, fleet_interval_slices)(
        states, rows, self_slots, gid_selfs, lo
    )


def mesh_fleet_tree_from_leaves(mesh, leaves):
    """:func:`fleet_tree_from_leaves` sharded over the replica mesh."""
    return _lift(mesh, fleet_tree_from_leaves)(leaves)


def mesh_fleet_own_ctr_columns(mesh, ctx_max, self_slots):
    """:func:`fleet_own_ctr_columns` sharded over the replica mesh."""
    return _lift(mesh, fleet_own_ctr_columns)(ctx_max, self_slots)


def mesh_fleet_hash_merge_rows(mesh, states, slices):
    """:func:`fleet_hash_merge_rows` sharded over the replica mesh."""
    return _lift(mesh, fleet_hash_merge_rows)(states, slices)


def mesh_fleet_hash_row_counts(mesh, states, rows):
    """:func:`fleet_hash_row_counts` sharded over the replica mesh."""
    return _lift(mesh, fleet_hash_row_counts)(states, rows)


def mesh_fleet_hash_own_delta_counts(mesh, states, rows, self_slots, lo):
    """:func:`fleet_hash_own_delta_counts` sharded over the mesh."""
    return _lift(mesh, fleet_hash_own_delta_counts)(
        states, rows, self_slots, lo
    )


def mesh_fleet_hash_extract_rows(mesh, states, rows, lanes: int):
    """:func:`fleet_hash_extract_rows` sharded over the replica mesh
    (``lanes`` is the bucket-wide static dense tier, unchanged)."""
    return _lift(mesh, lambda st, r: fleet_hash_extract_rows(st, r, lanes))(
        states, rows
    )


def mesh_fleet_hash_interval_slices(
    mesh, states, rows, self_slots, gid_selfs, lo, lanes: int
):
    """:func:`fleet_hash_interval_slices` sharded over the replica
    mesh."""
    return _lift(
        mesh,
        lambda st, r, ss, gs, lo_: fleet_hash_interval_slices(
            st, r, ss, gs, lo_, lanes
        ),
    )(states, rows, self_slots, gid_selfs, lo)


def mesh_plane_exchange(mesh, shift: int, depth: int, cols, src, slot):
    """Fused dense-scatter + rotation for the narrow delivery plane
    (ISSUE 17): ``cols`` holds one exchange group's entry rows as dense
    pow2-padded column stacks, ``src``/``slot`` the int32 position of
    each row in the padded ``[shards, depth, ...]`` collective layout.
    The scatter builds that layout ON DEVICE — pad rows carry
    ``src == shards`` and drop out of the scatter (``mode="drop"``) —
    then the buffers ride the same :func:`mesh_plane_rotate`
    ``ppermute``. The host never materialises the padded buffers and
    the rotated result stays device-resident for delivery, which is
    what retires the exchange's ``device_get`` from the transfer
    ledger."""
    shards = mesh.devices.size
    bufs = {
        c: jax.numpy.zeros((shards, depth) + a.shape[1:], a.dtype)
        .at[src, slot]
        .set(a, mode="drop")
        for c, a in cols.items()
    }
    return mesh_plane_rotate(mesh, shift, bufs)


def mesh_plane_rotate(mesh, shift: int, buffers):
    """The intra-mesh delivery plane's collective (ISSUE 13): rotate
    every leaf of ``buffers`` (padded ``[shards, depth, ...]`` slice
    column stacks) ``shift`` shards forward along the replica axis —
    one ``lax.ppermute`` per column, so sync-tick entries bound for a
    co-mesh member ride the interconnect instead of host TCP. The
    permutation is a full static rotation: entries grouped by shard
    distance share one dispatch, and the (mesh size − 1)-rotation
    vocabulary bounds distinct compiles per buffer geometry."""
    n = mesh.devices.size
    perm = [(i, (i + shift) % n) for i in range(n)]

    def rotate(tree):
        return jax.tree.map(
            lambda a: jax.lax.ppermute(a, MESH_AXIS, perm), tree
        )

    return _lift(mesh, rotate)(buffers)


jit_mesh_fleet_merge_rows = named_jit(
    mesh_fleet_merge_rows, static_argnames=("mesh",)
)
jit_mesh_fleet_row_apply = named_jit(
    mesh_fleet_row_apply, static_argnames=("mesh",)
)
jit_mesh_fleet_extract_rows = named_jit(
    mesh_fleet_extract_rows, static_argnames=("mesh",)
)
jit_mesh_fleet_interval_slices = named_jit(
    mesh_fleet_interval_slices, static_argnames=("mesh",)
)
jit_mesh_fleet_tree_from_leaves = named_jit(
    mesh_fleet_tree_from_leaves, static_argnames=("mesh",)
)
jit_mesh_fleet_own_ctr_columns = named_jit(
    mesh_fleet_own_ctr_columns, static_argnames=("mesh",)
)
jit_mesh_fleet_hash_merge_rows = named_jit(
    mesh_fleet_hash_merge_rows, static_argnames=("mesh",)
)
jit_mesh_fleet_hash_row_counts = named_jit(
    mesh_fleet_hash_row_counts, static_argnames=("mesh",)
)
jit_mesh_fleet_hash_own_delta_counts = named_jit(
    mesh_fleet_hash_own_delta_counts, static_argnames=("mesh",)
)
jit_mesh_fleet_hash_extract_rows = named_jit(
    mesh_fleet_hash_extract_rows, static_argnames=("mesh", "lanes")
)
jit_mesh_fleet_hash_interval_slices = named_jit(
    mesh_fleet_hash_interval_slices, static_argnames=("mesh", "lanes")
)
jit_mesh_plane_rotate = named_jit(
    mesh_plane_rotate, static_argnames=("mesh", "shift")
)
jit_mesh_plane_exchange = named_jit(
    mesh_plane_exchange, static_argnames=("mesh", "shift", "depth")
)


# ---------------------------------------------------------------------------
# stacking (pure pytree shuffles — no host round trips)


def stack_pytrees(*trees):
    """Stack per-replica pytrees (identical geometry) along a new
    leading replica axis. Jit this (``jit_stack_pytrees``) on hot
    paths: eager ``jnp.stack`` pays per-operand dispatch overhead
    (expand_dims + concat tracing per member — the dominant cost of
    restacking a 256-member bucket), while the jitted form compiles to
    ONE cached executable per (member count, geometry). Works on whole
    state pytrees and on bare arrays (leaf digests, ctx tables) alike."""
    return jax.tree.map(lambda *xs: jax.numpy.stack(xs), *trees)


jit_stack_pytrees = named_jit(stack_pytrees)


def stack_states(states: list) -> BinnedStore:
    """Stack per-replica states along a new leading replica axis — the
    fleet's resident form (:func:`stack_pytrees` over a list)."""
    return stack_pytrees(*states)


def index_state(stacked: BinnedStore, lane: int) -> BinnedStore:
    """Lane ``lane`` of a stacked fleet state as a solo state pytree."""
    return jax.tree.map(lambda a: a[lane], stacked)
