"""Pure, jit-able replica state transitions — the device half of the
replica split (ISSUE 6).

:mod:`delta_crdt_ex_tpu.runtime.replica` is two layers fused: a pure
state transition (merge/join/compact/read over the binned store) and an
I/O shell (locks, WAL, transport, payload dicts, telemetry). This module
is the *pure* layer factored out, generalised over a leading replica
axis: every function here is a deterministic function of its array
inputs — no locks, no WAL, no transport, no host syncs — and is safe to
``jax.jit``/``jax.vmap``/``shard_map``. crdtlint enforces the contract
structurally: every function in this module is host-sync-checked as a
jit entry point (SYNC001) and the lattice ops are purity-checked
(PURE001–003).

The fleet forms (``fleet_*``) are the DrJAX-style map/reduce shape over
a leading replica axis (PAPERS.md): N replica states stacked along axis
0 ride ONE batched dispatch instead of N host round-trips — the
single-process thousands-of-replicas scheduler
(:mod:`delta_crdt_ex_tpu.runtime.fleet`) drains N mailboxes per tick
into :func:`fleet_merge_rows`. ``vmap`` adds no arithmetic of its own
(integer/bool lattice math batches element-exact), so a vmapped lane is
bit-for-bit the solo kernel on that lane's inputs — the property
``tests/test_fleet.py`` pins.

Stacking helpers (:func:`stack_states`, :func:`index_state`) are pure
pytree shuffles and live here so the shell never touches array layout.
"""

from __future__ import annotations

import jax

from delta_crdt_ex_tpu.models.binned import BinnedStore
from delta_crdt_ex_tpu.models.hash_store import HashStore
from delta_crdt_ex_tpu.ops import binned as binned_ops
from delta_crdt_ex_tpu.ops import hash_map as hash_ops

# ---------------------------------------------------------------------------
# single-replica transitions (the replica loop's device calls, re-exported
# here as THE pure seam — the shell may only reach the store through these
# or through models/binned_map.py's tiered wrappers)

merge_rows = binned_ops.merge_rows
row_apply = binned_ops.row_apply
extract_rows = binned_ops.extract_rows
compact_rows = binned_ops.compact_rows
winner_all = binned_ops.winner_all


# ---------------------------------------------------------------------------
# fleet transitions: leading replica axis, one dispatch for N replicas


def fleet_merge_rows(states: BinnedStore, slices) -> binned_ops.MergeRowsResult:
    """Batched anti-entropy merge: lane k joins ``slices`` lane k into
    ``states`` lane k (:func:`~delta_crdt_ex_tpu.ops.binned.merge_rows`
    over a leading replica axis). Every result field gains the leading
    axis — per-lane ``ok``/``need_*`` flags let the host shell retry
    only the overflowing lanes through the solo growth path, and
    per-lane ``gap_row`` masks keep the ``CtxGapError`` repair
    per-sender. Padding lanes (rows all ``-1``) merge nothing and
    report ``ok``."""
    return jax.vmap(binned_ops.merge_rows)(states, slices)


def fleet_row_apply(states, self_slots, rows, op, key, valh, ts):
    """Batched local mutation: lane k applies its bucket-grouped batch
    to ``states`` lane k (:func:`~delta_crdt_ex_tpu.ops.binned.row_apply`
    over a leading replica axis)."""
    return jax.vmap(binned_ops.row_apply)(
        states, self_slots, rows, op, key, valh, ts
    )


def fleet_extract_rows(states, rows) -> binned_ops.RowSlice:
    """Batched sync-slice extraction: lane k gathers its own ``rows``
    lane (``-1`` pads) — the fleet-wide eager-push gather."""
    return jax.vmap(binned_ops.extract_rows)(states, rows)


def fleet_compact_rows(states: BinnedStore) -> BinnedStore:
    """Batched full repack + invariant rebuild, one dispatch for the
    whole stack."""
    return jax.vmap(binned_ops.compact_rows)(states)


def fleet_winner_all(states: BinnedStore) -> binned_ops.RowWinners:
    """Batched whole-table LWW winner resolution (the fleet read path)."""
    return jax.vmap(binned_ops.winner_all)(states)


jit_fleet_merge_rows = jax.jit(fleet_merge_rows)
jit_fleet_row_apply = jax.jit(fleet_row_apply)
jit_fleet_extract_rows = jax.jit(fleet_extract_rows)
jit_fleet_compact_rows = jax.jit(fleet_compact_rows)
jit_fleet_winner_all = jax.jit(fleet_winner_all)


# ---------------------------------------------------------------------------
# hash-store fleet transitions (ISSUE 8): the same leading-replica-axis
# shape over the open-addressing backend — hash-store fleet members
# bucket by TABLE CAPACITY (which moves only on a rehash) instead of
# the binned per-bucket lane tier, so batches survive growth


def fleet_hash_merge_rows(states: HashStore, slices) -> hash_ops.HashMergeResult:
    """Batched anti-entropy merge over stacked hash-store states: lane k
    joins ``slices`` lane k via
    :func:`~delta_crdt_ex_tpu.ops.hash_map.merge_rows`. Per-lane
    ``ok``/``need_*``/``gap_row`` escapes route through the solo
    growth/repair paths exactly like the binned fleet form."""
    return jax.vmap(hash_ops.merge_rows)(states, slices)


def fleet_hash_row_apply(states, self_slots, rows, op, key, valh, ts):
    """Batched local mutation over stacked hash-store states."""
    return jax.vmap(hash_ops.row_apply)(states, self_slots, rows, op, key, valh, ts)


def fleet_hash_winner_all(states: HashStore):
    """Batched whole-table LWW winner resolution, hash backend."""
    return jax.vmap(hash_ops.winner_all)(states)


jit_fleet_hash_merge_rows = jax.jit(fleet_hash_merge_rows)
jit_fleet_hash_row_apply = jax.jit(fleet_hash_row_apply)
jit_fleet_hash_winner_all = jax.jit(fleet_hash_winner_all)


# ---------------------------------------------------------------------------
# stacking (pure pytree shuffles — no host round trips)


def stack_states(states: list) -> BinnedStore:
    """Stack per-replica states (identical geometry) along a new leading
    replica axis — the fleet's resident form."""
    return jax.tree.map(lambda *xs: jax.numpy.stack(xs), *states)


def index_state(stacked: BinnedStore, lane: int) -> BinnedStore:
    """Lane ``lane`` of a stacked fleet state as a solo state pytree."""
    return jax.tree.map(lambda a: a[lane], stacked)
